"""Telemetry — the bundle engines and launchers actually pass around.

One object ties together the three obs primitives:

- a ``MetricsRegistry`` (defaults to the process-wide one, so comm-layer
  counters recorded by the backends show up in this run's round records);
- an ``EventLog`` over a rotating JSONL file (``log_dir/events.jsonl``) or
  an in-memory sink (tests);
- the ``jax.profiler`` bridge (``profile(logdir)`` — the opt-in XLA trace,
  reusing utils.tracing.trace);
- optionally a ``DistributedTracer`` (``trace_dir=``/``trace=True``) — the
  cross-rank per-round trace stitcher (obs/tracing.py); ``close()`` writes
  its Chrome trace-event JSON next to the event log;
- optionally the live run-health layer (docs/OBSERVABILITY.md §Live
  endpoints): ``http_port=`` binds a per-rank ``/metrics`` + ``/healthz``
  HTTP server (obs/httpd.py; port 0 = ephemeral, the bound port rides the
  run header), ``memwatch=`` samples device HBM + host RSS into gauges
  and a ``mem`` block on round records (obs/memwatch.py), and
  ``health=``/``health_rules=`` arm the rule-driven ``HealthMonitor``
  (obs/health.py) whose alerts land in this event log. ``http_port``
  alone implies memwatch + health — a live endpoint with no health
  verdict behind it would be an empty promise; pass ``memwatch=False`` /
  ``health=False`` to strip them.

Contract with the engines: a ``telemetry=None`` engine is bit-identical to
the pre-telemetry engine — no extra outputs in the jitted round program, no
extra device syncs, no host work. All cost is opt-in, and the new layers
follow the same rule: with http/memwatch/health off (the default) this
bundle starts zero threads and binds zero sockets.
"""

from __future__ import annotations

import os

from fedml_tpu.obs.comm_instrument import comm_counters
from fedml_tpu.obs.events import EventLog, JsonlSink, MemorySink
from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry


class Telemetry:
    def __init__(self, log_dir: str | None = None,
                 registry: MetricsRegistry | None = None,
                 sink=None, run_id: str | None = None,
                 round_stats: bool = True,
                 rotate_bytes: int = 64 << 20, backups: int = 3,
                 trace_dir: str | None = None, trace: bool = False,
                 trace_clock=None,
                 http_port: int | None = None, http_host: str = "127.0.0.1",
                 memwatch: bool | None = None, mem_interval_s: float = 5.0,
                 health: bool | None = None, health_rules=None,
                 health_interval_s: float = 5.0,
                 expected_ranks: int | None = None,
                 fleet: bool = False, fleet_job: str = ""):
        self.log_dir = log_dir
        # ``registry`` is where THIS bundle's own metrics live and what
        # close() dumps. Comm deltas always read the process-wide REGISTRY
        # regardless — the comm backends hard-wire their counters there
        # (they have no construction-time hook to receive another), so
        # honoring a custom registry for comm would silently report zero
        # traffic on a run that moved gigabytes.
        self.registry = registry or REGISTRY
        if sink is None:
            sink = (JsonlSink(os.path.join(log_dir, "events.jsonl"),
                              max_bytes=rotate_bytes, backups=backups)
                    if log_dir else MemorySink())
        self.events = EventLog(sink, run_id=run_id)
        # round_stats=False: keep the event stream but skip the in-graph
        # update-norm/drift outputs (an engine knob; comm counters stay on)
        self.round_stats = round_stats
        # cross-rank distributed tracing (obs/tracing.py): opt-in via
        # trace_dir (Chrome trace-event JSON written at close) or
        # trace=True (spans kept in memory — tests read tracer.spans()).
        # Off (the default): self.tracer is None, the engines add no trace
        # context to any frame, and the wire is byte-identical.
        self.trace_dir = trace_dir
        self.tracer = None
        if trace or trace_dir:
            import time as _time

            from fedml_tpu.obs.tracing import DistributedTracer

            self.tracer = DistributedTracer(
                self.events.run_id, clock=trace_clock or _time.time)
        # --- live run-health layer (all opt-in; docs/OBSERVABILITY.md
        # §Live endpoints / §Memory telemetry / §Health rules). None means
        # "follow http_port": a live endpoint without memory gauges or a
        # health verdict would scrape hollow.
        self.health = None
        self.memwatch = None
        self.httpd = None
        self.http_port = None
        if health is None:
            health = (health_rules is not None or http_port is not None
                      or fleet)
        if memwatch is None:
            memwatch = http_port is not None
        if health:
            from fedml_tpu.obs.health import HealthMonitor

            self.health = HealthMonitor(telemetry=self, rules=health_rules,
                                        registry=self.registry,
                                        expected_ranks=expected_ranks)
            self.health.start(health_interval_s)
        if memwatch:
            from fedml_tpu.obs.memwatch import MemoryWatcher

            self.memwatch = MemoryWatcher(interval_s=mem_interval_s,
                                          registry=self.registry).start()
        # --- fleet observability plane (docs/OBSERVABILITY.md §Fleet
        # rollup): rank 0's digest collector. The engines read
        # ``telemetry.fleet`` to decide whether broadcasts carry the
        # in-band marker; off (the default) keeps the wire byte-identical.
        self.fleet = None
        if fleet:
            from fedml_tpu.obs.fleet import FleetCollector

            self.fleet = FleetCollector(run_id=self.events.run_id,
                                        job=fleet_job,
                                        registry=self.registry,
                                        expected_ranks=expected_ranks,
                                        health=self.health)
            # with the plane armed and a file-backed run, arm the crash
            # flight recorder too (no recorder installed yet — a launcher
            # that installed its own wins): its dumps land next to the
            # event log, where report.py --post-mortem looks first
            from fedml_tpu.obs import flightrec as _flightrec

            if log_dir and _flightrec.active_recorder() is None:
                _flightrec.install_flight_recorder(
                    rank=0, run_id=self.events.run_id,
                    out_dir=os.path.join(log_dir, "flightrec"),
                    registry=self.registry)
        if http_port is not None:
            from fedml_tpu.obs.httpd import MetricsHTTPServer

            self.httpd = MetricsHTTPServer(port=http_port, host=http_host,
                                           registry=self.registry,
                                           health=self.health,
                                           fleet=self.fleet)
            self.http_port = self.httpd.port
        # the flight recorder tees every emitted record into its crash
        # ring and dumps on alert-fire; the observer routes through the
        # module-level hook so install order does not matter (no-op until
        # a recorder is armed)
        from fedml_tpu.obs import flightrec as _flightrec

        self.events.add_observer(_flightrec.on_event)
        # round-economics families (obs/goodput.py, obs/perf_instrument.py
        # §compile observatory) pre-register at zero the moment a run arms
        # telemetry — a clean export must carry them, not omit them
        from fedml_tpu.obs import goodput as _goodput
        from fedml_tpu.obs import perf_instrument as _perf_instr

        _goodput.ensure_goodput_families()
        _perf_instr.ensure_compile_attr_families()
        self._header_emitted = False
        self._last_comm = comm_counters(REGISTRY)

    # ------------------------------------------------------------- records
    def run_header(self, config: dict | None = None, **fields) -> None:
        """Emit the run-header record once (idempotent — standalone train()
        and a wrapping launcher may both call it)."""
        if self._header_emitted:
            return
        self._header_emitted = True
        if self.http_port is not None:
            # the bound port (http_port=0 asked for an ephemeral one) —
            # the run header is where a log reader learns where to scrape
            fields.setdefault("http_port", self.http_port)
        if (self.health is not None and self.health.expected_ranks is None
                and isinstance(fields.get("world_size"), int)):
            # the quorum rule's cohort: everyone but the server rank
            self.health.expected_ranks = fields["world_size"] - 1
        if (self.fleet is not None and self.fleet.expected_ranks is None
                and isinstance(fields.get("world_size"), int)):
            self.fleet.expected_ranks = fields["world_size"] - 1
        self.events.emit("run", config=config or {}, **fields)

    def comm_delta(self) -> dict:
        """Comm counter movement since the previous call — the per-round
        byte/message accounting, read from the process-wide registry the
        comm backends record into (see __init__). Cumulative totals ride
        along under ``total_`` so a record is interpretable on its own."""
        now = comm_counters(REGISTRY)
        delta = {k: now[k] - self._last_comm.get(k, 0.0)
                 for k in ("messages_sent", "bytes_sent",
                           "messages_received", "bytes_received",
                           "bytes_uplink", "bytes_downlink")}
        delta["total_bytes_sent"] = now["bytes_sent"]
        delta["total_messages_sent"] = now["messages_sent"]
        # dispatch stats come from a run-cumulative histogram (no per-round
        # reset), so they carry the total_ prefix like the other cumulatives
        if "dispatch_p95_s" in now:
            delta["total_dispatch_p95_s"] = now["dispatch_p95_s"]
            delta["total_dispatch_count"] = now["dispatch_count"]
        self._last_comm = now
        return delta

    def emit_round(self, round_idx: int, clients=None, spans=None,
                   metrics=None, evals=None, **extra) -> dict:
        """The standard per-round record: sampled client ids, host span
        timings (RoundTracer's dict for the round), scalar metrics (already
        floated by the caller), optional eval block, and the comm delta
        since the last round record."""
        rec: dict = {"round": int(round_idx)}
        if clients is not None:
            rec["clients"] = [int(c) for c in clients]
        if spans:
            rec["spans"] = {k: float(v) for k, v in spans.items()}
        if metrics:
            rec["metrics"] = {k: float(v) for k, v in metrics.items()}
        if evals:
            rec["eval"] = {k: (float(v) if isinstance(v, (int, float)) else v)
                           for k, v in evals.items()}
        rec["comm"] = self.comm_delta()
        if self.memwatch is not None:
            # exact-at-emit memory block (the background thread only keeps
            # the gauges fresh between rounds for live scrapes)
            mem = self.memwatch.sample()
            if mem:
                rec["mem"] = mem
        rec.update(extra)
        out = self.events.emit("round", **rec)
        if self.fleet is not None:
            # rank 0's own /fleetz row: round progress + the DP ε and the
            # round-economics figures the record already carries (no wire
            # hop for the server)
            gp = rec.get("goodput") or {}
            fps = gp.get("flops_per_s")
            self.fleet.note_server(
                round_idx, eps=(rec.get("privacy") or {}).get("eps"),
                duty=(gp.get("duty") or {}).get("compute"),
                gflops=(fps / 1e9 if fps else None))
        if self.health is not None:
            # the per-round health hook: every engine that emits a round
            # record (standalone, pipelined drain, sync server, async
            # flush) feeds the rule table through this one seam
            self.health.on_round(out)
        return out

    def emit_eval(self, round_idx: int, evals: dict) -> dict:
        out = self.events.emit(
            "eval", round=int(round_idx),
            eval={k: (float(v) if isinstance(v, (int, float)) else v)
                  for k, v in evals.items()})
        if self.health is not None:
            self.health.on_eval(out)
        return out

    # ------------------------------------------------------------ profiler
    def profile(self, logdir: str):
        """Opt-in jax.profiler bridge: context manager writing an XLA/TPU
        trace (TensorBoard 'profile' plugin / Perfetto) to ``logdir`` —
        utils.tracing.trace under the obs roof."""
        from fedml_tpu.utils.tracing import trace

        return trace(logdir)

    # ------------------------------------------------------------- teardown
    def close(self) -> None:
        """Flush and close the event log; when file-backed, also drop a
        Prometheus text dump of the registry next to it. With tracing on
        and a trace_dir, write the stitched Chrome trace (trace.json —
        load it in Perfetto / chrome://tracing)."""
        from fedml_tpu.obs import flightrec as _flightrec

        # final black-box dump before anything is torn down — a clean
        # close leaves the same durable artifact a crash would, so a
        # post-mortem on a *successful* run also renders
        _flightrec.dump_active("close")
        if self.httpd is not None:
            self.httpd.close()
        if self.memwatch is not None:
            self.memwatch.stop()
        if self.health is not None:
            self.health.stop()
        if self.tracer is not None:
            self.tracer.finish()
            if self.trace_dir:
                from fedml_tpu.obs.trace_export import write_chrome_trace

                try:
                    os.makedirs(self.trace_dir, exist_ok=True)
                    write_chrome_trace(
                        self.tracer.spans(),
                        os.path.join(self.trace_dir, "trace.json"))
                except OSError:
                    pass  # read-only dir: in-memory spans still stand
        if self.log_dir:
            try:
                with open(os.path.join(self.log_dir, "metrics.prom"),
                          "w") as f:
                    f.write(self.registry.to_prometheus())
            except OSError:
                pass  # read-only dir: the event log (already flushed) stands
        self.events.close()
