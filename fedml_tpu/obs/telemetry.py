"""Telemetry — the bundle engines and launchers actually pass around.

One object ties together the three obs primitives:

- a ``MetricsRegistry`` (defaults to the process-wide one, so comm-layer
  counters recorded by the backends show up in this run's round records);
- an ``EventLog`` over a rotating JSONL file (``log_dir/events.jsonl``) or
  an in-memory sink (tests);
- the ``jax.profiler`` bridge (``profile(logdir)`` — the opt-in XLA trace,
  reusing utils.tracing.trace);
- optionally a ``DistributedTracer`` (``trace_dir=``/``trace=True``) — the
  cross-rank per-round trace stitcher (obs/tracing.py); ``close()`` writes
  its Chrome trace-event JSON next to the event log.

Contract with the engines: a ``telemetry=None`` engine is bit-identical to
the pre-telemetry engine — no extra outputs in the jitted round program, no
extra device syncs, no host work. All cost is opt-in.
"""

from __future__ import annotations

import os

from fedml_tpu.obs.comm_instrument import comm_counters
from fedml_tpu.obs.events import EventLog, JsonlSink, MemorySink
from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry


class Telemetry:
    def __init__(self, log_dir: str | None = None,
                 registry: MetricsRegistry | None = None,
                 sink=None, run_id: str | None = None,
                 round_stats: bool = True,
                 rotate_bytes: int = 64 << 20, backups: int = 3,
                 trace_dir: str | None = None, trace: bool = False,
                 trace_clock=None):
        self.log_dir = log_dir
        # ``registry`` is where THIS bundle's own metrics live and what
        # close() dumps. Comm deltas always read the process-wide REGISTRY
        # regardless — the comm backends hard-wire their counters there
        # (they have no construction-time hook to receive another), so
        # honoring a custom registry for comm would silently report zero
        # traffic on a run that moved gigabytes.
        self.registry = registry or REGISTRY
        if sink is None:
            sink = (JsonlSink(os.path.join(log_dir, "events.jsonl"),
                              max_bytes=rotate_bytes, backups=backups)
                    if log_dir else MemorySink())
        self.events = EventLog(sink, run_id=run_id)
        # round_stats=False: keep the event stream but skip the in-graph
        # update-norm/drift outputs (an engine knob; comm counters stay on)
        self.round_stats = round_stats
        # cross-rank distributed tracing (obs/tracing.py): opt-in via
        # trace_dir (Chrome trace-event JSON written at close) or
        # trace=True (spans kept in memory — tests read tracer.spans()).
        # Off (the default): self.tracer is None, the engines add no trace
        # context to any frame, and the wire is byte-identical.
        self.trace_dir = trace_dir
        self.tracer = None
        if trace or trace_dir:
            import time as _time

            from fedml_tpu.obs.tracing import DistributedTracer

            self.tracer = DistributedTracer(
                self.events.run_id, clock=trace_clock or _time.time)
        self._header_emitted = False
        self._last_comm = comm_counters(REGISTRY)

    # ------------------------------------------------------------- records
    def run_header(self, config: dict | None = None, **fields) -> None:
        """Emit the run-header record once (idempotent — standalone train()
        and a wrapping launcher may both call it)."""
        if self._header_emitted:
            return
        self._header_emitted = True
        self.events.emit("run", config=config or {}, **fields)

    def comm_delta(self) -> dict:
        """Comm counter movement since the previous call — the per-round
        byte/message accounting, read from the process-wide registry the
        comm backends record into (see __init__). Cumulative totals ride
        along under ``total_`` so a record is interpretable on its own."""
        now = comm_counters(REGISTRY)
        delta = {k: now[k] - self._last_comm.get(k, 0.0)
                 for k in ("messages_sent", "bytes_sent",
                           "messages_received", "bytes_received",
                           "bytes_uplink", "bytes_downlink")}
        delta["total_bytes_sent"] = now["bytes_sent"]
        delta["total_messages_sent"] = now["messages_sent"]
        # dispatch stats come from a run-cumulative histogram (no per-round
        # reset), so they carry the total_ prefix like the other cumulatives
        if "dispatch_p95_s" in now:
            delta["total_dispatch_p95_s"] = now["dispatch_p95_s"]
            delta["total_dispatch_count"] = now["dispatch_count"]
        self._last_comm = now
        return delta

    def emit_round(self, round_idx: int, clients=None, spans=None,
                   metrics=None, evals=None, **extra) -> dict:
        """The standard per-round record: sampled client ids, host span
        timings (RoundTracer's dict for the round), scalar metrics (already
        floated by the caller), optional eval block, and the comm delta
        since the last round record."""
        rec: dict = {"round": int(round_idx)}
        if clients is not None:
            rec["clients"] = [int(c) for c in clients]
        if spans:
            rec["spans"] = {k: float(v) for k, v in spans.items()}
        if metrics:
            rec["metrics"] = {k: float(v) for k, v in metrics.items()}
        if evals:
            rec["eval"] = {k: (float(v) if isinstance(v, (int, float)) else v)
                           for k, v in evals.items()}
        rec["comm"] = self.comm_delta()
        rec.update(extra)
        return self.events.emit("round", **rec)

    def emit_eval(self, round_idx: int, evals: dict) -> dict:
        return self.events.emit(
            "eval", round=int(round_idx),
            eval={k: (float(v) if isinstance(v, (int, float)) else v)
                  for k, v in evals.items()})

    # ------------------------------------------------------------ profiler
    def profile(self, logdir: str):
        """Opt-in jax.profiler bridge: context manager writing an XLA/TPU
        trace (TensorBoard 'profile' plugin / Perfetto) to ``logdir`` —
        utils.tracing.trace under the obs roof."""
        from fedml_tpu.utils.tracing import trace

        return trace(logdir)

    # ------------------------------------------------------------- teardown
    def close(self) -> None:
        """Flush and close the event log; when file-backed, also drop a
        Prometheus text dump of the registry next to it. With tracing on
        and a trace_dir, write the stitched Chrome trace (trace.json —
        load it in Perfetto / chrome://tracing)."""
        if self.tracer is not None:
            self.tracer.finish()
            if self.trace_dir:
                from fedml_tpu.obs.trace_export import write_chrome_trace

                try:
                    os.makedirs(self.trace_dir, exist_ok=True)
                    write_chrome_trace(
                        self.tracer.spans(),
                        os.path.join(self.trace_dir, "trace.json"))
                except OSError:
                    pass  # read-only dir: in-memory spans still stand
        if self.log_dir:
            try:
                with open(os.path.join(self.log_dir, "metrics.prom"),
                          "w") as f:
                    f.write(self.registry.to_prometheus())
            except OSError:
                pass  # read-only dir: the event log (already flushed) stands
        self.events.close()
