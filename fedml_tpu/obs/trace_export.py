"""Trace exporters — Chrome trace-event JSON + the critical-path renderer.

- ``to_chrome_trace`` / ``write_chrome_trace``: the stitched span list as
  Chrome trace-event JSON (the ``traceEvents`` array of complete events),
  loadable in Perfetto (https://ui.perfetto.dev) or chrome://tracing; one
  ``pid`` track per rank, timestamps rebased to the earliest span so the
  file is stable under an injected clock (the golden test);
- ``validate_spans`` / ``validate_chrome_trace``: the span-schema checks
  the CI smoke step runs against an emitted trace;
- ``render_critical_path``: the text report behind
  ``scripts/report.py --critical-path``.

Span schema (documented in docs/OBSERVABILITY.md):

    {"tid": <16-hex trace id>, "sid": <16-hex span id>,
     "parent": <span id | null>, "rank": <int>, "name": <str>,
     "t0": <seconds>, "t1": <seconds>, "attrs": {...}?}
"""

from __future__ import annotations

import json

from fedml_tpu.obs.tracing import PHASES

_REQUIRED = ("tid", "sid", "parent", "rank", "name", "t0", "t1")


def validate_spans(spans: list[dict]) -> list[str]:
    """Schema errors (empty list = valid): required fields, non-negative
    durations, parent references resolving within the same trace."""
    errors: list[str] = []
    by_trace: dict[str, set] = {}
    for s in spans:
        by_trace.setdefault(s.get("tid", ""), set()).add(s.get("sid"))
    for i, s in enumerate(spans):
        missing = [k for k in _REQUIRED if k not in s]
        if missing:
            errors.append(f"span[{i}] missing fields {missing}")
            continue
        if not (isinstance(s["t0"], (int, float))
                and isinstance(s["t1"], (int, float))):
            errors.append(f"span[{i}] ({s['name']}) non-numeric timestamps")
        elif s["t1"] < s["t0"]:
            errors.append(f"span[{i}] ({s['name']}) ends before it starts")
        if s["parent"] is not None and \
                s["parent"] not in by_trace.get(s["tid"], ()):
            errors.append(f"span[{i}] ({s['name']}) dangling parent "
                          f"{s['parent']!r}")
    return errors


def to_chrome_trace(spans: list[dict]) -> dict:
    """Chrome trace-event JSON: metadata events naming one process per
    rank, then every span as a complete ('X') event. Timestamps are µs
    rebased to the earliest span; events are sorted so the output is a
    pure function of the span list."""
    spans = sorted(spans, key=lambda s: (s["t0"], s["rank"], s["sid"]))
    base = spans[0]["t0"] if spans else 0.0
    events: list[dict] = []
    for rank in sorted({s["rank"] for s in spans}):
        role = "server" if rank == 0 else "client"
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "tid": rank, "args": {"name": f"rank {rank} ({role})"}})
    for s in spans:
        args = {"trace_id": s["tid"], "span_id": s["sid"],
                "parent_id": s["parent"]}
        args.update(s.get("attrs") or {})
        events.append({
            "ph": "X", "cat": "fed", "name": s["name"],
            "pid": s["rank"], "tid": s["rank"],
            "ts": round((s["t0"] - base) * 1e6, 3),
            "dur": round((s["t1"] - s["t0"]) * 1e6, 3),
            "args": args,
        })
    return {"displayTimeUnit": "ms", "traceEvents": events}


def write_chrome_trace(spans: list[dict], path: str) -> None:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans), f, indent=1, sort_keys=True)


def validate_chrome_trace(doc: dict) -> list[str]:
    """Errors in an exported Chrome trace document (the CI gate)."""
    errors: list[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph == "M":
            if e.get("name") != "process_name" or "pid" not in e:
                errors.append(f"event[{i}] malformed metadata")
        elif ph == "X":
            for k in ("name", "ts", "dur", "pid", "tid"):
                if k not in e:
                    errors.append(f"event[{i}] missing {k!r}")
                    break
            else:
                if e["dur"] < 0 or e["ts"] < 0:
                    errors.append(f"event[{i}] negative ts/dur")
        else:
            errors.append(f"event[{i}] unknown phase {ph!r}")
    if not any(e.get("ph") == "X" for e in events):
        errors.append("no span events")
    return errors


# --------------------------------------------------------- critical path text
def _fmt_s(v: float) -> str:
    return f"{v * 1e3:.1f}ms" if v < 1.0 else f"{v:.3f}s"


def render_critical_path(records: list[dict]) -> str:
    """Per-round critical-path text from event-log round records. Degrades
    gracefully on pre-tracing logs (records without ``critical_path``)."""
    rounds = [r for r in records if r.get("kind") == "round"]
    cps = [(r.get("round"), r.get("critical_path")) for r in rounds
           if r.get("critical_path")]
    if not cps:
        return ("(no critical-path records — log predates cross-rank "
                "tracing or the run had no --trace-dir)")
    lines = []
    for rnd, cp in cps:
        head = (f"round {rnd}: rank {cp.get('straggler')} on the critical "
                f"path ({_fmt_s(float(cp.get('round_s', 0.0)))} round)")
        chaos = cp.get("chaos_delay_s") or {}
        if chaos:
            inj = ", ".join(f"rank {r} +{_fmt_s(float(s))}"
                            for r, s in sorted(chaos.items()))
            head += f"  [chaos: {inj}]"
        if cp.get("missing"):
            head += f"  [never reported: ranks {cp['missing']}]"
        lines.append(head)
        phases = cp.get("phases") or {}
        ordered = [p for p in PHASES if p in phases] + \
            sorted(set(phases) - set(PHASES))
        if ordered:
            lines.append("  phases: " + "  ".join(
                f"{p}={_fmt_s(float(phases[p]))}" for p in ordered))
        slack = cp.get("slack_s") or {}
        others = {r: s for r, s in slack.items()
                  if str(r) != str(cp.get("straggler"))}
        if others:
            lines.append("  slack:  " + "  ".join(
                f"rank {r}={_fmt_s(float(s))}"
                for r, s in sorted(others.items(), key=lambda kv: str(kv[0]))))
    return "\n".join(lines)
