"""Live run-health HTTP endpoints — per-rank ``/metrics`` + ``/healthz``.

Everything the obs stack exposes today is post-hoc: ``Telemetry.close()``
dumps ``metrics.prom``, ``scripts/report.py`` reads a finished event log.
A *running* fleet — a stalled async server, a diverging loss, an
HBM-exhausted mesh — is invisible until the run ends. This module is the
live view: a stdlib ``ThreadingHTTPServer`` per rank serving

- ``/metrics``  — the process registry as Prometheus text exposition,
  **the same snapshot** ``write_prometheus`` dumps at close (both call
  ``registry.to_prometheus()``), with ``comm_instrument.refresh_liveness()``
  run per scrape so the heartbeat-age gauges are fresh, not
  frozen-at-last-frame;
- ``/healthz``  — a JSON run-health summary (run id, current round,
  ``fed_ranks_alive``, seconds since last progress, quarantine/shed
  totals, status ``ok | degraded | stalled``) read from a
  ``HealthMonitor`` (obs/health.py) when one is attached, else a minimal
  registry-only view;
- ``/fleetz``   — rank 0 only, with the fleet plane armed
  (``Telemetry(fleet=True)``): the ``FleetCollector``'s aggregated JSON
  (per-rank round/staleness/bytes/ε rows, fleet rollups, status —
  obs/fleet.py, docs/OBSERVABILITY.md §Fleet rollup); 404 elsewhere.

Opt-in like every obs feature: ``Telemetry(http_port=...)`` (port 0 binds
an ephemeral port — the bound port is reported in the run header and on
``server.port``), ``--metrics_port`` on the distributed launcher (each
rank binds ``port + rank``; 0 = ephemeral everywhere), and
``FEDML_BENCH_METRICS_PORT`` on bench.py. With the port unset, no socket,
no thread, nothing.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("fedml_tpu.obs.httpd")

# Prometheus text exposition content type (node_exporter textfile shape)
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    """One rank's live endpoints. The server thread is a daemon (a hung
    scrape must never block job teardown); handler threads are daemons
    too (``ThreadingHTTPServer.daemon_threads``). ``close()`` is
    idempotent."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: MetricsRegistry | None = None, health=None,
                 fleet=None):
        self.registry = registry or REGISTRY
        # the HealthMonitor feeding /healthz (None -> minimal snapshot)
        self.health = health
        # the FleetCollector feeding /fleetz (None -> 404: only rank 0
        # with the fleet plane armed serves the fleet view)
        self.fleet = fleet
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    if self.path.split("?", 1)[0] in ("/metrics", "/"):
                        body = server.metrics_text().encode()
                        ctype = PROM_CONTENT_TYPE
                    elif self.path.split("?", 1)[0] == "/healthz":
                        body = (json.dumps(server.health_snapshot())
                                + "\n").encode()
                        ctype = "application/json"
                    elif self.path.split("?", 1)[0] == "/fleetz":
                        if server.fleet is None:
                            self.send_error(
                                404, "no fleet collector on this rank "
                                "(rank 0 with the fleet plane armed "
                                "serves /fleetz)")
                            return
                        body = (json.dumps(server.fleet.snapshot(),
                                           default=float) + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404, "unknown path "
                                        "(serving /metrics, /healthz, "
                                        "/fleetz)")
                        return
                except Exception:  # noqa: BLE001 — a scrape bug must not
                    #                 kill the handler thread loudly forever
                    log.exception("metrics endpoint failed on %s", self.path)
                    self.send_error(500, "scrape failed (see server log)")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                # scrapes land once per interval per collector — route to
                # the debug log, never stderr (the no-bare-print contract)
                log.debug("httpd: " + fmt, *args)

        try:
            self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        except OSError as e:
            # at fleet scale PORT+rank collides with whatever else the
            # host runs — failing hard would kill the rank over a
            # monitoring port. Fall back to an ephemeral bind, LOUDLY;
            # the bound port rides the run header / server.port so every
            # log reader still learns where to scrape.
            if int(port) == 0:
                raise  # an ephemeral bind that fails is a real error
            log.error("metrics port %d unavailable (%s) — falling back "
                      "to an ephemeral port (the bound port is in the "
                      "run header and this log)", int(port), e)
            self._httpd = ThreadingHTTPServer((host, 0), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])  # bound (0 -> real)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"obs-httpd:{self.port}", daemon=True)
        self._thread.start()
        self._closed = False
        log.info("live metrics endpoint up: http://%s:%d/metrics "
                 "(+ /healthz)", host, self.port)

    # ------------------------------------------------------------ endpoints
    def metrics_text(self) -> str:
        """The /metrics body. refresh_liveness() recomputes every rank's
        heartbeat-age gauge before the snapshot, so a scrape mid-round
        shows real ages; the text itself is ``registry.to_prometheus()`` —
        byte-compatible with the ``metrics.prom`` file ``write_prometheus``
        drops at close (one snapshot path, the scrape-vs-file consistency
        guarantee in docs/OBSERVABILITY.md)."""
        from fedml_tpu.obs.comm_instrument import refresh_liveness

        refresh_liveness()
        return self.registry.to_prometheus()

    def health_snapshot(self) -> dict:
        """The /healthz body. With a HealthMonitor attached this is its
        full verdict (status/alerts/windows); without one, the minimal
        registry-only view a bare metrics server can still answer."""
        if self.health is not None:
            snap = self.health.snapshot()
        else:
            snap = {
                "status": "ok",
                "ranks_alive": self.registry.total("fed_ranks_alive"),
                "quarantine_total": self.registry.total(
                    "fed_updates_rejected_total"),
                "shed_total": self.registry.total("fed_async_shed_total"),
                # server crash recovery: the WAL's restart epoch (0 =
                # never crashed; docs/ROBUSTNESS.md §Server crash
                # recovery)
                "restart_epoch": int(self.registry.total(
                    "fed_restart_epoch")),
            }
        snap["port"] = self.port
        return snap

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    # ------------------------------------------------------------- teardown
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def start_metrics_server(port: int = 0, host: str = "127.0.0.1",
                         registry: MetricsRegistry | None = None,
                         health=None) -> MetricsHTTPServer:
    """Standalone entry for ranks that carry no Telemetry bundle (client
    ranks under ``--metrics_port``): bind and serve this process's
    registry. Returns the server (``.port`` is the bound port)."""
    return MetricsHTTPServer(port=port, host=host, registry=registry,
                             health=health)
