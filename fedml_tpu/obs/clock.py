"""NTP-style clock-offset estimation for cross-rank trace stitching.

Client span timestamps are taken on the client's wall clock; the server
stitches them into one per-round timeline, which needs each rank's clock
offset relative to the server. The estimate piggybacks on the round
protocol itself — the broadcast/upload exchange IS a symmetric two-way
handshake, so no extra messages are sent:

    T1  server stamps the broadcast          (server clock)
    T2  client receives it                   (client clock)
    T3  client stamps its upload             (client clock)
    T4  server receives the upload           (server clock)

The classic NTP estimators (RFC 5905 §8):

    offset = ((T2 - T1) + (T3 - T4)) / 2      (client clock minus server)
    rtt    = (T4 - T1) - (T3 - T2)            (wire time both ways)

``offset`` is exact when the two wire legs are symmetric; an asymmetry of
``a`` seconds biases it by ``a/2`` — which is also the bound on any
passive estimator, and on a loopback/LAN round far below the span
durations being stitched. Per rank we keep the sample with the smallest
RTT seen in a sliding window (the standard NTP clock filter: the fastest
exchange had the least queueing, hence the least asymmetry).

Host-side only, a few floats per rank; never runs under jit.
"""

from __future__ import annotations

import threading


def estimate(t1: float, t2: float, t3: float, t4: float) -> tuple[float, float]:
    """(offset, rtt) of one exchange: offset = client clock - server clock."""
    offset = ((t2 - t1) + (t3 - t4)) / 2.0
    rtt = (t4 - t1) - (t3 - t2)
    return offset, rtt


class ClockSync:
    """Per-rank offset estimates with a min-RTT clock filter.

    ``update`` folds one (T1..T4) exchange and returns the rank's current
    best offset; ``offset`` reads it (0.0 for a never-seen rank, so
    rebasing a rank with no estimate is the identity).
    """

    def __init__(self, window: int = 8):
        self.window = int(window)
        # rank -> list of (rtt, offset), newest last, len <= window
        self._samples: dict[int, list[tuple[float, float]]] = {}
        self._lock = threading.Lock()

    def update(self, rank: int, t1: float, t2: float, t3: float,
               t4: float) -> float:
        offset, rtt = estimate(t1, t2, t3, t4)
        with self._lock:
            s = self._samples.setdefault(int(rank), [])
            s.append((rtt, offset))
            del s[:-self.window]
            return min(s)[1]

    def offset(self, rank: int) -> float:
        with self._lock:
            s = self._samples.get(int(rank))
            return min(s)[1] if s else 0.0

    def rtt(self, rank: int) -> float | None:
        with self._lock:
            s = self._samples.get(int(rank))
            return min(s)[0] if s else None

    def snapshot(self) -> dict[int, dict[str, float]]:
        """{rank: {offset_s, rtt_s, samples}} — the round record's
        ``clock_offset_s`` block and the docs' debugging view."""
        with self._lock:
            return {r: {"offset_s": min(s)[1], "rtt_s": min(s)[0],
                        "samples": len(s)}
                    for r, s in self._samples.items() if s}
