"""Structured event log — JSONL run records with pluggable sinks.

One run = one stream of JSON objects, one per line:

    {"ts": ..., "kind": "run",   "run": "...", "config": {...}}
    {"ts": ..., "kind": "round", "run": "...", "round": 3,
     "clients": [7, 12, ...], "spans": {"pack": ..., "round": ...},
     "metrics": {"loss_sum": ..., "update_norm": ...},
     "comm": {"messages_sent": ..., "bytes_sent": ...}}
    {"ts": ..., "kind": "eval",  "run": "...", "round": 3,
     "eval": {"test_acc": ..., "test_loss": ...}}

The schema is documented in docs/OBSERVABILITY.md and consumed by
scripts/report.py. Sinks: ``JsonlSink`` (size-rotated file — a long run
cannot fill the disk) and ``MemorySink`` (tests read ``.records``).
"""

from __future__ import annotations

import json
import os
import threading
import time


class MemorySink:
    """In-memory sink — tests and short-lived tools read ``records``.

    Locked like ``JsonlSink``: the HealthMonitor's background thread
    (obs/health.py) emits alert records concurrently with the engine
    thread's round emits, and an unsynchronized list.append can drop a
    record mid-resize on some interpreters — same discipline, both
    sinks."""

    def __init__(self):
        self.records: list[dict] = []
        self._lock = threading.Lock()

    def write(self, rec: dict) -> None:
        with self._lock:
            self.records.append(rec)

    def close(self) -> None:
        with self._lock:
            pass  # nothing to flush; the lock keeps close/write ordered


class JsonlSink:
    """Append-only JSONL file with size-based rotation: when the active file
    would exceed ``max_bytes`` the stack shifts (events.jsonl ->
    events.jsonl.1 -> ... -> .{backups}, oldest dropped) and a fresh file
    opens. Rotation is per-record, so a single record is never split."""

    def __init__(self, path: str, max_bytes: int = 64 << 20, backups: int = 3):
        self.path = path
        self.max_bytes = max_bytes
        self.backups = backups
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._size = self._f.tell()

    def _rotate(self) -> None:
        self._f.close()
        for i in range(self.backups - 1, 0, -1):
            src, dst = f"{self.path}.{i}", f"{self.path}.{i + 1}"
            if os.path.exists(src):
                os.replace(src, dst)
        if self.backups > 0:
            os.replace(self.path, f"{self.path}.1")
        else:
            os.remove(self.path)
        self._f = open(self.path, "a")
        self._size = 0

    def write(self, rec: dict) -> None:
        line = json.dumps(rec, default=float) + "\n"
        with self._lock:
            if self._size and self._size + len(line) > self.max_bytes:
                self._rotate()
            self._f.write(line)
            self._f.flush()
            self._size += len(line)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class EventLog:
    """Emit structured records into a sink. Every record carries ``ts``
    (wall clock), ``kind``, and the run id. Observers (``add_observer``)
    see every emitted record after the sink write — the flight recorder
    (obs/flightrec.py) tees records into its crash ring this way; an
    observer exception is logged-and-swallowed (telemetry fan-out must
    never kill the emitting engine)."""

    def __init__(self, sink, run_id: str | None = None, clock=time.time):
        self.sink = sink
        self.run_id = run_id or time.strftime("run_%Y%m%d_%H%M%S")
        self._clock = clock
        self._observers: list = []

    def add_observer(self, fn) -> None:
        self._observers.append(fn)

    def emit(self, kind: str, **fields) -> dict:
        rec = {"ts": self._clock(), "kind": kind, "run": self.run_id}
        rec.update(fields)
        self.sink.write(rec)
        for fn in self._observers:
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 — see class docstring
                import logging

                logging.getLogger("fedml_tpu.obs.events").exception(
                    "event observer failed on %r", kind)
        return rec

    def close(self) -> None:
        self.sink.close()


def read_jsonl(path: str, kinds: tuple[str, ...] | None = None,
               backups: bool = True) -> list[dict]:
    """Load a JSONL event file. ``backups=True`` (the default) folds the
    rotated stack back in first (``.N`` ... ``.1``, oldest to newest, then
    the active file) so a run that rotated mid-flight comes back in
    emission order with its oldest retained rounds intact — report.py and
    ``bench_blob`` would otherwise silently lose them. ``backups=False``
    reads the active file alone (tail-only tools). Unparseable lines are
    skipped — a run killed mid-write must not make its whole log
    unreadable."""
    paths = []
    if backups:
        i = 1
        while os.path.exists(f"{path}.{i}"):
            paths.append(f"{path}.{i}")
            i += 1
        paths.reverse()  # .N is oldest
    if os.path.exists(path):
        paths.append(path)
    out = []
    for p in paths:
        with open(p, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if kinds is None or rec.get("kind") in kinds:
                    out.append(rec)
    return out
