"""Crash flight recorder — the black box that survives a supervised death.

The WAL (core/wal.py) journals the server's round lifecycle durably, but
everything else a crash investigation needs — the last alerts, the spans
in flight, which digests had arrived, the final metric values — lives in
process memory and dies with a SIGKILL. This module is the bounded black
box: every process keeps a ring of recent flight records (events, spans,
alerts, digest arrivals, metric snapshots) and dumps it through the WAL's
``durable_*`` helpers at the moments that matter:

- **alert-fire** — the EventLog observer hook tees every emitted record
  into the ring and triggers a dump when an ``alert`` record fires, so
  the box holds the run's state at the first sign of trouble;
- **SIGTERM** — ``install_sigterm_dump()`` chains the previous handler
  behind a dump (the supervised shutdown path);
- **simulated / real crash** — the server's ``_maybe_crash`` dumps just
  before raising; ``Telemetry.close()`` dumps on clean teardown.

A SIGKILL cannot be intercepted: what survives it is the last dump (the
most recent alert-fire/round tick), plus the WAL — which is exactly why
dumps are cheap (one ``durable_write`` of a bounded JSON blob, atomic
latest-wins per rank at ``<dir>/rank<N>.json``) and frequent.

``render_post_mortem`` stitches WAL records, the flight dumps from every
rank, and the event log's alerts into ONE time-ordered crash timeline —
what every rank was doing in the seconds before rank 0 died, which
uploads were in flight, what ε was charged (``scripts/report.py
--post-mortem``).

The recorder is a process-wide optional singleton (mirroring
``metrics.REGISTRY``): ``install_flight_recorder()`` arms it,
``flight_record(kind, **fields)`` is a cheap no-op until then — hot paths
(digest emit, upload ingest) call it unconditionally.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from collections import deque

from fedml_tpu.core.wal import RoundWAL, durable_write
from fedml_tpu.obs.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("fedml_tpu.obs.flightrec")

# ring capacity: enough for the last few rounds of a busy fleet (digests,
# alerts, WAL echoes) while keeping a dump at a few hundred KB worst-case
DEFAULT_CAPACITY = 512


class FlightRecorder:
    """One process's bounded flight ring. Thread-safe: the comm dispatch
    loop, the health checker, and the engine thread all record."""

    def __init__(self, rank: int = 0, run_id: str | None = None,
                 out_dir: str | None = None,
                 capacity: int = DEFAULT_CAPACITY,
                 registry: MetricsRegistry | None = None,
                 clock=time.time):
        self.rank = int(rank)
        self.run_id = run_id
        self.out_dir = out_dir
        self.registry = registry or REGISTRY
        self._clock = clock
        self._ring: deque[dict] = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._dumps = 0

    # -------------------------------------------------------------- recording
    def record(self, kind: str, **fields) -> None:
        rec = {"ts": self._clock(), "kind": str(kind)}
        rec.update(fields)
        rec.setdefault("rank", self.rank)
        with self._lock:
            self._ring.append(rec)

    def on_event(self, rec: dict) -> None:
        """EventLog observer: tee the emitted record into the ring and
        dump on an alert transition (the box must hold the state that
        *preceded* the alert, so the tee happens first)."""
        with self._lock:
            self._ring.append(dict(rec))
        if rec.get("kind") == "alert":
            self.dump("alert")

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    # ------------------------------------------------------------------ dump
    def dump(self, reason: str) -> str | None:
        """Durably write the box: ring + a compact scalar snapshot of the
        registry (counters/gauges only — histograms ride as summaries in
        the records that sampled them). Atomic latest-wins per rank; a
        failed dump logs and returns None (the recorder must never crash
        the crashing process harder)."""
        if not self.out_dir:
            return None
        path = os.path.join(self.out_dir, f"rank{self.rank}.json")
        with self._lock:
            self._dumps += 1
            blob = {
                "kind": "flight_dump",
                "ts": self._clock(),
                "rank": self.rank,
                "run": self.run_id,
                "reason": str(reason),
                "dumps": self._dumps,
                "ring": list(self._ring),
                "counters": self._scalar_snapshot(),
            }
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            durable_write(path, (json.dumps(blob, default=float) +
                                 "\n").encode())
        except OSError:
            log.exception("flight-record dump to %s failed", path)
            return None
        log.info("flight recorder: dumped %d records to %s (%s)",
                 len(blob["ring"]), path, reason)
        return path

    def _scalar_snapshot(self) -> dict:
        """Caller holds the lock. Counter/gauge families only, flattened
        to {name{labels}: value} — the registry state at dump time."""
        out: dict = {}
        for name, fam in self.registry.snapshot().items():
            for label_s, v in fam.items():
                if isinstance(v, (int, float)):
                    key = f"{name}{{{label_s}}}" if label_s else name
                    out[key] = v
        return out


# ------------------------------------------------------- process-wide singleton
_lock = threading.Lock()
_RECORDER: FlightRecorder | None = None


def install_flight_recorder(rank: int = 0, run_id: str | None = None,
                            out_dir: str | None = None,
                            capacity: int = DEFAULT_CAPACITY,
                            registry: MetricsRegistry | None = None,
                            clock=time.time) -> FlightRecorder:
    """Arm this process's flight recorder (idempotent: re-installing
    replaces it — the newest run's identity wins, matching how loopback
    simulations reuse one process across jobs)."""
    global _RECORDER
    with _lock:
        _RECORDER = FlightRecorder(rank=rank, run_id=run_id, out_dir=out_dir,
                                   capacity=capacity, registry=registry,
                                   clock=clock)
        return _RECORDER


def uninstall_flight_recorder() -> None:
    """Disarm (tests: one test's ring must not leak into the next)."""
    global _RECORDER
    with _lock:
        _RECORDER = None


def active_recorder() -> FlightRecorder | None:
    return _RECORDER


def flight_record(kind: str, **fields) -> None:
    """Record into the installed ring; a no-op (one global read) when no
    recorder is armed — hot paths call this unconditionally."""
    rec = _RECORDER
    if rec is not None:
        rec.record(kind, **fields)


def on_event(rec: dict) -> None:
    """The EventLog observer Telemetry attaches unconditionally — routes
    to the installed recorder, no-op otherwise (install order must not
    matter: a launcher may arm the recorder after Telemetry exists)."""
    r = _RECORDER
    if r is not None:
        r.on_event(rec)


def dump_active(reason: str) -> str | None:
    r = _RECORDER
    return r.dump(reason) if r is not None else None


def install_sigterm_dump() -> None:
    """Chain a flight dump in front of the existing SIGTERM disposition —
    the supervised-shutdown path. Launcher-only (libraries must not steal
    signal handlers); a non-main thread / exotic platform degrades to a
    no-op."""
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _handler(signum, frame):
            dump_active("sigterm")
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _handler)
    except (ValueError, OSError):  # not the main thread / no signals here
        log.debug("SIGTERM flight-dump hook unavailable", exc_info=True)


# --------------------------------------------------------------- post-mortem
def read_flight_dumps(flight_dir: str) -> list[dict]:
    """Load every rank's dump from a flight directory (missing dir or a
    torn file → skipped; a crash artifact must never crash its reader)."""
    out: list[dict] = []
    if not flight_dir or not os.path.isdir(flight_dir):
        return out
    for name in sorted(os.listdir(flight_dir)):
        if not (name.startswith("rank") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(flight_dir, name), errors="replace") as f:
                blob = json.loads(f.read())
        except (OSError, ValueError):
            continue
        if isinstance(blob, dict):
            out.append(blob)
    return out


def _fmt_ts(ts, t0: float | None) -> str:
    if not isinstance(ts, (int, float)):
        return "        ?"
    if t0 is not None:
        return f"{ts - t0:+9.3f}s"
    return time.strftime("%H:%M:%S", time.localtime(ts))


def _fields_str(rec: dict, skip=("ts", "kind", "run")) -> str:
    parts = []
    for k, v in rec.items():
        if k in skip or v is None:
            continue
        if isinstance(v, float):
            v = f"{v:.4g}"
        elif isinstance(v, (dict, list)):
            v = json.dumps(v, default=float)
            if len(v) > 60:
                v = v[:57] + "..."
        parts.append(f"{k}={v}")
    return " ".join(parts)


def render_post_mortem(wal_dir: str | None = None,
                       flight_dir: str | None = None,
                       events: list[dict] | None = None,
                       window_s: float = 30.0) -> str:
    """Stitch WAL records + per-rank flight dumps + event-log alerts into
    one time-ordered crash timeline. The anchor is the newest ``restart``
    WAL record (the post-crash boot); everything inside ``window_s``
    before it is the pre-crash window the investigation reads first.
    Pre-PR inputs (a WAL whose records carry no ``ts``, no flight dir)
    degrade to a notice — same contract as report.py's columns."""
    entries: list[tuple[float, str, str]] = []  # (ts, source, line)
    undated = 0

    replay = RoundWAL.replay(wal_dir) if wal_dir else None
    restarts: list[dict] = []
    if replay is not None:
        for r in replay.records:
            ts = r.get("ts")
            kind = r.get("kind", "?")
            body = _fields_str(r, skip=("ts", "kind"))
            if kind == "restart":
                restarts.append(r)
                body = ">>> " + ("restart " + body).strip()
            else:
                body = f"{kind} {body}".strip()
            if isinstance(ts, (int, float)):
                entries.append((float(ts), "wal", body))
            else:
                undated += 1

    dumps = read_flight_dumps(flight_dir) if flight_dir else []
    for d in dumps:
        src = f"flight:{d.get('rank', '?')}"
        ts = d.get("ts")
        if isinstance(ts, (int, float)):
            entries.append((float(ts), src,
                            f"--- dump ({d.get('reason', '?')}, "
                            f"{len(d.get('ring', []))} records)"))
        for rec in d.get("ring", []):
            rts = rec.get("ts")
            if not isinstance(rts, (int, float)):
                undated += 1
                continue
            line = f"{rec.get('kind', '?')} " + _fields_str(rec)
            entries.append((float(rts), src, line.strip()))

    for rec in events or []:
        if rec.get("kind") not in ("alert", "run"):
            continue
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            entries.append((float(ts), "events",
                            f"{rec['kind']} " + _fields_str(rec)))

    if not entries:
        return ("(no post-mortem inputs — the WAL/flight dumps are absent "
                "or predate the flight recorder; run with the fleet plane "
                "armed to record them)")

    # de-duplicate: an alert teed into the ring AND in the event log would
    # otherwise print twice at the same instant
    seen: set[tuple] = set()
    entries = [e for e in sorted(entries)
               if not (e in seen or seen.add(e))]

    anchor = None
    for r in restarts:
        if isinstance(r.get("ts"), (int, float)):
            anchor = float(r["ts"])
    lines = [
        "post-mortem timeline",
        f"  wal: {len(replay.records) if replay else 0} records, "
        f"{len(restarts)} restart(s)"
        + (f", restart epoch {restarts[-1].get('epoch')}" if restarts
           and restarts[-1].get("epoch") is not None else ""),
        f"  flight dumps: {len(dumps)} "
        f"(ranks {sorted({d.get('rank') for d in dumps})})" if dumps
        else "  flight dumps: none found",
    ]
    if undated:
        lines.append(f"  ({undated} undated record(s) skipped — inputs "
                     "predate the timestamped WAL/flight format)")
    if anchor is not None:
        lines.append(f"  crash anchor: last restart at "
                     f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(anchor))}"
                     f" — pre-crash window is the {window_s:.0f}s before it")
    lines.append("")
    for ts, src, body in entries:
        mark = " "
        if anchor is not None and 0.0 <= anchor - ts <= window_s:
            mark = "*"  # inside the pre-crash window
        lines.append(f"{_fmt_ts(ts, anchor)} {mark} {src:<9} {body}")
    return "\n".join(lines)
