"""Ring attention + Ulysses sequence parallelism over a mesh axis.

Ring attention (Liu et al.): Q stays put; K/V blocks rotate around the ring
via lax.ppermute while each device accumulates its queries' attention with a
numerically-stable online softmax (the flash-attention recurrence). After N
steps every query has attended to every key with O(T/N) memory per device and
all communication riding ICI, overlapped by XLA with the einsums.

Layouts: block tensors are [B, T_blk, H, D]; scores are [B, H, Tq, Tk]
(contractions land on the MXU).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def full_attention(q, k, v, causal: bool = False):
    """Single-device reference: softmax(QK^T/sqrt(d))V. [B, T, H, D] in/out."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d).astype(q.dtype)
    if causal:
        T, S = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _online_block_update(q, k, v, o, l, m, q_offset, k_offset, causal, scale):
    """One flash-attention style block accumulation step."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B,H,Tq,Tk]
    if causal:
        Tq, Tk = scores.shape[-2], scores.shape[-1]
        qpos = q_offset + jnp.arange(Tq)[:, None]
        kpos = k_offset + jnp.arange(Tk)[None, :]
        scores = jnp.where(kpos <= qpos, scores, -jnp.inf)
    m_blk = jnp.max(scores, axis=-1)                      # [B,H,Tq]
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows: exp(-inf - -inf) -> 0
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(scores), scores - safe_m[..., None], -jnp.inf))
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = (o * corr.transpose(0, 2, 1)[..., None]
             + jnp.einsum("bhqk,bkhd->bqhd", p, v))
    return o_new, l_new, m_new


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Call INSIDE shard_map: q/k/v are this device's sequence block
    [B, T_blk, H, D]; returns the attention output for the local queries."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    T_blk = q.shape[1]
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    B, H = q.shape[0], q.shape[2]

    # accumulators must carry q's varying-manual-axes (not just axis_name —
    # on a multi-axis mesh q may also vary over e.g. a 'clients' axis) or the
    # fori_loop carry types mismatch after the first update; deriving them
    # from q*0 inherits the full vma set, pcast adds the ring axis
    def var(x):  # no-op when q was already varying over the ring axis
        vma = getattr(jax.typeof(x), "vma", frozenset())
        return x if axis_name in vma else lax.pcast(x, axis_name, to="varying")

    zero_q = (q * 0).astype(jnp.float32)
    zero_red = jnp.sum(zero_q, axis=-1).transpose(0, 2, 1)  # [B, H, T_blk]
    o = var(zero_q)
    l = var(zero_red)
    m = var(zero_red - jnp.inf)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        o, l, m, k, v = carry
        src = (idx - s) % n  # which device's block we currently hold
        o, l, m = _online_block_update(
            q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
            o, l, m, idx * T_blk, src * T_blk, causal, scale)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return o, l, m, k, v

    o, l, m, _, _ = lax.fori_loop(0, n, body, (o, l, m, k, v))
    l_safe = jnp.maximum(l, 1e-20)
    return (o / l_safe.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention_sharded(mesh: Mesh, axis_name: str = "seq",
                           causal: bool = False):
    """shard_map-wrapped ring attention: takes full [B, T, H, D] tensors
    sharded (or shardable) on T; returns same layout."""
    f = partial(ring_attention, axis_name=axis_name, causal=causal)
    return jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
    ))


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      use_flash: bool = False):
    """Call INSIDE shard_map. DeepSpeed-Ulysses: all_to_all swaps the sharded
    axis from sequence to heads, each device computes FULL-sequence attention
    for H/N heads, then swaps back. Requires H % axis_size == 0.
    ``use_flash`` runs the per-device full-sequence attention through the
    Pallas flash kernel (fedml_tpu.ops) — O(T) memory for the long sequence
    each device now holds."""
    n = lax.axis_size(axis_name)
    # [B, T/N, H, D] -> all_to_all on H -> [B, T, H/N, D]
    def scatter_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if use_flash:
        from fedml_tpu.ops.flash_attention import flash_attention

        oh = flash_attention(qh, kh, vh, causal)
    else:
        oh = full_attention(qh, kh, vh, causal=causal)
    return gather_seq(oh)


def ulysses_attention_sharded(mesh: Mesh, axis_name: str = "seq",
                              causal: bool = False, use_flash: bool = False):
    f = partial(ulysses_attention, axis_name=axis_name, causal=causal,
                use_flash=use_flash)
    return jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
    ))


def ring_attention_flash(q, k, v, axis_name: str, causal: bool = False,
                         block_q: int = 128, block_k: int = 128):
    """Ring attention with the Pallas flash kernel as the per-step block op.

    Call INSIDE shard_map (same contract as ring_attention). Each rotation
    computes this device's queries against the currently-held K/V block with
    fedml_tpu.ops.flash_attention_with_lse, then merges into the running
    result by logsumexp weighting:

        lse' = logaddexp(lse, lse_b)
        o'   = exp(lse - lse')*o + exp(lse_b - lse')*o_b

    Causality across blocks is positional: the s=0 rotation (own block) uses
    the kernel's causal mask; for s>0 a block contributes iff its ring
    source precedes this device (src < idx), else its lse is -inf and the
    merge is a no-op. Gradients are exact — the lse output carries a true
    cotangent through the kernel's custom VJP.
    """
    from fedml_tpu.ops.flash_attention import flash_attention_with_lse

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # accumulators start device-varying (vma rule): they merge with
    # per-rotation partials computed from this device's K/V block
    def vary(x):
        vma = getattr(jax.typeof(x), "vma", frozenset())
        return x if axis_name in vma else lax.pcast(x, axis_name, to="varying")

    o = vary(jnp.zeros(q.shape, jnp.float32))
    lse = vary(jnp.full((q.shape[0], q.shape[2], q.shape[1]), -jnp.inf,
                        jnp.float32))

    def merge(o, lse, o_b, lse_b):
        lse_new = jnp.logaddexp(lse, lse_b)
        w = lambda a: jnp.where(jnp.isfinite(lse_new), jnp.exp(a - lse_new), 0.0)
        w1, w2 = w(lse), w(lse_b)
        # weights are [B, H, Tq] -> broadcast over [B, Tq, H, D]
        bc = lambda t: t.transpose(0, 2, 1)[..., None]
        return bc(w1) * o + bc(w2) * o_b.astype(jnp.float32), lse_new

    # python loop: n is static inside shard_map, and s=0 needs the causal
    # kernel variant while s>0 uses the full kernel + dynamic src gating
    kk, vv = k, v
    for s in range(n):
        if s == 0:
            o_b, lse_b = flash_attention_with_lse(q, kk, vv, causal, block_q, block_k)
        else:
            o_b, lse_b = flash_attention_with_lse(q, kk, vv, False, block_q, block_k)
            if causal:
                src = (idx - s) % n
                lse_b = jnp.where(src < idx, lse_b, -jnp.inf)
        o, lse = merge(o, lse, o_b, lse_b)
        if s != n - 1:
            kk = lax.ppermute(kk, axis_name, perm)
            vv = lax.ppermute(vv, axis_name, perm)
    return o.astype(q.dtype)


def ring_attention_flash_sharded(mesh: Mesh, axis_name: str = "seq",
                                 causal: bool = False, block_q: int = 128,
                                 block_k: int = 128):
    f = partial(ring_attention_flash, axis_name=axis_name, causal=causal,
                block_q=block_q, block_k=block_k)
    return jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
    ))
