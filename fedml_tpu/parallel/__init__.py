"""Sequence/context parallelism (first-class TPU capability).

The reference has NO long-context machinery (its longest sequence is 80
chars, SURVEY.md §2.7) — this package is the TPU-native headroom the
framework is designed around: a 'seq' mesh axis with

- ring_attention: blockwise attention with K/V blocks rotating over the ICI
  ring (lax.ppermute) and online-softmax accumulation — memory per device is
  O(T/N), enabling sequences far beyond one chip's HBM.
- ulysses_attention: all-to-all sequence<->head re-sharding so each device
  computes full-sequence attention for a head subset (DeepSpeed-Ulysses
  pattern) — cheaper at moderate T, needs heads % N == 0.

Both are pure shard_map bodies usable inside any jitted train step, tested
for exactness against single-device full attention on a CPU mesh.
"""

from fedml_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_sharded,
    ulysses_attention_sharded,
    full_attention,
)
