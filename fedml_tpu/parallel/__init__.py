"""Parallelism strategies beyond client-DP (all absent from the reference,
SURVEY.md §2.7; each pinned to an exact single-device oracle).

- Sequence/context parallelism ('seq' axis): ring_attention — blockwise
  attention with K/V blocks rotating over the ICI ring (lax.ppermute),
  online-softmax accumulation, O(T/N) memory per device; ulysses_attention
  — all-to-all sequence<->head re-sharding (DeepSpeed-Ulysses pattern).
- Tensor + expert parallelism ('model' axis): tensor_parallel.py —
  Megatron-style PartitionSpecs placed at init (GSPMD inserts the
  collectives); the switch-MoE expert-stacked kernels shard their expert
  dim over the same axis.
- Pipeline parallelism ('stage' axis): pipeline.py — GPipe microbatch
  schedule as scan+ppermute; the backward schedule comes from jax.grad.
"""

from fedml_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_sharded,
    ulysses_attention_sharded,
    full_attention,
)
from fedml_tpu.parallel.pipeline import gpipe, microbatch, unmicrobatch
from fedml_tpu.parallel.tensor_parallel import (
    num_sharded,
    shard_params,
    tp_shardings,
)
