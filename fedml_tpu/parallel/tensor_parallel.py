"""Tensor parallelism via pjit auto-sharding (Megatron-style specs).

The reference has no tensor/model parallelism anywhere (SURVEY.md §2.7);
this is capability-plus, done the idiomatic XLA way: pick a mesh, annotate
parameter shardings, and let the compiler insert the collectives
("How to Scale Your Model" recipe). Because pjit/GSPMD preserves program
semantics for ANY sharding, the specs below only steer layout/performance —
a wrong match degrades speed, never correctness (pinned by
tests/test_tensor_parallel.py's TP ≡ single-device oracle).

Spec rules (classic Megatron-LM layout for a transformer block):
  - MLP in  kernel [C, 4C]  -> column-parallel  P(None, model)
  - MLP out kernel [4C, C]  -> row-parallel     P(model, None)
  - attention qkv  [C, 3HD] -> column-parallel (contiguous columns — NOT
    head-aligned: the (3, H, D) reshape downstream makes GSPMD reshard
    around the attention core, so attention TP here saves weight memory
    and the projection FLOPs, not the full Megatron attention pattern)
  - attention out  [HD, C]  -> row-parallel
  - lm head        [C, V]   -> column-parallel
  - embedding      [V, C]   -> vocab-sharded    P(model, None)
  - norms / biases of row-parallel layers / scalars -> replicated
A dimension is only sharded when divisible by the mesh axis size;
otherwise the leaf falls back to replicated.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# path-suffix fragments matched against the flax keystr of each param leaf
# (flax numbers Dense modules per block: dense_0 = MLP-in / qkv, dense_1 =
# MLP-out / attention-out — the suffix covers both plain and attention
# variants). 'embedding' is anchored as a suffix so e.g. a hypothetical
# patch_embedding/kernel is not silently vocab-sharded.
_COLUMN = ("dense_0/kernel",)  # shard dim -1
_ROW = ("dense_1/kernel",)     # shard dim 0
_EMBED = ("embedding",)        # shard dim 0 (suffix-matched)


def _norm_path(path) -> str:
    return jax.tree_util.keystr(path).replace("'", "").replace("][", "/") \
        .strip("[]").lower()


def tp_spec_for(path, leaf, axis_size: int, model_axis: str) -> P:
    """PartitionSpec for one param leaf under the Megatron rules."""
    p = _norm_path(path)
    shp = np.shape(leaf)
    if len(shp) < 1:
        return P()

    def ok(dim):
        return shp[dim] % axis_size == 0

    if len(shp) >= 2:
        # attention qkv/out + MLP in/out + lm head kernels
        if any(p.endswith(s) for s in _ROW) and ok(0):
            return P(*((model_axis,) + (None,) * (len(shp) - 1)))
        if any(p.endswith(s) for s in _COLUMN) and ok(len(shp) - 1):
            return P(*((None,) * (len(shp) - 1) + (model_axis,)))
        if any(p.endswith(s) for s in _EMBED) and ok(0):
            return P(*((model_axis,) + (None,) * (len(shp) - 1)))
        return P()
    # 1D: bias of a column-parallel layer lives on the sharded output dim
    if any(p.endswith(s.replace("/kernel", "/bias")) for s in _COLUMN) and ok(0):
        return P(model_axis)
    return P()


def shard_params(params, mesh: Mesh, model_axis: str = "model"):
    """device_put every param leaf per the Megatron rules; returns
    (sharded_params, flat list of (keystr, PartitionSpec)). Specs are
    returned flat — PartitionSpec's pytree status varies across jax
    versions, so a spec TREE is a trap for tree_map callers."""
    axis_size = int(mesh.shape[model_axis])
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    placed, specs = [], []
    for path, leaf in flat:
        spec = tp_spec_for(path, leaf, axis_size, model_axis)
        specs.append((jax.tree_util.keystr(path), spec))
        placed.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, placed), specs


def num_sharded(params, model_axis: str = "model") -> int:
    """How many leaves actually carry the model axis (diagnostics/tests)."""
    count = 0
    for leaf in jax.tree.leaves(params):
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", None)
        if spec is not None and model_axis in jax.tree.leaves(tuple(spec)):
            count += 1
    return count
