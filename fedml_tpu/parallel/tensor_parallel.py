"""Tensor parallelism via pjit auto-sharding (Megatron-style specs).

The reference has no tensor/model parallelism anywhere (SURVEY.md §2.7);
this is capability-plus, done the idiomatic XLA way: pick a mesh, annotate
parameter shardings, and let the compiler insert the collectives
("How to Scale Your Model" recipe). Because pjit/GSPMD preserves program
semantics for ANY sharding, the specs below only steer layout/performance —
a wrong match degrades speed, never correctness (pinned by
tests/test_tensor_parallel.py's TP ≡ single-device oracle).

Spec rules (classic Megatron-LM layout for a transformer block). The
PRIMARY matching contract is the repo's explicit leaf-module names
(models/transformer.py names its layers semantically so a parent-module
rename can never silently de-shard them):

  - mlp_in   kernel [C, 4C]    -> column-parallel  P(None, model)
  - mlp_out  kernel [4C, C]    -> row-parallel     P(model, None)
  - q/k/v_proj kernel [C, H, D] -> HEAD-aligned    P(None, model, None)
       (DenseGeneral keeps heads a real dim, so the attention core runs
        fully sharded on 'model' — no reshard/all-gather around it; pinned
        by test_attention_core_stays_sharded)
  - o_proj   kernel [H, D, C]  -> row-parallel     P(model, None, None)
       (contracting the sharded head dim = the one Megatron all-reduce)
  - lm_head  kernel [C, V]     -> column-parallel  P(None, model)
  - embedding        [V, C]    -> vocab-sharded    P(model, None)
  - *_experts        [E, ...]  -> expert-sharded   P(model, None, ...)
  - norms / row-parallel biases / scalars          -> replicated

FALLBACK (generic two-dense MLP heads, e.g. the CNN families' classifier):
flax auto-names ``dense_0``/``dense_1`` are treated as column/row-parallel.
This fallback is positional by nature — a model whose Dense ordering
differs gets a suboptimal (never incorrect) layout; rely on the explicit
names above for anything that matters.

A dimension is only sharded when divisible by the mesh axis size;
otherwise the leaf falls back to replicated.  ``tp_shardings`` logs a
warning when a model-axis mesh ends up sharding ZERO leaves, so a naming
drift can't silently degrade TP to full replication.
"""

from __future__ import annotations

import logging

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("fedml_tpu.parallel.tp")

# path-suffix fragments matched against the flax keystr of each param leaf.
# Explicit semantic names (the models/transformer.py contract) first;
# dense_0/dense_1 are the generic-MLP fallback documented above.
_COLUMN = ("mlp_in/kernel", "lm_head/kernel", "dense_0/kernel")  # shard dim -1
_ROW = ("mlp_out/kernel", "dense_1/kernel")                      # shard dim 0
_HEAD = ("q_proj/kernel", "k_proj/kernel", "v_proj/kernel")      # shard dim 1 of [C,H,D]
_HEAD_OUT = ("o_proj/kernel",)  # shard dim 0 of [H,D,C]
_EMBED = ("embedding",)        # shard dim 0 (suffix-matched: e.g. a
#                                hypothetical patch_embedding/kernel is NOT
#                                silently vocab-sharded)
# expert-stacked MoE kernels [E, ...]: shard the expert dim — this IS
# expert parallelism (each device holds+runs E/n experts; the one-hot
# combine einsum becomes a psum over expert shards)
_EXPERT = ("experts",)         # shard dim 0 (suffix-matched)


def _norm_path(path) -> str:
    return jax.tree_util.keystr(path).replace("'", "").replace("][", "/") \
        .strip("[]").lower()


def tp_spec_for(path, leaf, axis_size: int, model_axis: str) -> P:
    """PartitionSpec for one param leaf under the Megatron rules."""
    p = _norm_path(path)
    shp = np.shape(leaf)
    if len(shp) < 1:
        return P()

    def ok(dim):
        return shp[dim] % axis_size == 0

    if len(shp) >= 2:
        # head-aligned attention projections: [C, H, D] sharded on H whole
        # heads, so the (B,T,H,D) activations stay sharded through the core.
        # The rank==3 guards keep PipelineLM's STACKED per-stage kernels
        # ([depth, ...]) out of these rules — sharding their depth dim on
        # 'model' would be a nonsense layout.
        if any(p.endswith(s) for s in _HEAD) and len(shp) == 3 and ok(1):
            return P(None, model_axis, None)
        if any(p.endswith(s) for s in _HEAD_OUT) and len(shp) == 3 and ok(0):
            return P(model_axis, None, None)
        # dim-0 rules share one spec: row-parallel dense, expert-stacked
        # MoE, vocab-sharded embedding
        if any(p.endswith(s) for s in _ROW + _EXPERT + _EMBED) and ok(0):
            return P(*((model_axis,) + (None,) * (len(shp) - 1)))
        if any(p.endswith(s) for s in _COLUMN) and ok(len(shp) - 1):
            return P(*((None,) * (len(shp) - 1) + (model_axis,)))
        return P()
    # 1D: bias of a column-parallel layer lives on the sharded output dim
    if any(p.endswith(s.replace("/kernel", "/bias")) for s in _COLUMN) and ok(0):
        return P(model_axis)
    return P()


def tp_shardings(params_or_shapes, mesh: Mesh, model_axis: str = "model"):
    """NamedSharding tree for a param tree (or its jax.eval_shape result);
    returns (shardings_tree, flat list of (keystr, PartitionSpec)). Specs
    are returned flat — PartitionSpec's pytree status varies across jax
    versions, so a spec TREE is a trap for tree_map callers.

    Pairing this with ``jax.jit(init_fn, out_shardings=...)`` materializes
    each device's shard directly at init: the full unsharded tree never
    exists on any single device (the point of TP at real scale)."""
    axis_size = int(mesh.shape[model_axis])
    flat, treedef = jax.tree_util.tree_flatten_with_path(params_or_shapes)
    shardings, specs = [], []
    for path, leaf in flat:
        spec = tp_spec_for(path, leaf, axis_size, model_axis)
        specs.append((jax.tree_util.keystr(path), spec))
        shardings.append(NamedSharding(mesh, spec))
    if axis_size > 1 and not any(model_axis in jax.tree.leaves(tuple(s))
                                 for _, s in specs):
        # semantics-safe (GSPMD replicates) but almost certainly NOT what a
        # caller putting a model axis on the mesh intended — say so loudly
        # instead of silently degrading TP to replication (ADVICE r2 #5)
        log.warning(
            "tp_shardings: mesh has a %d-way %r axis but NO param leaf "
            "matched the Megatron rules — all params replicated. The rules "
            "key on explicit layer names (q/k/v/o_proj, mlp_in/out, "
            "lm_head, embedding, *_experts; fallback dense_0/dense_1) — "
            "see parallel/tensor_parallel.py.",
            axis_size, model_axis)
    return jax.tree_util.tree_unflatten(treedef, shardings), specs


def shard_params(params, mesh: Mesh, model_axis: str = "model"):
    """device_put an ALREADY-materialized param tree per the Megatron rules;
    returns (sharded_params, flat list of (keystr, PartitionSpec)). For
    large models prefer tp_shardings + jit(init, out_shardings=...), which
    never materializes the unsharded tree."""
    shardings, specs = tp_shardings(params, mesh, model_axis)
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_s = jax.tree_util.tree_flatten(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))[0]
    placed = [jax.device_put(p, s) for p, s in zip(flat_p, flat_s)]
    return jax.tree_util.tree_unflatten(treedef, placed), specs


def num_sharded(params, model_axis: str = "model") -> int:
    """How many leaves actually carry the model axis (diagnostics/tests)."""
    count = 0
    for leaf in jax.tree.leaves(params):
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", None)
        if spec is not None and model_axis in jax.tree.leaves(tuple(spec)):
            count += 1
    return count
