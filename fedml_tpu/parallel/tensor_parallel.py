"""Tensor parallelism via pjit auto-sharding (Megatron-style specs).

The reference has no tensor/model parallelism anywhere (SURVEY.md §2.7);
this is capability-plus, done the idiomatic XLA way: pick a mesh, annotate
parameter shardings, and let the compiler insert the collectives
("How to Scale Your Model" recipe). Because pjit/GSPMD preserves program
semantics for ANY sharding, the specs below only steer layout/performance —
a wrong match degrades speed, never correctness (pinned by
tests/test_tensor_parallel.py's TP ≡ single-device oracle).

Spec rules (classic Megatron-LM layout for a transformer block):
  - MLP in  kernel [C, 4C]  -> column-parallel  P(None, model)
  - MLP out kernel [4C, C]  -> row-parallel     P(model, None)
  - attention qkv  [C, 3HD] -> column-parallel (contiguous columns — NOT
    head-aligned: the (3, H, D) reshape downstream makes GSPMD reshard
    around the attention core, so attention TP here saves weight memory
    and the projection FLOPs, not the full Megatron attention pattern)
  - attention out  [HD, C]  -> row-parallel
  - lm head        [C, V]   -> column-parallel
  - embedding      [V, C]   -> vocab-sharded    P(model, None)
  - norms / biases of row-parallel layers / scalars -> replicated
A dimension is only sharded when divisible by the mesh axis size;
otherwise the leaf falls back to replicated.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# path-suffix fragments matched against the flax keystr of each param leaf
# (flax numbers Dense modules per block: dense_0 = MLP-in / qkv, dense_1 =
# MLP-out / attention-out — the suffix covers both plain and attention
# variants). 'embedding' is anchored as a suffix so e.g. a hypothetical
# patch_embedding/kernel is not silently vocab-sharded.
_COLUMN = ("dense_0/kernel",)  # shard dim -1
_ROW = ("dense_1/kernel",)     # shard dim 0
_EMBED = ("embedding",)        # shard dim 0 (suffix-matched)
# expert-stacked MoE kernels [E, ...]: shard the expert dim — this IS
# expert parallelism (each device holds+runs E/n experts; the one-hot
# combine einsum becomes a psum over expert shards)
_EXPERT = ("experts",)         # shard dim 0 (suffix-matched)


def _norm_path(path) -> str:
    return jax.tree_util.keystr(path).replace("'", "").replace("][", "/") \
        .strip("[]").lower()


def tp_spec_for(path, leaf, axis_size: int, model_axis: str) -> P:
    """PartitionSpec for one param leaf under the Megatron rules."""
    p = _norm_path(path)
    shp = np.shape(leaf)
    if len(shp) < 1:
        return P()

    def ok(dim):
        return shp[dim] % axis_size == 0

    if len(shp) >= 2:
        # the suffix sets are mutually exclusive; dim-0 rules (row-parallel
        # dense, expert-stacked MoE, vocab-sharded embedding) share one spec
        if any(p.endswith(s) for s in _ROW + _EXPERT + _EMBED) and ok(0):
            return P(*((model_axis,) + (None,) * (len(shp) - 1)))
        if any(p.endswith(s) for s in _COLUMN) and ok(len(shp) - 1):
            return P(*((None,) * (len(shp) - 1) + (model_axis,)))
        return P()
    # 1D: bias of a column-parallel layer lives on the sharded output dim
    if any(p.endswith(s.replace("/kernel", "/bias")) for s in _COLUMN) and ok(0):
        return P(model_axis)
    return P()


def tp_shardings(params_or_shapes, mesh: Mesh, model_axis: str = "model"):
    """NamedSharding tree for a param tree (or its jax.eval_shape result);
    returns (shardings_tree, flat list of (keystr, PartitionSpec)). Specs
    are returned flat — PartitionSpec's pytree status varies across jax
    versions, so a spec TREE is a trap for tree_map callers.

    Pairing this with ``jax.jit(init_fn, out_shardings=...)`` materializes
    each device's shard directly at init: the full unsharded tree never
    exists on any single device (the point of TP at real scale)."""
    axis_size = int(mesh.shape[model_axis])
    flat, treedef = jax.tree_util.tree_flatten_with_path(params_or_shapes)
    shardings, specs = [], []
    for path, leaf in flat:
        spec = tp_spec_for(path, leaf, axis_size, model_axis)
        specs.append((jax.tree_util.keystr(path), spec))
        shardings.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, shardings), specs


def shard_params(params, mesh: Mesh, model_axis: str = "model"):
    """device_put an ALREADY-materialized param tree per the Megatron rules;
    returns (sharded_params, flat list of (keystr, PartitionSpec)). For
    large models prefer tp_shardings + jit(init, out_shardings=...), which
    never materializes the unsharded tree."""
    shardings, specs = tp_shardings(params, mesh, model_axis)
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_s = jax.tree_util.tree_flatten(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))[0]
    placed = [jax.device_put(p, s) for p, s in zip(flat_p, flat_s)]
    return jax.tree_util.tree_unflatten(treedef, placed), specs


def num_sharded(params, model_axis: str = "model") -> int:
    """How many leaves actually carry the model axis (diagnostics/tests)."""
    count = 0
    for leaf in jax.tree.leaves(params):
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", None)
        if spec is not None and model_axis in jax.tree.leaves(tuple(spec)):
            count += 1
    return count
