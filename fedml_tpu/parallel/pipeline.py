"""Pipeline parallelism: GPipe-style microbatch pipeline as scan + ppermute.

Absent from the reference (SURVEY.md §2.7 lists no tensor/pipeline/sequence
parallelism); this is the TPU-idiomatic formulation: the S pipeline stages
live one-per-device on a 'stage' mesh axis, microbatches flow stage-to-stage
over ICI via ``lax.ppermute`` inside a ``lax.scan`` of S+M-1 ticks, and the
BACKWARD pipeline needs no code at all — differentiating through the
scan+ppermute schedule gives the exact reverse schedule (ppermute's
transpose is the reverse permutation), so one ``jax.grad`` runs the full
GPipe fwd+bwd.

Semantics are exactly sequential-stage application (bubbles compute on
zeros and are masked out of the collected outputs), pinned by
tests/test_pipeline_parallel.py's pipeline ≡ sequential oracle — values AND
gradients.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def gpipe(stage_fn, stacked_params, x_mb, axis: str, mesh: Mesh,
          data_axis: str | None = None):
    """Run a homogeneous S-stage pipeline over M microbatches.

    stage_fn(params_one_stage, x) -> y with ``y.shape == x.shape``;
    stacked_params: pytree whose leaves are stacked [S, ...] (stage s uses
    leaf[s]); x_mb: [M, mb, ...] microbatched input — replicated when
    data_axis is None, batch-sharded over data_axis otherwise.
    Returns [M, mb, ...] outputs (psum-collected from the last stage),
    with the same replication/sharding as x_mb.
    S = mesh.shape[axis]; M is independent of S.

    data_axis: composes the pipeline with DATA parallelism on the same
    mesh — the microbatch dim (axis 1 of x_mb) stays sharded over it, so a
    ('data','stage') mesh runs data_axis-many independent pipelines, each
    on its own batch shard. Stage params are replicated over 'data'
    (in_specs names only the stage axis), the schedule is unchanged, and
    the output keeps the batch sharding.
    """
    S = int(mesh.shape[axis])
    for leaf in jax.tree.leaves(stacked_params):
        if np.shape(leaf)[0] != S:
            # without this check shard_map would hand each device
            # stage_dim/S stages and body() would keep only the first —
            # silently SKIPPING the rest (zero gradients, wrong loss)
            raise ValueError(
                f"stacked stage dim {np.shape(leaf)[0]} != mesh "
                f"'{axis}' size {S}: one pipeline stage per device required")

    def body(stacked_local, x):
        # stacked_local leaves: [1, ...] — this device's stage params
        p = jax.tree.map(lambda t: t[0], stacked_local)
        idx = lax.axis_index(axis)
        M = x.shape[0]
        pad = jnp.zeros((S - 1,) + x.shape[1:], x.dtype)
        # tick t: stage 0 consumes stream[t] (a real microbatch for t < M,
        # bubble zeros after)
        stream = lax.pcast(jnp.concatenate([x, pad], 0), axis, to="varying")
        zero_buf = lax.pcast(jnp.zeros_like(x[0]), axis, to="varying")
        outs0 = lax.pcast(jnp.zeros_like(x), axis, to="varying")

        def tick(carry, t):
            recv, outs = carry
            inp = jnp.where(idx == 0,
                            lax.dynamic_index_in_dim(stream, t, keepdims=False),
                            recv)
            out = stage_fn(p, inp)
            # ring shift: stage s's output becomes stage s+1's next input
            # (the wrap S-1 -> 0 carries bubble garbage; stage 0 never
            # reads recv, so it is harmless)
            recv = lax.ppermute(out, axis,
                                [(i, (i + 1) % S) for i in range(S)])
            # the LAST stage emits microbatch t-(S-1) at tick t
            pos = jnp.clip(t - (S - 1), 0, M - 1)
            take = (t >= S - 1) & (idx == S - 1)
            cur = lax.dynamic_index_in_dim(outs, pos, keepdims=False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(take, out, cur), pos, 0)
            return (recv, outs), None

        (_, outs), _ = lax.scan(tick, (zero_buf, outs0),
                                jnp.arange(S + M - 1))
        # only the last stage holds real outputs; zero the rest and psum so
        # every stage exits with the replicated result
        outs = jnp.where(idx == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(outs, axis)

    x_spec = P(None, data_axis) if data_axis is not None else P()
    return jax.shard_map(
        body, mesh=mesh, in_specs=(P(axis), x_spec), out_specs=x_spec,
    )(stacked_params, x_mb)


def microbatch(x, num_microbatches: int):
    """[N, ...] -> [M, N//M, ...] (N must divide evenly; pipeline
    microbatches split the BATCH, sequence length stays whole)."""
    n = x.shape[0]
    if n % num_microbatches:
        raise ValueError(f"batch {n} not divisible by M={num_microbatches}")
    return x.reshape((num_microbatches, n // num_microbatches) + x.shape[1:])


def unmicrobatch(y):
    return y.reshape((y.shape[0] * y.shape[1],) + y.shape[2:])
