"""FedCon — federated learning over client-condensed synthetic data.

Reference: fedml_api/standalone/feddf/condense_api.py and
fedcon_init_api.py (fork additions). Behavior being matched:
- each client condenses its LOCAL data into a small synthetic set using the
  current global model (client.condense inside _setup_condense,
  condense_api.py:164-183; fedcon_init_api.py runs it once at init,
  _init_condense :164);
- per round, after the FedAvg aggregate, the server trains the global model
  on the union of the sampled clients' synthetic sets
  (_train_condense_server, condense_api.py:315-329), either with plain CE
  ("ce") or with softened teacher labels ("soft",
  my_model_trainer_ensemble.train_wth_condense[_soft]).

TPU form: per-client condensation is the jitted gradient-matching loop from
utils/condense.py, conditioned on the current global NetState (host-driven
per client since local sets are ragged). Every synthetic set is padded to a
fixed [class_num * ipc] shape with a validity mask, so the sampled union has
one static shape across rounds — the server's condensed-training scan
compiles once and the sets stay on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.algorithms.feddf import kl_divergence
from fedml_tpu.core.local import NetState
from fedml_tpu.utils.condense import condense_dataset


class FedConAPI(FedAvgAPI):
    """FedAvg + per-client dataset condensation + condensed server training.

    ``condense_train_type``: 'ce' (hard labels) | 'soft' (KL toward the
    pre-update global model's softened predictions on the synthetic set).
    ``init_only=True`` = fedcon_init_api semantics (condense once up front,
    at the initial weights); False re-condenses every ``recondense_every``
    rounds at the CURRENT global weights (condense_api's per-setup flow).
    """

    def __init__(self, dataset, task, config: FedAvgConfig,
                 images_per_class: int = 2, condense_iters: int = 20,
                 condense_steps: int = 10, condense_lr: float = 0.01,
                 condense_train_type: str = "ce", temperature: float = 3.0,
                 init_only: bool = True, recondense_every: int = 5,
                 syn_lr: float = 0.1, **kwargs):
        if condense_steps < 1:
            raise ValueError("condense_steps must be >= 1")
        if condense_train_type not in ("ce", "soft"):
            raise ValueError(f"undefined condense train type {condense_train_type!r}"
                             " (condense_api.py:321-329 offers ce|soft)")
        super().__init__(dataset, task, config, **kwargs)
        self.images_per_class = images_per_class
        self.condense_iters = condense_iters
        self.condense_steps = condense_steps
        self.condense_train_type = condense_train_type
        self.temperature = temperature
        self.init_only = init_only
        self.recondense_every = recondense_every
        self.syn_lr = syn_lr
        self.last_condense_loss = float("nan")
        self._ctx = optax.sgd(condense_lr)
        # per client: (x_syn [C*ipc, ...], y_syn [C*ipc], valid [C*ipc]) on
        # device at a FIXED shape (absent classes -> zero rows, valid 0)
        self.syn_data: dict[int, tuple] = {}
        self._condense_round = -1
        self._train_syn = jax.jit(self._build_syn_train())

    # -------------------------------------------------------- condensation
    def setup_condense(self, round_idx: int = 0) -> None:
        """Condense every client's local set at the current global weights
        (client.condense parity, condense_api.py:170-178)."""
        data = self.data
        C, ipc = data.class_num, self.images_per_class
        for c, idx in data.train_idx_map.items():
            idx = np.asarray(idx)
            x_syn, y_syn, _ = condense_dataset(
                self.task, data.train_x[idx], data.train_y[idx],
                num_classes=C, images_per_class=ipc,
                iters=self.condense_iters, syn_lr=self.syn_lr,
                seed=self.cfg.seed + 31 * int(c) + round_idx,
                net=self.net,
            )
            # pad to the fixed [C*ipc] layout (condense_dataset skips absent
            # classes): one static union shape -> one _train_syn compile
            n = x_syn.shape[0]
            full = C * ipc
            xs = np.zeros((full,) + x_syn.shape[1:], np.float32)
            ys = np.zeros((full,), np.int64)
            valid = np.zeros((full,), np.float32)
            xs[:n], ys[:n], valid[:n] = x_syn, y_syn, 1.0
            self.syn_data[int(c)] = (jnp.asarray(xs), jnp.asarray(ys),
                                     jnp.asarray(valid))
        self._condense_round = round_idx

    # ------------------------------------------------------ condensed train
    def _build_syn_train(self):
        task = self.task
        tx = self._ctx
        T = self.temperature
        soft = self.condense_train_type == "soft"
        steps = self.condense_steps

        def run(net: NetState, teacher_net: NetState, x_syn, y_syn, valid):
            # teacher = PRE-update global model (captured before the round's
            # aggregate): a teacher equal to the student would make the KL
            # gradient exactly zero at step 0 and soft training a no-op
            teacher = jax.nn.softmax(
                task.predict(teacher_net.params, teacher_net.extra, x_syn) / T,
                axis=-1)
            opt = tx.init(net.params)
            key = jax.random.PRNGKey(0)  # eval-mode loss; key unused

            def step(carry, _):
                params, opt = carry

                def loss_fn(p):
                    if soft:
                        logits = task.predict(p, net.extra, x_syn)
                        return kl_divergence(logits, teacher, T, mask=valid)
                    # masked CE = the task's own loss definition
                    return task.loss(p, net.extra, x_syn, y_syn, valid,
                                     key, False)[0]

                l, g = jax.value_and_grad(loss_fn)(params)
                upd, opt = tx.update(g, opt, params)
                return (optax.apply_updates(params, upd), opt), l

            (params, _), losses = jax.lax.scan(
                step, (net.params, opt), None, length=steps)
            return NetState(params, net.extra), losses

        return run

    def train_condense_server(self, round_idx: int, teacher_net: NetState) -> float:
        """Train the global net on the sampled clients' synthetic union
        (_train_condense_server, condense_api.py:315-329). Fixed per-client
        shapes make the union [K * C * ipc] static across rounds."""
        ids = self._sampled_ids(round_idx)
        xs = jnp.concatenate([self.syn_data[int(c)][0] for c in ids])
        ys = jnp.concatenate([self.syn_data[int(c)][1] for c in ids])
        valid = jnp.concatenate([self.syn_data[int(c)][2] for c in ids])
        self.net, losses = self._train_syn(self.net, teacher_net, xs, ys, valid)
        return float(np.asarray(losses)[-1])

    # ------------------------------------------------------------- rounds
    def run_round(self, round_idx: int):
        if not self.syn_data or (
            not self.init_only
            and round_idx - self._condense_round >= self.recondense_every
        ):
            self.setup_condense(round_idx)
        teacher_net = self.net  # pre-update global (soft-label teacher)
        metrics = super().run_round(round_idx)
        self.last_condense_loss = self.train_condense_server(round_idx, teacher_net)
        return metrics

    def run_rounds(self, start_round: int, num_rounds: int):
        raise NotImplementedError(
            "FedCon interleaves host-driven condensation and condensed "
            "server training with the round program; the R-round scan block "
            "would silently skip both — use run_round")
