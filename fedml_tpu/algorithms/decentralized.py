"""Decentralized (server-less) FL: gossip averaging, DSGD and PushSum.

References:
- fedml_api/distributed/decentralized_framework/ — gossip skeleton: each
  worker trains then pushes its result to topology out-neighbors
  (decentralized_worker_manager.py:29-46).
- fedml_api/standalone/decentralized/ — online decentralized learning:
  ClientDSGD (client_dsgd.py:6-101) and ClientPushsum (client_pushsum.py:7-129)
  do per-iteration local gradient steps followed by topology-weighted neighbor
  mixing (PushSum adds weight scalars for directed graphs).

TPU re-design: one worker per mesh shard; params are NOT replicated — each
shard carries its own pytree. A gossip step is: local SGD step(s), then
mixing with `collectives.mix_with_topology` (all_gather + contraction over
ICI) using each worker's row of the mixing matrix W from core.topology.
PushSum carries (x_tilde, w_scalar) and mixes both, estimating params as
x_tilde / w_scalar — exact for row-stochastic directed W.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.collectives.ops import mix_with_topology
from fedml_tpu.core.local import NetState, Task
from fedml_tpu.core.topology import SymmetricTopologyManager, AsymmetricTopologyManager
from fedml_tpu.utils.tree import tree_weighted_mean


@dataclasses.dataclass(frozen=True)
class DecentralizedConfig:
    n_workers: int = 8
    iterations: int = 100
    lr: float = 0.1
    batch_size: int = 16
    neighbor_num: int = 2
    method: str = "dsgd"  # 'dsgd' | 'pushsum' | 'local' (no mixing baseline)
    seed: int = 0


class DecentralizedFLAPI:
    """Runs DSGD/PushSum over a 'workers' mesh axis (or vmapped on 1 device).

    Data: each worker owns a stream [iterations, batch_size, ...] — the
    online-learning setting of the reference (regret over a stream).
    """

    def __init__(self, task: Task, config: DecentralizedConfig,
                 worker_x: np.ndarray, worker_y: np.ndarray,
                 mesh: Mesh | None = None):
        # worker_x: [n_workers, iterations, bs, ...]
        self.task = task
        self.cfg = config
        self.mesh = mesh
        n = config.n_workers
        topo = (AsymmetricTopologyManager if config.method == "pushsum"
                else SymmetricTopologyManager)(n, config.neighbor_num, config.seed)
        self.W = topo.generate_topology().astype(np.float32)
        self.topology_manager = topo

        key = jax.random.PRNGKey(config.seed)
        net0 = task.init(key, jnp.asarray(worker_x[0, 0]))
        # every worker starts from the same init (reference does likewise)
        self.params = jax.tree.map(
            lambda v: jnp.broadcast_to(v[None], (n,) + v.shape), net0.params
        )
        self.extra = net0.extra
        self.worker_x = jnp.asarray(worker_x)
        self.worker_y = jnp.asarray(worker_y)
        self._step = self._build()

    def _build(self):
        cfg = self.cfg
        task = self.task
        lr = cfg.lr
        mix_mode = cfg.method

        def grad_step(params, extra, x, y, key):
            mask = jnp.ones(x.shape[0])
            def loss_fn(p):
                l, new_extra, metr = task.loss(p, extra, x, y, mask, key, True)
                return l, metr
            (l, metr), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
            return jax.tree.map(lambda p_, g_: p_ - lr * g_, params, g), l

        if self.mesh is not None:
            axis = self.mesh.axis_names[0]

            def shard_step(params, wrow, wscalar, x, y, key):
                # shapes: leading dim 1 (this worker's slice); drop it
                p = jax.tree.map(lambda v: v[0], params)
                p, l = grad_step(p, self.extra, x[0], y[0], key)
                if mix_mode == "dsgd":
                    p = mix_with_topology(p, wrow[0], axis)
                elif mix_mode == "pushsum":
                    ws = mix_with_topology(wscalar[0], wrow[0], axis)
                    p = mix_with_topology(
                        jax.tree.map(lambda v: v * wscalar[0], p), wrow[0], axis
                    )
                    p = jax.tree.map(lambda v: v / jnp.maximum(ws, 1e-8), p)
                    wscalar = ws[None]
                return (jax.tree.map(lambda v: v[None], p), wscalar,
                        jax.lax.psum(l, axis)[None] / self.cfg.n_workers)

            smapped = jax.shard_map(
                shard_step, mesh=self.mesh,
                in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P()),
                out_specs=(P(axis), P(axis), P(axis)),
            )

            @jax.jit
            def run(params, W, x_all, y_all, key):
                wscalar = jnp.ones((self.cfg.n_workers,))
                def body(carry, it):
                    params, wscalar, key = carry
                    key, sub = jax.random.split(key)
                    params, wscalar, l = smapped(
                        params, W, wscalar, x_all[:, it], y_all[:, it], sub
                    )
                    return (params, wscalar, key), l[0]
                (params, _, _), losses = jax.lax.scan(
                    body, (params, wscalar, key), jnp.arange(x_all.shape[1])
                )
                return params, losses

            return run

        # single-device: vmap workers, mix via matmul with W
        def vstep(params, W, x, y, key):
            keys = jax.random.split(key, self.cfg.n_workers)
            new_p, losses = jax.vmap(
                lambda p, xx, yy, k: grad_step(p, self.extra, xx, yy, k)
            )(params, x, y, keys)
            if mix_mode in ("dsgd", "pushsum"):
                # x_i <- sum_j W[i,j] x_j  (PushSum with row-stochastic W and
                # uniform start reduces to the same linear mixing here)
                new_p = jax.tree.map(
                    lambda v: jnp.tensordot(W, v, axes=([1], [0])), new_p
                )
            return new_p, jnp.mean(losses)

        @jax.jit
        def run(params, W, x_all, y_all, key):
            def body(carry, it):
                params, key = carry
                key, sub = jax.random.split(key)
                params, l = vstep(params, W, x_all[:, it], y_all[:, it], sub)
                return (params, key), l
            (params, _), losses = jax.lax.scan(
                body, (params, key), jnp.arange(x_all.shape[1])
            )
            return params, losses

        return run

    def train(self):
        key = jax.random.PRNGKey(self.cfg.seed + 1)
        W = jnp.asarray(self.W)
        params, losses = self._step(self.params, W, self.worker_x, self.worker_y, key)
        self.params = params
        self.loss_stream = np.asarray(losses)
        return self.loss_stream

    def regret(self) -> np.ndarray:
        """Average-regret trajectory R_t/t = (1/t) sum_{s<=t} loss_s — the
        online-learning metric the reference's decentralized clients track
        (ClientDSGD/ClientPushsum regret accounting, client_dsgd.py:6-101).
        Decreasing => the gossip stream is learning."""
        if not hasattr(self, "loss_stream"):
            raise ValueError("call train() first")
        t = np.arange(1, len(self.loss_stream) + 1)
        return np.cumsum(self.loss_stream) / t

    def consensus_distance(self) -> float:
        """Mean squared distance of workers' params from their average — the
        gossip convergence diagnostic."""
        mean = jax.tree.map(lambda v: jnp.mean(v, 0, keepdims=True), self.params)
        sq = jax.tree.map(lambda v, m: jnp.sum((v - m) ** 2), self.params, mean)
        return float(sum(jax.tree.leaves(sq)) / self.cfg.n_workers)

    def average_params(self):
        return tree_weighted_mean(self.params, jnp.ones((self.cfg.n_workers,)))
