"""FedOpt — FedAvg + a server-side optimizer (Adaptive Federated Optimization).

Reference: fedml_api/distributed/fedopt/FedOptAggregator.py:70-121 — after the
weighted average, set pseudo-gradient grad = w_old - w_avg on the global model
and take one server optimizer step (SGD-momentum / Adam picked by name through
OptRepo reflection, optrepo.py:25-39; flags --server_optimizer/--server_lr,
main_fedopt.py:54-60).

Here the pseudo-gradient step is an optax update fused into the round program.
"""

from __future__ import annotations

import optax

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core.local import NetState
from fedml_tpu.utils.tree import tree_sub


def make_server_optimizer(name: str, lr: float, momentum: float = 0.9):
    """Name->optax dispatch (the OptRepo analogue; optrepo.py:25-39)."""
    name = name.lower()
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum or None)
    if name == "adam":
        return optax.adam(lr)
    if name == "adagrad":
        return optax.adagrad(lr)
    if name == "yogi":
        # FedYogi (Adaptive Federated Optimization, Reddi et al.)
        return optax.yogi(lr)
    raise ValueError(f"unknown server optimizer {name}")


def make_fedopt_server_update(tx):
    """Server-update hook applying ``tx`` to the FedOpt pseudo-gradient —
    shared by FedOptAPI and any engine exposing the server_update hook
    (e.g. FedAvgSeqAPI for long-context FedOpt)."""

    def server_update(old: NetState, avg: NetState, opt_state):
        # pseudo-gradient points from the average back toward the old
        # weights (FedOptAggregator.set_model_global_grads:109-121)
        pseudo_grad = tree_sub(old.params, avg.params)
        updates, new_state = tx.update(pseudo_grad, opt_state, old.params)
        new_params = optax.apply_updates(old.params, updates)
        # non-gradient collections (BN stats) take the plain average
        return NetState(new_params, avg.extra), new_state

    return server_update


class FedOptAPI(FedAvgAPI):
    def __init__(
        self,
        dataset,
        task,
        config: FedAvgConfig,
        mesh=None,
        server_optimizer: str = "sgd",
        server_lr: float = 1.0,
        server_momentum: float = 0.9,
        **kwargs,
    ):
        tx = make_server_optimizer(server_optimizer, server_lr, server_momentum)
        server_update = make_fedopt_server_update(tx)

        super().__init__(
            dataset, task, config, mesh=mesh,
            server_update=server_update, server_opt_init=tx.init, **kwargs,
        )
