"""FedNAS — federated differentiable architecture search.

Reference: fedml_api/distributed/fednas/ — clients run DARTS bilevel search
(FedNASTrainer.search / local_search, FedNASTrainer.py:34-110: per train
batch, the Architect updates alphas on a batch from the client's HELD-OUT
split (architect.step_v2, architect.py:58-100), then the weights take an
SGD step on the train batch), the server averages weights AND alphas
separately (FedNASAggregator.__aggregate_weight :71, __aggregate_alpha :95)
and records the discovered genotype per round (:173).

Bilevel semantics parity:
  - alphas update on a genuinely held-out stream: the client's local test
    split when the dataset provides one (the reference's ``test_local``
    valid_queue), else a disjoint seeded half of the client's train data
    (the original DARTS train/val split) — never the batches the weights
    train on;
  - first-order Architect = step_v2: alpha-grad = lambda_valid * g_val +
    lambda_train * g_train, Adam(arch_lr, betas=(0.5, 0.999)) with L2
    arch_weight_decay (architect.py:22-25, defaults
    main_fednas.py:87-92);
  - optional second-order (``unrolled=True``): the reference approximates
    d/dα L_val(w - η∇_w L_train(w,α), α) with finite differences
    (architect.py:_backward_step_unrolled); in JAX the inner SGD step is a
    pure function, so we differentiate through it EXACTLY.

TPU re-design: alphas are just params of the DARTS supernet (models/darts),
so the FedAvg engine already vmaps/shard_maps the search; the (train, val)
streams ride the round batch as a pytree pair, and the whole bilevel
alternation is one lax.scan inside the jitted local update.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core.client_data import ClientBatch, FederatedData, pack_clients
from fedml_tpu.core.local import NetState
from fedml_tpu.core.tasks import aux_classification_task, classification_task
from fedml_tpu.models.darts import (DARTSNetwork, NetworkCIFAR, as_genotype,
                                    extract_genotype)


def _split_arch(params):
    arch = {k: v for k, v in params.items() if k.startswith("alphas")}
    weights = {k: v for k, v in params.items() if not k.startswith("alphas")}
    return weights, arch


def _held_out_split(data: FederatedData, seed: int, val_fraction: float):
    """(w_data, a_data): weight-train stream and held-out alpha stream.

    Clients with a local test split use it as the alpha stream (the
    reference's valid_queue = test_local); otherwise the client's train
    indices are split disjointly (seeded, per-client) like the original
    DARTS search."""
    if data.test_idx_map:
        a_map = {c: np.asarray(data.test_idx_map.get(c, np.empty(0, np.int64)),
                               np.int64)
                 for c in data.train_idx_map}
        a_data = dataclasses.replace(
            data, train_x=data.test_x, train_y=data.test_y,
            train_idx_map=a_map)
        return data, a_data

    w_map, a_map = {}, {}
    for c, idx in data.train_idx_map.items():
        idx = np.asarray(idx, np.int64)
        perm = np.random.RandomState((seed * 1_000_003 + int(c)) & 0x7FFFFFFF
                                     ).permutation(len(idx))
        n_val = max(1, int(len(idx) * val_fraction)) if len(idx) > 1 else 0
        a_map[c] = idx[perm[:n_val]]
        w_map[c] = idx[perm[n_val:]]
    return (dataclasses.replace(data, train_idx_map=w_map),
            dataclasses.replace(data, train_idx_map=a_map))


class FedNASAPI(FedAvgAPI):
    """Search phase: FedAvg over the supernet with the reference's bilevel
    local search. After search, ``genotype()`` extracts the discovered
    normal+reduce cells."""

    def __init__(self, dataset, config: FedAvgConfig, mesh=None,
                 arch_lr: float = 3e-4, arch_wd: float = 1e-3,
                 lambda_train: float = 1.0, lambda_valid: float = 1.0,
                 unrolled: bool = False, val_fraction: float = 0.5,
                 layers: int = 4, init_filters: int = 16, steps: int = 4,
                 multiplier: int = 4, nas_method: str = "darts",
                 tau: float = 10.0, **kwargs):
        module = DARTSNetwork(num_classes=dataset.class_num, layers=layers,
                              steps=steps, multiplier=multiplier,
                              init_filters=init_filters,
                              nas_method=nas_method, tau=tau)
        task = classification_task(module)
        self.arch_lr, self.arch_wd = arch_lr, arch_wd
        self.steps, self.multiplier = steps, multiplier
        if kwargs.get("device_data"):
            raise ValueError("FedNASAPI packs (train, val) stream pairs; the "
                             "device-resident index plane is not supported")

        w_data, a_data = _held_out_split(dataset, config.seed, val_fraction)
        super().__init__(w_data, task, config, mesh=mesh, **kwargs)
        self.data_a = a_data
        a_counts = [len(v) for v in a_data.train_idx_map.values()]
        b_needed = max(1, int(np.ceil(max(a_counts) / config.batch_size)))
        self.num_batches_a = min(config.max_batches or b_needed, b_needed)

        w_tx = optax.sgd(config.lr, momentum=config.momentum or 0.9)
        if config.wd:
            w_tx = optax.chain(optax.add_decayed_weights(config.wd), w_tx)
        # torch Adam's weight_decay is L2-into-the-grad (not AdamW), so the
        # decay feeds the moment estimates: decay first, then adam
        a_tx = optax.chain(optax.add_decayed_weights(arch_wd),
                           optax.adam(arch_lr, b1=0.5, b2=0.999))
        t = self.task
        epochs = config.epochs
        eta = config.lr  # unrolled inner-step size (reference eta = network lr)

        def local_update(rng, global_net: NetState, x, y, mask):
            xw, xa = x
            yw, ya = y
            mw, ma = mask
            Ba = xa.shape[0]
            w0, a0 = _split_arch(global_net.params)
            w_opt, a_opt = w_tx.init(w0), a_tx.init(a0)

            def arch_grad(w, a, xb, yb, mb, xv, yv, mv, key):
                def loss_a(a_, x_, y_, m_):
                    l, _, _ = t.loss({**w, **a_}, {}, x_, y_, m_, key, True)
                    return l

                if unrolled:
                    def train_loss(w_, a_):
                        l, _, _ = t.loss({**w_, **a_}, {}, xb, yb, mb, key, True)
                        return l

                    def val_after_inner(a_):
                        gw = jax.grad(train_loss)(w, a_)
                        w_un = jax.tree.map(lambda p, g: p - eta * g, w, gw)
                        l, _, _ = t.loss({**w_un, **a_}, {}, xv, yv, mv,
                                         key, True)
                        return l

                    return jax.grad(val_after_inner)(a)
                g_val = jax.grad(loss_a)(a, xv, yv, mv)
                g_tr = jax.grad(loss_a)(a, xb, yb, mb)
                return jax.tree.map(
                    lambda gv, gt: lambda_valid * gv + lambda_train * gt,
                    g_val, g_tr)

            def batch_step(carry, inp):
                params, w_opt, a_opt, rng, i = carry
                xb, yb, mb = inp
                rng, k_a, k_w = jax.random.split(rng, 3)
                w, a = _split_arch(params)

                # ---- Architect step FIRST (FedNASTrainer.local_search:
                # architect.step_v2 precedes the weight step), on the cycled
                # held-out batch i % Ba
                j = i % Ba
                xv, yv, mv = xa[j], ya[j], ma[j]
                ga = arch_grad(w, a, xb, yb, mb, xv, yv, mv, k_a)
                has_a = (jnp.sum(mv) > 0) & (jnp.sum(mb) > 0)
                ua, a_opt_n = a_tx.update(ga, a_opt, a)
                a = jax.tree.map(lambda p, u: jnp.where(has_a, p + u, p), a, ua)
                a_opt = jax.tree.map(lambda n_, o: jnp.where(has_a, n_, o),
                                     a_opt_n, a_opt)

                # ---- weight step on the train batch
                def loss_w(w_):
                    l, _, metr = t.loss({**w_, **a}, {}, xb, yb, mb, k_w, True)
                    return l, metr

                (_, metr), gw = jax.value_and_grad(loss_w, has_aux=True)(w)
                has_w = jnp.sum(mb) > 0
                uw, w_opt_n = w_tx.update(gw, w_opt, w)
                w = jax.tree.map(lambda p, u: jnp.where(has_w, p + u, p), w, uw)
                w_opt = jax.tree.map(lambda n_, o: jnp.where(has_w, n_, o),
                                     w_opt_n, w_opt)
                return ({**w, **a}, w_opt, a_opt, rng, i + 1), metr

            def epoch(carry, _):
                carry, metrs = jax.lax.scan(batch_step, carry, (xw, yw, mw))
                return carry, metrs

            (params, _, _, _, _), metrs = jax.lax.scan(
                epoch, (global_net.params, w_opt, a_opt, rng, 0), None,
                length=epochs)
            metrics = {k: jnp.sum(metrs[k]) for k in ("loss_sum", "correct", "count")}
            return NetState(params, global_net.extra), metrics

        self.local_update = local_update
        self.round_fn = self._build_round_fn()
        self.genotype_history: list = []

    # ------------------------------------------------------------------ data
    def _pack_pair(self, ids, round_idx: int) -> ClientBatch:
        """Pack BOTH streams as a pytree pair riding one ClientBatch: leaf
        arrays [K, Bw, ...] for the weight stream, [K, Ba, ...] for the
        held-out alpha stream. vmap/shard_map treat the pair like any other
        pytree, so the engine's round program is unchanged. Also the packer
        for the cross-process runtime (distributed/fednas.py), which packs
        a single client id — same seeds, same budgets, so the two runtimes
        stay batch-identical."""
        cfg = self.cfg

        def pack(data, n_batches, seed_off):
            cb = pack_clients(data, ids, cfg.batch_size, max_batches=n_batches,
                              seed=cfg.seed + seed_off, round_idx=round_idx)
            if cb.num_batches < n_batches:
                pad = n_batches - cb.num_batches
                z = lambda arr: np.concatenate(
                    [arr, np.zeros((arr.shape[0], pad) + arr.shape[2:],
                                   arr.dtype)], 1)
                cb = ClientBatch(x=z(cb.x), y=z(cb.y), mask=z(cb.mask),
                                 num_samples=cb.num_samples)
            return cb

        cb_w = pack(self.data, self.num_batches, 0)
        cb_a = pack(self.data_a, self.num_batches_a, 1)
        return ClientBatch(x=(cb_w.x, cb_a.x), y=(cb_w.y, cb_a.y),
                           mask=(cb_w.mask, cb_a.mask),
                           num_samples=cb_w.num_samples)

    def _pack_round(self, round_idx: int, device_data: bool | None = None):
        # device_data accepted for base-signature parity (the NAS pack is
        # always the host-packed pair — there is no index plane here)
        merged = self._pack_pair(self._sampled_ids(round_idx), round_idx)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = NamedSharding(self.mesh, P(self.mesh.axis_names[0]))
            merged = jax.tree.map(lambda v: jax.device_put(v, sh), merged)
        return merged

    def run_round(self, round_idx: int):
        m = super().run_round(round_idx)
        # record the global architecture each round (FedNASAggregator.py:173)
        self.genotype_history.append(self.genotype())
        return m

    def genotype(self):
        return extract_genotype(self.net.params, steps=self.steps,
                                multiplier=self.multiplier)


class FedNASTrainAPI(FedAvgAPI):
    """Train stage (``--stage train``): federated training of the DERIVED
    fixed-genotype network — the half of the reference's NAS story the
    search stage hands off to (main_fednas.py:44-45, 188-193: --stage
    train builds NetworkCIFAR from a genotype and runs the same federated
    loop with plain local SGD; FedNASTrainer.train/local_train
    FedNASTrainer.py:129-183 adds the auxiliary-head loss term).

    ``genotype`` accepts a registry name ("FedNAS_V1", the reference's
    train-stage default at main_fednas.py:191), a search result
    (FedNASAPI.genotype() dict), or a json file path — so
    search -> extract -> train composes in one run (the
    CI-script-fednas.sh:16-23 two-stage flow)."""

    def __init__(self, dataset, config: FedAvgConfig, mesh=None,
                 genotype="FedNAS_V1", layers: int = 8,
                 init_filters: int = 16, auxiliary: bool = False,
                 auxiliary_weight: float = 0.4,
                 drop_path_prob: float = 0.5, **kwargs):
        module = NetworkCIFAR(genotype=as_genotype(genotype),
                              num_classes=dataset.class_num, layers=layers,
                              init_filters=init_filters, auxiliary=auxiliary,
                              drop_path_prob=drop_path_prob)
        task = aux_classification_task(module, aux_weight=auxiliary_weight)
        super().__init__(dataset, task, config, mesh=mesh, **kwargs)
