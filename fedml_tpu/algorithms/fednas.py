"""FedNAS — federated differentiable architecture search.

Reference: fedml_api/distributed/fednas/ — clients run DARTS bilevel search
(FedNASTrainer.search, FedNASTrainer.py:34-50: update alphas on a val split
via the Architect :28-31, then weights on train), the server averages weights
AND alphas separately (FedNASAggregator.__aggregate_weight :71,
__aggregate_alpha :95) and records the discovered genotype per round (:173).

TPU re-design: alphas are just params of the DARTS supernet (models/darts),
so the FedAvg engine already vmaps/shard_maps the search. The bilevel step is
the first-order DARTS approximation (the reference defaults to
--arch_search_method first-order as well): alternate alpha-steps on the
client's validation half and weight-steps on the train half, all inside the
jitted local update.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core.local import LocalSpec, NetState
from fedml_tpu.core.tasks import classification_task
from fedml_tpu.models.darts import DARTSNetwork, extract_genotype


def _split_arch(params):
    arch = {k: v for k, v in params.items() if k.startswith("alphas")}
    weights = {k: v for k, v in params.items() if not k.startswith("alphas")}
    return weights, arch


class FedNASAPI(FedAvgAPI):
    """Search phase: FedAvg over the supernet with alternating w/alpha local
    steps. After search, ``genotype()`` extracts the discovered cell."""

    def __init__(self, dataset, config: FedAvgConfig, mesh=None,
                 arch_lr: float = 3e-3, layers: int = 4, init_filters: int = 16,
                 **kwargs):
        module = DARTSNetwork(num_classes=dataset.class_num, layers=layers,
                              init_filters=init_filters)
        task = classification_task(module)
        self.arch_lr = arch_lr
        super().__init__(dataset, task, config, mesh=mesh, **kwargs)

        # Replace the plain local update with the bilevel variant:
        # even batches update weights (SGD lr), odd batches update alphas
        # (Adam arch_lr) on held-out-like data — the first-order DARTS
        # alternation, expressed as a masked two-optimizer step so control
        # flow stays static.
        w_tx = optax.sgd(config.lr, momentum=0.9)
        a_tx = optax.adam(arch_lr)
        t = self.task
        epochs = config.epochs

        def local_update(rng, global_net: NetState, x, y, mask):
            params = global_net.params
            w0, a0 = _split_arch(params)
            w_opt = w_tx.init(w0)
            a_opt = a_tx.init(a0)

            def batch_step(carry, inp):
                params, w_opt, a_opt, rng, idx = carry
                xb, yb, mb = inp
                rng, sub = jax.random.split(rng)

                def loss_fn(p):
                    l, _, metr = t.loss(p, {}, xb, yb, mb, sub, True)
                    return l, metr

                (l, metr), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
                gw, ga = _split_arch(g)
                w, a = _split_arch(params)
                is_w_step = (idx % 2) == 0
                uw, w_opt_n = w_tx.update(gw, w_opt, w)
                ua, a_opt_n = a_tx.update(ga, a_opt, a)
                has = jnp.sum(mb) > 0
                w_new = jax.tree.map(
                    lambda p_, u: jnp.where(has & is_w_step, p_ + u, p_), w, uw)
                a_new = jax.tree.map(
                    lambda p_, u: jnp.where(has & (~is_w_step), p_ + u, p_), a, ua)
                w_opt = jax.tree.map(
                    lambda n_, o: jnp.where(has & is_w_step, n_, o), w_opt_n, w_opt)
                a_opt = jax.tree.map(
                    lambda n_, o: jnp.where(has & (~is_w_step), n_, o), a_opt_n, a_opt)
                params = {**w_new, **a_new}
                return (params, w_opt, a_opt, rng, idx + 1), metr

            def epoch(carry, _):
                params, w_opt, a_opt, rng, idx = carry
                carry, metrs = jax.lax.scan(
                    batch_step, (params, w_opt, a_opt, rng, idx), (x, y, mask))
                return carry, metrs

            (params, _, _, _, _), metrs = jax.lax.scan(
                epoch, (params, w_opt, a_opt, rng, 0), None, length=epochs)
            metrics = {k: jnp.sum(metrs[k]) for k in ("loss_sum", "correct", "count")}
            return NetState(params, global_net.extra), metrics

        self.local_update = local_update
        self.round_fn = self._build_round_fn()
        self.genotype_history: list = []

    def run_round(self, round_idx: int):
        m = super().run_round(round_idx)
        # record the global architecture each round (FedNASAggregator.py:173)
        self.genotype_history.append(self.genotype())
        return m

    def genotype(self):
        return extract_genotype(self.net.params)
