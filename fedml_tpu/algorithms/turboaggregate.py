"""TurboAggregate — pairwise-masked secure aggregation, standalone engine.

Reference: fedml_api/distributed/turboaggregate/ (Lagrange-coded MPC over a
finite field). The TPU form now shares its whole masking layer with the
cross-process tier (core/secure_agg.py, docs/ROBUSTNESS.md §Secure
aggregation): each simulated client quantizes its weighted update into
GF(2^31-1), adds cancelling pairwise masks (jitted counter-PRG over
sha256-derived DH pair seeds) plus a Shamir-shared self-mask, and the
"server" half of the loop folds masked vectors mod p and decodes only the
SUM after reconstructing the self-mask seeds from t+1 shares. Additive
homomorphism makes the result equal plain FedAvg up to quantization
(tested: <1e-3 relative error); no per-client cleartext update ever
exists on the aggregation path.

This engine runs the full-cohort protocol (the simulated cohort cannot
drop mid-`run_round`); dropout recovery — reveal frames, elastic partial
decode, shed-and-rebroadcast — lives on the cross-process tier
(distributed/turboaggregate.py), where clients actually fail.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core import secure_agg as sa
from fedml_tpu.core.local import NetState
from fedml_tpu.utils.tree import tree_unvectorize, tree_vectorize


class TurboAggregateAPI(FedAvgAPI):
    """FedAvg whose aggregation path goes through masked field vectors.

    The engine's device-side weighted mean is replaced by a host-driven
    secure sum: each client's weighted params vector is field-encoded and
    masked (core/secure_agg.py); only the folded masked sum is decoded.
    """

    def __init__(self, dataset, task, config: FedAvgConfig,
                 threshold_t: int | None = None,
                 quant_scale: float = 2**16,
                 secagg_max_abs: float = 4.0, n_shares=None, **kwargs):
        if config.client_num_per_round > 32:
            raise ValueError("TurboAggregate secure path is for cross-silo scale")
        # n_shares kept for API compatibility; self-mask seeds are Shamir-
        # shared across the whole cohort now (one share per slot).
        # threshold_t=None adapts to the cohort (min(2, K-1)); an explicit
        # out-of-range t stays a loud error.
        if threshold_t is None:
            threshold_t = sa.default_threshold_t(config.client_num_per_round)
        self.quant_scale = quant_scale
        # capacity guard at construction (collectives/finite_field.py):
        # cohort * 2 * quant_scale * max_abs must stay inside GF(p)
        self.secagg = sa.SecAggConfig(
            cohort=config.client_num_per_round, threshold_t=threshold_t,
            quant_scale=quant_scale, max_abs=secagg_max_abs)
        super().__init__(dataset, task, config, **kwargs)
        # rebuild round fn: we need the per-client nets, not the engine mean
        self._local_batch = jax.jit(self._build_local_batch())

    def _build_local_batch(self):
        local_update = self.local_update

        def run(rng, net, x, y, mask):
            keys = jax.random.split(rng, x.shape[0])
            nets, metrics = jax.vmap(local_update, in_axes=(0, None, 0, 0, 0))(
                keys, net, x, y, mask
            )
            return nets, {k: jnp.sum(v) for k, v in metrics.items()}

        return run

    def run_round(self, round_idx: int):
        cb = self._pack_round_host(round_idx)
        self.rng, rk = jax.random.split(self.rng)
        nets, metrics = self._local_batch(rk, self.net,
                                          jnp.asarray(cb.x), jnp.asarray(cb.y),
                                          jnp.asarray(cb.mask))
        K = cb.x.shape[0]
        nsamp = np.asarray(cb.num_samples, np.float64)
        wts = nsamp / max(nsamp.sum(), 1e-12)

        # --- masked secure aggregation of params (core/secure_agg.py) ---
        # each slot: weighted vector -> field encode -> self + pairwise
        # masks; the fold is one streaming add mod p per slot
        template = self.net.params
        acc = None
        for k in range(K):
            pk = jax.tree.map(lambda v, i=k: v[i], nets.params)
            vec = np.asarray(tree_vectorize(pk), np.float64)
            masked = sa.mask_update(vec, float(wts[k]), k, self.cfg.seed,
                                    round_idx, self.secagg)
            acc = sa.fold_masked(acc, masked, self.secagg.p)
        # full cohort: reconstruct every self-mask seed from the t+1-of-K
        # Shamir shares and strip; no pairwise mask survives the full sum
        slots = list(range(K))
        self_seeds = {
            i: sa.recover_self_seed(
                slots,
                sa.self_mask_shares(self.cfg.seed, round_idx, i,
                                    self.secagg)[slots],
                self.secagg.threshold_t, self.secagg.p)
            for i in slots}
        vec_sum = sa.unmask_sum(acc, slots, [], self_seeds, {}, self.secagg)
        new_params = tree_unvectorize(
            jnp.asarray(np.asarray(vec_sum, np.float32)), template)

        # extras (BN stats) take the plain weighted mean (not secret)
        from fedml_tpu.utils.tree import tree_weighted_mean

        new_extra = tree_weighted_mean(nets.extra, jnp.asarray(nsamp, jnp.float32))
        avg = NetState(new_params, new_extra)
        new_net, self.server_opt_state = self.server_update(
            self.net, avg, self.server_opt_state
        )
        self.net = new_net
        return metrics
