"""TurboAggregate — secure aggregation via coded shares over GF(p).

Reference: fedml_api/distributed/turboaggregate/ — Lagrange-coded MPC over a
finite field (mpc_function.py: modular_inv :4-18, gen_Lagrange_coeffs :38-59,
BGW_encoding :62-76) arranged in a decentralized ring; TA_Aggregator.aggregate
(TA_Aggregator.py:56+) reconstructs the sum without seeing any single update.

TPU form: clients quantize their updates into GF(2^31-1)
(collectives.finite_field.field_encode), Shamir-encode into n shares; share j
of every client is summed (this is where, on hardware, an int psum over ICI
runs per share index — no party ever holds another's cleartext update);
the aggregate is reconstructed from t+1 summed shares by Lagrange
interpolation at 0 and dequantized. Additive homomorphism makes the result
equal plain FedAvg up to quantization (tested: <1e-3 relative error).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.collectives import finite_field as ff
from fedml_tpu.core.local import NetState
from fedml_tpu.utils.tree import tree_unvectorize, tree_vectorize


class TurboAggregateAPI(FedAvgAPI):
    """FedAvg whose aggregation path goes through coded shares.

    The engine's device-side weighted mean is replaced by a host-driven
    secure-sum: each client's weighted params vector is field-encoded and
    Shamir-shared; only summed shares are decoded.
    """

    def __init__(self, dataset, task, config: FedAvgConfig,
                 n_shares: int = 5, threshold_t: int = 2,
                 quant_scale: float = 2**16, **kwargs):
        if config.client_num_per_round > 32:
            raise ValueError("TurboAggregate secure path is for cross-silo scale")
        self.n_shares = n_shares
        self.threshold_t = threshold_t
        self.quant_scale = quant_scale
        super().__init__(dataset, task, config, **kwargs)
        # rebuild round fn: we need the per-client nets, not the engine mean
        self._local_batch = jax.jit(self._build_local_batch())

    def _build_local_batch(self):
        local_update = self.local_update

        def run(rng, net, x, y, mask):
            keys = jax.random.split(rng, x.shape[0])
            nets, metrics = jax.vmap(local_update, in_axes=(0, None, 0, 0, 0))(
                keys, net, x, y, mask
            )
            return nets, {k: jnp.sum(v) for k, v in metrics.items()}

        return run

    def run_round(self, round_idx: int):
        cb = self._pack_round_host(round_idx)
        self.rng, rk, sk = jax.random.split(self.rng, 3)
        nets, metrics = self._local_batch(rk, self.net,
                                          jnp.asarray(cb.x), jnp.asarray(cb.y),
                                          jnp.asarray(cb.mask))
        K = cb.x.shape[0]
        nsamp = np.asarray(cb.num_samples, np.float64)
        wts = nsamp / max(nsamp.sum(), 1e-12)

        # --- secure aggregation of params ---
        # each client: weighted vector -> field encode -> Shamir shares
        template = self.net.params
        summed_shares = None
        for k in range(K):
            pk = jax.tree.map(lambda v, i=k: v[i], nets.params)
            vec = tree_vectorize(pk) * wts[k]
            z = ff.field_encode(vec, self.quant_scale)
            shares = ff.shamir_encode(z, jax.random.fold_in(sk, k),
                                      self.n_shares, self.threshold_t)
            sh = np.asarray(shares, np.int64)
            summed_shares = sh if summed_shares is None else (
                (summed_shares + sh) % ff.P_DEFAULT
            )
        alphas = np.arange(1, self.n_shares + 1, dtype=np.int64)
        z_sum = ff.shamir_decode(jnp.asarray(summed_shares), jnp.asarray(alphas),
                                 self.threshold_t)
        vec_sum = np.asarray(ff.field_decode(z_sum, self.quant_scale), np.float32)
        new_params = tree_unvectorize(jnp.asarray(vec_sum), template)

        # extras (BN stats) take the plain weighted mean (not secret)
        from fedml_tpu.utils.tree import tree_weighted_mean

        new_extra = tree_weighted_mean(nets.extra, jnp.asarray(nsamp, jnp.float32))
        avg = NetState(new_params, new_extra)
        new_net, self.server_opt_state = self.server_update(
            self.net, avg, self.server_opt_state
        )
        self.net = new_net
        return metrics
