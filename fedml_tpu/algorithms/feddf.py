"""FedDF — ensemble distillation after averaging (fork's flagship addition).

Reference: fedml_api/standalone/feddf/feddf_api.py — per round: FedAvg-style
local training + weighted average (train :325-473), then server-side ensemble
distillation on unlabeled/public data (_ensemble_distillation :567): the
teacher signal is the averaged softmax of all client models' logits on a
public batch; the student (initialized at the weighted average) takes KL
steps toward it. FedDF-hard (feddf_hard_api.py:404) uses argmax hard labels
+ cross-entropy instead of soft KL.

TPU form: the K client nets from the round are already a stacked pytree on
device; the ensemble teacher is one vmapped forward (K models x public batch
= one batched matmul on the MXU) and the distillation loop is a lax.scan —
the whole post-aggregation phase is a second jitted program, no state leaves
the device between phases.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core.client_data import batch_global
from fedml_tpu.core.local import NetState
from fedml_tpu.utils.tree import tree_weighted_mean


def kl_divergence(student_logits, teacher_probs, temperature: float = 1.0,
                  mask=None):
    """KL(teacher || student) with temperature, averaged over batch (the
    reference's utils.KL_Loss, fedml_api/distributed/fedgkt/utils.py).
    With ``mask`` the mean runs over masked samples only (padded rows must
    not train — FedGKT's blocks are padded to a static batch budget)."""
    s = jax.nn.log_softmax(student_logits / temperature, axis=-1)
    per = -jnp.sum(teacher_probs * s, axis=-1) * (temperature ** 2)
    if mask is None:
        return jnp.mean(per)
    return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class FedDFAPI(FedAvgAPI):
    def __init__(
        self,
        dataset,
        task,
        config: FedAvgConfig,
        public_x: np.ndarray | None = None,
        distill_steps: int = 20,
        distill_lr: float = 0.001,
        distill_batch_size: int = 64,
        temperature: float = 3.0,
        hard_label: bool = False,  # FedDF-hard variant
        mesh=None,
        **kwargs,
    ):
        super().__init__(dataset, task, config, mesh=mesh, **kwargs)
        if public_x is None:
            # reference uses an unlabeled public set (e.g. CIFAR-100 for
            # CIFAR-10 training); default to held-out test inputs
            public_x = dataset.test_x
        n = min(len(public_x), distill_steps * distill_batch_size)
        self.public_x = np.asarray(public_x[:n], np.float32)
        self.distill_steps = distill_steps
        self.distill_lr = distill_lr
        self.distill_batch_size = distill_batch_size
        self.temperature = temperature
        self.hard_label = hard_label
        self._distill = jax.jit(self._build_distill())
        # keep per-client nets: rebuild a round fn that returns them
        self._local_batch = jax.jit(self._build_local_batch())

    def _build_local_batch(self):
        local_update = self.local_update

        def run(rng, net, x, y, mask):
            keys = jax.random.split(rng, x.shape[0])
            nets, metrics = jax.vmap(local_update, in_axes=(0, None, 0, 0, 0))(
                keys, net, x, y, mask
            )
            return nets, {k: jnp.sum(v) for k, v in metrics.items()}

        return run

    def _build_distill(self):
        task = self.task
        T = self.temperature
        tx = optax.adam(self.distill_lr)
        hard = self.hard_label

        def distill(student: NetState, client_nets, public_batches):
            # public_batches: [S, bs, ...]
            opt_state = tx.init(student.params)

            def step(carry, xb):
                params, opt_state = carry
                # ensemble teacher: mean softmax over the K client models
                t_logits = jax.vmap(
                    lambda p, e: task.predict(p, e, xb)
                )(client_nets.params, client_nets.extra)  # [K, bs, C]
                t_probs = jnp.mean(jax.nn.softmax(t_logits / T, axis=-1), axis=0)

                def loss_fn(p):
                    s_logits = task.predict(p, student.extra, xb)
                    if hard:
                        yhard = jnp.argmax(t_probs, axis=-1)
                        return jnp.mean(
                            optax.softmax_cross_entropy_with_integer_labels(
                                s_logits, yhard)
                        )
                    return kl_divergence(s_logits, t_probs, T)

                l, g = jax.value_and_grad(loss_fn)(params)
                upd, opt_state = tx.update(g, opt_state, params)
                return (optax.apply_updates(params, upd), opt_state), l

            (params, _), losses = jax.lax.scan(
                step, (student.params, opt_state), public_batches
            )
            return NetState(params, student.extra), losses

        return distill

    def _public_batches(self, round_idx: int):
        rng = np.random.RandomState(self.cfg.seed * 977 + round_idx)
        idx = rng.permutation(len(self.public_x))
        bs = self.distill_batch_size
        S = min(self.distill_steps, len(idx) // bs)
        sel = idx[: S * bs].reshape(S, bs)
        return jnp.asarray(self.public_x[sel])

    def run_round(self, round_idx: int):
        cb = self._pack_round_host(round_idx)
        self.rng, rk = jax.random.split(self.rng)
        nets, metrics = self._local_batch(
            rk, self.net, jnp.asarray(cb.x), jnp.asarray(cb.y), jnp.asarray(cb.mask)
        )
        avg = tree_weighted_mean(nets, jnp.asarray(cb.num_samples))
        student, d_losses = self._distill(avg, nets, self._public_batches(round_idx))
        self.net = student
        metrics = dict(metrics)
        metrics["distill_loss"] = d_losses[-1]
        return metrics
