"""FedDF — ensemble distillation after averaging (fork's flagship addition).

Reference: fedml_api/standalone/feddf/feddf_api.py — per round: FedAvg-style
local training + weighted average (train :325-473), then server-side ensemble
distillation on unlabeled/public data (_ensemble_distillation :567): the
teacher signal is the averaged softmax of all client models' logits on a
public batch; the student (initialized at the weighted average) takes KL
steps toward it. FedDF-hard (feddf_hard_api.py:404) uses argmax hard labels
+ cross-entropy instead of soft KL.

TPU form: the K client nets from the round are already a stacked pytree on
device; the ensemble teacher is one vmapped forward (K models x public batch
= one batched matmul on the MXU) and the distillation loop is a lax.scan —
the whole post-aggregation phase is a second jitted program, no state leaves
the device between phases.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core.local import NetState
from fedml_tpu.utils.tree import tree_weighted_mean


def kl_divergence(student_logits, teacher_probs, temperature: float = 1.0,
                  mask=None):
    """KL(teacher || student) with temperature, averaged over batch (the
    reference's utils.KL_Loss, fedml_api/distributed/fedgkt/utils.py).
    With ``mask`` the mean runs over masked samples only (padded rows must
    not train — FedGKT's blocks are padded to a static batch budget)."""
    s = jax.nn.log_softmax(student_logits / temperature, axis=-1)
    per = -jnp.sum(teacher_probs * s, axis=-1) * (temperature ** 2)
    if mask is None:
        return jnp.mean(per)
    return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class FedDFAPI(FedAvgAPI):
    def __init__(
        self,
        dataset,
        task,
        config: FedAvgConfig,
        public_x: np.ndarray | None = None,
        distill_steps: int = 20,
        distill_lr: float = 0.001,
        distill_batch_size: int = 64,
        temperature: float = 3.0,
        hard_label: bool = False,  # FedDF-hard variant
        hard_sample_ratio: float = 1.0,  # random public subset (--hard_sample)
        fedmix_server: bool = False,  # distill on per-client batch-mean images
        val_fraction: float = 0.0,    # >0: val-gated early stop of distillation
        val_every: int = 10,
        patience_steps: int | None = None,
        mesh=None,
        **kwargs,
    ):
        super().__init__(dataset, task, config, mesh=mesh, **kwargs)
        # carve the validation split FIRST so the default public pool is
        # disjoint from it (the reference feeds a separate valid_data_global,
        # feddf_api.py:32-41; gating the early stop on distillation inputs
        # would track training fit, not generalization)
        self._val_cache = None
        n_val = 0
        if val_fraction > 0.0:
            n_val = max(1, int(len(dataset.test_x) * val_fraction))
            self._val_cache = (
                jnp.asarray(dataset.test_x[:n_val]),
                jnp.asarray(dataset.test_y[:n_val]),
            )
        if public_x is None:
            # reference uses an unlabeled public set (e.g. CIFAR-100 for
            # CIFAR-10 training); default to held-out test inputs, minus
            # the validation rows
            public_x = dataset.test_x[n_val:]
        public_x = np.asarray(public_x, np.float32)
        if fedmix_server and (hard_sample_ratio < 1.0):
            raise ValueError("fedmix_server replaces the public pool with "
                             "batch-mean images; combining it with "
                             "hard_sample_ratio would silently discard the "
                             "subsetting — pick one")
        if hard_sample_ratio < 1.0:
            # the reference's "hard sample mining" is a seeded random subset
            # of the unlabeled pool (my_model_trainer_ensemble.py:87-104)
            rng = np.random.RandomState(0)
            idx = rng.permutation(len(public_x))
            public_x = public_x[idx[: int(np.floor(len(idx) * hard_sample_ratio))]]
        if fedmix_server:
            # FedMix server path (feddf_api.py:360-363, ensemble trainer
            # train(train_data, average_data, ...)): the distillation inputs
            # are per-client per-batch MEAN images (generate_mean,
            # condense_api.py:129-147) — privacy-preserving mixup stand-ins
            public_x = self._batch_mean_images()
        if len(public_x) == 0:
            raise ValueError("public distillation pool is empty "
                             "(hard_sample_ratio too small?)")
        n = min(len(public_x), distill_steps * distill_batch_size)
        self.public_x = public_x[:n]
        self.distill_steps = distill_steps
        self.distill_lr = distill_lr
        self.distill_batch_size = distill_batch_size
        self.temperature = temperature
        self.hard_label = hard_label
        self.val_every = val_every
        self.patience_steps = patience_steps or distill_steps
        self.best_val_acc = float("nan")
        self._distill = jax.jit(self._build_distill())
        # keep per-client nets: rebuild a round fn that returns them
        self._local_batch = jax.jit(self._build_local_batch())

    def _batch_mean_images(self) -> np.ndarray:
        """Per-client per-batch mean images (generate_mean parity): for each
        client, mean over each local batch of ``batch_size`` samples."""
        data, bs = self.data, self.cfg.batch_size
        means = []
        for c, idx in data.train_idx_map.items():
            xs = np.asarray(data.train_x[np.asarray(idx)], np.float32)
            for i in range(0, len(xs), bs):
                means.append(xs[i : i + bs].mean(axis=0))
        return np.stack(means)

    def _build_local_batch(self):
        local_update = self.local_update

        def run(rng, net, x, y, mask):
            keys = jax.random.split(rng, x.shape[0])
            nets, metrics = jax.vmap(local_update, in_axes=(0, None, 0, 0, 0))(
                keys, net, x, y, mask
            )
            return nets, {k: jnp.sum(v) for k, v in metrics.items()}

        return run

    def _build_distill(self):
        task = self.task
        T = self.temperature
        # cosine LR over the distillation budget (the reference pairs Adam
        # with CosineAnnealingLR(server_steps), ensemble trainer :127-128)
        schedule = optax.cosine_decay_schedule(self.distill_lr,
                                               max(self.distill_steps, 1))
        tx = optax.adam(schedule)
        hard = self.hard_label
        val = self._val_cache
        val_every = self.val_every
        patience = self.patience_steps

        def val_acc(params, extra):
            logits = task.predict(params, extra, val[0])
            return jnp.mean((jnp.argmax(logits, -1) == val[1]).astype(jnp.float32))

        def distill(student: NetState, client_nets, public_batches):
            # public_batches: [S, bs, ...]
            opt_state = tx.init(student.params)

            def step(carry, inp):
                params, opt_state, best, since_best, stopped = carry
                xb, step_idx = inp
                # ensemble teacher: mean softmax over the K client models
                t_logits = jax.vmap(
                    lambda p, e: task.predict(p, e, xb)
                )(client_nets.params, client_nets.extra)  # [K, bs, C]
                t_probs = jnp.mean(jax.nn.softmax(t_logits / T, axis=-1), axis=0)

                def loss_fn(p):
                    s_logits = task.predict(p, student.extra, xb)
                    if hard:
                        yhard = jnp.argmax(t_probs, axis=-1)
                        return jnp.mean(
                            optax.softmax_cross_entropy_with_integer_labels(
                                s_logits, yhard)
                        )
                    return kl_divergence(s_logits, t_probs, T)

                l, g = jax.value_and_grad(loss_fn)(params)
                upd, opt_state_n = tx.update(g, opt_state, params)
                new_params = optax.apply_updates(params, upd)
                if val is None:
                    # no gating machinery in the hot scan body
                    return (new_params, opt_state_n, best, since_best,
                            stopped), l
                # val-gated early stop (ensemble trainer :137-175): check
                # every val_every steps; stop after `patience` steps without
                # a new best. Static scan length; stopped steps are no-ops.
                acc = jax.lax.cond(
                    ((step_idx + 1) % val_every == 0) & ~stopped,
                    lambda: val_acc(new_params, student.extra),
                    lambda: jnp.float32(-1.0))
                improved = acc > best
                best = jnp.maximum(best, acc)
                since_best = jnp.where(acc >= 0,
                                       jnp.where(improved, 0, since_best + val_every),
                                       since_best)
                stopped = stopped | (since_best >= patience)
                keep = lambda a, b: jax.tree.map(
                    lambda u, v: jnp.where(stopped, v, u), a, b)
                return (keep(new_params, params), keep(opt_state_n, opt_state),
                        best, since_best, stopped), l

            S = public_batches.shape[0]
            # best starts at -1: distinguishes "no val check ever ran"
            # (e.g. S < val_every) from a genuinely 0%-accurate model
            (params, _, best, _, _), losses = jax.lax.scan(
                step,
                (student.params, opt_state, jnp.float32(-1.0), jnp.int32(0),
                 jnp.bool_(False)),
                (public_batches, jnp.arange(S))
            )
            return NetState(params, student.extra), losses, best

        return distill

    def run_rounds(self, start_round: int, num_rounds: int):
        raise NotImplementedError(
            "FedDF interleaves ensemble distillation with the round program; "
            "the R-round scan block would silently skip it — use run_round")

    def _public_batches(self, round_idx: int):
        rng = np.random.RandomState(self.cfg.seed * 977 + round_idx)
        idx = rng.permutation(len(self.public_x))
        # small public pools (e.g. fedmix mean images) shrink the batch
        # rather than yielding zero distillation steps (pool is non-empty,
        # enforced at construction, so S >= 1)
        bs = min(self.distill_batch_size, len(idx))
        S = min(self.distill_steps, len(idx) // bs)
        sel = idx[: S * bs].reshape(S, bs)
        return jnp.asarray(self.public_x[sel])

    def run_round(self, round_idx: int):
        cb = self._pack_round_host(round_idx)
        self.rng, rk = jax.random.split(self.rng)
        nets, metrics = self._local_batch(
            rk, self.net, jnp.asarray(cb.x), jnp.asarray(cb.y), jnp.asarray(cb.mask)
        )
        avg = tree_weighted_mean(nets, jnp.asarray(cb.num_samples))
        student, d_losses, best = self._distill(
            avg, nets, self._public_batches(round_idx))
        self.net = student
        if self._val_cache is not None:
            b = float(best)
            self.best_val_acc = b if b >= 0 else float("nan")
        metrics = dict(metrics)
        metrics["distill_loss"] = d_losses[-1]
        return metrics
