"""FedAvg-affinity — FedAvg + server-side affinity tracking (fork addition).

Reference: fedml_api/standalone/fedavg_affinity/fedavg_api.py:12-130 — the
fork's variant that records similarity metrics between client updates at the
server each round (plus server-side testing, _test_on_server :130-153).

TPU form: the pairwise affinity matrix of client updates is one device-side
computation on the vmapped round results: normalize each client's flattened
delta and take the Gram matrix (a single [K, D] x [D, K] matmul on the MXU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.utils.tree import tree_weighted_mean


class FedAvgAffinityAPI(FedAvgAPI):
    def __init__(self, dataset, task, config: FedAvgConfig, **kwargs):
        super().__init__(dataset, task, config, **kwargs)
        self._local_batch = jax.jit(self._build_local_batch())
        self._affinity = jax.jit(self._build_affinity())
        self.affinity_history: list[np.ndarray] = []

    def _build_local_batch(self):
        local_update = self.local_update

        def run(rng, net, x, y, mask):
            keys = jax.random.split(rng, x.shape[0])
            nets, metrics = jax.vmap(local_update, in_axes=(0, None, 0, 0, 0))(
                keys, net, x, y, mask
            )
            return nets, {k: jnp.sum(v) for k, v in metrics.items()}

        return run

    def _build_affinity(self):
        def affinity(client_params, global_params):
            # deltas: [K, D] normalized; affinity = cosine Gram matrix
            deltas = jax.vmap(
                lambda p: jnp.concatenate([
                    jnp.ravel(a - b) for a, b in zip(
                        jax.tree.leaves(p), jax.tree.leaves(global_params))
                ])
            )(client_params)
            norms = jnp.linalg.norm(deltas, axis=1, keepdims=True)
            unit = deltas / jnp.maximum(norms, 1e-12)
            return unit @ unit.T

        return affinity

    def run_round(self, round_idx: int):
        cb = self._pack_round_host(round_idx)
        self.rng, rk = jax.random.split(self.rng)
        nets, metrics = self._local_batch(
            rk, self.net, jnp.asarray(cb.x), jnp.asarray(cb.y), jnp.asarray(cb.mask))
        aff = self._affinity(nets.params, self.net.params)
        self.affinity_history.append(np.asarray(aff))
        avg = tree_weighted_mean(nets, jnp.asarray(cb.num_samples))
        self.net, self.server_opt_state = self.server_update(
            self.net, avg, self.server_opt_state)
        return metrics
