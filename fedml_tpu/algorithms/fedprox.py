"""FedProx — FedAvg with a proximal term on the client objective.

Reference: fedml_api/distributed/fedprox/ — whose distributed trainer is
byte-identical to FedAvg's, i.e. the proximal term is NOT implemented there
(SURVEY.md §2.2). We implement the published algorithm (Li et al., MLSys'20):
client loss += mu/2 ||w - w_global||^2, realized in
core.local.make_local_update via LocalSpec.prox_mu. With mu=0 this is exactly
FedAvg, matching the reference's de-facto behavior.
"""

from __future__ import annotations

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig, make_client_optimizer
from fedml_tpu.core.local import LocalSpec


class FedProxAPI(FedAvgAPI):
    def __init__(self, dataset, task, config: FedAvgConfig, mesh=None, mu: float = 0.1, **kwargs):
        spec = LocalSpec(
            optimizer=make_client_optimizer(config), epochs=config.epochs,
            prox_mu=mu, remat=config.remat,
        )
        super().__init__(dataset, task, config, mesh=mesh, local_spec=spec, **kwargs)
