"""FedAvg with sequence-parallel clients — long-context federated training.

The reference tops out at 80-token LSTMs (SURVEY.md §2.7: no sequence
parallelism anywhere); this engine makes long sequences first-class in the
FL loop itself: a 2-axis ``('clients','seq')`` mesh where

  - the 'clients' axis is the usual FL client parallelism (one shard of the
    sampled cohort per mesh column; aggregation = weighted psum), and
  - the 'seq' axis shards every client's ACTIVATIONS over the sequence
    dimension: the TransformerLM runs ring attention (`parallel/
    ring_attention.py`, ppermuted kv blocks over ICI) so a context that
    doesn't fit one device's HBM trains across the axis. The task's loss is
    psum-ed over 'seq' and params stay seq-invariant, so shard_map's
    vma-aware transpose produces the full-sequence gradient on every shard
    with no explicit collective in the update loop.

Equivalence (test-enforced): with T divisible by the 'seq' axis, a round on
the 2-axis mesh matches the single-device engine on the same config — ring
attention ≡ full attention, psum-ed grads ≡ unsharded grads, and the
fold_in key chain is shape-independent.

Labels arrive pre-shifted per position (data convention y[t] = x[t+1],
data/synthetic.py:synthetic_sequences), so sharding T splits x and y
consistently and no cross-shard label exchange is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.fedavg import (
    FedAvgConfig,
    _make_client_keys,
    _shard_aggregate,
    agg_weights,
    make_client_optimizer,
)
from fedml_tpu.core.client_data import (
    FederatedData,
    batch_global,
    pack_clients,
    pad_batches,
)
from fedml_tpu.core.local import LocalSpec, make_eval_fn, make_local_update
from fedml_tpu.core.sampling import prepare_sampling, sample_for
from fedml_tpu.core.tasks import sequence_task


class FedAvgSeqAPI:
    """FedAvg over a ('clients','seq') mesh.

    ``model_ctor(seq_axis)`` builds the language model; it is called twice —
    with the mesh's seq axis name for the sharded round program, and with
    ``None`` for init/eval (identical parameter structure; only apply-time
    collectives differ)."""

    def __init__(
        self,
        dataset: FederatedData,
        model_ctor,
        config: FedAvgConfig,
        mesh: Mesh,
        pad_id: int = 0,
        server_update=None,
        server_opt_init=None,
        local_spec: LocalSpec | None = None,
        donate: bool = False,
    ):
        if "clients" not in mesh.axis_names or "seq" not in mesh.axis_names:
            raise ValueError(
                f"FedAvgSeqAPI needs axes ('clients','seq'), got {mesh.axis_names}")
        self.data, self.cfg, self.mesh = dataset, config, mesh
        # sampling dispatch is shared with FedAvgAPI (core/sampling
        # sample_for); size_weighted forces the uniform aggregate (the
        # unbiased pairing — see FedAvgAPI.uniform_avg)
        self.uniform_avg = config.sampling == "size_weighted"
        self._client_sizes = prepare_sampling(config, dataset)
        self.donate = donate  # same opt-in contract as FedAvgAPI
        cd, sd = mesh.shape["clients"], mesh.shape["seq"]
        T = int(dataset.train_x.shape[1])
        if T % sd != 0:
            raise ValueError(f"sequence length {T} not divisible by seq axis {sd}")
        if config.client_num_per_round % cd != 0:
            raise ValueError(
                f"client_num_per_round={config.client_num_per_round} must be "
                f"a multiple of the clients axis {cd}")

        self.rng = jax.random.PRNGKey(config.seed)
        self.task_plain = sequence_task(model_ctor(None), pad_id=pad_id)
        sharded_model = model_ctor("seq")
        if (getattr(sharded_model, "seq_impl", "ring") == "ulysses"
                and getattr(sharded_model, "num_heads", None) is not None
                and sharded_model.num_heads % mesh.shape["seq"] != 0):
            # fail at construction with the real reason, not a low-level
            # all_to_all split error mid-trace
            raise ValueError(
                f"ulysses needs num_heads ({sharded_model.num_heads}) "
                f"divisible by the seq axis ({mesh.shape['seq']})")
        self.task_sharded = sequence_task(sharded_model, pad_id=pad_id,
                                          seq_axis="seq")
        self.eval_fn = make_eval_fn(self.task_plain)

        counts = [len(v) for v in dataset.train_idx_map.values()]
        b_needed = int(np.ceil(max(counts) / config.batch_size))
        self.num_batches = min(config.max_batches or b_needed, b_needed)

        # no explicit grad psum: the task's seq-psum-ed loss + seq-invariant
        # params make shard_map's transpose insert it (see core/local.py).
        # local_spec composes variants exactly as on FedAvgAPI — e.g. a
        # prox_mu>0 spec gives FedProx on long context (the proximal term is
        # over seq-invariant params: identical on every shard, no collective;
        # equivalence test-enforced)
        spec = local_spec or LocalSpec(
            optimizer=make_client_optimizer(config), epochs=config.epochs,
            remat=config.remat)
        self.local_update = make_local_update(self.task_sharded, spec)

        self.rng, init_key = jax.random.split(self.rng)
        x_sample = jnp.asarray(dataset.train_x[: config.batch_size])
        self.net = self.task_plain.init(init_key, x_sample)

        # server update hook — identity for FedAvg; FedOpt-style server
        # optimizers plug in exactly as on FedAvgAPI
        self.server_update = server_update or (lambda old, avg, s: (avg, s))
        self.server_opt_state = (server_opt_init(self.net.params)
                                 if server_opt_init else ())

        self.round_fn = self._build_round_fn()
        self._test_cache = None
        self.history: list[dict] = []

    # ---------------------------------------------------------------- round
    def _sampled_ids(self, round_idx: int):
        return sample_for(self.cfg, round_idx, self._client_sizes)

    def _per_round(self, net, opt, keys, x, y, mask, nsamp):
        """Shared per-round body of the single-round fn AND the scan block
        (their numeric identity is test-enforced). Runs INSIDE shard_map:
        per-device block is [K/cd] clients x [.., T/sd] sequence slices.
        Params stay seq-INVARIANT (the vma-aware grad transpose restores
        invariance each step) and become clients-varying for the fits."""
        net_v = jax.tree.map(
            lambda v: jax.lax.pcast(v, "clients", to="varying"), net)
        nets, metrics = jax.vmap(self.local_update, in_axes=(0, None, 0, 0, 0))(
            keys, net_v, x, y, mask)
        # metrics are already seq-psum-ed inside the task (identical on
        # every seq shard); aggregate clients with the shared helper
        avg, msum = _shard_aggregate(
            nets, metrics, agg_weights(nsamp, self.uniform_avg), "clients")
        new_net, new_opt = self.server_update(net, avg, opt)
        return new_net, new_opt, msum

    def _build_round_fn(self):
        mesh = self.mesh
        client_keys = _make_client_keys(self.cfg.seed)

        def body(keys, net, opt, x, y, mask, nsamp):
            return self._per_round(net, opt, keys, x, y, mask, nsamp)

        smapped = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P("clients"), P(), P(),
                      P("clients", None, None, "seq"),
                      P("clients", None, None, "seq"),
                      P("clients"), P("clients")),
            out_specs=(P(), P(), P()),
        )

        from functools import partial

        @partial(jax.jit, donate_argnums=(0, 1) if self.donate else ())
        def round_fn(net, server_opt_state, x, y, mask, nsamp, round_idx, ids):
            keys = client_keys(round_idx, ids)
            # seq shards hold duplicate metric copies psum-ed over 'clients'
            # only; the seq axis saw identical (invariant) values
            return smapped(keys, net, server_opt_state, x, y, mask, nsamp)

        return round_fn

    def run_rounds(self, start_round: int, num_rounds: int):
        """R rounds as ONE compiled program: lax.scan over rounds inside the
        two-axis shard_map (the long-context analogue of FedAvgAPI.run_rounds
        — host fully out of the loop for the block). Numerically identical to
        sequential run_round calls (same key chain; test-enforced)."""
        cfg = self.cfg
        xs, ys, ms, ns, ids_l = [], [], [], [], []
        for r in range(start_round, start_round + num_rounds):
            ids = self._sampled_ids(r)
            cb = pad_batches(
                pack_clients(self.data, ids, cfg.batch_size,
                             max_batches=self.num_batches, seed=cfg.seed,
                             round_idx=r),
                self.num_batches)
            xs.append(cb.x); ys.append(cb.y); ms.append(cb.mask)
            ns.append(cb.num_samples)
            ids_l.append(np.asarray(ids, np.int32))
        sh = lambda spec: NamedSharding(self.mesh, spec)
        x = jax.device_put(np.stack(xs), sh(P(None, "clients", None, None, "seq")))
        y = jax.device_put(np.stack(ys), sh(P(None, "clients", None, None, "seq")))
        mask = jax.device_put(np.stack(ms), sh(P(None, "clients")))
        nsamp = jax.device_put(np.stack(ns), sh(P(None, "clients")))
        ids = jax.device_put(np.stack(ids_l), sh(P(None, "clients")))
        rounds = jnp.arange(start_round, start_round + num_rounds, dtype=jnp.int32)
        if not hasattr(self, "_block_fn"):
            self._block_fn = self._build_block_fn()
        self.net, self.server_opt_state, metrics = self._block_fn(
            self.net, self.server_opt_state, x, y, mask, nsamp, ids, rounds)
        return metrics

    def _build_block_fn(self):
        mesh = self.mesh
        client_keys = _make_client_keys(self.cfg.seed)

        def shard_block(net, opt, x, y, mask, nsamp, ids, rounds):
            def step(carry, inp):
                net, opt = carry
                x_r, y_r, m_r, ns_r, ids_r, r = inp
                keys = client_keys(r, ids_r)
                net, opt, msum = self._per_round(
                    net, opt, keys, x_r, y_r, m_r, ns_r)
                return (net, opt), msum

            (net, opt), ms = jax.lax.scan(
                step, (net, opt), (x, y, mask, nsamp, ids, rounds))
            return net, opt, ms

        smapped = jax.shard_map(
            shard_block, mesh=mesh,
            in_specs=(P(), P(),
                      P(None, "clients", None, None, "seq"),
                      P(None, "clients", None, None, "seq"),
                      P(None, "clients"), P(None, "clients"),
                      P(None, "clients"), P()),
            out_specs=(P(), P(), P()),
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def run_round(self, round_idx: int):
        cfg = self.cfg
        ids = self._sampled_ids(round_idx)
        cb = pack_clients(self.data, ids, cfg.batch_size,
                          max_batches=self.num_batches, seed=cfg.seed,
                          round_idx=round_idx)
        # fixed B across rounds -> the round program compiles exactly once
        # (padded batches are exact no-ops in the local fit)
        cb = pad_batches(cb, self.num_batches)
        sh = lambda spec: NamedSharding(self.mesh, spec)
        x = jax.device_put(cb.x, sh(P("clients", None, None, "seq")))
        y = jax.device_put(cb.y, sh(P("clients", None, None, "seq")))
        mask = jax.device_put(cb.mask, sh(P("clients")))
        nsamp = jax.device_put(cb.num_samples, sh(P("clients")))
        self.net, self.server_opt_state, metrics = self.round_fn(
            self.net, self.server_opt_state, x, y, mask, nsamp,
            jnp.int32(round_idx), jnp.asarray(ids, jnp.int32))
        return metrics

    def train(self, num_rounds: int | None = None):
        rounds = num_rounds or self.cfg.comm_round
        for r in range(rounds):
            metrics = self.run_round(r)
            if r % self.cfg.frequency_of_the_test == 0 or r == rounds - 1:
                ev = self.evaluate()
                n = float(max(float(metrics["count"]), 1.0))
                self.history.append({
                    "round": r,
                    "train_loss": float(metrics["loss_sum"]) / n,
                    "train_acc": float(metrics["correct"]) / n,
                    "test_loss": float(ev["loss"]),
                    "test_acc": float(ev["acc"]),
                })
        return self.net

    # ---------------------------------------------------------------- state
    def load_state(self, net, server_opt_state, rng):
        """Install restored state, re-placing it replicated over the 2-axis
        mesh (mirrors FedAvgAPI.load_state; the CLI resume path calls this
        for every engine it checkpoints)."""
        rep = NamedSharding(self.mesh, P())
        put = lambda t: jax.tree.map(lambda v: jax.device_put(v, rep), t)
        self.net, self.server_opt_state, self.rng = (
            put(net), put(server_opt_state), put(rng))

    # ----------------------------------------------------------------- eval
    def evaluate(self):
        """Global test eval on the axis-free twin (replicated params; the
        T-sharded program is only needed where activations must not
        materialize — for eval-sized batches the plain path is fine)."""
        from fedml_tpu.algorithms.fedavg import eval_subset

        fresh = (self.cfg.eval_subset_mode == "fresh"
                 and self.cfg.eval_max_samples is not None
                 and len(self.data.test_x) > self.cfg.eval_max_samples)
        self._eval_calls = getattr(self, "_eval_calls", 0) + 1
        if self._test_cache is None or fresh:
            # same validation-subset policy as FedAvgAPI.evaluate
            tx, ty = eval_subset(self.data.test_x, self.data.test_y,
                                 self.cfg, self._eval_calls)
            n = len(tx)
            if self.cfg.ci:
                n = min(n, 512)
            self._test_cache = tuple(
                jnp.asarray(a) for a in batch_global(
                    tx[:n], ty[:n], self.cfg.eval_batch_size))
        xb, yb, mb = self._test_cache
        return self.eval_fn(self.net, xb, yb, mb)
