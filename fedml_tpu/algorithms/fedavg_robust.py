"""Robust FedAvg — norm clipping + weak-DP noise against poisoning/backdoors.

Reference: fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py
applies fedml_core/robustness/robust_aggregation.py defenses
(--defense_type norm_diff_clipping|weak_dp, --norm_bound, --stddev flags
consumed at robust_aggregation.py:33-36) before/after the weighted average,
and evaluates backdoor targeted-task accuracy (:14-80).

TPU form: clipping is the engine's client_result_hook (runs vmapped on
device, per client, before the psum); noise is the post_aggregate_hook.
Backdoor evaluation = eval_fn on a poisoned test set with target labels.

Byzantine-robust aggregation (core/robust_agg.py) composes through the
inherited ``aggregator=``/``sanitize=``/``adversary_plan=`` kwargs:
``FedAvgRobustAPI(..., defense_type='norm_diff_clipping',
aggregator='krum')`` clips every update AND feeds the clipped stack to
Krum behind the sanitation gate — defenses stack, they don't compete
(clipping bounds magnitude, the robust estimator survives colluding
direction; docs/ROBUSTNESS.md §Byzantine-robust aggregation).
"""

from __future__ import annotations

import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core.local import NetState
from fedml_tpu.core.robust import add_gaussian_noise, norm_diff_clipping
from fedml_tpu.core.client_data import batch_global


class FedAvgRobustAPI(FedAvgAPI):
    def __init__(
        self,
        dataset,
        task,
        config: FedAvgConfig,
        mesh=None,
        defense_type: str = "norm_diff_clipping",  # | 'weak_dp' | 'dp' | 'none'
        norm_bound: float = 30.0,
        stddev: float = 0.025,
        noise_multiplier: float = 1.0,  # z, for defense_type='dp'
        poisoned_test: tuple | None = None,  # (x, y_target) backdoor eval set
        **kwargs,
    ):
        """defense_type='dp' is REAL DP-FedAvg (McMahan et al. 2018),
        unlike the reference's hand-tuned 'weak_dp'
        (robust_aggregation.py:51-55): per-client updates clip to L2 ball
        norm_bound (=C), the server adds N(0, (z*C/m)^2) to the m-client
        average, and ``self.accountant`` tracks cumulative Rényi DP —
        ``self.epsilon(delta)`` gives the (ε, δ) spent so far
        (core/privacy.py)."""
        self.defense_type = defense_type
        self.accountant = None
        self._privacy_cache = None
        self._dp_block_charged = False
        hooks = {}
        if defense_type in ("norm_diff_clipping", "weak_dp", "dp"):
            def clip_hook(net_k: NetState, net_global: NetState, rng):
                return NetState(
                    norm_diff_clipping(net_k.params, net_global.params, norm_bound),
                    net_k.extra,
                )
            hooks["client_result_hook"] = clip_hook
        if defense_type in ("weak_dp", "dp"):
            if defense_type == "dp":
                from fedml_tpu.core.privacy import DPAccountant

                if noise_multiplier <= 0:
                    raise ValueError("defense_type='dp' needs "
                                     f"noise_multiplier > 0, got {noise_multiplier}")
                # the accountant charges the Poisson-subsampled-Gaussian
                # bound at q = m/N, which assumes UNIFORM sampling; under
                # size-weighted sampling a data-rich client's inclusion
                # probability exceeds q and the reported epsilon would
                # silently understate its true loss (the cross-process
                # aggregator enforces the same rule)
                if getattr(config, "sampling", "uniform") != "uniform":
                    raise ValueError(
                        "defense_type='dp' requires config.sampling="
                        f"'uniform' (got {config.sampling!r}): the RDP "
                        "accountant's q=m/N subsampling bound does not "
                        "hold for non-uniform client sampling")
                # noise on the AVERAGED update: z * C / m. Sensitivity C/m
                # only holds under a UNIFORM client average — sample-
                # weighted averaging lets one data-rich client move the
                # mean by up to (n_k/Σn)·C — so dp forces uniform_avg.
                stddev = (noise_multiplier * norm_bound
                          / config.client_num_per_round)
                kwargs["uniform_avg"] = True
                self.accountant = DPAccountant()
                self._dp_q = (config.client_num_per_round
                              / config.client_num_in_total)
                self._dp_z = noise_multiplier
                self._dp_C = norm_bound

            def noise_hook(net: NetState, rng):
                return NetState(add_gaussian_noise(rng, net.params, stddev), net.extra)
            hooks["post_aggregate_hook"] = noise_hook

        super().__init__(dataset, task, config, mesh=mesh, **hooks, **kwargs)
        self._poisoned = None
        if poisoned_test is not None:
            px, py = poisoned_test
            self._poisoned = tuple(
                jnp.asarray(a) for a in batch_global(px, py, config.eval_batch_size)
            )

    def _charge(self, rounds: int) -> None:
        """Step the accountant and refresh the privacy ledger surfaces
        (round-record block + the live ε gauge the privacy_budget health
        rule alerts on)."""
        from fedml_tpu.core.privacy import charge_and_record

        self._privacy_cache = charge_and_record(
            self.accountant, self._dp_q, self._dp_z, self._dp_C,
            realized_m=self.cfg.client_num_per_round, rounds=rounds)

    def _privacy_extra(self) -> dict:
        return ({"privacy": dict(self._privacy_cache)}
                if self._privacy_cache is not None else {})

    def run_round(self, round_idx: int):
        # charge BEFORE the dispatch: the round's telemetry record must
        # carry the ε that INCLUDES this round's spend (a budget ledger
        # may over-report mid-flight, never under-report). When a block
        # already charged its rounds up front, the per-round calls it
        # degrades to (fedavg.py run_rounds' mesh/stacked fallback
        # dispatches via run_round) must NOT charge again — double-
        # counting would report ~2x the true ε and trip the budget alert
        # at half the real spend.
        if self.accountant is not None and not self._dp_block_charged:
            self._charge(1)
        return super().run_round(round_idx)

    def run_rounds(self, start_round: int, num_rounds: int):
        # the scan block applies clip/noise hooks with the pre-derived
        # sequential key stream (fedavg.py _build_block_fn), so DP rides
        # the flagship throughput path; the accountant charges all the
        # block's rounds up front — every record in the block reports the
        # end-of-block ε (conservative, never an under-report)
        if self.accountant is None:
            return super().run_rounds(start_round, num_rounds)
        self._charge(num_rounds)
        self._dp_block_charged = True
        try:
            return super().run_rounds(start_round, num_rounds)
        finally:
            self._dp_block_charged = False

    def epsilon(self, delta: float = 1e-5) -> float:
        """Cumulative (ε, δ)-DP spent by the rounds run so far."""
        if self.accountant is None:
            raise ValueError("defense_type='dp' required for accounting")
        return self.accountant.epsilon(delta)

    def evaluate_backdoor(self):
        """Targeted-task accuracy on the poisoned set: fraction of poisoned
        inputs classified as the attacker's target label (the reference's
        backdoor test loop, FedAvgRobustAggregator.py:14-80)."""
        if self._poisoned is None:
            raise ValueError("no poisoned_test set provided")
        xb, yb, mb = self._poisoned
        return self.eval_fn(self.net, xb, yb, mb)
