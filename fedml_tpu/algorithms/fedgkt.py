"""FedGKT — group knowledge transfer (split computing + bidirectional KD).

Reference: fedml_api/distributed/fedgkt/ — each client trains a small model
(feature extractor + lightweight classifier) with CE + KL toward the server's
logits (GKTClientTrainer.train, GKTClientTrainer.py:49-60, KL at :39), then
ships its extracted feature maps + logits + labels; the server trains a large
model that consumes feature maps, with CE + KL toward each client's logits
(GKTServerTrainer.train_large_model_on_the_server, GKTServerTrainer.py:233),
and returns per-client server logits for the next round. Models:
fedml_api/model/cv/resnet56_gkt/.

TPU form: three jitted programs per round — (1) vmapped client phase (K small
models train concurrently), (2) one batched feature-extraction forward, (3)
server phase scanning over the pooled (features, client-logits, labels)
tensors. The "exchange" is just device arrays flowing between programs.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.client_data import (FederatedData, pack_clients,
                                        pad_batches)
from fedml_tpu.algorithms.feddf import kl_divergence
from fedml_tpu.core.sampling import sample_clients


@dataclasses.dataclass(frozen=True)
class FedGKTConfig:
    comm_round: int = 5
    client_num_in_total: int = 4
    client_num_per_round: int = 4
    epochs_client: int = 1
    epochs_server: int = 1
    batch_size: int = 16
    lr_client: float = 0.01
    lr_server: float = 0.01
    temperature: float = 3.0
    kd_alpha: float = 1.0  # weight of the KL term
    max_batches: int | None = None
    seed: int = 0


class FedGKTAPI:
    """extractor: x -> features; client_head: features -> logits;
    server_model: features -> logits (the large trunk)."""

    def __init__(self, dataset: FederatedData, extractor, client_head,
                 server_model, config: FedGKTConfig, num_classes: int):
        self.data = dataset
        self.cfg = config
        self.extractor, self.client_head, self.server_model = (
            extractor, client_head, server_model)
        self.num_classes = num_classes

        key = jax.random.PRNGKey(config.seed)
        ke, kh, ks = jax.random.split(key, 3)
        x0 = jnp.asarray(dataset.train_x[: config.batch_size])
        evars = extractor.init(ke, x0, train=False)
        f0 = extractor.apply(evars, x0, train=False)
        hvars = client_head.init(kh, f0, train=False)
        svars = server_model.init(ks, f0, train=False)

        K = config.client_num_per_round
        # per-client small models, stacked for vmap
        self.ext_params = jax.tree.map(
            lambda v: jnp.broadcast_to(v[None], (K,) + v.shape), evars["params"])
        self.head_params = jax.tree.map(
            lambda v: jnp.broadcast_to(v[None], (K,) + v.shape), hvars["params"])
        self.server_params = svars["params"]
        self.ctx = optax.sgd(config.lr_client)
        self.stx = optax.sgd(config.lr_server)
        self.server_opt = self.stx.init(self.server_params)
        self.rng = key
        self._client_phase = jax.jit(self._build_client_phase())
        self._server_phase = jax.jit(self._build_server_phase())
        self.history: list[dict] = []

    # ---------------------------------------------------------------- client
    def _build_client_phase(self):
        cfg = self.cfg
        ext, head = self.extractor, self.client_head
        tx = self.ctx
        T, alpha = cfg.temperature, cfg.kd_alpha

        def one_client(ep, hp, x, y, m, s_logits, use_kd):
            # x: [B, bs, ...], s_logits: [B, bs, C] server logits from last round
            opt = tx.init((ep, hp))

            def batch_step(carry, batch):
                (ep, hp), opt = carry
                xb, yb, mb, sl = batch

                def loss_fn(params):
                    ep_, hp_ = params
                    feats = ext.apply({"params": ep_}, xb, train=True)
                    logits = head.apply({"params": hp_}, feats, train=True)
                    n = jnp.maximum(jnp.sum(mb), 1.0)
                    per = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
                    ce = jnp.sum(per * mb) / n
                    t_probs = jax.nn.softmax(sl / T, axis=-1)
                    # masked KL: padded rows must not train
                    kl = kl_divergence(logits, t_probs, T, mask=mb)
                    return ce + alpha * use_kd * kl, (jnp.sum(per * mb),
                                                      jnp.sum((jnp.argmax(logits, -1) == yb) * mb),
                                                      jnp.sum(mb))

                (l, aux), g = jax.value_and_grad(loss_fn, has_aux=True)((ep, hp))
                upd, opt_n = tx.update(g, opt, (ep, hp))
                newp = optax.apply_updates((ep, hp), upd)
                has = jnp.sum(mb) > 0
                keep = lambda a, b: jax.tree.map(
                    lambda u, v: jax.lax.select(has, u, v), a, b)
                return (keep(newp, (ep, hp)), keep(opt_n, opt)), jnp.stack(aux)

            def epoch(carry, _):
                return jax.lax.scan(batch_step, carry, (x, y, m, s_logits))

            ((ep, hp), _), aux = jax.lax.scan(
                epoch, ((ep, hp), opt), None, length=cfg.epochs_client)
            # after training: extract features + logits to ship to the server
            def fwd(xb):
                feats = ext.apply({"params": ep}, xb, train=False)
                logits = head.apply({"params": hp}, feats, train=False)
                return feats, logits

            feats, logits = jax.vmap(fwd)(x)
            return ep, hp, feats, logits, aux.sum((0, 1))

        def phase(ext_p, head_p, x, y, m, s_logits, use_kd):
            return jax.vmap(one_client, in_axes=(0, 0, 0, 0, 0, 0, None))(
                ext_p, head_p, x, y, m, s_logits, use_kd)

        return phase

    # ---------------------------------------------------------------- server
    def _build_server_phase(self):
        cfg = self.cfg
        sm = self.server_model
        tx = self.stx
        T, alpha = cfg.temperature, cfg.kd_alpha

        def phase(sp, sopt, feats, c_logits, y, m):
            # feats: [K, B, bs, F...] -> flatten client/batch dims into steps
            K, B = feats.shape[0], feats.shape[1]
            fl = feats.reshape((K * B,) + feats.shape[2:])
            cl = c_logits.reshape((K * B,) + c_logits.shape[2:])
            yl = y.reshape((K * B,) + y.shape[2:])
            ml = m.reshape((K * B,) + m.shape[2:])

            def batch_step(carry, batch):
                sp, sopt = carry
                fb, cb, yb, mb = batch

                def loss_fn(sp_):
                    logits = sm.apply({"params": sp_}, fb, train=True)
                    n = jnp.maximum(jnp.sum(mb), 1.0)
                    per = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
                    ce = jnp.sum(per * mb) / n
                    kl = kl_divergence(logits, jax.nn.softmax(cb / T, -1), T,
                                       mask=mb)
                    return ce + alpha * kl

                l, g = jax.value_and_grad(loss_fn)(sp)
                upd, sopt_n = tx.update(g, sopt, sp)
                has = jnp.sum(mb) > 0
                keep = lambda a, b: jax.tree.map(
                    lambda u, v: jax.lax.select(has, u, v), a, b)
                return (keep(optax.apply_updates(sp, upd), sp),
                        keep(sopt_n, sopt)), l

            def epoch(carry, _):
                return jax.lax.scan(batch_step, carry, (fl, cl, yl, ml))

            (sp, sopt), _ = jax.lax.scan(
                epoch, (sp, sopt), None, length=cfg.epochs_server)
            # fresh server logits per client sample for next round's KD
            s_logits = sm.apply({"params": sp}, fl, train=False)
            return sp, sopt, s_logits.reshape((K, B) + s_logits.shape[1:])

        return phase

    # ----------------------------------------------------------------- round
    def run_round(self, round_idx: int):
        cfg = self.cfg
        ids = sample_clients(round_idx, cfg.client_num_in_total,
                             cfg.client_num_per_round, cfg.seed)
        cb = pack_clients(self.data, ids, cfg.batch_size,
                          max_batches=cfg.max_batches, seed=cfg.seed,
                          round_idx=round_idx)
        # pad the cohort block to the GLOBAL batch budget: ragged cohorts
        # would otherwise change B per round, resetting the KD cache (and
        # retracing both phases) every time the sampled max size changes;
        # padded batches are masked no-ops in both phases
        counts = [len(v) for v in self.data.train_idx_map.values()]
        b_all = int(np.ceil(max(counts) / cfg.batch_size))
        B_glob = min(cfg.max_batches or b_all, b_all)
        cb = pad_batches(cb, B_glob)
        x, y, m = jnp.asarray(cb.x), jnp.asarray(cb.y), jnp.asarray(cb.mask)
        K, B, bs = x.shape[0], x.shape[1], x.shape[2]
        if not hasattr(self, "_s_logits") or self._s_logits.shape[:3] != (K, B, bs):
            self._s_logits = jnp.zeros((K, B, bs, self.num_classes))
            use_kd = 0.0  # first round: no server logits yet (reference warms up too)
        else:
            use_kd = 1.0

        self.ext_params, self.head_params, feats, c_logits, aux = \
            self._client_phase(self.ext_params, self.head_params, x, y, m,
                               self._s_logits, use_kd)
        self.server_params, self.server_opt, self._s_logits = \
            self._server_phase(self.server_params, self.server_opt,
                               feats, c_logits, y, m)
        loss_sum, correct, count = (float(aux[:, i].sum()) for i in range(3))
        rec = {"round": round_idx, "train_loss": loss_sum / max(count, 1),
               "train_acc": correct / max(count, 1)}
        self.history.append(rec)
        return rec

    def evaluate(self, batch_size: int = 256):
        """Server-side eval: extractor(client 0) + server trunk on the global
        test set (the reference evaluates the joint small+large pipeline)."""
        from fedml_tpu.core.client_data import batch_global

        xb, yb, mb = (jnp.asarray(a) for a in batch_global(
            self.data.test_x, self.data.test_y, batch_size))
        ext, sm = self.extractor, self.server_model
        ep = jax.tree.map(lambda v: v[0], self.ext_params)

        @jax.jit
        def ev(ep, sp):
            def body(acc, b):
                x, y, m = b
                feats = ext.apply({"params": ep}, x, train=False)
                logits = sm.apply({"params": sp}, feats, train=False)
                return (acc[0] + jnp.sum((jnp.argmax(logits, -1) == y) * m),
                        acc[1] + jnp.sum(m)), None
            (c, n), _ = jax.lax.scan(body, (0.0, 0.0), (xb, yb, mb))
            return c / jnp.maximum(n, 1.0)

        return float(ev(ep, self.server_params))
