"""FedSeg — federated semantic segmentation.

Reference: fedml_api/distributed/fedseg/ (867 LoC). Its round machinery is
the FedAvg pattern (FedSegAggregator mirrors FedAVGAggregator); what makes it
FedSeg is (a) pixel-wise CE/focal losses with ignore_index=255
(SegmentationLosses, utils.py:66-110), (b) poly/cos/step LR scheduling with
warmup (LR_Scheduler, utils.py:113-170), and (c) confusion-matrix evaluation
reported as Pixel Acc / Class Acc / mIoU / FWIoU per round
(Evaluator utils.py:246-288, EvaluationMetricsKeeper utils.py:57-63).

TPU re-design: the round engine is the shared FedAvg SPMD program; the LR
schedule is traced into the client optimizer; eval accumulates the [C, C]
confusion matrix on device across the whole test scan and only the final
matrix crosses to the host.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core.local import LocalSpec
from fedml_tpu.core.schedules import make_lr_schedule
from fedml_tpu.core.tasks import segmentation_task
from fedml_tpu.utils.seg_metrics import confusion_matrix, seg_scores


@dataclasses.dataclass(frozen=True)
class FedSegConfig(FedAvgConfig):
    """FedAvg flags + the reference's segmentation-specific surface
    (--loss_type ce|focal, --lr_scheduler poly|cos|step, --lr_step,
    --warmup_epochs; fedml_experiments/distributed/fedseg main args)."""

    loss_type: str = "ce"          # 'ce' | 'focal'
    lr_scheduler: str = "poly"     # 'poly' | 'cos' | 'step' | 'constant'
    lr_step: int = 30
    warmup_epochs: int = 0
    ignore_index: int = 255


class FedSegAPI(FedAvgAPI):
    """FedAvg engine + segmentation task + scheduled client LR + mIoU eval.

    ``module`` is a flax segmentation model mapping [bs, H, W, C] ->
    [bs, H, W, num_classes] (models/segmentation.py).
    """

    def __init__(self, dataset, module, config: FedSegConfig, mesh=None, **kwargs):
        self.num_classes = dataset.class_num
        self.cfg_seg = config
        task = segmentation_task(
            module, ignore_index=config.ignore_index, loss_mode=config.loss_type
        )

        # LR schedule over a client's local steps (epochs x batches within the
        # round — the reference steps its scheduler per local iteration,
        # FedSegTrainer using LR_Scheduler(iters_per_epoch)).
        counts = [len(v) for v in dataset.train_idx_map.values()]
        b = int(np.ceil(max(counts) / config.batch_size))
        if config.max_batches:
            b = min(b, config.max_batches)
        steps_per_epoch = max(b, 1)
        schedule = make_lr_schedule(
            config.lr_scheduler, config.lr, config.epochs * steps_per_epoch,
            warmup_steps=config.warmup_epochs * steps_per_epoch,
            steps_per_epoch=steps_per_epoch, lr_step=config.lr_step,
        )
        tx = optax.sgd(schedule, momentum=config.momentum or None)
        if config.wd:
            tx = optax.chain(optax.add_decayed_weights(config.wd), tx)
        local_spec = LocalSpec(optimizer=tx, epochs=config.epochs,
                               remat=config.remat)

        super().__init__(dataset, task, config, mesh=mesh,
                         local_spec=local_spec, **kwargs)
        self._seg_eval_fn = self._build_seg_eval()

    def _build_seg_eval(self):
        C = self.num_classes
        task = self.task
        ignore = self.cfg_seg.ignore_index

        def eval_fn(net, xb, yb, mb):
            def body(acc, batch):
                x, y, m = batch
                logits = task.predict(net.params, net.extra, x)
                pred = jnp.argmax(logits, -1)
                valid = (y != ignore).astype(jnp.float32) * m[:, None, None]
                conf = confusion_matrix(pred, y, C, valid)
                metr = task.eval_batch(net.params, net.extra, x, y, m)
                return (
                    {
                        "conf": acc["conf"] + conf,
                        "loss_sum": acc["loss_sum"] + metr["loss_sum"],
                        "count": acc["count"] + metr["count"],
                    },
                    None,
                )

            init = {"conf": jnp.zeros((C, C)), "loss_sum": jnp.zeros(()),
                    "count": jnp.zeros(())}
            acc, _ = lax.scan(body, init, (xb, yb, mb))
            return acc

        return jax.jit(eval_fn)

    def evaluate(self):
        """EvaluationMetricsKeeper-shaped dict: acc / acc_class / mIoU /
        FWIoU / loss (reference utils.py:57-63)."""
        if self._test_cache is None:
            from fedml_tpu.core.client_data import batch_global

            n = len(self.data.test_x)
            if self.cfg.ci:
                n = min(n, 64)
            self._test_cache = tuple(
                jnp.asarray(a)
                for a in batch_global(
                    self.data.test_x[:n], self.data.test_y[:n], self.cfg.eval_batch_size
                )
            )
        xb, yb, mb = self._test_cache
        acc = self._seg_eval_fn(self.net, xb, yb, mb)
        scores = seg_scores(np.asarray(acc["conf"]))
        n = float(max(acc["count"], 1.0))
        return {
            "loss": float(acc["loss_sum"]) / n,
            "acc": scores["pixel_acc"],
            "acc_class": scores["class_acc"],
            "mIoU": scores["mIoU"],
            "FWIoU": scores["FWIoU"],
        }
