"""FedNova — normalized averaging for heterogeneous local work.

Reference: fedml_api/standalone/fednova/ — a custom torch Optimizer tracks
per-client accumulated gradient direction and local step count tau
(fednova.py:10-60+); the server aggregates *normalized* gradients scaled by
effective tau (fednova_trainer.py:97: aggregate(params, norm_grads, tau_effs)).

TPU form: each client returns its cumulative update d_k = (w_global - w_k)
and its local step count tau_k (counted exactly as its number of REAL
batches x epochs, from the mask). Then with p_k = n_k / n:
    tau_eff = sum_k p_k * tau_k            (the 'effective' steps)
    w_new   = w_global - tau_eff * sum_k p_k * d_k / tau_k
which reproduces FedNova's normalized averaging (momentum-free case) without
a stateful optimizer class — the normalization is pure arithmetic on the
aggregated pytrees, fused into the round program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core.local import NetState


class FedNovaAPI(FedAvgAPI):
    """FedNova via the FedAvg engine.

    The engine aggregates a weighted mean of client NetStates; FedNova needs
    the mean of d_k/tau_k instead. So the local update is wrapped to return
    the pre-normalized state  w_global - d_k / tau_k  (tau_k derived exactly
    from the batch mask), and the server update rescales the aggregated
    direction by tau_eff.
    """

    def __init__(self, dataset, task, config: FedAvgConfig, mesh=None, **kwargs):
        def server_update(old: NetState, avg: NetState, opt_state):
            # avg was computed over normalized client states (see run_round):
            # avg.params = sum_k p_k (w_global - d_k / tau_k)
            #            = w_global - sum_k p_k d_k / tau_k
            tau_eff = opt_state  # stashed per-round scalar
            d = jax.tree.map(lambda g, a: (g - a) * tau_eff, old.params, avg.params)
            new_params = jax.tree.map(lambda g, dd: g - dd, old.params, d)
            return NetState(new_params, avg.extra), opt_state

        super().__init__(dataset, task, config, mesh=mesh,
                         server_update=server_update, **kwargs)
        # wrap local_update so each client's output is pre-normalized by tau_k
        base_local = self.local_update
        cfg = config

        def normalized_local(rng, global_net, x, y, mask):
            net_k, metrics = base_local(rng, global_net, x, y, mask)
            # tau_k = real steps taken = epochs * (#batches with any data)
            real_batches = jnp.sum(jnp.any(mask > 0, axis=-1).astype(jnp.float32))
            tau_k = jnp.maximum(cfg.epochs * real_batches, 1.0)
            normed = jax.tree.map(
                lambda g, wk: g - (g - wk) / tau_k, global_net.params, net_k.params
            )
            return NetState(normed, net_k.extra), dict(metrics, tau=tau_k)

        self.local_update = normalized_local
        self.round_fn = self._build_round_fn()

    def run_round(self, round_idx: int):
        # tau_eff = sum_k p_k tau_k needs this round's client sizes; compute
        # host-side from the same pack (cheap, numpy) and stash it as the
        # "server opt state" consumed by server_update.
        cb = self._pack_round(round_idx)
        import numpy as np

        mask = np.asarray(jax.device_get(cb.mask))
        nsamp = np.asarray(jax.device_get(cb.num_samples))
        real_batches = (mask.sum(-1) > 0).sum(-1).astype(np.float32)
        tau = np.maximum(self.cfg.epochs * real_batches, 1.0)
        p = nsamp / max(nsamp.sum(), 1e-12)
        tau_eff = float((p * tau).sum())
        self.server_opt_state = jnp.asarray(tau_eff, jnp.float32)

        ids = self._sampled_ids(round_idx)
        self.rng, rk = jax.random.split(self.rng)
        self.net, self.server_opt_state, metrics = self.round_fn(
            rk, self.net, self.server_opt_state, cb,
            jnp.int32(round_idx), jnp.asarray(ids, jnp.int32),
        )
        return metrics
