"""Federated algorithms (L4).

Each module re-designs one reference algorithm family
(fedml_api/{distributed,standalone}/<algo>/) as host-driven rounds around ONE
jitted SPMD program. The reference's six-file pattern (API / Aggregator /
Trainer / ServerManager / ClientManager / message_define) collapses into a
config + round function: the managers' message loop is the jit boundary, the
aggregator is a weighted psum, the trainer is core.local.make_local_update.
"""

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.algorithms.fedavg_seq import FedAvgSeqAPI
from fedml_tpu.algorithms.fedopt import FedOptAPI
from fedml_tpu.algorithms.fedprox import FedProxAPI
