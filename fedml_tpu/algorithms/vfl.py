"""Classical vertical FL — feature-partitioned training (guest + hosts).

Reference: fedml_api/distributed/classical_vertical_fl/ — the guest holds the
labels and a slice of the features; each host holds another feature slice.
Per batch the hosts send their logit contributions to the guest
(host_trainer), the guest sums them, computes the loss, and returns each
host's gradient (guest_trainer.py:10-50+, vfl_api.py:16-42). Party models are
the guest/host towers of fedml_api/model/finance/vfl_models_standalone.py:1-72.

TPU re-design: the logit exchange is a function composition —
  logits = guest_tower(xg) + sum_h host_tower_h(x_h)
jax.grad differentiates through all parties at once; each party's params
update with its own optimizer. Host towers with identical architecture are
vmapped into one stacked pytree so H hosts cost one batched matmul on the
MXU. Cross-silo DCN placement: each party's tower pjits onto its own slice
and only the [bs, num_classes] logit tensors cross — same cut as the
reference, expressed as sharding instead of gRPC messages.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax


@dataclasses.dataclass(frozen=True)
class VFLConfig:
    epochs: int = 10
    batch_size: int = 64
    guest_lr: float = 0.05
    host_lr: float = 0.05
    seed: int = 0


class VFLAPI:
    """guest_module/host_module: feature-slice -> per-class logit contribution.

    data: x_guest [N, dg], x_hosts [H, N, dh], y [N] (binary or multi-class).
    """

    def __init__(self, guest_module, host_module, x_guest, x_hosts, y,
                 config: VFLConfig, num_classes: int = 2):
        self.cfg = config
        self.gm, self.hm = guest_module, host_module
        self.xg = np.asarray(x_guest, np.float32)
        self.xh = np.asarray(x_hosts, np.float32)
        self.y = np.asarray(y, np.int64)
        if len(self.y) < config.batch_size:
            raise ValueError(
                f"dataset ({len(self.y)} samples) smaller than one batch "
                f"({config.batch_size}): zero steps per epoch")
        self.H = self.xh.shape[0]
        self.num_classes = num_classes

        key = jax.random.PRNGKey(config.seed)
        kg, kh = jax.random.split(key)
        gvars = guest_module.init(kg, jnp.asarray(self.xg[: config.batch_size]),
                                  train=False)
        self.guest_params = gvars["params"]
        hvars = [
            host_module.init(jax.random.fold_in(kh, h),
                             jnp.asarray(self.xh[h, : config.batch_size]),
                             train=False)["params"]
            for h in range(self.H)
        ]
        # stack host towers -> one vmapped pytree (one batched matmul for all)
        self.host_params = jax.tree.map(lambda *xs: jnp.stack(xs), *hvars)
        self.gtx = optax.sgd(config.guest_lr)
        self.htx = optax.sgd(config.host_lr)
        self.gopt = self.gtx.init(self.guest_params)
        self.hopt = self.htx.init(self.host_params)
        self._step = jax.jit(self._build_step())

    def _build_step(self):
        gm, hm = self.gm, self.hm
        gtx, htx = self.gtx, self.htx

        def step(gp, hp, gopt, hopt, xg, xh, y):
            def loss_fn(gp_, hp_):
                glog = gm.apply({"params": gp_}, xg, train=True)
                hlog = jax.vmap(
                    lambda p, x: hm.apply({"params": p}, x, train=True)
                )(hp_, xh)  # [H, bs, C]
                logits = glog + jnp.sum(hlog, axis=0)
                l = jnp.mean(
                    optax.softmax_cross_entropy_with_integer_labels(logits, y)
                )
                acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
                return l, acc

            (l, acc), (gg, gh) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(gp, hp)
            ug, gopt = gtx.update(gg, gopt, gp)
            uh, hopt = htx.update(gh, hopt, hp)
            return (optax.apply_updates(gp, ug), optax.apply_updates(hp, uh),
                    gopt, hopt, l, acc)

        return step

    def train(self):
        cfg = self.cfg
        n = len(self.y)
        bs = cfg.batch_size
        rng = np.random.RandomState(cfg.seed)
        history = []
        for e in range(cfg.epochs):
            order = rng.permutation(n)
            losses, accs = [], []
            for i in range(0, n - bs + 1, bs):
                sel = order[i : i + bs]
                (self.guest_params, self.host_params, self.gopt, self.hopt,
                 l, acc) = self._step(
                    self.guest_params, self.host_params, self.gopt, self.hopt,
                    jnp.asarray(self.xg[sel]), jnp.asarray(self.xh[:, sel]),
                    jnp.asarray(self.y[sel]),
                )
                losses.append(float(l)); accs.append(float(acc))
            history.append({"epoch": e, "loss": float(np.mean(losses)),
                            "acc": float(np.mean(accs))})
        return history

    def evaluate(self, xg, xh, y):
        @jax.jit
        def ev(gp, hp):
            glog = self.gm.apply({"params": gp}, jnp.asarray(xg), train=False)
            hlog = jax.vmap(
                lambda p, x: self.hm.apply({"params": p}, x, train=False)
            )(hp, jnp.asarray(xh))
            logits = glog + jnp.sum(hlog, axis=0)
            return jnp.mean((jnp.argmax(logits, -1) == jnp.asarray(y)).astype(jnp.float32))

        return float(ev(self.guest_params, self.host_params))
