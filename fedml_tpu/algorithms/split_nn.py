"""SplitNN — split learning: model cut between client and server.

Reference: fedml_api/distributed/split_nn/ — client holds the lower layers,
server the upper; per batch the client sends activations + labels
(client.py:25-31), the server computes loss and returns activation gradients
(server.py:40-60), the client backprops and steps (client.py:33-35); clients
take turns in a ring (SplitNNAPI.py). Control crosses the process boundary
twice per batch — the latency-critical pattern (SURVEY.md §3.4).

TPU re-design: the activation/gradient exchange is NOT a message — the
composed function  loss = head(server_params, body(client_params_k, x))  is
differentiated end-to-end by jax.grad, and XLA schedules the cut as a single
fused program; on a two-stage mesh the same code pjits with the boundary
riding ICI. Semantics preserved exactly: per-client lower weights, shared
upper weights, updates per batch, clients in ring order (a lax.scan).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core.client_data import FederatedData, pack_clients
from fedml_tpu.core.sampling import sample_clients


@dataclasses.dataclass(frozen=True)
class SplitNNConfig:
    epochs: int = 1            # passes over the client ring
    batch_size: int = 32
    lr: float = 0.01
    client_num: int = 4
    comm_round: int = 1        # rounds driven by the cross-process runtime
    max_batches: int | None = None
    seed: int = 0


class SplitNNAPI:
    """client_module: x -> activations; server_module: activations -> logits."""

    def __init__(self, dataset: FederatedData, client_module, server_module,
                 config: SplitNNConfig):
        self.data = dataset
        self.cfg = config
        self.client_module = client_module
        self.server_module = server_module

        key = jax.random.PRNGKey(config.seed)
        k1, k2 = jax.random.split(key)
        x0 = jnp.asarray(dataset.train_x[: config.batch_size])
        cvars = client_module.init(k1, x0, train=False)
        acts0 = client_module.apply(cvars, x0, train=False)
        svars = server_module.init(k2, acts0, train=False)
        # per-client lower params (each client owns its cut), shared upper
        self.client_params = [cvars["params"] for _ in range(config.client_num)]
        self.server_params = svars["params"]
        self.ctx = optax.sgd(config.lr)
        self.stx = optax.sgd(config.lr)
        self.client_opt = [self.ctx.init(p) for p in self.client_params]
        self.server_opt = self.stx.init(self.server_params)
        self.rng = key
        self._fit_client = jax.jit(self._build_fit())

    def _build_fit(self):
        cm, sm = self.client_module, self.server_module
        ctx, stx = self.ctx, self.stx

        def batch_step(carry, batch):
            cp, sp, copt, sopt = carry
            x, y, m = batch

            def loss_fn(cp_, sp_):
                acts = cm.apply({"params": cp_}, x, train=True)
                logits = sm.apply({"params": sp_}, acts, train=True)
                per = optax.softmax_cross_entropy_with_integer_labels(logits, y)
                n = jnp.maximum(jnp.sum(m), 1.0)
                l = jnp.sum(per * m) / n
                correct = jnp.sum((jnp.argmax(logits, -1) == y) * m)
                return l, (jnp.sum(per * m), correct, jnp.sum(m))

            (l, aux), (gc, gs) = jax.value_and_grad(loss_fn, argnums=(0, 1),
                                                    has_aux=True)(cp, sp)
            has = jnp.sum(m) > 0
            upd_c, copt_n = ctx.update(gc, copt, cp)
            upd_s, sopt_n = stx.update(gs, sopt, sp)
            keep = lambda new, old: jax.tree.map(
                lambda a, b: jax.lax.select(has, a, b), new, old)
            cp = keep(optax.apply_updates(cp, upd_c), cp)
            sp = keep(optax.apply_updates(sp, upd_s), sp)
            copt = keep(copt_n, copt)
            sopt = keep(sopt_n, sopt)
            return (cp, sp, copt, sopt), jnp.stack(aux)

        def fit_client(cp, sp, copt, sopt, x, y, mask):
            (cp, sp, copt, sopt), aux = jax.lax.scan(
                batch_step, (cp, sp, copt, sopt), (x, y, mask)
            )
            return cp, sp, copt, sopt, aux.sum(0)

        return fit_client

    def train(self, rounds: int = 1):
        """Ring passes: client 0..K-1 each fit their shard against the shared
        server model in turn (the reference's turn-taking ring)."""
        cfg = self.cfg
        history = []
        for r in range(rounds):
            ids = sample_clients(r, self.data.num_clients, cfg.client_num, cfg.seed)
            cb = pack_clients(self.data, ids, cfg.batch_size,
                              max_batches=cfg.max_batches, seed=cfg.seed, round_idx=r)
            loss_sum = correct = count = 0.0
            for e in range(cfg.epochs):
                for k in range(cfg.client_num):
                    cp, sp, copt, sopt, aux = self._fit_client(
                        self.client_params[k], self.server_params,
                        self.client_opt[k], self.server_opt,
                        jnp.asarray(cb.x[k]), jnp.asarray(cb.y[k]),
                        jnp.asarray(cb.mask[k]),
                    )
                    self.client_params[k] = cp
                    self.server_params = sp
                    self.client_opt[k] = copt
                    self.server_opt = sopt
                    loss_sum += float(aux[0]); correct += float(aux[1]); count += float(aux[2])
            history.append({
                "round": r,
                "train_loss": loss_sum / max(count, 1.0),
                "train_acc": correct / max(count, 1.0),
            })
        return history

    def evaluate(self, client_idx: int = 0, batch_size: int = 256):
        from fedml_tpu.core.client_data import batch_global

        xb, yb, mb = (jnp.asarray(a) for a in batch_global(
            self.data.test_x, self.data.test_y, batch_size))
        cm, sm = self.client_module, self.server_module
        cp, sp = self.client_params[client_idx], self.server_params

        @jax.jit
        def ev(cp, sp):
            def body(acc, b):
                x, y, m = b
                logits = sm.apply({"params": sp},
                                  cm.apply({"params": cp}, x, train=False),
                                  train=False)
                correct = jnp.sum((jnp.argmax(logits, -1) == y) * m)
                return (acc[0] + correct, acc[1] + jnp.sum(m)), None
            (c, n), _ = jax.lax.scan(body, (0.0, 0.0), (xb, yb, mb))
            return c / jnp.maximum(n, 1.0)

        return float(ev(cp, sp))
