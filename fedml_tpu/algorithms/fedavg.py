"""FedAvg — the centerpiece algorithm, TPU-first.

Reference behavior being matched (fedml_api/distributed/fedavg/ and
fedml_api/standalone/fedavg/fedavg_api.py:40-115):
  per round: sample clients (FedAVGAggregator.client_sampling:89-97)
  -> each client: local SGD from the global weights (MyModelTrainer.py:19-49)
  -> server: sample-weighted average of all returned weights
     (FedAVGAggregator.aggregate:58-87)
  -> periodic eval on train/test (fedavg_api.py:117-180).

TPU re-design: one round = ONE jitted program.
  - standalone mode (1 device): clients are a vmapped leading axis — the
    reference's sequential client loop (fedavg_api.py:56-66) becomes a batched
    axis so every client's local SGD runs concurrently on the MXU.
  - distributed mode (mesh): the vmapped block is shard_mapped over the
    'clients' mesh axis; aggregation is a weighted psum over ICI
    (replacing the MPI upload/download round, SURVEY.md §2.8).
The host loop only samples ids, packs data, and logs — no message machinery.

Server update is a hook (identity for FedAvg) so FedOpt/FedNova/robust
variants reuse this engine (see fedopt.py etc.).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.client_data import (
    ClientBatch,
    FederatedData,
    IndexBatch,
    batch_global,
    pack_client_indices,
    pack_clients,
    pad_batches,
    pad_index_batches,
)
from fedml_tpu.core.client_source import (
    ClientDataSource,
    pack_clients_source,
)
from fedml_tpu.core.local import LocalSpec, Task, make_eval_fn, make_local_update
from fedml_tpu.core.partition_rules import tree_bytes as _tree_bytes
from fedml_tpu.core.pipeline import (
    InflightRing,
    Prefetcher,
    compile_concurrently,
)
from fedml_tpu.core.robust_agg import (
    DEFAULT_NORM_MULT,
    QuarantineLedger,
    gated_aggregate,
    make_robust_aggregator,
)
from fedml_tpu.core.sampling import prepare_sampling, sample_for
from fedml_tpu.obs import goodput as _goodput
from fedml_tpu.obs import perf_instrument as _perf
from fedml_tpu.obs.tracing import RoundTracer
from fedml_tpu.utils.tree import tree_weighted_mean

log = logging.getLogger("fedml_tpu.fedavg")


def _gather_rows(dev_x, dev_y, idx, mask):
    """Row gather for the device-resident data plane (single-device and
    per-shard SPMD paths share this). Padded slots (mask==0) carry idx 0, so
    gathered garbage rows are zeroed to match the host packer's zero padding
    bit-for-bit — models with mutable batch_stats (BatchNorm ignores the
    loss mask) see identical statistics on both planes."""
    shp = idx.shape
    flat = idx.reshape(-1)
    x = jnp.take(dev_x, flat, axis=0).reshape(shp + dev_x.shape[1:])
    y = jnp.take(dev_y, flat, axis=0).reshape(shp + dev_y.shape[1:])
    mx = mask.reshape(mask.shape + (1,) * (x.ndim - mask.ndim)) > 0
    my = mask.reshape(mask.shape + (1,) * (y.ndim - mask.ndim)) > 0
    return jnp.where(mx, x, jnp.zeros_like(x)), jnp.where(my, y, jnp.zeros_like(y))


def _sq_norm(tree):
    """Global squared L2 norm of a pytree (a scalar, inside jit)."""
    leaves = jax.tree.leaves(tree)
    return sum((jnp.vdot(v, v) for v in leaves), jnp.zeros(()))


def _update_norm(new_params, old_params):
    """||new - old|| over params — the single definition every telemetry
    path (standalone stats, mesh round fn, mesh block step) emits under
    the ``update_norm`` record key."""
    return jnp.sqrt(_sq_norm(jax.tree.map(jnp.subtract, new_params,
                                          old_params)))


def round_stats(old_net, new_net, nets, avg, nsamp) -> dict:
    """Telemetry round stats, computed IN-GRAPH so enabling them adds no
    device sync — they ride out with the metrics dict the round program
    already returns. (With telemetry off the round program is bit-identical
    to the pre-telemetry build: none of this is traced.)

    - ``update_norm``: ||new - old|| over params — the aggregate step size
      the server actually applied (post server_update / post hooks);
    - ``client_drift_mean``/``client_drift_max``: per-client ||net_k - avg||
      over the round's REAL clients (zero-sample padding excluded) — the
      non-IID dispersion statistic FedProx/FedNova papers reason about.
    """
    out = {"update_norm": _update_norm(new_net.params, old_net.params)}
    drift, real = _client_drift(nets.params, avg.params, nsamp)
    n_real = jnp.maximum(jnp.sum(real), 1.0)
    out["client_drift_mean"] = jnp.sum(drift * real) / n_real
    out["client_drift_max"] = jnp.max(drift * real)
    return out


def _client_drift(net_params, avg_params, nsamp):
    """[K] per-client ||net_k - avg|| over params plus the real-client mask
    (zero-sample padding excluded) — the ONE definition of client drift.
    ``round_stats`` reduces it locally; ``_mesh_drift_stats`` via
    psum/pmax, so the two stay in sync by construction."""
    drift_sq = sum(
        (jnp.sum((s - a) ** 2, axis=tuple(range(1, s.ndim)))
         for s, a in zip(jax.tree.leaves(net_params),
                         jax.tree.leaves(avg_params))),
        jnp.zeros(nsamp.shape),
    )
    drift = jnp.sqrt(drift_sq)
    real = (nsamp > 0).astype(drift.dtype)
    return drift, real


def agg_weights(nsamp, uniform: bool):
    """Aggregation weights: sample counts (FedAvg default) or, with
    ``uniform``, 1 per participating client / 0 for zero-sample padding —
    the pairing DP and size-weighted sampling require. Shared by the
    FedAvg and long-context engines."""
    if not uniform:
        return nsamp
    return jnp.where(nsamp > 0, jnp.ones_like(nsamp), jnp.zeros_like(nsamp))


def _mesh_drift_stats(net_params, avg_params, nsamp, axis) -> dict:
    """The client-drift half of ``round_stats`` under shard_map: each
    device computes its client shard's ||net_k - avg|| distances and the
    mean/max are psum/pmax-reduced over the mesh — so the mesh paths emit
    the SAME record keys as the standalone engine instead of only a
    partial stat set (``update_norm`` joins outside, where the updated
    params exist). Zero-sample padding is excluded exactly as in
    ``round_stats`` (shared ``_client_drift``)."""
    drift, real = _client_drift(net_params, avg_params, nsamp)
    n_real = jnp.maximum(jax.lax.psum(jnp.sum(real), axis), 1.0)
    return {
        "client_drift_mean": jax.lax.psum(jnp.sum(drift * real), axis)
        / n_real,
        "client_drift_max": jax.lax.pmax(jnp.max(drift * real), axis),
    }


def _shard_aggregate(nets, metrics, nsamp, axis):
    """Per-shard weighted aggregation under shard_map: weighted psum of the
    client nets (numerator+denominator over the mesh axis) and psum-med
    metric sums. Single source of truth for the sequential round fn AND the
    R-round block (their numerical identity is test-enforced)."""
    wsum = jax.tree.map(
        lambda t: jax.lax.psum(jnp.tensordot(nsamp, t, axes=([0], [0])), axis),
        nets,
    )
    den = jax.lax.psum(jnp.sum(nsamp), axis)
    avg = jax.tree.map(lambda t: t / jnp.maximum(den, 1e-12), wsum)
    msum = {k: jax.lax.psum(jnp.sum(v), axis) for k, v in metrics.items()}
    return avg, msum


def eval_subset(tx, ty, cfg: "FedAvgConfig", call_idx: int):
    """Apply the eval_max_samples subset policy (see FedAvgConfig).
    ``call_idx`` only matters in 'fresh' mode, where each eval resamples
    (reference FedAVGAggregator.py:99-107)."""
    if cfg.eval_max_samples is None or len(tx) <= cfg.eval_max_samples:
        return tx, ty
    if cfg.eval_subset_mode == "fresh":
        rs = np.random.RandomState((cfg.seed * 1_000_003 + call_idx) & 0x7FFFFFFF)
    elif cfg.eval_subset_mode == "fixed":
        rs = np.random.RandomState(cfg.seed)
    else:
        raise ValueError(f"eval_subset_mode={cfg.eval_subset_mode!r} "
                         "(expected 'fixed' or 'fresh')")
    sel = rs.choice(len(tx), cfg.eval_max_samples, replace=False)
    return tx[sel], ty[sel]


def _make_client_keys(seed: int):
    """Per-client training keys, derived inside jit: the same
    fold_in(fold_in(PRNGKey(seed), round), client_id) chain as the
    cross-process DistributedTrainer (distributed/fedavg/trainer.py)."""

    def client_keys(round_idx, ids):
        base = jax.random.fold_in(jax.random.PRNGKey(seed), round_idx)
        return jax.vmap(lambda i: jax.random.fold_in(base, i))(ids)

    return client_keys


@dataclasses.dataclass(frozen=True)
class FedAvgConfig:
    """Flag surface parity with the reference argparse
    (fedml_experiments/distributed/fedavg/main_fedavg.py:48-119)."""

    comm_round: int = 10
    client_num_in_total: int = 10
    client_num_per_round: int = 10
    epochs: int = 1
    batch_size: int = 32
    client_optimizer: str = "sgd"  # 'sgd' | 'adam'
    lr: float = 0.03
    wd: float = 0.0
    momentum: float = 0.0
    frequency_of_the_test: int = 5
    seed: int = 0
    max_batches: int | None = None  # static per-client batch budget (B)
    ci: bool = False  # truncate eval, reference --ci semantics
    eval_batch_size: int = 256
    # cap global eval to a random subset of the test set — the reference's
    # stackoverflow validation subset of 10k samples
    # (FedAVGAggregator._generate_validation_set, FedAVGAggregator.py:99-107);
    # None = full test set
    eval_max_samples: int | None = None
    # rematerialize per-batch forwards under autodiff (jax.checkpoint) in
    # the default LocalSpec — HBM for FLOPs on deep models/long sequences
    remat: bool = False
    # 'fixed': ONE seeded subset reused every eval (comparable curves across
    # rounds); 'fresh': a new subset each eval — the reference's exact
    # semantics (random.sample per call, FedAVGAggregator.py:99-107),
    # deterministic here via (seed, eval-call-index)
    eval_subset_mode: str = "fixed"
    # 'uniform' (reference parity): uniform without replacement +
    # sample-weighted aggregate. 'size_weighted': P(k) ∝ n_k + UNIFORM
    # aggregate (the FedAvg paper's alternative scheme — both are
    # unbiased; size-weighting concentrates rounds on data-rich clients)
    sampling: str = "uniform"
    # client-compute precision policy (docs/PERFORMANCE.md §Mixed
    # precision): 'bf16' runs the vmapped local fits on bfloat16 casts of
    # the f32 master weights (grad-scale-free; aggregation and the server
    # update stay f32); 'f32' (default) traces no casts — bit-identical
    # to the pre-policy engine (test-enforced). Applied through the
    # default LocalSpec in BOTH runtimes (FedAvgAPI and the cross-process
    # DistributedTrainer), and grafted onto an explicitly-passed
    # LocalSpec that left compute_dtype at its default.
    precision: str = "f32"
    # per-client eval inside train() (reference _local_test_on_all_clients,
    # fedavg_api.py:117-180: every eval round the CURRENT global model is
    # scored on EVERY client's own train and test split, aggregated by
    # sample count). 'auto': on exactly when the dataset has per-client
    # test splits (natural partitions — where the weighting differs from a
    # shared global test set); 'on'/'off' force it.
    local_test_on_all_clients: str = "auto"
    # scheduled client availability (chaos/churn.py ChurnTrace, or None):
    # every engine's per-round cohort draw restricts to the trace's
    # available clients for the round's window (core/sampling.sample_
    # available). Orthogonal to chaos faults — scheduled-offline is the
    # fleet's NORMAL state, not a failure. Recorded in the run header via
    # asdict like every other flag, so a run replays from its header.
    churn_trace: object | None = None


def resolve_local_spec(local_spec: LocalSpec | None,
                       cfg: FedAvgConfig) -> LocalSpec:
    """The engine's LocalSpec: the default build honors ``cfg.precision``;
    an explicitly-passed spec (fedprox's prox_spec, engine subclasses)
    that left ``compute_dtype`` at its default is grafted with it, so
    ``precision='bf16'`` composes with every engine instead of silently
    reverting to f32 — a spec that SET its own compute_dtype wins."""
    from fedml_tpu.core.local import COMPUTE_DTYPES

    prec = getattr(cfg, "precision", "f32")
    if prec not in COMPUTE_DTYPES:
        raise ValueError(f"precision={prec!r} (one of "
                         f"{sorted(COMPUTE_DTYPES)})")
    if local_spec is None:
        return LocalSpec(optimizer=make_client_optimizer(cfg),
                         epochs=cfg.epochs, remat=cfg.remat,
                         compute_dtype=prec)
    if COMPUTE_DTYPES[prec] is not None \
            and local_spec.compute_dtype in ("f32", "float32"):
        return dataclasses.replace(local_spec, compute_dtype=prec)
    return local_spec


def make_client_optimizer(cfg: FedAvgConfig) -> optax.GradientTransformation:
    """The reference builds torch SGD(momentum, wd) or Adam(wd, amsgrad)
    per client (MyModelTrainer.py:24-32)."""
    if cfg.client_optimizer == "sgd":
        tx = optax.sgd(cfg.lr, momentum=cfg.momentum or None)
    elif cfg.client_optimizer == "adam":
        tx = optax.adam(cfg.lr)
    else:
        raise ValueError(cfg.client_optimizer)
    if cfg.wd:
        tx = optax.chain(optax.add_decayed_weights(cfg.wd), tx)
    return tx


class FedAvgAPI:
    """Host-side round driver + jitted round program.

    ``mesh=None`` -> single-device (standalone simulation parity).
    ``mesh=Mesh(..., ('clients',))`` -> SPMD over devices (distributed parity).
    """

    def __init__(
        self,
        dataset: FederatedData,
        task: Task,
        config: FedAvgConfig,
        mesh: Mesh | None = None,
        server_update: Callable | None = None,
        server_opt_init: Callable | None = None,
        client_result_hook: Callable | None = None,
        post_aggregate_hook: Callable | None = None,
        local_spec: LocalSpec | None = None,
        device_data: bool = False,
        donate: bool = False,
        block_working_set: bool = False,
        uniform_avg: bool = False,
        bucket_batches: bool = False,
        telemetry=None,
        aggregator: str | Callable | None = None,
        aggregator_params: dict | None = None,
        sanitize: bool | float | None = None,
        adversary_plan=None,
        prefetch: int = 0,
        drain_lag: int = 2,
        shard_server_state: bool = False,
        partition_rules=None,
    ):
        self.data = dataset
        self.task = task
        self.cfg = config
        self.mesh = mesh
        # Streamed client state (core/client_source.py, docs/PERFORMANCE.md
        # §Streaming & cohort bucketing): a ClientDataSource keeps per-client
        # payload OUT of host memory — packing reads only the sampled
        # cohort's rows, so host RSS stays flat in population size (the
        # memwatch fed_host_rss_bytes gauge is the live evidence). The
        # device-resident planes require the full train set in HBM, which is
        # exactly what a streamed population cannot afford — refuse loudly.
        self._source = dataset if isinstance(dataset, ClientDataSource) \
            else None
        if self._source is not None and (device_data or block_working_set):
            raise ValueError(
                "device_data/block_working_set park the FULL train set on "
                "device — incompatible with a streamed ClientDataSource "
                "(pass the host-packed plane, or materialize the dataset)")
        if self._source is not None \
                and config.local_test_on_all_clients == "on":
            # 'auto' already degrades to the global test set (sources carry
            # no per-client test splits); a FORCED per-client eval would
            # die mid-run in evaluate_per_client — refuse at construction
            raise ValueError(
                "local_test_on_all_clients='on' iterates every client's "
                "own split — not available on a streamed ClientDataSource "
                "(use 'auto'/'off': the global test split is evaluated)")
        # Pipelined round execution (core/pipeline.py, docs/PERFORMANCE.md):
        # ``prefetch`` > 0 arms the double-buffered host->device prefetch —
        # a packer thread prepares round r+1's batch and issues its
        # device_put while round r executes, with up to ``prefetch`` batches
        # staged ahead (2 = classic double buffering). ``drain_lag`` is how
        # many rounds behind dispatch the metrics/quarantine drain trails,
        # so JAX async dispatch stays that deep. Bit-identical to the
        # synchronous driver (packing is a pure function of (seed, round);
        # test-enforced); prefetch=0 (default) changes nothing.
        if prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        if drain_lag < 0:
            raise ValueError(f"drain_lag must be >= 0, got {drain_lag}")
        self.prefetch = int(prefetch)
        self.drain_lag = int(drain_lag)
        # test/instrumentation hook: a callable observing the pipeline's
        # ("produced"/"got"/"drained", key) events — the overlap oracle
        self._pipe_on_event = None
        # Byzantine-robust aggregation (core/robust_agg.py). ``aggregator``
        # replaces the weighted mean with a robust estimator over the
        # stacked client updates: 'mean' | 'median' | 'trimmed_mean' |
        # 'krum' | 'multi_krum' | 'geometric_median', or a callable
        # ``(stacked, weights) -> (tree, info)``. ``sanitize`` fronts it
        # with the non-finite/norm-outlier gate (True = default norm_mult,
        # a float = that multiple, False = off; None = on iff an
        # aggregator is set). The default (None/None) keeps the round
        # program bit-identical to the plain weighted-mean build.
        if aggregator is None:
            self._robust_agg = None
        elif callable(aggregator):
            self._robust_agg = aggregator
        else:
            self._robust_agg = make_robust_aggregator(
                aggregator, n=config.client_num_per_round,
                **(aggregator_params or {}))
        if sanitize is None:
            sanitize = self._robust_agg is not None
        self._sanitize_mult = (
            None if sanitize is False
            else DEFAULT_NORM_MULT if sanitize is True else float(sanitize))
        self._needs_stacked = (self._robust_agg is not None
                               or self._sanitize_mult is not None)
        # per-round gate/aggregator verdicts (suspected/rejected ranks);
        # rank = stacked slot + 1, matching the loopback runtime's worker
        # ranks so the two ledgers are comparable entry-for-entry
        self.quarantine = QuarantineLedger()
        # model-space adversary injection (chaos/adversary.py): perturb the
        # stacked client nets INSIDE the jitted round program, per the
        # plan's (round-window, rank) schedule — the standalone twin of a
        # Byzantine client lying on the wire.
        self._adversary = None
        if adversary_plan is not None:
            if mesh is not None:
                raise ValueError(
                    "adversary_plan is a standalone-simulation feature "
                    "(single device); on a mesh run the cross-process "
                    "runtime with per-client adversaries instead")
            from fedml_tpu.chaos.adversary import make_in_graph_injector

            self._adversary = make_in_graph_injector(
                adversary_plan, config.client_num_per_round)
            self.adversary_plan = adversary_plan
        # telemetry: an obs.Telemetry bundle. None (default) keeps the round
        # program bit-identical to the untelemetered build — the stats below
        # are extra jit OUTPUTS, so the off path has zero overhead and the
        # on path adds no device sync beyond the metrics it already returns.
        self.telemetry = telemetry
        self._emit_stats = telemetry is not None and telemetry.round_stats
        # uniform_avg: aggregate with weight 1 per REAL client (0 for
        # zero-sample padding) instead of sample counts. DP-FedAvg needs
        # this: with sample-weighted averaging a clipped update's influence
        # is (n_k/Σn)·C, unbounded by C/m on unbalanced data, which
        # invalidates the sensitivity the DP noise is calibrated for.
        # size_weighted sampling FORCES it: P(k) ∝ n_k + uniform average
        # is the unbiased pairing (sampling twice — by probability AND by
        # weight — would double-count data-rich clients).
        self.uniform_avg = uniform_avg or config.sampling == "size_weighted"
        if getattr(config, "churn_trace", None) is not None \
                and mesh is not None:
            raise ValueError(
                "churn_trace varies the per-round cohort size, which breaks "
                "the mesh's static client-shard shapes — run churned "
                "cohorts standalone or through the cross-process runtime "
                "(rank-level scheduled availability)")
        self._client_sizes = prepare_sampling(config, dataset)
        self.rng = jax.random.PRNGKey(config.seed)

        # device-resident data plane: park the whole train set in HBM once;
        # each round ships only an IndexBatch (KBs) and the row gather runs
        # on device. Batches are bit-identical to the host packer's.
        # donate=True: the per-round program consumes the incoming net/opt
        # buffers (XLA writes outputs in place — no second copy of the model
        # in HBM). Opt-in because a caller may legitimately hold a reference
        # to api.net across rounds (e.g. comparing against round-0 weights);
        # the bench paths enable it. The R-round block fns always donate —
        # their contract never exposed intermediate nets.
        # block_working_set: do NOT park the whole train set in HBM. Each
        # run_rounds block instead uploads only the UNIQUE rows its sampled
        # clients touch (indices remapped into the compact array, row count
        # padded to a bucket so jit re-uses one compiled executable across
        # blocks). Batches stay bit-identical to the full-park plane
        # (test-enforced); what changes is transfer: ~R*K*samples rows
        # (tens of MB) per block instead of the full set (hundreds of MB)
        # up front — the difference between dying and finishing on a slow
        # host->device link. run_round falls back to the host-packed plane.
        self.donate = donate
        self.device_data = device_data
        self.block_working_set = block_working_set
        if block_working_set and not device_data:
            raise ValueError("block_working_set is a device_data mode "
                             "(pass device_data=True)")
        if device_data and not block_working_set:
            sh = NamedSharding(mesh, P()) if mesh is not None else None
            put = (lambda a: jax.device_put(a, sh)) if sh else jax.device_put
            self._dev_x = put(dataset.train_x)
            self._dev_y = put(dataset.train_y)

        # static per-client batch budget: fixed across rounds so the round
        # program compiles once (see SURVEY.md §7 "hard parts" (1)).
        # Streamed sources answer from size METADATA — no payload read.
        if self._source is not None:
            max_count = int(np.max(self._source.client_sizes))
        else:
            max_count = max(len(v) for v in dataset.train_idx_map.values())
        b_needed = int(np.ceil(max_count / config.batch_size))
        self.num_batches = min(config.max_batches or b_needed, b_needed)
        # bucket_batches: shrink each round's (or block's) common batch
        # depth to the max the SAMPLED clients actually need, rounded up a
        # small static ladder. Trailing all-masked batch slots are exact
        # state no-ops (local.py's has_data select; rng chains are
        # position-based) — so this is bit-exact while skipping their full
        # compute cost, at the price of one extra jit variant per bucket
        # (<=4). On size-skewed natural partitions (FEMNIST lognormal)
        # most rounds sample no near-maximal client, so the common depth
        # drops well below num_batches.
        self.bucket_batches = bucket_batches
        ladder = sorted({-(-self.num_batches // d) for d in (8, 4, 2, 1)})
        self._b_ladder = [b for b in ladder if b > 0]

        self.local_spec = resolve_local_spec(local_spec, config)
        self.local_update = make_local_update(task, self.local_spec)
        self.eval_fn = make_eval_fn(task)

        # server update hook: (net_old, net_avg, opt_state) -> (net_new, opt_state)
        self.server_update = server_update or (lambda old, avg, s: (avg, s))
        self.client_result_hook = client_result_hook  # (net_k, net_global, rng) -> net_k
        self.post_aggregate_hook = post_aggregate_hook  # (net, rng) -> net

        # init model
        self.rng, init_key = jax.random.split(self.rng)
        x_sample = jnp.asarray(
            self._source.init_batch(config.batch_size)
            if self._source is not None
            else dataset.train_x[: config.batch_size])
        self.net = task.init(init_key, x_sample)
        # federated TENSOR parallelism: a ('clients','model') mesh shards
        # each client's local fit over 'model' (Megatron specs, GSPMD
        # collectives) while 'clients' stays the manual FL axis — the
        # round program is shard_map(axis_names={'clients'}) so the model
        # axis remains auto and the compiler partitions the vmapped local
        # SGD. Params are placed TP-sharded up front.
        self._tp = mesh is not None and "model" in mesh.axis_names
        if self._tp:
            from fedml_tpu.parallel.tensor_parallel import shard_params

            params, self.tp_specs = shard_params(self.net.params, mesh)
            rep = NamedSharding(mesh, P())
            extra = jax.tree.map(lambda v: jax.device_put(v, rep),
                                 self.net.extra)
            self.net = self.net._replace(params=params, extra=extra)
        # Mesh-sharded server state (core/partition_rules.py,
        # docs/PERFORMANCE.md §Partitioned server state): the global model
        # + server optimizer state live PARTITIONED over the client mesh
        # axis per a regex partition-rule table; the round program
        # constrains the aggregate and the updated state to that layout, so
        # XLA reduce-scatters the weighted update sum into each device's
        # shard, runs the server update shard-locally, and all-gathers only
        # at the broadcast into the next round's local fits
        # (arXiv:2004.13336). Bitwise-identical to the replicated mesh path
        # (test-enforced: resharding moves bits, the psum aggregation math
        # is byte-for-byte the same program).
        self._sharded = bool(shard_server_state)
        self.partitioner = None
        self._agg_reshard = None
        if self._sharded:
            if mesh is None:
                raise ValueError("shard_server_state partitions the server "
                                 "plane over a mesh — pass mesh=")
            if self._tp:
                raise ValueError(
                    "shard_server_state composes with the pure 'clients' "
                    "mesh; a ('clients','model') TP mesh already shards "
                    "params over 'model'")
            from fedml_tpu.core.partition_rules import ServerStatePartitioner
            from fedml_tpu.core.robust_agg import COORDINATEWISE

            self.partitioner = ServerStatePartitioner(
                mesh, rules=partition_rules)
            self.net = self.partitioner.shard(self.net)
            # coordinate-wise estimators run shard-local after an
            # all-to-all to param-sharded stacked layout (specs derived
            # from the NET template so custom rule tables apply);
            # krum/geo-median keep the gathered path (COORDINATEWISE)
            if isinstance(aggregator, str) and aggregator in COORDINATEWISE:
                self._agg_reshard = self.partitioner.stacked_constrainer(
                    self.net)
        self.server_opt_state = server_opt_init(self.net.params) if server_opt_init else ()
        if self._sharded and server_opt_init is not None:
            # fedopt-style server optimizer state (momenta mirror the param
            # tree) shards by the same rule table — the Adam moments are
            # the 2x multiplier that makes sharding the server plane matter
            self.server_opt_state = self.partitioner.shard(
                self.server_opt_state)

        self.round_fn = self._build_round_fn()
        self._test_cache = None
        self.history: list[dict] = []
        # per-round pack/bucket accounting (docs/PERFORMANCE.md §Streaming
        # & cohort bucketing): written at pack time (possibly on the
        # prefetch thread — single-key dict writes are GIL-atomic), popped
        # into the telemetry round record at emit time. Bounded by the
        # prefetch depth.
        self._pack_stats: dict[int, dict] = {}
        # pack/compute/eval spans (SURVEY.md §5); with a tracing-enabled
        # Telemetry bundle, the same spans also feed the distributed
        # tracer's single-rank timeline (all host-side — nothing traced
        # here touches the jitted round program)
        self.tracer = RoundTracer(
            sink=telemetry.tracer if telemetry is not None else None)
        # server-plane sizing + per-round aggregation-bytes accounting
        # (obs/perf_instrument: fed_server_state_bytes{placement} /
        # fed_agg_bytes_total{mode}) — the metrics the sharded-vs-
        # replicated HBM claim is asserted on
        # sized component-by-component: one (net, opt) tuple would prefix
        # every leaf path with '0/'/'1/' and anchored custom rules would
        # resolve differently here than they did in shard()
        per_dev = (
            self.partitioner.bytes_per_device(self.net)
            + self.partitioner.bytes_per_device(self.server_opt_state)
            if self._sharded
            else _tree_bytes((self.net, self.server_opt_state)))
        self._state_placement = "sharded" if self._sharded else "replicated"
        self._agg_bytes_round = (_tree_bytes(self.net)
                                 * config.client_num_per_round)
        _perf.set_server_state_bytes(self._state_placement, per_dev)
        # rides every telemetry round record (report.py renders srv_B/mode)
        self._agg_record = {
            "mode": self._state_placement,
            "server_state_bytes_per_device": int(per_dev),
            "bytes_per_round": int(self._agg_bytes_round),
        }
        # mixed-precision runs stamp the policy on every round record
        # (report.py's `prec` column; absent = f32, so pre-policy logs
        # render unchanged)
        if self.local_spec.compute_dtype not in ("f32", "float32"):
            self._agg_record["prec"] = self.local_spec.compute_dtype

    # ------------------------------------------------------------------ round
    def _round_body(self, keys, net, server_opt_state, x, y, mask, nsamp,
                    hook_key, round_idx=None):
        """Per-shard body: vmap local fits, weighted-aggregate, server update.

        In distributed mode this runs inside shard_map: the leading client
        axis is this device's slice and the weighted mean is a psum over
        'clients'. In standalone mode axis_name is None and the weighted mean
        is local.
        """
        nets, metrics = jax.vmap(self.local_update, in_axes=(0, None, 0, 0, 0))(
            keys, net, x, y, mask
        )
        if self._adversary is not None and round_idx is not None:
            # Byzantine slots lie BEFORE any server-side defense sees them
            # (the clipping client_result_hook models the server's view).
            # The FULL NetState is perturbed — params AND extra — because
            # that is what a Byzantine client controls on the wire
            # (perturb_leaves hits every packed leaf), and the two
            # runtimes' gate verdicts must agree on models with
            # batch_stats, not just param-only ones.
            nets = self._adversary(nets, net, round_idx)
        if self.client_result_hook is not None:
            # x may be a pytree (FedNAS packs (train, val) streams) — take K
            # from the keys, which are always a flat [K, 2] array
            hkeys = jax.random.split(hook_key, keys.shape[0])
            nets = jax.vmap(lambda n, k: self.client_result_hook(n, net, k))(nets, hkeys)
        return nets, metrics, nsamp

    def _agg_weights(self, nsamp):
        return agg_weights(nsamp, self.uniform_avg)

    def _aggregate_and_update(self, net, server_opt_state, nets, metrics, nsamp, post_key):
        if self._needs_stacked:
            # gate -> estimator -> suspected merge -> all-rejected
            # fallback, via the ONE composition both runtimes share
            # (core/robust_agg.gated_aggregate). With a sharded server
            # state, coordinate-wise estimators get the partitioner's
            # stacked-layout constraint so their sorts run shard-local.
            avg, _, reasons = gated_aggregate(
                nets, net, self._agg_weights(nsamp),
                robust_fn=self._robust_agg, norm_mult=self._sanitize_mult,
                reshard_fn=self._agg_reshard)
        else:
            avg = tree_weighted_mean(nets, self._agg_weights(nsamp))
            reasons = None
        new_net, new_opt = self._update_from_aggregate(
            net, avg, server_opt_state, post_key)
        agg_metrics = {k: jnp.sum(v) for k, v in metrics.items()}
        if self._emit_stats:
            agg_metrics.update(round_stats(net, new_net, nets, avg, nsamp))
        if reasons is not None:
            # [K] reason codes ride out of the jit with the metrics and are
            # popped host-side into the quarantine ledger (never floated)
            agg_metrics["__quarantine"] = reasons
        return new_net, new_opt, agg_metrics

    def _update_from_aggregate(self, net, avg, server_opt_state, post_key):
        """constrain(aggregate) -> server_update -> post hook ->
        constrain(new state): the ONE server-side update composition every
        driver dispatches (stacked/robust, mesh per-round, sharded block).
        The sharded constraint points live only here, so the bitwise
        block ≡ per-round ≡ sharded parity contract cannot drift between
        copies; with a replicated state the constraints are skipped and
        this is plain server_update + hook. The avg constraint is the
        reduce-scatter point: the aggregate lands in rule-table layout, so
        the server update runs shard-local and the new state never
        materializes replicated (arXiv:2004.13336)."""
        if self._sharded:
            avg = self.partitioner.constrain(avg)
        new_net, new_opt = self.server_update(net, avg, server_opt_state)
        if self.post_aggregate_hook is not None:
            new_net = self.post_aggregate_hook(new_net, post_key)
        if self._sharded:
            new_net = self.partitioner.constrain(new_net)
            new_opt = self.partitioner.constrain(new_opt)
        return new_net, new_opt

    def _materialize(self, batch):
        """(x, y, mask, nsamp) from either data plane. IndexBatch -> on-device
        row gather from the HBM-resident train set (device_data mode);
        ClientBatch passes through."""
        if isinstance(batch, IndexBatch):
            x, y = _gather_rows(self._dev_x, self._dev_y, batch.idx, batch.mask)
            return x, y, batch.mask, batch.num_samples
        return batch.x, batch.y, batch.mask, batch.num_samples

    def _build_round_fn(self):
        cfg = self.cfg

        client_keys = _make_client_keys(cfg.seed)

        donate_args = (1, 2) if self.donate else ()

        if self.mesh is None:

            @partial(jax.jit, donate_argnums=donate_args)
            def round_fn(rng, net, server_opt_state, batch, round_idx, ids):
                x, y, mask, nsamp_in = self._materialize(batch)
                keys = client_keys(round_idx, ids)
                rng, kh, kp = jax.random.split(rng, 3)
                nets, metrics, nsamp = self._round_body(
                    keys, net, server_opt_state, x, y, mask, nsamp_in, kh,
                    round_idx=round_idx,
                )
                new_net, new_opt, m = self._aggregate_and_update(
                    net, server_opt_state, nets, metrics, nsamp, kp
                )
                return new_net, new_opt, m

            return round_fn

        mesh = self.mesh
        axis = mesh.axis_names[0]
        if axis == "model":
            raise ValueError("the first mesh axis is the client axis; put "
                             "'model' second: Mesh(..., ('clients','model'))")
        # clients shard over the FIRST axis only; a 'model' axis (federated
        # TP) is left auto for GSPMD and contributes no client slots
        ndev = int(mesh.shape[axis])
        self._smap_kw = (dict(mesh=mesh, axis_names={axis}) if self._tp
                         else dict(mesh=mesh))
        if cfg.client_num_per_round % ndev != 0:
            raise ValueError(
                f"client_num_per_round={cfg.client_num_per_round} must be a "
                f"multiple of the '{axis}' mesh size {ndev} (pad with "
                "zero-weight clients)"
            )

        def shard_fits(keys, net, x, y, mask, hook_key):
            # keys/x/y/mask have this device's client slice. The global
            # net enters replicated but the scan carry becomes device-varying
            # after the first local step — mark it varying up front (vma rule).
            net = jax.tree.map(lambda v: jax.lax.pcast(v, axis, to="varying"), net)
            nets, metrics = jax.vmap(self.local_update, in_axes=(0, None, 0, 0, 0))(
                keys, net, x, y, mask
            )
            if self.client_result_hook is not None:
                hkeys = jax.random.split(hook_key, keys.shape[0])
                nets = jax.vmap(lambda n, k: self.client_result_hook(n, net, k))(nets, hkeys)
            return nets, metrics

        def shard_body(keys, net, x, y, mask, nsamp, hook_key):
            nets, metrics = shard_fits(keys, net, x, y, mask, hook_key)
            avg, msum = _shard_aggregate(nets, metrics,
                                         self._agg_weights(nsamp), axis)
            if self._emit_stats:
                # full round_stats on the mesh too (the drift half lives
                # here, where the per-client nets exist; update_norm joins
                # after the server update) — replicated and sharded runs
                # emit identical record keys, and so do mesh vs standalone
                msum = dict(msum)
                msum.update(_mesh_drift_stats(nets.params, avg.params,
                                              nsamp, axis))
            return avg, msum

        smapped = jax.shard_map(
            shard_body,
            in_specs=(P(axis), P(), P(axis), P(axis), P(axis), P(axis), P()),
            out_specs=(P(), P()),
            **self._smap_kw,
        )

        def shard_body_devdata(keys, net, dev_x, dev_y, idx, mask, nsamp, hook_key):
            # device-resident plane under SPMD: the train set is replicated,
            # the index block is sharded -> each device gathers its own
            # clients' rows locally (no collective)
            x, y = _gather_rows(dev_x, dev_y, idx, mask)
            return shard_body(keys, net, x, y, mask, nsamp, hook_key)

        smapped_dd = jax.shard_map(
            shard_body_devdata,
            in_specs=(P(axis), P(), P(), P(), P(axis), P(axis), P(axis), P()),
            out_specs=(P(), P()),
            **self._smap_kw,
        )
        # the sharded block driver re-dispatches this per-round body from
        # an outer scan (_build_block_fn) — keep a handle
        self._smapped_dd = smapped_dd

        if self._needs_stacked:
            # Robust aggregation needs the FULL stacked client set (sorts,
            # pairwise distances — not psum-able). Run only the local fits
            # under shard_map (the same shard_fits the weighted-mean path
            # aggregates in-shard; out_specs P(axis): each device returns
            # its client shard) and aggregate in the enclosing jit, where
            # GSPMD handles the gather the estimator implies.
            smapped_fits = jax.shard_map(
                shard_fits,
                in_specs=(P(axis), P(), P(axis), P(axis), P(axis), P()),
                out_specs=(P(axis), P(axis)),
                **self._smap_kw,
            )

            def shard_fits_devdata(keys, net, dev_x, dev_y, idx, mask,
                                   hook_key):
                x, y = _gather_rows(dev_x, dev_y, idx, mask)
                return shard_fits(keys, net, x, y, mask, hook_key)

            smapped_fits_dd = jax.shard_map(
                shard_fits_devdata,
                in_specs=(P(axis), P(), P(), P(), P(axis), P(axis), P()),
                out_specs=(P(axis), P(axis)),
                **self._smap_kw,
            )

            @partial(jax.jit, donate_argnums=donate_args)
            def robust_round_fn(rng, net, server_opt_state, batch, round_idx,
                                ids):
                keys = client_keys(round_idx, ids)
                rng, kh, kp = jax.random.split(rng, 3)
                if isinstance(batch, IndexBatch):
                    nets, metrics = smapped_fits_dd(
                        keys, net, self._dev_x, self._dev_y,
                        batch.idx, batch.mask, kh)
                    nsamp = batch.num_samples
                else:
                    nets, metrics = smapped_fits(
                        keys, net, batch.x, batch.y, batch.mask, kh)
                    nsamp = batch.num_samples
                return self._aggregate_and_update(
                    net, server_opt_state, nets, metrics, nsamp, kp)

            return robust_round_fn

        @partial(jax.jit, donate_argnums=donate_args)
        def round_fn(rng, net, server_opt_state, batch, round_idx, ids):
            keys = client_keys(round_idx, ids)
            rng, kh, kp = jax.random.split(rng, 3)
            if isinstance(batch, IndexBatch):
                avg, metrics = smapped_dd(
                    keys, net, self._dev_x, self._dev_y,
                    batch.idx, batch.mask, batch.num_samples, kh,
                )
            else:
                avg, metrics = smapped(
                    keys, net, batch.x, batch.y, batch.mask, batch.num_samples, kh
                )
            new_net, new_opt = self._update_from_aggregate(
                net, avg, server_opt_state, kp)
            if self._emit_stats:
                # the drift half rode out of shard_body; the update norm
                # joins here, where the post-update params exist (on a
                # sharded state GSPMD psums the shard-local partials, so
                # the record still carries the FULL norm)
                metrics = dict(metrics)
                metrics["update_norm"] = _update_norm(new_net.params,
                                                      net.params)
            return new_net, new_opt, metrics

        return round_fn

    # ------------------------------------------------------------------ data
    def _pack_round_host(self, round_idx: int) -> ClientBatch:
        """Always the dense host-packed ClientBatch, regardless of
        device_data — for engines that consume .x/.y directly (FedDF's
        distillation batches, TurboAggregate's share encoding, affinity).
        Delegates through the explicit ``device_data`` argument (never a
        mutate-self-and-restore toggle: the prefetch thread packs
        concurrently with the driver, and a shared flag flip would race)."""
        return self._pack_round(round_idx, device_data=False)

    def _bucketed_B(self, b_needed: int) -> int:
        """Smallest ladder bucket covering ``b_needed`` (ladder tops out at
        num_batches, so the result never exceeds the static budget)."""
        for b in self._b_ladder:
            if b >= b_needed:
                return b
        return self.num_batches

    def _record_pack_stats(self, round_idx: int, b_needed: int,
                           batch) -> None:
        """One round's pack/bucket accounting: the dispatched batch depth
        (the ladder bucket when bucket_batches is on), the natural depth
        the cohort needed, the fraction of batch slots that are pure
        padding, and the packed host bytes — the numbers that show whether
        a skewed population is paying for its largest client every round."""
        if self.telemetry is None:
            return  # nobody will pop it — don't grow the dict forever
        if isinstance(batch, IndexBatch):
            K, B = batch.idx.shape[0], batch.idx.shape[1]
            nbytes = batch.idx.nbytes + batch.mask.nbytes
        else:
            K, B = batch.x.shape[0], batch.x.shape[1]
            nbytes = batch.x.nbytes + batch.y.nbytes + batch.mask.nbytes
        used = float(np.sum(np.ceil(
            np.asarray(batch.num_samples) / self.cfg.batch_size)))
        slots = float(K * B)
        self._pack_stats[round_idx] = {
            "bucket_B": int(B), "b_needed": int(b_needed),
            "budget_B": int(self.num_batches),
            "pad_frac": round(1.0 - used / slots, 4) if slots else 0.0,
            "bytes": int(nbytes),
        }

    def _pack_extra(self, round_idx: int) -> dict:
        """The optional ``pack`` block a telemetry round record carries —
        absent when nothing was recorded (engines that override packing)."""
        ps = self._pack_stats.pop(round_idx, None)
        return {"pack": ps} if ps else {}

    def _pack_round_indices_host(self, round_idx: int,
                                 pad_to: int | None = None) -> IndexBatch:
        """Host-side padded IndexBatch (no device placement) — shared by the
        per-round path and the R-round block packer. ``pad_to`` is the
        common batch depth: default the static num_batches; the bucketed
        paths pass their (smaller) bucket; 0 = natural depth (no pad)."""
        cfg = self.cfg
        ids = self._sampled_ids(round_idx)
        ib = pack_client_indices(
            self.data, ids, cfg.batch_size, max_batches=self.num_batches,
            seed=cfg.seed, round_idx=round_idx,
        )
        b_needed = ib.idx.shape[1]
        if pad_to is None:
            pad_to = (self._bucketed_B(b_needed)
                      if self.bucket_batches else self.num_batches)
            ib = pad_index_batches(ib, pad_to)
            self._record_pack_stats(round_idx, b_needed, ib)
            return ib
        return pad_index_batches(ib, pad_to)

    def _shard_round_batch(self, batch):
        """Mesh placement of one round's batch: every leaf client-sharded
        over the first mesh axis (no-op off-mesh). One definition shared by
        the round packer, the prefetch thread, and warmup lowering."""
        if self.mesh is None:
            return batch
        sh = NamedSharding(self.mesh, P(self.mesh.axis_names[0]))
        return jax.tree.map(lambda v: jax.device_put(v, sh), batch)

    def _pack_round(self, round_idx: int, device_data: bool | None = None):
        """One round's batch on the engine's data plane. ``device_data``
        overrides the engine default explicitly (None = self.device_data)
        so callers needing the dense host pack — and the prefetch thread —
        never toggle shared state."""
        cfg = self.cfg
        if device_data is None:
            device_data = self.device_data
        if device_data and not self.block_working_set:
            ib = self._pack_round_indices_host(round_idx)
            return self._shard_round_batch(ib)
        ids = self._sampled_ids(round_idx)
        if self._source is not None:
            # streamed plane: only the sampled cohort's rows are read
            cb = pack_clients_source(
                self._source, ids, cfg.batch_size,
                max_batches=self.num_batches, seed=cfg.seed,
                round_idx=round_idx)
        else:
            cb = pack_clients(
                self.data, ids, cfg.batch_size, max_batches=self.num_batches,
                seed=cfg.seed, round_idx=round_idx,
            )
        # fixed B across rounds -> single compilation (or, with
        # bucket_batches, the round's ladder bucket -> <=4 compilations)
        b_needed = cb.num_batches
        cb = pad_batches(cb, self._bucketed_B(b_needed)
                         if self.bucket_batches else self.num_batches)
        self._record_pack_stats(round_idx, b_needed, cb)
        return self._shard_round_batch(cb)

    def _sampled_ids(self, round_idx: int):
        return sample_for(self.cfg, round_idx, self._client_sizes)

    # ----------------------------------------------------------- round block
    def _build_block_fn(self):
        """R rounds as ONE compiled program: lax.scan over rounds, the whole
        block's index batches resident on device. Removes per-round host
        dispatch + transfer entirely — for small models (the flagship
        FedAvg-CNN) dispatch dominates, so this is the main throughput lever.
        Client keys are the same fold_in(fold_in(seed, round), client) chain
        as run_round; the per-round hook keys (kh, kp) are PRE-DERIVED with
        the exact split chain sequential run_round calls would draw
        (self.rng -> rk per round, rk -> (_, kh, kp)) and scanned with the
        rounds — so a block is bit-identical to the sequential path even for
        hooked engines (clipping client_result_hook, DP post_aggregate_hook;
        tested). With a mesh, the scan runs INSIDE shard_map: every
        device scans its client shard for R rounds and aggregation is a
        weighted psum per step — the whole block is one SPMD program and the
        host is out of the loop entirely (the v4-32 north-star path). The
        post-aggregate hook runs right after the server update inside the
        shard; its key is replicated, so the hook's draw (e.g. DP noise) is
        identical on every device and the net stays replicated — the same
        values the per-round path computes outside shard_map."""
        client_keys = _make_client_keys(self.cfg.seed)

        def derive_hook_keys(rng, n_rounds):
            """The sequential key stream, precomputed: run_round does
            ``self.rng, rk = split(self.rng)`` then ``_, kh, kp =
            split(rk, 3)`` — reproduce exactly that chain for each round in
            the block so hooked engines keep bit-exact key parity."""
            def kstep(r, _):
                r, rk = jax.random.split(r)
                _, kh, kp = jax.random.split(rk, 3)
                return r, (kh, kp)

            return jax.lax.scan(kstep, rng, None, length=n_rounds)

        if self.mesh is None:

            def make_step(dev_x, dev_y):
                def step(carry, inp):
                    net, opt = carry
                    idx_r, mask_r, nsamp_r, ids_r, r, kh, kp = inp
                    keys = client_keys(r, ids_r)
                    x, y = _gather_rows(dev_x, dev_y, idx_r, mask_r)
                    nets, metrics, _ = self._round_body(
                        keys, net, opt, x, y, mask_r, nsamp_r, kh,
                        round_idx=r,
                    )
                    net, opt, m = self._aggregate_and_update(
                        net, opt, nets, metrics, nsamp_r, kp
                    )
                    return (net, opt), m

                return step

            @partial(jax.jit, donate_argnums=(0, 1, 2))
            def block_fn(rng, net, opt, dev_x, dev_y, idx, mask, nsamp, ids,
                         round_idxs):
                rng, (khs, kps) = derive_hook_keys(rng, idx.shape[0])
                (net, opt), ms = jax.lax.scan(
                    make_step(dev_x, dev_y), (net, opt),
                    (idx, mask, nsamp, ids, round_idxs, khs, kps)
                )
                return rng, net, opt, ms

            return block_fn

        mesh = self.mesh
        axis = mesh.axis_names[0]
        server_update = self.server_update
        local_update = self.local_update

        if self._sharded:
            # Sharded block: the replicated block scans INSIDE one
            # shard_map, where state is per-device-manual and a partitioned
            # carry cannot be expressed. Here the scan runs in the OUTER
            # jit instead, re-dispatching the per-round shard_mapped body
            # each step — same per-element ops, so block ≡ per-round stays
            # bitwise — with the carry constrained to the rule-table layout
            # (server update shard-local; net all-gathered at each step's
            # shard_map broadcast boundary, exactly like the per-round fn).
            smapped_dd = self._smapped_dd

            @partial(jax.jit, donate_argnums=(1, 2))
            def sharded_block_fn(rng, net, opt, dev_x, dev_y, idx, mask,
                                 nsamp, ids, round_idxs):
                rng, (khs, kps) = derive_hook_keys(rng, idx.shape[0])

                def step(carry, inp):
                    net, opt = carry
                    idx_r, mask_r, nsamp_r, ids_r, r, kh, kp = inp
                    keys = client_keys(r, ids_r)
                    avg, msum = smapped_dd(keys, net, dev_x, dev_y,
                                           idx_r, mask_r, nsamp_r, kh)
                    old_net = net
                    net, opt = self._update_from_aggregate(net, avg, opt, kp)
                    if self._emit_stats:
                        msum = dict(msum)
                        msum["update_norm"] = _update_norm(net.params,
                                                           old_net.params)
                    return (net, opt), msum

                (net, opt), ms = jax.lax.scan(
                    step, (net, opt),
                    (idx, mask, nsamp, ids, round_idxs, khs, kps))
                return rng, net, opt, ms

            return sharded_block_fn

        def shard_block(net, opt, dev_x, dev_y, idx, mask, nsamp, ids, rounds,
                        khs, kps):
            # idx/mask/nsamp/ids carry this device's client slice on axis 1:
            # [R, K/n, ...]; net/opt/rounds/khs/kps are replicated
            def step(carry, inp):
                net, opt = carry
                idx_r, mask_r, nsamp_r, ids_r, r, kh, kp = inp
                keys = client_keys(r, ids_r)
                x, y = _gather_rows(dev_x, dev_y, idx_r, mask_r)
                net_v = jax.tree.map(
                    lambda v: jax.lax.pcast(v, axis, to="varying"), net)
                nets, metrics = jax.vmap(
                    local_update, in_axes=(0, None, 0, 0, 0))(
                        keys, net_v, x, y, mask_r)
                if self.client_result_hook is not None:
                    # same per-device split count as the per-round mesh
                    # path's shard_body: block ≡ run_round on this mesh
                    hkeys = jax.random.split(kh, keys.shape[0])
                    nets = jax.vmap(
                        lambda n, k: self.client_result_hook(n, net_v, k))(
                            nets, hkeys)
                avg, msum = _shard_aggregate(
                    nets, metrics, self._agg_weights(nsamp_r), axis)
                old_net = net
                # self._sharded is always False here (the sharded block
                # scans in the outer jit above), so this is plain
                # server_update + hook — but through the ONE composition
                net, opt = self._update_from_aggregate(net, avg, opt, kp)
                if self._emit_stats:
                    # full round_stats, like shard_body: drift from the
                    # in-shard nets, update norm from the post-update params
                    msum = dict(msum)
                    msum.update(_mesh_drift_stats(nets.params, avg.params,
                                                  nsamp_r, axis))
                    msum["update_norm"] = _update_norm(net.params,
                                                       old_net.params)
                return (net, opt), msum

            (net, opt), ms = jax.lax.scan(
                step, (net, opt), (idx, mask, nsamp, ids, rounds, khs, kps))
            return net, opt, ms

        smapped_block = jax.shard_map(
            shard_block,
            in_specs=(P(), P(), P(), P(), P(None, axis), P(None, axis),
                      P(None, axis), P(None, axis), P(), P(), P()),
            out_specs=(P(), P(), P()),
            **self._smap_kw,
        )

        @partial(jax.jit, donate_argnums=(1, 2))
        def block_fn(rng, net, opt, dev_x, dev_y, idx, mask, nsamp, ids,
                     round_idxs):
            rng, (khs, kps) = derive_hook_keys(rng, idx.shape[0])
            net, opt, ms = smapped_block(net, opt, dev_x, dev_y,
                                         idx, mask, nsamp, ids, round_idxs,
                                         khs, kps)
            return rng, net, opt, ms

        return block_fn

    def run_rounds(self, start_round: int, num_rounds: int):
        """Run ``num_rounds`` rounds as one device-side program (requires
        ``device_data=True``; works single-chip and over a client mesh).
        Returns per-round metrics stacked along axis 0."""
        if not self.device_data:
            raise ValueError("run_rounds needs device_data=True")
        if getattr(self.cfg, "churn_trace", None) is not None:
            raise ValueError(
                "churn_trace varies the per-round cohort size — the scanned "
                "round block needs one static K across its rounds; drive "
                "churned runs through train()/run_round (per-round dispatch)")
        if self.mesh is not None and self._needs_stacked:
            # the mesh block scans INSIDE shard_map, where a robust
            # aggregator's full-stack sorts/distances cannot run — degrade
            # to per-round dispatch (run_round's fits-only mesh path),
            # returning the same stacked-metrics contract
            rounds = [self.run_round(r)
                      for r in range(start_round, start_round + num_rounds)]
            return {k: jnp.stack([m[k] for m in rounds])
                    for k in rounds[0]}
        if not hasattr(self, "_block_fn"):
            self._block_fn = self._build_block_fn()
        if self.telemetry is not None:
            t_wall = time.perf_counter()
            spans_before = dict(self.tracer.rounds[-1])
            if self.telemetry.tracer is not None:
                # one trace per scanned block (its spans are amortized
                # over the R rounds, like the 'block' event record)
                self.telemetry.tracer.begin_round(start_round)

        with self.tracer.span("pack"):
            packed = self._pack_block_host(start_round, num_rounds)
            ids_l, placed = self._place_block(packed)
        with self.tracer.span("round"):
            ms = self._dispatch_block(placed)
        ms = self._drain_quarantine_block(ms, start_round, ids_l)
        if self.telemetry is not None:
            # per-round records from the scanned block's stacked metrics
            # (one sync for the whole block); the block's host spans
            # (pack + one dispatch) ride on a separate 'block' event since
            # they are amortized over the R rounds, not per-round
            wait = self._goodput_wait(ms)
            self._emit_block_records(start_round, num_rounds, ids_l, ms,
                                     spans=self._span_delta(spans_before),
                                     wall_s=time.perf_counter() - t_wall,
                                     compute_wait_s=wait)
            if self.telemetry.tracer is not None:
                self.telemetry.tracer.finish_round()  # see run_round
        return ms

    def _pack_block_host(self, start_round: int, num_rounds: int):
        """Host-side pack of one R-round block — a pure function of
        (seed, rounds), safe on the prefetch thread. Returns
        (rounds, ids_l, idx_stack, mask_stack, ns_stack), all numpy."""
        ids_l, idx_l, mask_l, ns_l = [], [], [], []
        # pack at natural depth first, then pad every round to the BLOCK's
        # common depth — the ladder bucket when bucket_batches is on (the
        # scan needs one B; jit caches per bucket, <=4 variants), the
        # static budget otherwise. One path, so the per-round pack stats
        # are recorded identically in both modes.
        for r in range(start_round, start_round + num_rounds):
            # host-side pack: the stacked block is device_put ONCE in
            # _place_block (per-round device_puts would round-trip, and on
            # multi-host meshes a sharded array can't return via np.asarray)
            ib = self._pack_round_indices_host(r, pad_to=0)
            ids_l.append(np.asarray(self._sampled_ids(r), np.int32))
            idx_l.append(ib.idx)
            mask_l.append(ib.mask)
            ns_l.append(ib.num_samples)
        B = (self._bucketed_B(max(a.shape[1] for a in idx_l))
             if self.bucket_batches else self.num_batches)
        for i, (ix, mk, ns) in enumerate(zip(idx_l, mask_l, ns_l)):
            b_needed = ix.shape[1]
            ib = pad_index_batches(
                IndexBatch(idx=ix, mask=mk, num_samples=ns), B)
            idx_l[i], mask_l[i] = ib.idx, ib.mask
            self._record_pack_stats(start_round + i, b_needed, ib)
        rounds = np.arange(start_round, start_round + num_rounds,
                           dtype=np.int32)
        return rounds, ids_l, np.stack(idx_l), np.stack(mask_l), np.stack(ns_l)

    def _place_block(self, packed):
        """Device placement for a packed block: working-set compaction (its
        grow-only caches are touched by exactly one placer at a time — the
        prefetch thread in pipelined mode, the driver otherwise) plus the
        block's H2D transfers. Returns (ids_l, dispatch args)."""
        rounds, ids_l, idx_stack, mask_stack, ns_stack = packed
        if self.block_working_set:
            idx_stack, dev_x, dev_y = self._compact_block_rows(idx_stack)
        else:
            dev_x, dev_y = self._dev_x, self._dev_y
        blocks = [idx_stack, mask_stack, ns_stack, np.stack(ids_l)]
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P(None, self.mesh.axis_names[0]))
            blocks = [jax.device_put(b, sh) for b in blocks]
        blocks = [jnp.asarray(b) for b in blocks]
        return ids_l, (dev_x, dev_y, blocks, jnp.asarray(rounds))

    def _dispatch_block(self, placed):
        dev_x, dev_y, blocks, rounds = placed
        self.rng, self.net, self.server_opt_state, ms = self._block_fn(
            self.rng, self.net, self.server_opt_state, dev_x, dev_y,
            *blocks, rounds,
        )
        _perf.record_agg_bytes(self._state_placement,
                               self._agg_bytes_round * rounds.shape[0])
        return ms

    def _emit_block_records(self, start_round: int, num_rounds: int, ids_l,
                            ms, spans=None, pipeline=None, wall_s=None,
                            compute_wait_s: float = 0.0,
                            pipelined: bool = False):
        ms_host = {k: np.asarray(v) for k, v in ms.items()}
        self.telemetry.events.emit(
            "block", start=int(start_round), rounds=int(num_rounds),
            spans=spans or {},
            **({"pipeline": pipeline} if pipeline else {}))
        # the block's wall/spans/wait are amortized over its R rounds so
        # each per-round record carries a comparable goodput block (the
        # block variant's cost analysis covers R rounds -> cost_rounds=R)
        R = max(int(num_rounds), 1)
        per_spans = {k: v / R for k, v in (spans or {}).items()}
        for i in range(num_rounds):
            pack_extra = self._pack_extra(start_round + i)
            gp = ({} if wall_s is None else self._goodput_extra(
                wall_s / R, per_spans, pipelined=pipelined,
                compute_wait_s=compute_wait_s / R, pack_extra=pack_extra,
                block_rounds=R))
            self.telemetry.emit_round(
                start_round + i, clients=ids_l[i].tolist(),
                metrics={k: float(v[i]) for k, v in ms_host.items()},
                block=True, agg=self._agg_record,
                **gp,
                **pack_extra,
                **self._quarantine_extra(start_round + i),
                **self._privacy_extra())

    def _drain_block_entry(self, start_round: int, entry):
        """Block analogue of _drain_round_entry: the only sync, one block
        behind dispatch; ledger + telemetry flushed in block order."""
        num_rounds, ids_l, spans, pipeline, ms = entry
        wall = wait = None
        if self.telemetry is not None:
            wait = self._goodput_wait(ms)
            wall = self._goodput_interval()
        ms = self._drain_quarantine_block(ms, start_round, ids_l)
        ms_host = {k: np.asarray(v) for k, v in ms.items()}
        if self.telemetry is not None:
            self._emit_block_records(start_round, num_rounds, ids_l, ms_host,
                                     spans=spans, pipeline=pipeline,
                                     wall_s=wall, compute_wait_s=wait or 0.0,
                                     pipelined=True)
        return start_round, ms_host

    def run_blocks_pipelined(self, start_round: int, num_blocks: int,
                             block_rounds: int):
        """``num_blocks`` scanned R-round blocks with block-level prefetch:
        block b+1's host pack + H2D run on the packer thread while block
        b's program executes; the metrics drain trails one block behind.
        Bit-identical to the same sequence of run_rounds calls
        (test-enforced). Returns drained [(start_round, host metrics)]."""
        self._warn_tracer_unsupported()
        if not self.device_data:
            raise ValueError("run_blocks_pipelined needs device_data=True")
        if self.mesh is not None and self._needs_stacked:
            # the robust mesh block already degrades to per-round dispatch
            # (see run_rounds) — pipeline per round instead of per block
            out = []
            for b in range(num_blocks):
                out.extend(self.run_pipelined(
                    start_round + b * block_rounds, block_rounds))
            return out
        if not hasattr(self, "_block_fn"):
            self._block_fn = self._build_block_fn()

        def produce(s):
            t0 = time.perf_counter()
            packed = self._pack_block_host(s, block_rounds)
            t1 = time.perf_counter()
            ids_l, placed = self._place_block(packed)
            h2d = time.perf_counter() - t1
            _perf.record_span("prefetch_pack", t1 - t0)
            _perf.record_h2d(h2d)
            return ids_l, placed, {"prefetch_pack": t1 - t0, "h2d": h2d}

        starts = [start_round + b * block_rounds for b in range(num_blocks)]
        pf = Prefetcher(produce, starts, depth=max(1, self.prefetch),
                        on_event=self._pipe_on_event)
        # block units are R rounds each, so the lag is capped at one block
        # — but drain_lag=0 (the documented "correlate api.net with its
        # metrics" escape hatch) must still mean drain-immediately here
        ring = InflightRing(min(self.drain_lag, 1), self._drain_block_entry,
                            on_event=self._pipe_on_event)
        self._gp_prev_drain_t = time.perf_counter()
        out = []
        try:
            for s in starts:
                (ids_l, placed, spans), stall = pf.get(s)
                with self.tracer.span("round"):
                    ms = self._dispatch_block(placed)
                spans = dict(spans, prefetch_stall=stall)
                out.extend(ring.push(
                    s, (block_rounds, ids_l, spans, {"depth": len(ring) + 1},
                        ms)))
            out.extend(ring.drain_all())
        finally:
            pf.close()
        return out

    # ----------------------------------------------------------------- warmup
    def _warmup_batch(self, B: int):
        """A zero-filled round batch with exactly the shapes/dtypes/sharding
        the round program sees at bucket depth ``B`` — values are irrelevant
        (lowering abstracts them); shapes select the jit variant."""
        K, bs = self.cfg.client_num_per_round, self.cfg.batch_size
        if self.device_data and not self.block_working_set:
            ib = IndexBatch(
                idx=np.zeros((K, B, bs), np.int32),
                mask=np.zeros((K, B, bs), np.float32),
                num_samples=np.zeros((K,), np.float32))
            return self._shard_round_batch(ib)
        if self._source is not None:
            (xs, xd), (ys, yd) = self._source.row_meta()
        else:
            x, y = self.data.train_x, self.data.train_y
            (xs, xd), (ys, yd) = ((x.shape[1:], x.dtype),
                                  (y.shape[1:], y.dtype))
        cb = ClientBatch(
            x=np.zeros((K, B, bs) + xs, xd),
            y=np.zeros((K, B, bs) + ys, yd),
            mask=np.zeros((K, B, bs), np.float32),
            num_samples=np.zeros((K,), np.float32))
        return self._shard_round_batch(cb)

    def warmup(self, block_rounds: int | None = None,
               per_round: bool = True,
               max_workers: int | None = None) -> dict:
        """AOT-compile every round-program variant this engine can dispatch
        — the <=4 bucket depths of the per-round fn plus, with
        ``block_rounds=R``, the scanned R-round block fn per bucket —
        concurrently on a thread pool (``.lower()`` serially, ``.compile()``
        overlapped; XLA releases the GIL).

        Wired to the persistent compile cache: warmup enables it when no
        cache dir is configured yet, every compile lands on disk, and the
        jit dispatch that later runs the round deserializes instead of
        recompiling — so a repeat run (or the N-1 sibling ranks of a
        simulated cluster) performs zero fresh compiles, which the returned
        report asserts rather than assumes (``fresh_compiles`` /
        ``cache_hits`` deltas from obs/perf_instrument).

        ``per_round=False`` drops the per-round variants (a block-only
        driver should not pay compiles it will never dispatch). Skipped
        variants that the first dispatch compiles instead: the block fn
        under ``block_working_set`` (its parked-row count is
        data-dependent) and on a robust mesh (that path degrades to
        per-round dispatch)."""
        if not getattr(jax.config, "jax_compilation_cache_dir", None):
            from fedml_tpu.utils.metrics import enable_compile_cache

            enable_compile_cache()
        cfg = self.cfg
        K = cfg.client_num_per_round
        buckets = (list(self._b_ladder) if self.bucket_batches
                   else [self.num_batches])
        rng = jax.random.PRNGKey(0)
        r0, ids = jnp.int32(0), jnp.zeros((K,), jnp.int32)
        # precision x bucket variant naming: a bf16 engine's warmed
        # executables are DIFFERENT programs from the f32 engine's, and
        # the report must say which ladder was precompiled
        prec = ("" if self.local_spec.compute_dtype in ("f32", "float32")
                else f"_{self.local_spec.compute_dtype}")
        lowered = {}
        if per_round:
            for B in buckets:
                lowered[f"round{prec}_b{B}"] = self.round_fn.lower(
                    rng, self.net, self.server_opt_state,
                    self._warmup_batch(B), r0, ids)
        if block_rounds and self.device_data and not self.block_working_set \
                and not (self.mesh is not None and self._needs_stacked):
            if not hasattr(self, "_block_fn"):
                self._block_fn = self._build_block_fn()
            R = int(block_rounds)
            for B in buckets:
                bs = cfg.batch_size
                blocks = [np.zeros((R, K, B, bs), np.int32),
                          np.zeros((R, K, B, bs), np.float32),
                          np.zeros((R, K), np.float32),
                          np.zeros((R, K), np.int32)]
                if self.mesh is not None:
                    sh = NamedSharding(self.mesh,
                                       P(None, self.mesh.axis_names[0]))
                    blocks = [jax.device_put(b, sh) for b in blocks]
                blocks = [jnp.asarray(b) for b in blocks]
                lowered[f"block{prec}_r{R}_b{B}"] = self._block_fn.lower(
                    rng, self.net, self.server_opt_state,
                    self._dev_x, self._dev_y, *blocks,
                    jnp.asarray(np.arange(R, dtype=np.int32)))
        rep = compile_concurrently(lowered, max_workers=max_workers)
        rep.pop("executables", None)
        rep["bucket_depths"] = buckets
        log.info("warmup: %d variant(s) in %.2fs (%d fresh compiles, "
                 "%d persistent-cache hits)", len(rep["variants"]),
                 rep["seconds"], rep["fresh_compiles"], rep["cache_hits"])
        if self.telemetry is not None:
            # the compile observatory's event record: per-variant wall from
            # the AOT pass plus the registry's per-variant attribution
            # (hits/misses/backend seconds) — report.py --compiles renders it
            self.telemetry.events.emit(
                "compiles", variants=rep.get("per_variant") or {},
                seconds=rep["seconds"], fresh=rep["fresh_compiles"],
                cache_hits=rep["cache_hits"],
                cache_misses=rep["cache_misses"],
                instrumented=rep["instrumented"],
                attribution=_perf.variant_compile_stats())
        return rep

    _WORKING_SET_BUCKET = 8192  # rows; pad-to-bucket keeps ONE compiled block

    def _compact_block_rows(self, idx_stack: np.ndarray):
        """Working-set park: upload only the unique train rows this block's
        index batches touch. Indices are remapped into the compact array and
        its row count padded up to a _WORKING_SET_BUCKET multiple —
        GROW-ONLY across blocks (a later, slightly smaller working set pads
        up to the largest size seen instead of shrinking into a different
        bucket), so steady-state blocks hit one compiled executable (jit
        caches by shape) and a recompile can only happen on genuine growth."""
        uniq, inv = np.unique(idx_stack, return_inverse=True)
        remapped = inv.reshape(idx_stack.shape).astype(np.int32)
        # bucket round-up is >= len(uniq), and uniq indexes train_x so
        # len(uniq) <= len(train_x): the min never under-allocates
        n_rows = min(
            -(-len(uniq) // self._WORKING_SET_BUCKET) * self._WORKING_SET_BUCKET,
            len(self.data.train_x),
        )
        n_rows = max(n_rows, getattr(self, "_ws_rows", 0))
        if (n_rows == getattr(self, "_ws_rows", 0)
                and getattr(self, "_ws_uniq", None) is not None
                and np.array_equal(uniq, self._ws_uniq)):
            # same unique-row set as the previous block: the parked device
            # buffers are already exactly right — skip the host gather AND
            # the upload entirely
            return remapped, self._ws_dev_x, self._ws_dev_y
        self._ws_rows = n_rows
        self._ws_uniq = uniq
        # FRESH host buffers every refill: device_put may alias (CPU) or
        # asynchronously read (accelerator) the numpy buffer, so a cached
        # staging buffer refilled in place could corrupt the previous
        # block's parked rows while its round program is still in flight.
        # np.zeros is calloc'd (near-free); the real cost here is the row
        # gather, which only happens when the working set actually changed
        # (the unchanged case short-circuits above).
        cx = np.zeros((n_rows,) + self.data.train_x.shape[1:],
                      self.data.train_x.dtype)
        cy = np.zeros((n_rows,) + self.data.train_y.shape[1:],
                      self.data.train_y.dtype)
        cx[: len(uniq)] = self.data.train_x[uniq]
        cy[: len(uniq)] = self.data.train_y[uniq]
        sh = (NamedSharding(self.mesh, P()) if self.mesh is not None else None)
        put = (lambda a: jax.device_put(a, sh)) if sh else jax.device_put
        self._ws_dev_x, self._ws_dev_y = put(cx), put(cy)
        return remapped, self._ws_dev_x, self._ws_dev_y

    def _span_delta(self, before: dict) -> dict:
        """This call's span seconds: current tracer round minus a snapshot
        taken at entry. run_round/run_rounds may be driven directly (bench,
        CLI loops) without train()'s next_round() between calls, so the
        tracer's round dict ACCUMULATES — the emitted record must carry the
        delta, not the running total."""
        cur = self.tracer.rounds[-1]
        return {k: v - before.get(k, 0.0) for k, v in cur.items()
                if v - before.get(k, 0.0) > 0.0}

    # ------------------------------------------------------------- quarantine
    def _drain_quarantine(self, metrics: dict, round_idx: int, ids):
        """Pop the round's in-graph ``__quarantine`` reason codes (if the
        gate/aggregator is armed) into the host-side ledger + metric
        families. Returns the metrics dict without the codes — they are a
        [K] int vector, not a floatable round scalar."""
        if "__quarantine" not in metrics:
            return metrics
        metrics = dict(metrics)
        codes = np.asarray(metrics.pop("__quarantine"))
        self.quarantine.record_codes(round_idx, codes,
                                     clients=np.asarray(ids).tolist())
        return metrics

    def _drain_quarantine_block(self, ms: dict, start_round: int, ids_l):
        if "__quarantine" not in ms:
            return ms
        ms = dict(ms)
        codes = np.asarray(ms.pop("__quarantine"))  # [R, K]
        for i in range(codes.shape[0]):
            self.quarantine.record_codes(start_round + i, codes[i],
                                         clients=ids_l[i].tolist())
        return ms

    def _quarantine_extra(self, round_idx: int) -> dict:
        """The per-round record field telemetry rides the verdicts on —
        absent entirely on clean rounds to keep records stable."""
        entries = self.quarantine.for_round(round_idx)
        return {"quarantine": entries} if entries else {}

    def _privacy_extra(self) -> dict:
        """The optional ``privacy`` block a DP engine rides on round
        records (docs/ROBUSTNESS.md §Privacy ledger) — {} here;
        FedAvgRobustAPI overrides with its accountant's cumulative ε."""
        return {}

    # ------------------------------------------------------ round economics
    def _variant_name(self, B=None, block_rounds: int | None = None) -> str:
        """The jit variant name this dispatch selects — the same
        ``round{prec}_b{B}`` / ``block{prec}_r{R}_b{B}`` scheme warmup()
        compiles under, so the goodput block finds the variant's cached
        XLA cost analysis (docs/PERFORMANCE.md §Round economics)."""
        prec = ("" if self.local_spec.compute_dtype in ("f32", "float32")
                else f"_{self.local_spec.compute_dtype}")
        if B is None:
            B = self.num_batches
        if block_rounds:
            return f"block{prec}_r{int(block_rounds)}_b{int(B)}"
        return f"round{prec}_b{int(B)}"

    def _goodput_wait(self, metrics) -> float:
        """Block until this round's device outputs are ready and return the
        wait — the device-compute backpressure the driver pays. Only called
        on telemetry paths that were about to sync on the same arrays
        anyway (emit floats them / drain np.asarray's them), so the off
        path stays bit-identical and sync-free."""
        t0 = time.perf_counter()
        try:
            jax.block_until_ready(metrics)
        except Exception:  # noqa: BLE001 — non-array metrics: nothing to wait
            pass
        return time.perf_counter() - t0

    def _goodput_extra(self, wall_s, spans, *, pipelined: bool = False,
                       compute_wait_s: float = 0.0, pack_extra=None,
                       block_rounds: int | None = None) -> dict:
        """The ``goodput`` block one round record carries (obs/goodput.py):
        exclusive duty-cycle buckets of this round's wall plus FLOPs/s and
        MFU when the dispatched variant's cost analysis is cached. {} when
        the wall was not measured."""
        if wall_s is None:
            return {}
        B = ((pack_extra or {}).get("pack") or {}).get("bucket_B")
        variant = self._variant_name(B=B, block_rounds=block_rounds)
        buckets = _goodput.buckets_from_spans(
            wall_s, spans, pipelined=pipelined,
            compute_wait_s=compute_wait_s)
        return {"goodput": _goodput.round_goodput(
            wall_s, buckets, variant=variant,
            cost_rounds=block_rounds or 1,
            n_devices=(self.mesh.size if self.mesh is not None else 1))}

    def _goodput_interval(self) -> float:
        """Per-round wall in pipelined mode: time since the previous drain
        (one drain per dispatch in steady state, so inter-drain time IS
        the per-round wall — docs/PERFORMANCE.md §Round economics)."""
        now = time.perf_counter()
        prev = getattr(self, "_gp_prev_drain_t", None)
        self._gp_prev_drain_t = now
        # None (no goodput block) when the interval base is missing — the
        # pipelined drivers seed the stamp at loop entry
        return (now - prev) if prev is not None else None

    # ------------------------------------------------------------------ train
    def _dispatch_round(self, round_idx: int, ids, cb):
        """Advance the rng chain and dispatch one round program — the ONE
        jit call site both the synchronous driver (run_round) and the
        pipelined drivers share, so their rng chains cannot diverge.
        Returns the round's metrics as device arrays (no sync)."""
        with self.tracer.span("round"):
            self.rng, rk = jax.random.split(self.rng)
            self.net, self.server_opt_state, metrics = self.round_fn(
                rk, self.net, self.server_opt_state, cb,
                jnp.int32(round_idx), jnp.asarray(ids, jnp.int32),
            )
        _perf.record_agg_bytes(self._state_placement, self._agg_bytes_round)
        return metrics

    def run_round(self, round_idx: int):
        if self.telemetry is not None:
            t_wall = time.perf_counter()
            spans_before = dict(self.tracer.rounds[-1])
            if self.telemetry.tracer is not None:
                self.telemetry.tracer.begin_round(round_idx)
        with self.tracer.span("pack"):
            ids = self._sampled_ids(round_idx)
            cb = self._pack_round(round_idx)
        metrics = self._dispatch_round(round_idx, ids, cb)
        metrics = self._drain_quarantine(metrics, round_idx, ids)
        if self.telemetry is not None:
            # floating the metrics syncs on the round's outputs — a cost the
            # caller opted into by passing telemetry; the off path returns
            # the device arrays untouched (no sync, dispatch still overlaps)
            wait = self._goodput_wait(metrics)
            spans = self._span_delta(spans_before)
            pack_extra = self._pack_extra(round_idx)
            self.telemetry.emit_round(
                round_idx, clients=np.asarray(ids).tolist(),
                spans=spans,
                metrics={k: float(v) for k, v in metrics.items()},
                agg=self._agg_record,
                **self._goodput_extra(
                    time.perf_counter() - t_wall, spans,
                    compute_wait_s=wait, pack_extra=pack_extra),
                **pack_extra,
                **self._quarantine_extra(round_idx),
                **self._privacy_extra())
            if self.telemetry.tracer is not None:
                # close the trace envelope HERE: left open it would absorb
                # inter-round idle (timing loops, the post-run gap to
                # close()) and misreport per-round wall-clock. train()'s
                # eval spans still reach the histograms/event record; only
                # the single-rank trace view scopes to the round program.
                self.telemetry.tracer.finish_round()
        return metrics

    # --------------------------------------------------------------- pipeline
    def _place_round_batch(self, batch):
        """Issue the host->device transfer for a packed round batch NOW (on
        the prefetch thread) instead of implicitly at jit dispatch. Leaves
        already on device (the mesh packer shards in _pack_round) pass
        through. Transfers are exact, so a placed batch is bit-identical to
        letting dispatch transfer it."""
        leaves, treedef = jax.tree.flatten(batch)
        return jax.tree.unflatten(
            treedef,
            [v if isinstance(v, jax.Array) else jax.device_put(v)
             for v in leaves])

    def _pack_round_placed(self, round_idx: int):
        """Prefetch producer (runs on the packer thread): sample ids, pack
        the round batch into FRESH host buffers (every pack path allocates
        anew — donation-safe while earlier rounds are still in flight), and
        issue its device_put. Returns (ids, device batch, span dict)."""
        t0 = time.perf_counter()
        ids = self._sampled_ids(round_idx)
        cb = self._pack_round(round_idx)
        t1 = time.perf_counter()
        cb = self._place_round_batch(cb)
        h2d = time.perf_counter() - t1
        # the packer thread must not touch self.tracer (its per-round dict
        # belongs to the driver thread) — spans go straight to the
        # fed_span_seconds / fed_h2d_seconds histograms and ride the round
        # record at drain time
        _perf.record_span("prefetch_pack", t1 - t0)
        _perf.record_h2d(h2d)
        return ids, cb, {"prefetch_pack": t1 - t0, "h2d": h2d}

    def _drain_round_entry(self, round_idx: int, entry):
        """Materialize one in-flight round's outputs (this is the only
        sync, and it happens drain_lag rounds behind dispatch): quarantine
        codes into the ledger, metrics to host, telemetry record flushed —
        all in dispatch order, so ledgers and event logs are bit-identical
        to the synchronous driver's."""
        ids, spans, pipeline, metrics = entry
        if self.telemetry is not None:
            # the drain is the pipeline's one sync point: the wait here IS
            # the device-compute backpressure this round cost the driver
            # (goodput's compute bucket); inter-drain time is the per-round
            # wall. Off path syncs implicitly at np.asarray — unchanged.
            wait = self._goodput_wait(metrics)
            wall = self._goodput_interval()
        metrics = self._drain_quarantine(metrics, round_idx, ids)
        host = {k: np.asarray(v) for k, v in metrics.items()}
        if self.telemetry is not None:
            pack_extra = self._pack_extra(round_idx)
            self.telemetry.emit_round(
                round_idx, clients=np.asarray(ids).tolist(),
                spans=spans, pipeline=pipeline,
                metrics={k: float(v) for k, v in host.items()},
                agg=self._agg_record,
                **self._goodput_extra(
                    wall, spans, pipelined=True, compute_wait_s=wait,
                    pack_extra=pack_extra),
                **pack_extra,
                **self._quarantine_extra(round_idx),
                **self._privacy_extra())
        return round_idx, host

    def _warn_tracer_unsupported(self):
        """Pipelined drivers overlap rounds, which the sequential per-round
        distributed-trace model (obs/tracing.py begin_round..finish_round)
        cannot represent — so they emit NO per-round traces. Say so loudly
        once instead of silently exporting an empty trace.json."""
        if (self.telemetry is not None and self.telemetry.tracer is not None
                and not getattr(self, "_tracer_warned", False)):
            self._tracer_warned = True
            log.warning(
                "pipelined drivers do not emit per-round distributed "
                "traces (rounds overlap; the trace model is sequential) — "
                "round records carry prefetch/h2d/stall spans instead; "
                "use the synchronous driver (prefetch=0) for trace runs")

    def run_pipelined(self, start_round: int, num_rounds: int):
        """Per-round dispatch through the prefetch pipeline: round r+1's
        pack + H2D overlap round r's execution, and the metrics drain
        trails ``drain_lag`` rounds behind so async dispatch stays that
        deep. Bit-identical to the run_round loop (same packs, same rng
        chain, same ledger order — test-enforced). Returns the drained
        [(round_idx, host metrics dict)] in round order."""
        self._warn_tracer_unsupported()
        depth = max(1, self.prefetch)
        pf = Prefetcher(self._pack_round_placed,
                        range(start_round, start_round + num_rounds),
                        depth=depth, on_event=self._pipe_on_event)
        ring = InflightRing(self.drain_lag, self._drain_round_entry,
                            on_event=self._pipe_on_event)
        self._gp_prev_drain_t = time.perf_counter()
        out = []
        try:
            for r in range(start_round, start_round + num_rounds):
                (ids, cb, spans), stall = pf.get(r)
                metrics = self._dispatch_round(r, ids, cb)
                spans = dict(spans, prefetch_stall=stall)
                out.extend(ring.push(
                    r, (ids, spans, {"depth": len(ring) + 1}, metrics)))
            out.extend(ring.drain_all())
        finally:
            pf.close()
        return out

    def _train_pipelined(self, rounds: int):
        """train() body with the pipeline armed: same eval cadence and
        history records as the synchronous loop; an eval round drains the
        ring (its own metrics must be host-side), which re-syncs — set
        frequency_of_the_test high for pure-throughput runs."""
        self._warn_tracer_unsupported()
        cfg = self.cfg
        depth = max(1, self.prefetch)
        pf = Prefetcher(self._pack_round_placed, range(rounds), depth=depth,
                        on_event=self._pipe_on_event)
        ring = InflightRing(self.drain_lag, self._drain_round_entry,
                            on_event=self._pipe_on_event)
        self._gp_prev_drain_t = time.perf_counter()
        pending: dict[int, dict] = {}
        try:
            for r in range(rounds):
                t0 = time.perf_counter()
                (ids, cb, spans), stall = pf.get(r)
                metrics = self._dispatch_round(r, ids, cb)
                spans = dict(spans, prefetch_stall=stall)
                for k, m in ring.push(
                        r, (ids, spans, {"depth": len(ring) + 1}, metrics)):
                    pending[k] = m
                if (r % cfg.frequency_of_the_test == 0) or (r == rounds - 1):
                    for k, m in ring.drain_all():
                        pending[k] = m
                    rec = self.eval_record(r, pending[r])
                    rec["round_time"] = time.perf_counter() - t0
                    self.history.append(rec)
                    log.info("round %d: %s", r, rec)
                    if self.telemetry is not None:
                        self.telemetry.emit_eval(r, rec)
                pending = {k: v for k, v in pending.items() if k >= r}
                self.tracer.next_round()
            ring.drain_all()
        finally:
            pf.close()
        return self.net

    def _eval_on_all_clients(self) -> bool:
        mode = getattr(self.cfg, "local_test_on_all_clients", "auto")
        if mode == "auto":
            # natural per-client test splits AND no validation-subset cap:
            # when eval_max_samples is configured (the reference's 10k
            # stackoverflow validation set, FedAVGAggregator.py:99-107) the
            # capped global eval wins — iterating every client's full split
            # is exactly what that cap exists to avoid at 342k-client scale
            return (self.data.test_idx_map is not None
                    and self.cfg.eval_max_samples is None)
        if mode in ("on", "off"):
            return mode == "on"
        raise ValueError(f"local_test_on_all_clients={mode!r} "
                         "(expected 'auto', 'on' or 'off')")

    def eval_record(self, round_idx: int, metrics) -> dict:
        """Assemble one eval-round history record for the current model:
        in-round training metrics plus either the per-client aggregate
        (reference _local_test_on_all_clients, fedavg_api.py:117-180 —
        the global model scored on every client's OWN train and test split,
        sum(num_correct)/sum(num_samples) weighting) or the global test-set
        eval. Shared by train() and the CLI round loop so the metrics
        schema cannot drift between them."""
        n = float(max(float(metrics.get("count", 1.0)), 1.0))
        rec = {
            "round": round_idx,
            "train_loss": float(metrics.get("loss_sum", 0.0)) / n,
            "train_acc": float(metrics.get("correct", 0.0)) / n,
        }
        with self.tracer.span("eval"):
            if self._eval_on_all_clients():
                _, tr = self.evaluate_per_client("train")
                _, te = self.evaluate_per_client("test")
                rec.update(
                    train_all_loss=float(tr["loss"]),
                    train_all_acc=float(tr["acc"]),
                    test_loss=float(te["loss"]), test_acc=float(te["acc"]),
                )
            else:
                ev = self.evaluate()
                rec.update(test_loss=float(ev["loss"]),
                           test_acc=float(ev["acc"]))
        return rec

    def train(self, num_rounds: int | None = None):
        cfg = self.cfg
        rounds = num_rounds or cfg.comm_round
        if self.telemetry is not None:
            from fedml_tpu.data import dataset_source

            self.telemetry.run_header(dataclasses.asdict(cfg),
                                      engine="standalone",
                                      dataset_source=dataset_source(
                                          self.data))
        if self.prefetch and rounds > 0:
            return self._train_pipelined(rounds)
        for r in range(rounds):
            t0 = time.perf_counter()
            metrics = self.run_round(r)
            if (r % cfg.frequency_of_the_test == 0) or (r == rounds - 1):
                rec = self.eval_record(r, metrics)
                rec["round_time"] = time.perf_counter() - t0
                self.history.append(rec)
                log.info("round %d: %s", r, rec)
                if self.telemetry is not None:
                    self.telemetry.emit_eval(r, rec)
            self.tracer.next_round()
        return self.net

    # ------------------------------------------------------------------ async
    def run_async(self, num_updates: int, buffer_k: int,
                  staleness="constant", staleness_bound: int | None = None,
                  deadline_s: float | None = None,
                  capacity: int | None = None, chaos_plan=None,
                  adversary_plan=None, base_duration_s: float = 1.0):
        """Buffered-async rounds on a virtual clock (docs/ROBUSTNESS.md
        §Asynchronous buffered rounds; core/async_buffer.py): worker slots
        train continuously against possibly-stale globals, the server
        aggregates every ``buffer_k`` sanitized arrivals with
        staleness-discounted weights through this engine's own gate/
        estimator/server_update composition, and admission control
        rejects-and-requeues updates staler than ``staleness_bound``. A
        chaos FaultPlan's straggle/crash rules drive the virtual durations,
        so async-vs-sync wall-clock claims are deterministic and replay
        bit-for-bit. ``buffer_k = cohort`` with ``staleness_bound = 0`` is
        bitwise-identical to the run_round loop — model bits AND quarantine
        ledger (test-enforced).

        Returns the runner (``.history`` per-update records, ``.stats()``
        wall-clock/staleness/shed summary); the engine's net/opt/rng/
        quarantine advance exactly as if the updates had run
        synchronously."""
        if self._source is not None:
            # the virtual-clock runner packs through pack_clients (index
            # maps) — refuse HERE instead of AttributeError-ing deep in
            # its event loop after warmup time is spent
            raise ValueError(
                "run_async is not wired for streamed ClientDataSources "
                "yet — materialize the dataset for the async simulator")
        from fedml_tpu.core.async_buffer import VirtualClockAsyncRunner

        runner = VirtualClockAsyncRunner(
            self, buffer_k, staleness=staleness,
            staleness_bound=staleness_bound, deadline_s=deadline_s,
            capacity=capacity, chaos_plan=chaos_plan,
            adversary_plan=adversary_plan, base_duration_s=base_duration_s)
        runner.run(num_updates)
        return runner

    # ------------------------------------------------------------------ state
    def load_state(self, net, server_opt_state, rng):
        """Install restored state, re-placing it for the engine's mesh (a
        checkpoint restored host-side lands on one device; the round program
        expects replicated layout when a mesh is active — or the Megatron
        TP layout on a ('clients','model') mesh, which a blanket
        replicated placement would silently discard)."""
        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            put = lambda t: jax.tree.map(lambda v: jax.device_put(v, rep), t)
            if self._tp:
                from fedml_tpu.parallel.tensor_parallel import shard_params

                params, self.tp_specs = shard_params(net.params, self.mesh)
                net = net._replace(params=params, extra=put(net.extra))
            elif self._sharded:
                # checkpoints are saved gathered (core/checkpoint.py's
                # gather-on-save layout) — re-partition per the rule table
                # so resume lands in exactly the round program's layout
                net = self.partitioner.shard(net)
                server_opt_state = self.partitioner.shard(server_opt_state)
                rng = put(rng)
                self.net, self.server_opt_state, self.rng = (
                    net, server_opt_state, rng)
                return
            else:
                net = put(net)
            server_opt_state, rng = put(server_opt_state), put(rng)
        self.net, self.server_opt_state, self.rng = net, server_opt_state, rng

    # ------------------------------------------------------------------ eval
    def evaluate_per_client(self, split: str = "test", chunk: int = 64,
                            max_clients: int | None = None):
        """Reference-fidelity eval: iterate EVERY client's own split
        (_local_test_on_all_clients, fedavg_api.py:117-180), vectorized —
        clients are packed in chunks of ``chunk`` and evaluated as one
        vmapped masked batch block per chunk.

        Returns (per_client list of {client, loss, acc, count}, aggregate
        dict weighted by sample counts — the reference's Train/Acc /
        Test/Acc numbers).
        """
        import dataclasses as _dc

        if split == "test" and self.data.test_idx_map is not None:
            view = _dc.replace(self.data, train_x=self.data.test_x,
                               train_y=self.data.test_y,
                               train_idx_map=self.data.test_idx_map)
        elif split == "test":
            # no per-client test partition: every client shares the global
            # test set (the cross-silo datasets' convention)
            view = None
        else:
            view = self.data

        if view is None:
            ev = self.evaluate()
            agg = {"loss": float(ev["loss"]), "acc": float(ev["acc"]),
                   "count": float(ev["count"])}
            return [], agg

        ids = np.arange(view.num_clients if max_clients is None
                        else min(max_clients, view.num_clients))
        if self.cfg.ci:
            ids = ids[:1]  # --ci truncation (FedAVGAggregator.py:126-131)

        if not hasattr(self, "_chunk_eval"):

            @jax.jit
            def chunk_eval(net, x, y, mask):
                # [K, B, bs, ...] -> per-client metric sums
                def per_client(xk, yk, mk):
                    def body(acc, b):
                        xb, yb, mb = b
                        metr = self.task.eval_batch(net.params, net.extra, xb, yb, mb)
                        return {k: acc[k] + metr[k] for k in acc}, None

                    init = {"loss_sum": jnp.zeros(()), "correct": jnp.zeros(()),
                            "count": jnp.zeros(())}
                    acc, _ = lax.scan(body, init, (xk, yk, mk))
                    return acc

                return jax.vmap(per_client)(x, y, mask)

            self._chunk_eval = chunk_eval
        chunk_eval = self._chunk_eval

        per_client: list[dict] = []
        tot = {"loss_sum": 0.0, "correct": 0.0, "count": 0.0}
        for s in range(0, len(ids), chunk):
            cids = ids[s : s + chunk]
            cb = pack_clients(view, cids, self.cfg.eval_batch_size,
                              seed=self.cfg.seed, round_idx=0)
            m = jax.device_get(chunk_eval(self.net, jnp.asarray(cb.x),
                                          jnp.asarray(cb.y), jnp.asarray(cb.mask)))
            for i, cid in enumerate(cids):
                n = float(max(m["count"][i], 1.0))
                per_client.append({
                    "client": int(cid),
                    "loss": float(m["loss_sum"][i]) / n,
                    "acc": float(m["correct"][i]) / n,
                    "count": float(m["count"][i]),
                })
                for k in tot:
                    tot[k] += float(m[k][i])
        n = max(tot["count"], 1.0)
        agg = {"loss": tot["loss_sum"] / n, "acc": tot["correct"] / n, "count": tot["count"]}
        return per_client, agg

    def evaluate(self):
        """Global test-set eval (the reference evaluates per client over all
        clients, fedavg_api.py:117-180; on a global-shared test set the two
        coincide up to weighting)."""
        # 'fresh' only forces a rebuild when a subset is actually drawn —
        # uncapped eval would rebuild+re-upload an identical test set
        fresh = (self.cfg.eval_subset_mode == "fresh"
                 and self.cfg.eval_max_samples is not None
                 and len(self.data.test_x) > self.cfg.eval_max_samples)
        self._eval_calls = getattr(self, "_eval_calls", 0) + 1
        if self._test_cache is None or fresh:
            tx, ty = eval_subset(self.data.test_x, self.data.test_y,
                                 self.cfg, self._eval_calls)
            n = len(tx)
            if self.cfg.ci:
                n = min(n, 512)  # --ci truncation analogue (FedAVGAggregator.py:126-131)
            self._test_cache = tuple(
                jnp.asarray(a)
                for a in batch_global(tx[:n], ty[:n], self.cfg.eval_batch_size)
            )
        xb, yb, mb = self._test_cache
        return self.eval_fn(self.net, xb, yb, mb)
