"""Hierarchical (two-tier) FL: groups of clients, nested aggregation.

Reference: fedml_api/standalone/hierarchical_fl/ — Group.train runs
group_comm_round local FedAvg rounds inside each group (group.py:24), the
global trainer samples clients per group and averages group models every
global round (trainer.py:32-43). The reference CI asserts that with
global_rounds x group_rounds held constant the result matches flat FedAvg
(CI-script-fedavg.sh:51-58) — reproduced in tests/test_hierarchical.py.

This module is the SPMD simulation of the hierarchy; the real
cross-process 2-tier topology (edge aggregator ranks tree-reducing their
worker blocks' uplinks, root fan-in O(edges), tree == flat bitwise) lives
in fedml_tpu/distributed/fedavg/hierarchy.py — docs/ROBUSTNESS.md
§Hierarchical tiers.

TPU form: group state is a stacked pytree [G, ...]; one jitted sub-round
program vmaps (groups) x vmaps (clients) the local update and does the
group-level weighted mean; the global aggregation is a weighted mean over the
group axis. On a ('groups','clients') mesh the same body shard_maps with the
group psum riding DCN and the client psum riding ICI (mesh.make_hierarchical_mesh).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
from fedml_tpu.core.client_data import ClientBatch, pack_clients
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.utils.tree import tree_weighted_mean


class HierarchicalFLAPI(FedAvgAPI):
    def __init__(
        self,
        dataset,
        task,
        config: FedAvgConfig,
        group_num: int = 2,
        group_comm_round: int = 1,
        group_method: str = "random",  # client->group assignment
        mesh=None,
        **kwargs,
    ):
        # The mesh contract, stated up front (it used to look like the
        # argument was silently discarded): a hierarchical mesh MUST carry
        # ('groups', 'clients') axes and drives the GROUP round program
        # (group_round_mesh below + the shardable-K padding in
        # _pack_groups). The PARENT engine deliberately gets mesh=None —
        # its flat round_fn is never dispatched by this subclass
        # (run_round is overridden), and handing it a ('groups','clients')
        # mesh would make it treat 'groups' as the client axis. Any other
        # mesh shape is refused HERE, before the parent pays its engine
        # build, instead of half-working with the mesh ignored.
        if mesh is not None:
            if ("groups" not in mesh.axis_names
                    or "clients" not in mesh.axis_names):
                raise ValueError(
                    "hierarchical mesh needs axes ('groups','clients') "
                    f"(mesh.make_hierarchical_mesh), got {mesh.axis_names}"
                    " — a plain ('clients',) mesh is not supported here")
            if group_num % mesh.shape["groups"] != 0:
                raise ValueError(
                    f"group_num={group_num} not divisible by mesh groups "
                    f"axis {mesh.shape['groups']}")
        super().__init__(dataset, task, config, mesh=None, **kwargs)
        if config.sampling != "uniform":
            # group sub-rounds sample WITHIN groups (sample_clients over
            # members); size weighting is not wired there — refuse rather
            # than silently ignore the flag
            raise ValueError(
                f"sampling={config.sampling!r} is not wired for "
                "hierarchical FL; use uniform")
        self.group_num = group_num
        self.group_comm_round = group_comm_round
        self.group_mesh = mesh
        rng = np.random.RandomState(config.seed)
        ids = np.arange(config.client_num_in_total)
        if group_method == "random":
            rng.shuffle(ids)
        self.groups = np.array_split(ids, group_num)  # group -> client ids

        # jitted: one group sub-round vmapped over groups
        local_update = self.local_update

        def grid_keys(rng, G, K):
            # (g, k)-indexed fold_in chain: key depends only on (rng, g, k),
            # NOT on the padded grid shape — so the sharded path (which pads
            # K up to the mesh tile) derives bit-identical keys for real
            # clients (same trick as the fedavg engine's fold_in chain)
            return jax.vmap(
                lambda g: jax.vmap(
                    lambda k: jax.random.fold_in(jax.random.fold_in(rng, g), k)
                )(jnp.arange(K))
            )(jnp.arange(G))

        if mesh is None:

            @jax.jit
            def group_round(rng, group_nets, x, y, mask, nsamp):
                # group_nets: stacked [G, ...]; x: [G, K, B, bs, ...]
                G, K = x.shape[0], x.shape[1]
                keys = grid_keys(rng, G, K)

                def per_group(net_g, keys_g, xg, yg, mg, ng):
                    nets, metrics = jax.vmap(local_update, in_axes=(0, None, 0, 0, 0))(
                        keys_g, net_g, xg, yg, mg
                    )
                    avg = tree_weighted_mean(nets, ng)
                    return avg, {k: jnp.sum(v) for k, v in metrics.items()}

                return jax.vmap(per_group)(group_nets, keys, x, y, mask, nsamp)

            self._group_round = group_round
        else:
            # SURVEY §2.7 two-level mesh: each device holds a [G/gd, K/cd]
            # block; the GROUP mean is a weighted psum over the 'clients'
            # axis (ICI), while the global mean over groups happens after the
            # sub-rounds (on a multislice mesh 'groups' rides DCN — the
            # hierarchy exists precisely so the frequent intra-group syncs
            # stay on the fast axis).
            # (mesh axes/divisibility validated up front, before super())
            from jax import lax
            from jax.sharding import PartitionSpec as P

            def body(keys, group_nets, x, y, mask, nsamp):
                # local block: nets [Gl, ...]; data [Gl, Kl, B, bs, ...]
                def per_group(net_g, keys_g, xg, yg, mg, ng):
                    net_v = jax.tree.map(
                        lambda v: lax.pcast(v, "clients", to="varying"), net_g)
                    nets, metrics = jax.vmap(local_update, in_axes=(0, None, 0, 0, 0))(
                        keys_g, net_v, xg, yg, mg)
                    wsum = jax.tree.map(
                        lambda t: lax.psum(
                            jnp.tensordot(ng, t, axes=([0], [0])), "clients"),
                        nets)
                    den = lax.psum(jnp.sum(ng), "clients")
                    avg = jax.tree.map(lambda t: t / jnp.maximum(den, 1e-12), wsum)
                    msum = {k: lax.psum(jnp.sum(v), "clients")
                            for k, v in metrics.items()}
                    return avg, msum

                return jax.vmap(per_group)(group_nets, keys, x, y, mask, nsamp)

            smapped = jax.shard_map(
                body, mesh=mesh,
                in_specs=(P("groups", "clients"), P("groups"),
                          P("groups", "clients"), P("groups", "clients"),
                          P("groups", "clients"), P("groups", "clients")),
                out_specs=(P("groups"), P("groups")),
            )

            @jax.jit
            def group_round_mesh(rng, group_nets, x, y, mask, nsamp):
                G, K = x.shape[0], x.shape[1]
                # same (g,k) fold_in chain as the single-device path —
                # bit-identical keys for real clients (test-enforced)
                keys = grid_keys(rng, G, K)
                return smapped(keys, group_nets, x, y, mask, nsamp)

            self._group_round = group_round_mesh

    def _pack_groups(self, round_idx: int, sub_round: int):
        """Sample cfg.client_num_per_round/G clients per group and pack to
        [G, K, B, bs, ...] (groups padded to a common K)."""
        cfg = self.cfg
        G = self.group_num
        k_per = max(1, cfg.client_num_per_round // G)
        packs = []
        for g, members in enumerate(self.groups):
            # per-group deterministic sampling (trainer.py:32-43 semantics)
            local_round = round_idx * self.group_comm_round * 131 + sub_round * 31 + g
            sel = sample_clients(local_round, len(members), min(k_per, len(members)), cfg.seed)
            cb = pack_clients(self.data, members[sel], cfg.batch_size,
                              max_batches=self.num_batches, seed=cfg.seed,
                              round_idx=local_round)
            packs.append(cb)
        K = max(p.x.shape[0] for p in packs)
        if self.group_mesh is not None:
            cd = self.group_mesh.shape["clients"]
            K = ((K + cd - 1) // cd) * cd  # shardable K (pads carry weight 0)
        B = self.num_batches

        def pad(cb: ClientBatch):
            k, b = cb.x.shape[0], cb.x.shape[1]
            pads = [(0, K - k), (0, B - b)]
            x = np.pad(cb.x, pads + [(0, 0)] * (cb.x.ndim - 2))
            y = np.pad(cb.y, pads + [(0, 0)] * (cb.y.ndim - 2))
            m = np.pad(cb.mask, pads + [(0, 0)])
            n = np.pad(cb.num_samples, (0, K - k))
            return x, y, m, n

        xs, ys, ms, ns = zip(*[pad(p) for p in packs])
        return (np.stack(xs), np.stack(ys), np.stack(ms), np.stack(ns))

    def run_round(self, round_idx: int):
        # broadcast global net to all groups, run group_comm_round sub-rounds,
        # then weighted-average groups by their processed sample counts
        group_nets = jax.tree.map(
            lambda v: jnp.broadcast_to(v[None], (self.group_num,) + v.shape), self.net
        )
        group_counts = jnp.zeros((self.group_num,))
        metrics_acc = None
        for s in range(self.group_comm_round):
            x, y, m, n = self._pack_groups(round_idx, s)
            self.rng, rk = jax.random.split(self.rng)
            group_nets, metrics = self._group_round(rk, group_nets, x, y, m, n)
            group_counts = group_counts + jnp.asarray(n.sum(axis=1))
            metrics_acc = metrics if metrics_acc is None else {
                k: metrics_acc[k] + v for k, v in metrics.items()
            }
        self.net = tree_weighted_mean(group_nets, group_counts)
        return {k: jnp.sum(v) for k, v in metrics_acc.items()}
