"""Device mesh + sharding helpers (L0).

Replaces the reference's process/device placement layer: mpirun rank spawning
plus the rank->GPU yaml map (fedml_api/distributed/utils/gpu_mapping.py:8-37).
On TPU there is one process per host and an N-device mesh; "which client runs
where" is a sharding annotation, not a process boundary.

Axis conventions:
  'clients'          — the FL client-parallel axis (the reference's one process
                       per client, FedAvgAPI.py:20-28).
  ('groups','clients') — hierarchical FL (standalone/hierarchical_fl/).
  'data'             — within-client batch data parallelism (centralized mode's
                       DistributedDataParallel, fedml_experiments/centralized/main.py:13).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_client_mesh(num_devices: int | None = None, axis_name: str = "clients") -> Mesh:
    """1-D mesh over all (or the first ``num_devices``) local devices."""
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def make_hierarchical_mesh(num_groups: int, clients_per_group: int) -> Mesh:
    """2-D ('groups','clients') mesh for hierarchical FL.

    On a multi-slice pod, the 'groups' axis should map to DCN (slower,
    inter-slice) and 'clients' to ICI — group aggregation happens rarely
    (every group_comm_round), client aggregation every round.
    """
    devs = jax.devices()
    n = num_groups * clients_per_group
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    arr = np.asarray(devs[:n]).reshape(num_groups, clients_per_group)
    return Mesh(arr, ("groups", "clients"))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for fully-replicated values (global model params)."""
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh, axis_name: str = "clients") -> NamedSharding:
    """Sharding that splits the leading axis across the client axis."""
    return NamedSharding(mesh, P(axis_name))


def shard_leading_axis(tree, mesh: Mesh, axis_name: str = "clients"):
    """Device_put a host pytree with its leading axis split over ``axis_name``."""
    sh = client_sharded(mesh, axis_name)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
