"""Device mesh + sharding helpers (L0).

Replaces the reference's process/device placement layer: mpirun rank spawning
plus the rank->GPU yaml map (fedml_api/distributed/utils/gpu_mapping.py:8-37).
On TPU there is one process per host and an N-device mesh; "which client runs
where" is a sharding annotation, not a process boundary.

Axis conventions:
  'clients'          — the FL client-parallel axis (the reference's one process
                       per client, FedAvgAPI.py:20-28).
  ('groups','clients') — hierarchical FL (standalone/hierarchical_fl/).
  'data'             — within-client batch data parallelism (centralized mode's
                       DistributedDataParallel, fedml_experiments/centralized/main.py:13).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_client_mesh(num_devices: int | None = None, axis_name: str = "clients") -> Mesh:
    """1-D mesh over all (or the first ``num_devices``) local devices."""
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def make_2d_mesh(n_devices: int | None, minor: int,
                 axes: tuple[str, str],
                 n_flag: str = "--mesh", minor_flag: str = "") -> Mesh:
    """2-D (major, minor) mesh over the first n_devices devices (None/0 =
    all). Raises clear errors naming the CLI flags involved when the
    device budget is exceeded or not divisible by ``minor``."""
    avail = len(jax.devices())
    n = n_devices or avail
    if n > avail:
        raise ValueError(f"{n_flag} {n} exceeds {avail} devices")
    if n % minor:
        raise ValueError(
            f"{n_flag} {n} not divisible by {minor_flag or 'minor axis'} "
            f"{minor} (devices would be silently dropped)")
    arr = np.asarray(jax.devices()[:n]).reshape(n // minor, minor)
    return Mesh(arr, axes)


def make_hierarchical_mesh(num_groups: int, clients_per_group: int) -> Mesh:
    """2-D ('groups','clients') mesh for hierarchical FL.

    On a multi-slice pod, the 'groups' axis should map to DCN (slower,
    inter-slice) and 'clients' to ICI — group aggregation happens rarely
    (every group_comm_round), client aggregation every round.
    """
    devs = jax.devices()
    n = num_groups * clients_per_group
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    arr = np.asarray(devs[:n]).reshape(num_groups, clients_per_group)
    return Mesh(arr, ("groups", "clients"))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for fully-replicated values (global model params)."""
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh, axis_name: str = "clients") -> NamedSharding:
    """Sharding that splits the leading axis across the client axis."""
    return NamedSharding(mesh, P(axis_name))


def shard_leading_axis(tree, mesh: Mesh, axis_name: str = "clients"):
    """Device_put a host pytree with its leading axis split over ``axis_name``."""
    sh = client_sharded(mesh, axis_name)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> None:
    """Multi-host entry: one python process per host, all chips in one
    global mesh afterwards (jax.distributed). Replaces the reference's
    mpirun+hostfile spawning (run_fedavg_distributed_pytorch.sh:16-35) —
    after this, cross-host communication is XLA collectives over ICI/DCN,
    not pickled sends. No-op when already initialized or single-process."""
    import jax

    if coordinator_address is None:
        return  # single-host run
    # must run BEFORE any JAX computation initializes the local backend
    # (probing jax.process_count() here would itself initialize it);
    # tolerate a launcher that already called initialize
    try:
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
    except RuntimeError as e:
        if "already" not in str(e):
            raise


def make_multislice_mesh(ici_per_slice: int | None = None,
                         dcn_slices: int | None = None,
                         axis_names: Sequence[str] = ("groups", "clients")) -> Mesh:
    """DCN x ICI mesh for multi-slice pods: the slow inter-slice axis first
    (map rare collectives — e.g. hierarchical FL's group aggregation — onto
    it), the fast intra-slice axis second (per-round client psums ride ICI).

    Uses mesh_utils.create_hybrid_device_mesh when running across slices
    (device kind exposes a slice_index); falls back to a reshape of the
    local devices so the same code runs on one host/slice.
    """
    devs = jax.devices()
    n = len(devs)
    if dcn_slices is None:
        slice_ids = {getattr(d, "slice_index", 0) for d in devs}
        dcn_slices = max(len(slice_ids), 1)
    if ici_per_slice is None:
        ici_per_slice = n // dcn_slices
    if dcn_slices > 1:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            (1, ici_per_slice), (dcn_slices, 1), devices=devs)
        return Mesh(arr.reshape(dcn_slices, ici_per_slice), tuple(axis_names))
    arr = np.asarray(devs[: dcn_slices * ici_per_slice]).reshape(
        dcn_slices, ici_per_slice)
    return Mesh(arr, tuple(axis_names))
