from fedml_tpu.mesh.mesh import (
    make_client_mesh,
    make_hierarchical_mesh,
    replicated,
    client_sharded,
    shard_leading_axis,
)
