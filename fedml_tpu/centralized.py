"""Centralized (non-federated) trainer — the baseline mode.

Reference: fedml_experiments/centralized/main.py + fedml_api/centralized/
centralized_trainer.py:9-104 — trains the same models/datasets centrally,
optionally with DistributedDataParallel (--data_parallel, main.py:52).

TPU form: one jitted epoch (lax.scan over batches); the DDP analogue is the
same step pjit-ed over a 'data' mesh axis — batch sharded, params replicated,
XLA inserts the gradient psum (exactly what DDP's allreduce does, minus the
process management).

Capability-plus (absent from the reference, SURVEY.md §2.7): tensor
parallelism. Pass a mesh with a 'model' axis — e.g.
``Mesh(np.asarray(jax.devices()).reshape(2, 4), ('data', 'model'))`` — and
the parameters are INITIALIZED sharded per Megatron-style PartitionSpecs
(parallel/tensor_parallel.py, jit out_shardings);
the SAME epoch program then runs DP x TP, with XLA inserting the
all-reduces/all-gathers the layout implies. No step-function changes:
sharding is layout, not semantics (TP ≡ single-device oracle in
tests/test_tensor_parallel.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.client_data import batch_global
from fedml_tpu.core.local import NetState, Task


@dataclasses.dataclass(frozen=True)
class CentralizedConfig:
    epochs: int = 10
    batch_size: int = 64
    lr: float = 0.03
    momentum: float = 0.9
    wd: float = 0.0
    seed: int = 0
    # eval batch rows; models with internal batch-dim sharding constraints
    # (e.g. PipelineLM's data_axis) need this divisible like batch_size
    eval_batch_size: int = 256


class CentralizedTrainer:
    def __init__(self, task: Task, x, y, test_x, test_y,
                 config: CentralizedConfig, mesh: Mesh | None = None):
        self.task = task
        self.cfg = config
        self.mesh = mesh
        self.x, self.y = np.asarray(x), np.asarray(y)
        self.test = batch_global(np.asarray(test_x), np.asarray(test_y),
                                 config.eval_batch_size)
        key = jax.random.PRNGKey(config.seed)
        self.rng, init_key = jax.random.split(key)
        x_sample = jnp.asarray(self.x[: config.batch_size])
        self.tp_specs: list | None = None
        if mesh is not None and "model" in mesh.axis_names:
            from fedml_tpu.parallel.tensor_parallel import tp_shardings

            # sharded-at-init: out_shardings makes every device materialize
            # only ITS shard — the full unsharded tree never exists anywhere
            # (task.init under plain eager would build it on one device,
            # which defeats TP for any model big enough to need it)
            shapes = jax.eval_shape(task.init, init_key, x_sample)
            p_shard, self.tp_specs = tp_shardings(shapes.params, mesh)
            rep = NamedSharding(mesh, P())
            e_shard = jax.tree.map(lambda _: rep, shapes.extra)
            self.net = jax.jit(
                task.init, out_shardings=type(shapes)(p_shard, e_shard),
            )(init_key, x_sample)
        else:
            self.net = task.init(init_key, x_sample)
        tx = optax.sgd(config.lr, momentum=config.momentum or None)
        if config.wd:
            tx = optax.chain(optax.add_decayed_weights(config.wd), tx)
        self.tx = tx
        # init over already-placed params: momentum buffers inherit the TP
        # layout (zeros_like follows the input's sharding)
        self.opt_state = tx.init(self.net.params)
        self._epoch = jax.jit(self._build_epoch())
        self.history: list[dict] = []

    def _build_epoch(self):
        task, tx = self.task, self.tx

        def epoch(rng, net: NetState, opt_state, xb, yb, mb):
            def step(carry, batch):
                params, extra, opt_state, rng = carry
                x, y, m = batch
                rng, sub = jax.random.split(rng)

                def loss_fn(p):
                    l, new_extra, metr = task.loss(p, extra, x, y, m, sub, True)
                    return l, (new_extra, metr)

                (l, (new_extra, metr)), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                upd, opt_state = tx.update(g, opt_state, params)
                return (optax.apply_updates(params, upd), new_extra,
                        opt_state, rng), metr

            (params, extra, opt_state, _), metrs = jax.lax.scan(
                step, (net.params, net.extra, opt_state, rng), (xb, yb, mb))
            return NetState(params, extra), opt_state, {
                k: jnp.sum(v) for k, v in metrs.items()}

        if self.mesh is None:
            return epoch

        # data-parallel: shard the batch axis over the mesh (DDP analogue).
        # With a 'model' axis present the batch shards over 'data' only and
        # params keep their TP placement — the same program is DP x TP.
        mesh = self.mesh
        if "model" in mesh.axis_names or "stage" in mesh.axis_names:
            # batch shards over the first non-model/non-stage axis (the
            # 'stage' axis belongs to a PipelineLM's internal gpipe region;
            # a pure-TP/PP mesh leaves the batch replicated)
            data_axis = next((a for a in mesh.axis_names
                              if a not in ("model", "stage")), None)
        else:
            data_axis = mesh.axis_names[0]

        if data_axis is None:
            return epoch  # pure TP/PP mesh: batch stays replicated

        def epoch_dp(rng, net, opt_state, xb, yb, mb):
            # xb: [B, bs, ...] -> shard bs across devices via in_shardings
            shd = NamedSharding(mesh, P(None, data_axis))
            xb = jax.device_put(xb, shd)
            yb = jax.device_put(yb, shd)
            mb = jax.device_put(mb, shd)
            return epoch(rng, net, opt_state, xb, yb, mb)

        return epoch_dp

    def train(self):
        cfg = self.cfg
        rng = np.random.RandomState(cfg.seed)
        for e in range(cfg.epochs):
            order = rng.permutation(len(self.x))
            xb, yb, mb = batch_global(self.x[order], self.y[order], cfg.batch_size)
            self.rng, sub = jax.random.split(self.rng)
            self.net, self.opt_state, m = self._epoch(
                sub, self.net, self.opt_state,
                jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(mb))
            n = float(m["count"])
            rec = {"epoch": e, "train_loss": float(m["loss_sum"]) / max(n, 1),
                   "train_acc": float(m["correct"]) / max(n, 1)}
            if e == cfg.epochs - 1 or e % 5 == 0:
                rec.update(self.evaluate())
            self.history.append(rec)
        return self.net

    def evaluate(self):
        from fedml_tpu.core.local import make_eval_fn

        if not hasattr(self, "_eval_fn"):
            # cache: a fresh make_eval_fn per call would re-trace (and
            # recompile) the eval program on every evaluation
            self._eval_fn = make_eval_fn(self.task)
        xb, yb, mb = (jnp.asarray(a) for a in self.test)
        ev = self._eval_fn(self.net, xb, yb, mb)
        return {"test_loss": float(ev["loss"]), "test_acc": float(ev["acc"])}
