"""Centralized (non-federated) trainer — the baseline mode.

Reference: fedml_experiments/centralized/main.py + fedml_api/centralized/
centralized_trainer.py:9-104 — trains the same models/datasets centrally,
optionally with DistributedDataParallel (--data_parallel, main.py:52).

TPU form: one jitted epoch (lax.scan over batches); the DDP analogue is the
same step pjit-ed over a 'data' mesh axis — batch sharded, params replicated,
XLA inserts the gradient psum (exactly what DDP's allreduce does, minus the
process management).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.client_data import batch_global
from fedml_tpu.core.local import NetState, Task


@dataclasses.dataclass(frozen=True)
class CentralizedConfig:
    epochs: int = 10
    batch_size: int = 64
    lr: float = 0.03
    momentum: float = 0.9
    wd: float = 0.0
    seed: int = 0


class CentralizedTrainer:
    def __init__(self, task: Task, x, y, test_x, test_y,
                 config: CentralizedConfig, mesh: Mesh | None = None):
        self.task = task
        self.cfg = config
        self.mesh = mesh
        self.x, self.y = np.asarray(x), np.asarray(y)
        self.test = batch_global(np.asarray(test_x), np.asarray(test_y), 256)
        key = jax.random.PRNGKey(config.seed)
        self.rng, init_key = jax.random.split(key)
        self.net = task.init(init_key, jnp.asarray(self.x[: config.batch_size]))
        tx = optax.sgd(config.lr, momentum=config.momentum or None)
        if config.wd:
            tx = optax.chain(optax.add_decayed_weights(config.wd), tx)
        self.tx = tx
        self.opt_state = tx.init(self.net.params)
        self._epoch = jax.jit(self._build_epoch())
        self.history: list[dict] = []

    def _build_epoch(self):
        task, tx = self.task, self.tx

        def epoch(rng, net: NetState, opt_state, xb, yb, mb):
            def step(carry, batch):
                params, extra, opt_state, rng = carry
                x, y, m = batch
                rng, sub = jax.random.split(rng)

                def loss_fn(p):
                    l, new_extra, metr = task.loss(p, extra, x, y, m, sub, True)
                    return l, (new_extra, metr)

                (l, (new_extra, metr)), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                upd, opt_state = tx.update(g, opt_state, params)
                return (optax.apply_updates(params, upd), new_extra,
                        opt_state, rng), metr

            (params, extra, opt_state, _), metrs = jax.lax.scan(
                step, (net.params, net.extra, opt_state, rng), (xb, yb, mb))
            return NetState(params, extra), opt_state, {
                k: jnp.sum(v) for k, v in metrs.items()}

        if self.mesh is None:
            return epoch

        # data-parallel: shard the batch axis over the mesh (DDP analogue)
        mesh = self.mesh
        axis = mesh.axis_names[0]

        def epoch_dp(rng, net, opt_state, xb, yb, mb):
            # xb: [B, bs, ...] -> shard bs across devices via in_shardings
            shd = NamedSharding(mesh, P(None, axis))
            xb = jax.device_put(xb, shd)
            yb = jax.device_put(yb, shd)
            mb = jax.device_put(mb, shd)
            return epoch(rng, net, opt_state, xb, yb, mb)

        return epoch_dp

    def train(self):
        cfg = self.cfg
        rng = np.random.RandomState(cfg.seed)
        for e in range(cfg.epochs):
            order = rng.permutation(len(self.x))
            xb, yb, mb = batch_global(self.x[order], self.y[order], cfg.batch_size)
            self.rng, sub = jax.random.split(self.rng)
            self.net, self.opt_state, m = self._epoch(
                sub, self.net, self.opt_state,
                jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(mb))
            n = float(m["count"])
            rec = {"epoch": e, "train_loss": float(m["loss_sum"]) / max(n, 1),
                   "train_acc": float(m["correct"]) / max(n, 1)}
            if e == cfg.epochs - 1 or e % 5 == 0:
                rec.update(self.evaluate())
            self.history.append(rec)
        return self.net

    def evaluate(self):
        from fedml_tpu.core.local import make_eval_fn

        xb, yb, mb = (jnp.asarray(a) for a in self.test)
        ev = make_eval_fn(self.task)(self.net, xb, yb, mb)
        return {"test_loss": float(ev["loss"]), "test_acc": float(ev["acc"])}
