"""Partitioned dataset loaders (L3b).

Re-implements the reference data layer (fedml_api/data_preprocessing/*): every
loader returns a ``FederatedData`` (global train/test arrays + client->index
map + class_num), convertible to the reference's 8-tuple via
``as_eight_tuple()`` (contract at cifar10/data_loader.py:468).

Real dataset files are read when present under ``data_dir`` (LEAF json, TFF
h5, CIFAR pickles); otherwise loaders fall back to a deterministic synthetic
dataset with IDENTICAL shapes, vocab sizes, and client counts, so every
algorithm, test, and benchmark runs in a zero-download environment. The
fallback is flagged on the returned object (``synthetic_fallback=True``).
"""

from fedml_tpu.data.registry import load_dataset, DATASETS
from fedml_tpu.core.client_data import FederatedData


def dataset_source(data) -> str:
    """'real' | 'synthetic' for the telemetry run header — so bench
    artifacts can never masquerade a synthetic fallback run as
    real-dataset evidence. Streamed ClientDataSources carry the verdict
    themselves; FederatedData carries the loaders' synthetic_fallback
    flag (absent = real files were read)."""
    src = getattr(data, "source", None)
    if isinstance(src, str):
        return src
    return ("synthetic" if getattr(data, "synthetic_fallback", False)
            else "real")
