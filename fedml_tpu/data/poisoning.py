"""Backdoor / poisoning attack data utilities.

Reference: fedml_api/data_preprocessing/edge_case_examples/data_loader.py
(load_poisoned_dataset :283, 1,294 LoC) — injects attacker-controlled
"edge case" samples (ARDIS digits into MNIST clients, southwest-airline
planes into CIFAR clients, green cars) labeled with the attacker's target
class, so the aggregate model misclassifies that semantic slice while clean
accuracy stays high. Consumed by fedavg_robust for attack/defense evaluation.

Without the proprietary edge-case archives, the same attack structure is
reproduced synthetically: (1) pixel-pattern (BadNets) triggers, (2) semantic
edge-case clusters drawn from a distribution shifted off the clean manifold,
(3) label flipping. Each returns (x_poison, y_target) pairs to blend into
attacker-controlled clients plus a poisoned eval set for targeted-accuracy
measurement (FedAvgRobustAPI.evaluate_backdoor).
"""

from __future__ import annotations

import numpy as np

from fedml_tpu.core.client_data import FederatedData


def add_pixel_trigger(x: np.ndarray, size: int = 3, value: float = 2.5):
    """BadNets-style bottom-right square trigger."""
    x = np.array(x, copy=True)
    x[..., -size:, -size:, :] = value
    return x


def make_backdoor_dataset(
    data: FederatedData,
    target_label: int,
    poison_client_ids: list[int],
    poison_frac: float = 0.5,
    trigger_size: int = 3,
    seed: int = 0,
):
    """Inject triggered+relabeled samples into the given clients' partitions.

    Returns (poisoned FederatedData, eval set (x_triggered, y_target)) — the
    eval pair measures targeted-task accuracy like the reference's backdoor
    test loop (FedAvgRobustAggregator.test :14-80).
    """
    rng = np.random.RandomState(seed)
    x = np.array(data.train_x, copy=True)
    y = np.array(data.train_y, copy=True)
    for cid in poison_client_ids:
        idx = data.train_idx_map[cid]
        n_poison = max(1, int(len(idx) * poison_frac))
        sel = rng.choice(idx, n_poison, replace=False)
        x[sel] = add_pixel_trigger(x[sel], trigger_size)
        y[sel] = target_label

    poisoned = FederatedData(
        train_x=x, train_y=y, test_x=data.test_x, test_y=data.test_y,
        train_idx_map=data.train_idx_map, test_idx_map=data.test_idx_map,
        class_num=data.class_num,
    )
    # eval: clean test inputs NOT already of the target class, with trigger
    keep = np.where(np.asarray(data.test_y) != target_label)[0]
    ex = add_pixel_trigger(np.asarray(data.test_x)[keep], trigger_size)
    ey = np.full(len(keep), target_label, dtype=np.int64)
    return poisoned, (ex, ey)


def make_edge_case_dataset(
    data: FederatedData,
    target_label: int,
    poison_client_ids: list[int],
    num_edge_samples: int = 50,
    shift: float = 3.0,
    seed: int = 0,
):
    """Semantic edge-case attack: a tight off-manifold cluster labeled with
    the target class, appended to attacker clients (the ARDIS/southwest
    pattern — samples that are RARE in clean data, so defenses relying on
    majority statistics miss them)."""
    rng = np.random.RandomState(seed)
    shape = data.train_x.shape[1:]
    center = rng.normal(0, 1, shape).astype(np.float32)
    center = center / max(np.linalg.norm(center), 1e-6) * shift
    edge_x = (center[None] + 0.1 * rng.normal(0, 1, (num_edge_samples,) + shape)
              ).astype(np.float32)
    edge_y = np.full(num_edge_samples, target_label, dtype=np.int64)

    x = np.concatenate([data.train_x, edge_x])
    y = np.concatenate([data.train_y, edge_y])
    idx_map = {k: np.array(v, copy=True) for k, v in data.train_idx_map.items()}
    edge_ids = np.arange(len(data.train_x), len(x))
    split = np.array_split(edge_ids, len(poison_client_ids))
    for cid, extra in zip(poison_client_ids, split):
        idx_map[cid] = np.concatenate([idx_map[cid], extra])

    poisoned = FederatedData(
        train_x=x, train_y=y, test_x=data.test_x, test_y=data.test_y,
        train_idx_map=idx_map, test_idx_map=data.test_idx_map,
        class_num=data.class_num,
    )
    # eval: fresh draws from the same edge distribution
    ex = (center[None] + 0.1 * rng.normal(0, 1, (num_edge_samples,) + shape)
          ).astype(np.float32)
    ey = np.full(num_edge_samples, target_label, dtype=np.int64)
    return poisoned, (ex, ey)


def flip_labels(data: FederatedData, client_ids: list[int], from_label: int,
                to_label: int):
    """Label-flip attack on the given clients."""
    y = np.array(data.train_y, copy=True)
    for cid in client_ids:
        idx = data.train_idx_map[cid]
        sel = idx[np.asarray(data.train_y)[idx] == from_label]
        y[sel] = to_label
    return FederatedData(
        train_x=data.train_x, train_y=y, test_x=data.test_x,
        test_y=data.test_y, train_idx_map=data.train_idx_map,
        test_idx_map=data.test_idx_map, class_num=data.class_num,
    )
