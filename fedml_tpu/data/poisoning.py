"""Backdoor / poisoning attack data utilities.

Reference: fedml_api/data_preprocessing/edge_case_examples/data_loader.py
(load_poisoned_dataset :283, 1,294 LoC) — injects attacker-controlled
"edge case" samples (ARDIS digits into MNIST clients, southwest-airline
planes into CIFAR clients, green cars) labeled with the attacker's target
class, so the aggregate model misclassifies that semantic slice while clean
accuracy stays high. Consumed by fedavg_robust for attack/defense evaluation.

Two paths:
  * REAL archives present: ``inject_edge_case_files`` reads the reference's
    on-disk formats — southwest/green-car bare-array pickles
    (data_loader.py:346-352,642-646) and ARDIS-style torch saves
    (data_loader.py:293,321) — and performs the same mixing (downsample the
    edge set, append to attacker clients, edge test set = targeted eval).
  * No archives (this environment has zero egress): the same attack
    structure is reproduced synthetically — (1) pixel-pattern (BadNets)
    triggers, (2) semantic edge-case clusters drawn from a distribution
    shifted off the clean manifold, (3) label flipping.
Each returns (x_poison, y_target) pairs blended into attacker-controlled
clients plus a poisoned eval set for targeted-accuracy measurement
(FedAvgRobustAPI.evaluate_backdoor).
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from fedml_tpu.core.client_data import FederatedData

# attacker target labels the reference hard-codes per archive
# (data_loader.py:370 southwest->9 'truck'; :592 green-car->2 'bird').
# ARDIS saves carry their own targets inside the file (data_loader.py:321).
EDGE_CASE_TARGETS = {"southwest": 9, "greencar": 2}


def add_pixel_trigger(x: np.ndarray, size: int = 3, value: float = 2.5):
    """BadNets-style bottom-right square trigger. ``value`` is on the
    float-image scale (>1 = super-saturated); integer (uint8) images get
    the equivalent 0..255 intensity — assigning 2.5 raw into uint8 would
    truncate to 2, a near-black non-trigger."""
    x = np.array(x, copy=True)
    if np.issubdtype(x.dtype, np.integer):
        value = int(np.clip(value * 255, 0, 255))
    x[..., -size:, -size:, :] = value
    return x


def make_backdoor_dataset(
    data: FederatedData,
    target_label: int,
    poison_client_ids: list[int],
    poison_frac: float = 0.5,
    trigger_size: int = 3,
    seed: int = 0,
):
    """Inject triggered+relabeled samples into the given clients' partitions.

    Returns (poisoned FederatedData, eval set (x_triggered, y_target)) — the
    eval pair measures targeted-task accuracy like the reference's backdoor
    test loop (FedAvgRobustAggregator.test :14-80).
    """
    rng = np.random.RandomState(seed)
    x = np.array(data.train_x, copy=True)
    y = np.array(data.train_y, copy=True)
    for cid in poison_client_ids:
        idx = data.train_idx_map[cid]
        n_poison = max(1, int(len(idx) * poison_frac))
        sel = rng.choice(idx, n_poison, replace=False)
        x[sel] = add_pixel_trigger(x[sel], trigger_size)
        y[sel] = target_label

    poisoned = FederatedData(
        train_x=x, train_y=y, test_x=data.test_x, test_y=data.test_y,
        train_idx_map=data.train_idx_map, test_idx_map=data.test_idx_map,
        class_num=data.class_num,
    )
    # eval: clean test inputs NOT already of the target class, with trigger
    keep = np.where(np.asarray(data.test_y) != target_label)[0]
    ex = add_pixel_trigger(np.asarray(data.test_x)[keep], trigger_size)
    ey = np.full(len(keep), target_label, dtype=np.int64)
    return poisoned, (ex, ey)


def _load_edge_file(path: str):
    """One edge-case archive file -> (x images, y labels-or-None).

    Formats (reference data_loader.py):
      * ``.pkl``/``.pickle`` — southwest (:346) / green-car (:642): a bare
        pickled uint8 image array [N, 32, 32, 3]; labels are implicit (the
        caller supplies the attacker's target class).
      * ``.pt``/``.pth`` — ARDIS-style torch saves (:293, :321): a tensor,
        a (data, targets) pair, a {'data','targets'} dict, or any
        dataset-like object exposing .data/.targets.
    Grayscale [N, H, W] arrays gain a trailing channel dim (MNIST NHWC).
    """
    ext = os.path.splitext(path)[1].lower()
    if ext in (".pt", ".pth"):
        import torch

        try:
            obj = torch.load(path, map_location="cpu", weights_only=True)
        except Exception as e:
            # legacy archives (the reference's ARDIS saves predate
            # weights_only) need full unpickling, which EXECUTES code from
            # the file — an automatic fallback would run exactly the
            # payloads the safe loader refused, so it requires an explicit
            # opt-in for archives the operator has vetted
            if os.environ.get("FEDML_ALLOW_LEGACY_TORCH_LOAD") != "1":
                raise ValueError(
                    f"{path}: torch.load(weights_only=True) refused this "
                    "archive ({!r}). If it is a LEGACY save from a source "
                    "you trust, set FEDML_ALLOW_LEGACY_TORCH_LOAD=1 to "
                    "allow full unpickling (which executes code from the "
                    "file).".format(e)) from e
            import warnings

            warnings.warn(
                f"{path}: falling back to torch.load(weights_only=False); "
                "this executes arbitrary code from the archive — make sure "
                "it comes from a trusted source", stacklevel=2)
            obj = torch.load(path, map_location="cpu", weights_only=False)
        if isinstance(obj, dict):
            x, y = obj["data"], obj.get("targets")
        elif isinstance(obj, (tuple, list)) and len(obj) == 2:
            x, y = obj
        elif hasattr(obj, "data"):
            x, y = obj.data, getattr(obj, "targets", None)
        else:
            x, y = obj, None
        x = np.asarray(x)
        y = None if y is None else np.asarray(y).reshape(-1).astype(np.int64)
    else:
        with open(path, "rb") as f:
            x = np.asarray(pickle.load(f))
        y = None
    if x.ndim == 3:  # [N, H, W] grayscale -> NHWC
        x = x[..., None]
    return x, y


def _match_pixels(edge_x: np.ndarray, like: np.ndarray) -> np.ndarray:
    """Convert edge images to the host dataset's pixel convention (uint8
    0..255 on the flagship device-data path, float 0..1 elsewhere)."""
    if like.dtype == np.uint8:
        if edge_x.dtype == np.uint8:
            return edge_x
        return np.clip(np.asarray(edge_x, np.float32) * 255.0, 0, 255) \
            .astype(np.uint8)
    edge_x = np.asarray(edge_x, like.dtype)
    if edge_x.max() > 1.5:  # was uint8-scaled
        edge_x = edge_x / np.asarray(255.0, like.dtype)
    return edge_x


def _append_to_clients(data: FederatedData, edge_x, edge_y,
                       poison_client_ids: list[int]) -> FederatedData:
    """Append the edge samples to the attacker clients' partitions (the
    reference mixes them into the poisoned trainset, data_loader.py:407)."""
    x = np.concatenate([data.train_x, edge_x])
    y = np.concatenate([data.train_y, edge_y])
    idx_map = {k: np.array(v, copy=True) for k, v in data.train_idx_map.items()}
    edge_ids = np.arange(len(data.train_x), len(x))
    split = np.array_split(edge_ids, len(poison_client_ids))
    for cid, extra in zip(poison_client_ids, split):
        idx_map[cid] = np.concatenate([idx_map[cid], extra])
    return FederatedData(
        train_x=x, train_y=y, test_x=data.test_x, test_y=data.test_y,
        train_idx_map=idx_map, test_idx_map=data.test_idx_map,
        class_num=data.class_num,
    )


def inject_edge_case_files(
    data: FederatedData,
    train_path: str,
    test_path: str | None = None,
    *,
    poison_client_ids: list[int],
    target_label: int | None = None,
    num_edge_samples: int = 100,
    seed: int = 0,
):
    """REAL edge-case attack from the reference's on-disk archives.

    Mirrors load_poisoned_dataset's edge-case mixing (data_loader.py:380-426):
    the edge train set is downsampled to ``num_edge_samples`` (the
    reference's N=100), relabeled with the attacker's target class (implicit
    for .pkl archives — pass ``target_label`` or rely on the file's own
    targets for ARDIS saves), appended to the attacker clients' partitions;
    the edge TEST set becomes the targeted-task eval pair.

    Returns (poisoned FederatedData, (edge_test_x, edge_test_y)).
    """
    rng = np.random.RandomState(seed)
    ex, ey = _load_edge_file(train_path)
    if target_label is not None:
        ey = np.full(len(ex), target_label, dtype=np.int64)
    elif ey is None:
        raise ValueError(
            f"{train_path}: archive carries no labels — pass target_label "
            f"(reference conventions: {EDGE_CASE_TARGETS})")
    if num_edge_samples < len(ex):  # data_loader.py:382-386 downsample
        sel = rng.choice(len(ex), num_edge_samples, replace=False)
        ex, ey = ex[sel], ey[sel]
    ex = _match_pixels(ex, data.train_x)
    if ex.shape[1:] != data.train_x.shape[1:]:
        raise ValueError(f"edge images {ex.shape[1:]} don't match the host "
                         f"dataset {data.train_x.shape[1:]}")
    poisoned = _append_to_clients(data, ex, ey, poison_client_ids)

    if test_path is not None:
        tx, ty = _load_edge_file(test_path)
        if target_label is not None:
            ty = np.full(len(tx), target_label, dtype=np.int64)
        elif ty is None:
            raise ValueError(f"{test_path}: no labels and no target_label")
        tx = _match_pixels(tx, data.train_x)
    else:  # no test archive: eval on the (held-in) edge train samples
        tx, ty = ex, ey
    return poisoned, (tx, ty)


def make_edge_case_dataset(
    data: FederatedData,
    target_label: int,
    poison_client_ids: list[int],
    num_edge_samples: int = 50,
    shift: float = 3.0,
    seed: int = 0,
):
    """Semantic edge-case attack: a tight off-manifold cluster labeled with
    the target class, appended to attacker clients (the ARDIS/southwest
    pattern — samples that are RARE in clean data, so defenses relying on
    majority statistics miss them)."""
    rng = np.random.RandomState(seed)
    shape = data.train_x.shape[1:]
    center = rng.normal(0, 1, shape).astype(np.float32)
    center = center / max(np.linalg.norm(center), 1e-6) * shift

    def conv(e):
        # match the host dataset's pixel convention: concatenating a f32
        # cluster onto a uint8 train set would silently promote the WHOLE
        # set to f32 and disable the on-device /255 normalization. On
        # uint8 hosts the cluster is clipped into the valid pixel range
        # (still a distinctive off-manifold pattern); eval draws get the
        # identical transform so targeted eval measures the same thing.
        if data.train_x.dtype == np.uint8:
            return np.clip(e * 255.0, 0, 255).astype(np.uint8)
        return e.astype(data.train_x.dtype)

    edge_x = conv(center[None]
                  + 0.1 * rng.normal(0, 1, (num_edge_samples,) + shape))
    edge_y = np.full(num_edge_samples, target_label, dtype=np.int64)

    x = np.concatenate([data.train_x, edge_x])
    y = np.concatenate([data.train_y, edge_y])
    idx_map = {k: np.array(v, copy=True) for k, v in data.train_idx_map.items()}
    edge_ids = np.arange(len(data.train_x), len(x))
    split = np.array_split(edge_ids, len(poison_client_ids))
    for cid, extra in zip(poison_client_ids, split):
        idx_map[cid] = np.concatenate([idx_map[cid], extra])

    poisoned = FederatedData(
        train_x=x, train_y=y, test_x=data.test_x, test_y=data.test_y,
        train_idx_map=idx_map, test_idx_map=data.test_idx_map,
        class_num=data.class_num,
    )
    # eval: fresh draws from the same edge distribution
    ex = conv(center[None]
              + 0.1 * rng.normal(0, 1, (num_edge_samples,) + shape))
    ey = np.full(num_edge_samples, target_label, dtype=np.int64)
    return poisoned, (ex, ey)


def flip_labels(data: FederatedData, client_ids: list[int], from_label: int,
                to_label: int):
    """Label-flip attack on the given clients."""
    y = np.array(data.train_y, copy=True)
    for cid in client_ids:
        idx = data.train_idx_map[cid]
        sel = idx[np.asarray(data.train_y)[idx] == from_label]
        y[sel] = to_label
    return FederatedData(
        train_x=data.train_x, train_y=y, test_x=data.test_x,
        test_y=data.test_y, train_idx_map=data.train_idx_map,
        test_idx_map=data.test_idx_map, class_num=data.class_num,
    )
