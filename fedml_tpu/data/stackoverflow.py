"""StackOverflow vocabulary + encoding utilities (NWP and tag-LR tasks).

Mirror of fedml_api/data_preprocessing/stackoverflow_nwp/ and
stackoverflow_lr/ vocab utils: the NWP task uses the 10,000 most frequent
words plus 4 special ids (pad=0, then vocab, then bos/eos/oov), giving the
10004-way output of RNN_StackOverFlow (model/nlp/rnn.py:39-70); the LR task
uses the top-500 tags and top-10,000 words as a bag-of-words multi-label
problem.

File-format note: the TFF h5 stores per-client token strings; when the real
h5 is absent, the registry's synthetic sequence fallback is used and these
utilities still define the id space.
"""

from __future__ import annotations

import collections

import numpy as np

DEFAULT_WORD_VOCAB_SIZE = 10000
DEFAULT_TAG_VOCAB_SIZE = 500
PAD, BOS, EOS, OOV = "<pad>", "<bos>", "<eos>", "<oov>"


def build_word_vocab(word_counts: dict[str, int], vocab_size: int = DEFAULT_WORD_VOCAB_SIZE):
    """Top-``vocab_size`` words by count -> id. Ids: pad=0, words 1..V,
    bos=V+1, eos=V+2, oov=V+3 (the reference's 10004 = 10000+4 layout)."""
    most = sorted(word_counts.items(), key=lambda kv: (-kv[1], kv[0]))[:vocab_size]
    vocab = {PAD: 0}
    for i, (w, _) in enumerate(most):
        vocab[w] = i + 1
    vocab[BOS] = vocab_size + 1
    vocab[EOS] = vocab_size + 2
    vocab[OOV] = vocab_size + 3
    return vocab


def build_tag_vocab(tag_counts: dict[str, int], vocab_size: int = DEFAULT_TAG_VOCAB_SIZE):
    most = sorted(tag_counts.items(), key=lambda kv: (-kv[1], kv[0]))[:vocab_size]
    return {t: i for i, (t, _) in enumerate(most)}


def encode_nwp(sentence: str, vocab: dict[str, int], seq_len: int = 20) -> np.ndarray:
    """bos + tokens + eos, truncated/padded to seq_len+1 ids (x = ids[:-1],
    y = ids[1:] is the next-word-prediction frame)."""
    V = len(vocab) - 4
    oov = vocab[OOV]
    ids = [vocab[BOS]] + [vocab.get(w, oov) for w in sentence.split()] + [vocab[EOS]]
    ids = ids[: seq_len + 1]
    ids += [vocab[PAD]] * (seq_len + 1 - len(ids))
    return np.asarray(ids, np.int32)


def encode_tags(tags: str, tag_vocab: dict[str, int],
                num_tags: int | None = None) -> np.ndarray:
    """'|'-separated tag string -> multi-hot [num_tags] float32 (pass
    ``num_tags`` to keep the fixed 500-dim layout when the corpus yields a
    smaller vocab)."""
    out = np.zeros((num_tags or len(tag_vocab),), np.float32)
    for t in tags.split("|"):
        i = tag_vocab.get(t)
        if i is not None:
            out[i] = 1.0
    return out


def encode_bow(sentence: str, vocab: dict[str, int],
               dim: int | None = None) -> np.ndarray:
    """Normalized bag-of-words over the word vocab (the LR task's input).
    The id layout is FIXED at vocab_size+4 (pad/words/bos/eos/oov) even when
    the corpus has fewer distinct words, so the default dim is max-id+1,
    NOT len(vocab) — a small corpus + len(vocab) would put OOV out of
    bounds."""
    out = np.zeros((dim or max(vocab.values()) + 1,), np.float32)
    words = sentence.split()
    oov = vocab[OOV]
    for w in words:
        out[vocab.get(w, oov)] += 1.0
    if words:
        out /= len(words)
    return out


def word_counts_from_clients(client_sentences: dict[int, list[str]]):
    """Aggregate corpus counts (the h5 preprocessing step)."""
    counts: collections.Counter = collections.Counter()
    for sents in client_sentences.values():
        for s in sents:
            counts.update(s.split())
    return dict(counts)


def tag_counts_from_clients(client_tags: dict[int, list[str]]):
    """Aggregate tag counts over clients' '|'-separated tag strings (the
    tag-vocab preprocessing step of stackoverflow_lr)."""
    counts: collections.Counter = collections.Counter()
    for tags in client_tags.values():
        for t in tags:
            for tag in t.split("|"):
                if tag:
                    counts[tag] += 1
    return counts
