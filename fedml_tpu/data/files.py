"""Real dataset file readers (used when files exist under data_dir).

Covers the reference's on-disk formats:
- LEAF json train/test dirs (MNIST power-law, shakespeare —
  reference fedml_api/data_preprocessing/MNIST/data_loader.py:131-165)
- TFF h5 (femnist/fed_cifar100/fed_shakespeare/stackoverflow —
  FederatedEMNIST/data_loader.py:22-24 reads examples/<cid>/{pixels,label})
- CIFAR-10/100 python pickles (cifar10/data_loader.py)

Returns None when the expected files are missing so the caller can fall back
to synthetic data.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import pickle

import numpy as np

log = logging.getLogger("fedml_tpu.data.files")

from fedml_tpu.core.client_data import FederatedData
from fedml_tpu.core.partition import partition_data


def try_load(spec, data_dir, n_clients, partition_method, partition_alpha, seed,
             partition_fix_path=None, image_size=None):
    name = spec.name
    isz = (image_size, image_size) if image_size else None
    try:
        if name in ("mnist", "shakespeare") and os.path.isdir(os.path.join(data_dir, "train")):
            return _load_leaf_json(data_dir, spec, n_clients)
        if name in ("femnist", "fed_cifar100", "fed_shakespeare"):
            fd = _load_tff_h5(data_dir, spec, n_clients)
            if fd is not None:
                return fd
        if name in ("cifar10", "cifar100"):
            fd = _load_cifar_pickle(data_dir, spec, n_clients, partition_method or "hetero", partition_alpha, seed,
                                    fix_path=partition_fix_path)
            if fd is not None:
                return fd
        if name == "cinic10":
            fd = _load_cinic_folder(data_dir, spec, n_clients,
                                    partition_method or "hetero",
                                    partition_alpha, seed,
                                    fix_path=partition_fix_path)
            if fd is not None:
                return fd
        if name == "svhn":
            fd = _load_svhn_mat(data_dir, spec, n_clients,
                                partition_method or "hetero", partition_alpha,
                                seed, fix_path=partition_fix_path)
            if fd is not None:
                return fd
        if name in ("gld23k", "gld160k"):
            fd = _load_landmarks_csv(data_dir, spec, n_clients,
                                     **({"image_size": isz} if isz else {}))
            if fd is not None:
                return fd
        if name == "imagenet":
            fd = _load_imagenet_folder(data_dir, spec, n_clients,
                                       **({"image_size": isz} if isz else {}))
            if fd is not None:
                return fd
        if name in ("stackoverflow_nwp", "stackoverflow_lr"):
            fd = _load_stackoverflow_h5(data_dir, spec, n_clients)
            if fd is not None:
                return fd
    except Exception:  # noqa: BLE001 — any reader failure falls back, but
        # NEVER silently: a truncated download or schema drift must not
        # masquerade a synthetic run as real-dataset evidence (the run
        # header's dataset_source field is the machine-readable twin)
        log.warning("real-dataset reader for %r failed under %s — falling "
                    "back to synthetic data", name, data_dir, exc_info=True)
        return None
    log.warning("no loadable %r files under %s — falling back to "
                "synthetic data", name, data_dir)
    return None


def _load_leaf_json(data_dir, spec, n_clients):
    """LEAF format: {train,test}/*.json with users/user_data{x,y}."""

    def read_split(split):
        xs, ys, users = [], [], []
        for path in sorted(glob.glob(os.path.join(data_dir, split, "*.json"))):
            with open(path) as f:
                blob = json.load(f)
            for u in blob["users"]:
                ud = blob["user_data"][u]
                xs.append(np.asarray(ud["x"], dtype=np.float32))
                ys.append(np.asarray(ud["y"], dtype=np.int64))
                users.append(u)
        return xs, ys, users

    tr_x, tr_y, users = read_split("train")
    te_x, te_y, _ = read_split("test")
    if not tr_x:
        return None
    tr_x, tr_y = tr_x[:n_clients], tr_y[:n_clients]
    te_x, te_y = te_x[:n_clients], te_y[:n_clients]
    idx_map, te_map, off, toff = {}, {}, 0, 0
    for k in range(len(tr_x)):
        idx_map[k] = np.arange(off, off + len(tr_x[k])); off += len(tr_x[k])
        te_map[k] = np.arange(toff, toff + len(te_x[k])); toff += len(te_x[k])
    X = np.concatenate(tr_x).reshape((-1,) + spec.input_shape)
    TX = np.concatenate(te_x).reshape((-1,) + spec.input_shape)
    return FederatedData(X, np.concatenate(tr_y), TX, np.concatenate(te_y),
                         idx_map, te_map, spec.num_classes)


def _load_tff_h5(data_dir, spec, n_clients):
    try:
        import h5py
    except ImportError:
        return None
    paths = {p: os.path.join(data_dir, p) for p in os.listdir(data_dir) if p.endswith(".h5")}
    train_p = next((v for k, v in paths.items() if "train" in k), None)
    test_p = next((v for k, v in paths.items() if "test" in k), None)
    if train_p is None:
        return None

    def read(path, limit):
        xs, ys, idx_map, off = [], [], {}, 0
        with h5py.File(path, "r") as f:
            ex = f["examples"]
            cids = sorted(ex.keys())[:limit]
            for k, cid in enumerate(cids):
                g = ex[cid]
                xkey = "pixels" if "pixels" in g else ("image" if "image" in g else "snippets")
                ykey = "label" if "label" in g else None
                x = np.asarray(g[xkey])
                xs.append(x.astype(np.float32) if x.dtype != np.dtype("O") else x)
                ys.append(np.asarray(g[ykey], dtype=np.int64) if ykey else None)
                idx_map[k] = np.arange(off, off + len(x)); off += len(x)
        return xs, ys, idx_map

    tr_x, tr_y, idx_map = read(train_p, n_clients)
    te_x, te_y, te_map = read(test_p, n_clients) if test_p else (tr_x, tr_y, idx_map)
    X = np.concatenate(tr_x)
    if X.ndim == 3:  # [N, H, W] -> NHWC
        X = X[..., None]
    TX = np.concatenate(te_x)
    if TX.ndim == 3:
        TX = TX[..., None]
    return FederatedData(X, np.concatenate(tr_y), TX, np.concatenate(te_y),
                         idx_map, te_map, spec.num_classes)


def _load_imagenet_folder(data_dir, spec, n_clients, image_size=(64, 64),
                          max_per_class=64):
    """ImageNet ILSVRC layout: ``train/<wnid>/*.JPEG`` (+ optional
    ``val/<wnid>/*``). Mirror of fedml_api/data_preprocessing/ImageNet/
    data_loader.py: sorted wnids become class ids and clients take whole
    classes round-robin (the federated-ImageNet convention — each client
    holds a disjoint label subset). Decoding is PIL-gated; images are
    resized to ``image_size`` and capped at ``max_per_class`` so a full
    ILSVRC tree loads at study scale rather than 150 GB."""
    train_dir = os.path.join(data_dir, "train")
    if not os.path.isdir(train_dir):
        return None
    wnids = sorted(d for d in os.listdir(train_dir)
                   if os.path.isdir(os.path.join(train_dir, d)))
    if not wnids:
        return None
    try:
        from PIL import Image
    except ImportError:
        return None

    exts = (".jpeg", ".jpg", ".png")

    def read_split(split_dir):
        xs, ys = [], []
        for cls, wnid in enumerate(wnids):
            d = os.path.join(split_dir, wnid)
            if not os.path.isdir(d):
                continue
            names = [n for n in sorted(os.listdir(d))
                     if n.lower().endswith(exts)]  # filter BEFORE capping so
            for name in names[:max_per_class]:     # junk can't starve a class
                try:
                    with Image.open(os.path.join(d, name)) as im:
                        arr = np.asarray(
                            im.convert("RGB").resize(image_size), np.float32
                        ) / 255.0
                except Exception:  # noqa: BLE001 — truncated/bomb/degenerate
                    # image; anything narrower (OSError) would let e.g.
                    # DecompressionBombError escape to try_load's blanket
                    # except and silently swap the WHOLE dataset for the
                    # synthetic fallback
                    continue
                xs.append(arr)
                ys.append(cls)
        if not xs:
            return None, None
        return np.stack(xs), np.asarray(ys, np.int64)

    X, Y = read_split(train_dir)
    if X is None:
        return None
    TX, TY = read_split(os.path.join(data_dir, "val"))
    if TX is None:
        # no val split shipped: hold out every 5th row as test and REMOVE it
        # from train (train/test must stay disjoint)
        held = np.zeros(len(X), bool)
        held[::5] = True
        TX, TY = X[held], Y[held]
        X, Y = X[~held], Y[~held]

    # whole classes round-robin; empty clients are forbidden (an all-empty
    # sampled round would zero the model), so the cap counts classes with at
    # least one TRAIN row after the holdout — not wnid directories, which
    # can be empty or lose their only image to val
    present = np.unique(Y)
    n_eff = min(n_clients, len(present))
    if n_eff == 0:
        return None
    idx_map: dict[int, list] = {k: [] for k in range(n_eff)}
    for j, cls in enumerate(present):
        rows = np.nonzero(Y == cls)[0]
        idx_map[j % n_eff].extend(rows.tolist())
    idx_map = {k: np.asarray(v, np.int64) for k, v in idx_map.items()}
    return FederatedData(X, Y, TX, TY, idx_map, None, len(wnids))


def _load_landmarks_csv(data_dir, spec, n_clients, image_size=(64, 64)):
    """Google Landmarks federated split (gld23k/gld160k).

    Mirror of fedml_api/data_preprocessing/Landmarks/data_loader.py: a csv
    maps (user_id, image_id, class); images live under ``images/``. Images
    are decoded with PIL (gated) and resized to a fixed size; users become
    clients in csv order. Returns None when csv or images are absent.
    """
    csvs = sorted(glob.glob(os.path.join(data_dir, "*train*.csv")))
    img_dir = os.path.join(data_dir, "images")
    if not csvs or not os.path.isdir(img_dir):
        return None
    try:
        from PIL import Image
    except ImportError:
        return None
    import csv as _csv

    per_user: dict[str, list] = {}
    with open(csvs[0]) as f:
        for row in _csv.DictReader(f):
            per_user.setdefault(row["user_id"], []).append(
                (row["image_id"], int(row["class"]))
            )

    xs, ys, idx_map, off = [], [], {}, 0
    for k, (_uid, items) in enumerate(sorted(per_user.items())[:n_clients]):
        cnt = 0
        for image_id, cls in items:
            path = os.path.join(img_dir, f"{image_id}.jpg")
            if not os.path.exists(path):
                continue
            with Image.open(path) as im:
                arr = np.asarray(
                    im.convert("RGB").resize(image_size), np.float32
                ) / 255.0
            xs.append(arr)
            ys.append(cls)
            cnt += 1
        if cnt:
            idx_map[k] = np.arange(off, off + cnt)
            off += cnt
    if not xs:
        return None
    X = np.stack(xs)
    Y = np.asarray(ys, np.int64)

    test_csvs = sorted(glob.glob(os.path.join(data_dir, "*test*.csv")))
    TX, TY = X[:256], Y[:256]
    if test_csvs:
        txs, tys = [], []
        with open(test_csvs[0]) as f:
            for row in _csv.DictReader(f):
                path = os.path.join(img_dir, f"{row['image_id']}.jpg")
                if not os.path.exists(path):
                    continue
                with Image.open(path) as im:
                    txs.append(np.asarray(
                        im.convert("RGB").resize(image_size), np.float32) / 255.0)
                tys.append(int(row["class"]))
        if txs:
            TX, TY = np.stack(txs), np.asarray(tys, np.int64)
    return FederatedData(X, Y, TX, TY, idx_map, None, spec.num_classes)


def _load_cifar_pickle(data_dir, spec, n_clients, method, alpha, seed,
                       fix_path=None):
    batches = sorted(glob.glob(os.path.join(data_dir, "data_batch*"))) or \
        sorted(glob.glob(os.path.join(data_dir, "train")))
    if not batches:
        return None
    xs, ys = [], []
    for p in batches:
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(np.asarray(d[b"data"], dtype=np.float32).reshape(-1, 3, 32, 32))
        ys.append(np.asarray(d.get(b"labels", d.get(b"fine_labels")), dtype=np.int64))
    X = np.concatenate(xs).transpose(0, 2, 3, 1) / 255.0  # NHWC
    Y = np.concatenate(ys)
    test_path = os.path.join(data_dir, "test_batch")
    if os.path.exists(test_path):
        with open(test_path, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        TX = np.asarray(d[b"data"], np.float32).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1) / 255.0
        TY = np.asarray(d.get(b"labels", d.get(b"fine_labels")), dtype=np.int64)
    else:
        TX, TY = X[:1000], Y[:1000]
    idx_map = partition_data(Y, n_clients, method, alpha, seed, fix_path=fix_path)
    return FederatedData(X, Y, TX, TY, idx_map, None, spec.num_classes)


def _load_cinic_folder(data_dir, spec, n_clients, method, alpha, seed,
                       fix_path=None):
    """CINIC-10 imagefolder layout: ``{train,valid,test}/<class>/*.png``
    (reference fedml_api/data_preprocessing/cinic10/data_loader.py — an
    ImageFolder over the same tree, then the shared LDA partition path).
    'valid' merges into train like the reference's enlarged train split."""
    train_dir = os.path.join(data_dir, "train")
    if not os.path.isdir(train_dir):
        return None
    classes = sorted(d for d in os.listdir(train_dir)
                     if os.path.isdir(os.path.join(train_dir, d)))
    if not classes:
        return None
    try:
        from PIL import Image
    except ImportError:
        return None
    exts = (".png", ".jpeg", ".jpg")

    def read_split(split):
        sdir = os.path.join(data_dir, split)
        if not os.path.isdir(sdir):
            return None, None
        xs, ys = [], []
        for cls, cname in enumerate(classes):
            d = os.path.join(sdir, cname)
            if not os.path.isdir(d):
                continue
            for name in sorted(os.listdir(d)):
                if not name.lower().endswith(exts):
                    continue
                try:
                    with Image.open(os.path.join(d, name)) as im:
                        arr = np.asarray(im.convert("RGB"), np.float32) / 255.0
                except Exception:  # noqa: BLE001 — skip unreadable images
                    continue
                xs.append(arr)
                ys.append(cls)
        if not xs:
            return None, None
        return np.stack(xs), np.asarray(ys, np.int64)

    X, Y = read_split("train")
    if X is None:
        return None
    VX, VY = read_split("valid")
    if VX is not None:  # reference merges valid into train
        X, Y = np.concatenate([X, VX]), np.concatenate([Y, VY])
    TX, TY = read_split("test")
    if TX is None:
        held = np.zeros(len(X), bool)
        held[::5] = True
        TX, TY, X, Y = X[held], Y[held], X[~held], Y[~held]
    idx_map = partition_data(Y, n_clients, method, alpha, seed, fix_path=fix_path)
    return FederatedData(X, Y, TX, TY, idx_map, None, len(classes))


def _load_svhn_mat(data_dir, spec, n_clients, method, alpha, seed,
                   fix_path=None):
    """SVHN cropped-digit .mat files (``train_32x32.mat``/``test_32x32.mat``):
    X is [32, 32, 3, N] uint8, y is [N, 1] with label 10 meaning digit 0
    (torchvision convention). Partitioned through the shared LDA path like
    the reference's cifar10/data_loader.py:140-209 family."""
    train_p = os.path.join(data_dir, "train_32x32.mat")
    if not os.path.exists(train_p):
        return None
    try:
        from scipy.io import loadmat
    except ImportError:
        return None

    def read(path):
        m = loadmat(path)
        X = np.transpose(m["X"], (3, 0, 1, 2)).astype(np.float32) / 255.0
        y = np.asarray(m["y"], np.int64).reshape(-1)
        y[y == 10] = 0
        return X, y

    X, Y = read(train_p)
    test_p = os.path.join(data_dir, "test_32x32.mat")
    if os.path.exists(test_p):
        TX, TY = read(test_p)
    else:
        held = np.zeros(len(X), bool)
        held[::5] = True
        TX, TY, X, Y = X[held], Y[held], X[~held], Y[~held]
    idx_map = partition_data(Y, n_clients, method, alpha, seed, fix_path=fix_path)
    return FederatedData(X, Y, TX, TY, idx_map, None, spec.num_classes)


def _load_stackoverflow_h5(data_dir, spec, n_clients):
    """TFF stackoverflow h5: examples/<uid>/{tokens, tags, ...} byte strings
    (reference fedml_api/data_preprocessing/stackoverflow_{nwp,lr}). The
    vocab is built from the loaded clients' corpora via data/stackoverflow.py
    (the reference ships precomputed top-10000 word / top-500 tag counts;
    corpus-derived counts converge to them on the same data)."""
    try:
        import h5py
    except ImportError:
        return None

    from fedml_tpu.data.stackoverflow import (
        DEFAULT_TAG_VOCAB_SIZE, DEFAULT_WORD_VOCAB_SIZE, build_tag_vocab,
        build_word_vocab, encode_bow, encode_nwp, encode_tags,
        tag_counts_from_clients, word_counts_from_clients)

    paths = {p: os.path.join(data_dir, p) for p in os.listdir(data_dir) if p.endswith(".h5")}
    train_p = next((v for k, v in paths.items() if "train" in k), None)
    test_p = next((v for k, v in paths.items() if "test" in k), None)
    if train_p is None:
        return None
    nwp = spec.name == "stackoverflow_nwp"

    def read_text(path, limit):
        sents, tags = {}, {}
        with h5py.File(path, "r") as f:
            ex = f["examples"]
            for k, cid in enumerate(sorted(ex.keys())[:limit]):
                g = ex[cid]
                sents[k] = [t.decode() if isinstance(t, bytes) else str(t)
                            for t in np.asarray(g["tokens"])]
                if "tags" in g:
                    tags[k] = [t.decode() if isinstance(t, bytes) else str(t)
                               for t in np.asarray(g["tags"])]
        return sents, tags

    tr_s, tr_t = read_text(train_p, n_clients)
    te_s, te_t = read_text(test_p, n_clients) if test_p else (tr_s, tr_t)
    vocab = build_word_vocab(word_counts_from_clients(tr_s),
                             DEFAULT_WORD_VOCAB_SIZE)

    if nwp:
        def encode_all(sents_by_client):
            xs, idx_map, off = [], {}, 0
            for k in sorted(sents_by_client):
                ids = np.stack([encode_nwp(s, vocab) for s in sents_by_client[k]])
                xs.append(ids)
                idx_map[k] = np.arange(off, off + len(ids)); off += len(ids)
            return np.concatenate(xs), idx_map

        X, idx_map = encode_all(tr_s)
        TX, te_map = encode_all(te_s)
        # next-word prediction frame: x = ids[:-1], y = ids[1:]
        return FederatedData(X[:, :-1], X[:, 1:], TX[:, :-1], TX[:, 1:],
                             idx_map, te_map, spec.num_classes)

    tag_vocab = build_tag_vocab(tag_counts_from_clients(tr_t),
                                DEFAULT_TAG_VOCAB_SIZE)
    # FIXED spec dims (10004-dim bow, 500-dim tags) regardless of how many
    # distinct words/tags the loaded corpus slice has — the model factory
    # builds from spec.input_shape/num_classes, and OOV ids sit at the top
    # of the fixed layout
    dim_x = DEFAULT_WORD_VOCAB_SIZE + 4
    num_tags = DEFAULT_TAG_VOCAB_SIZE

    def encode_all(sents_by_client, tags_by_client):
        xs, ys, idx_map, off = [], [], {}, 0
        for k in sorted(sents_by_client):
            xs.append(np.stack([encode_bow(s, vocab, dim=dim_x)
                                for s in sents_by_client[k]]))
            ys.append(np.stack([encode_tags(t, tag_vocab, num_tags=num_tags)
                                for t in tags_by_client.get(k, [""] * len(sents_by_client[k]))]))
            idx_map[k] = np.arange(off, off + len(xs[-1])); off += len(xs[-1])
        return np.concatenate(xs), np.concatenate(ys), idx_map

    X, Y, idx_map = encode_all(tr_s, tr_t)
    TX, TY, te_map = encode_all(te_s, te_t)
    return FederatedData(X, Y, TX, TY, idx_map, te_map, spec.num_classes)
