"""Synthetic federated data generators.

Two roles:
1. The LEAF synthetic(alpha, beta) logistic-regression benchmark
   (reference data/synthetic_0.5_0.5/ etc.): per-client softmax-linear models
   whose weights are drawn around a client-specific mean u_k ~ N(0, alpha),
   inputs around a client-specific mean B_k ~ N(0, beta).
2. Shape-compatible stand-ins for image/text datasets when the real files are
   absent (zero-egress environments): class-conditional Gaussian images and
   Markov-chain token streams — learnable, deterministic, correct shapes.
"""

from __future__ import annotations

import numpy as np

from fedml_tpu.core.client_data import FederatedData
from fedml_tpu.core.partition import partition_data


def synthetic_lr(
    num_clients: int = 30,
    alpha: float = 0.5,
    beta: float = 0.5,
    dim: int = 60,
    num_classes: int = 10,
    seed: int = 0,
) -> FederatedData:
    """LEAF synthetic(alpha,beta): y = argmax(softmax(W_k x + b_k))."""
    rng = np.random.RandomState(seed)
    sizes = np.clip(rng.lognormal(4, 2, num_clients).astype(int) + 50, 50, 10_000)
    B = rng.normal(0, beta, num_clients)
    xs, ys, idx_map, test_xs, test_ys, test_map = [], [], {}, [], [], {}
    tr_off = te_off = 0
    diag = np.array([(j + 1) ** -1.2 for j in range(dim)])
    for k in range(num_clients):
        u = rng.normal(0, alpha)
        W = rng.normal(u, 1, (dim, num_classes))
        b = rng.normal(u, 1, num_classes)
        v = rng.normal(B[k], 1, dim)
        n = int(sizes[k])
        x = rng.multivariate_normal(v, np.diag(diag), n).astype(np.float32)
        logits = x @ W + b
        y = np.argmax(logits, axis=1).astype(np.int64)
        n_tr = max(1, int(0.9 * n))
        xs.append(x[:n_tr]); ys.append(y[:n_tr])
        test_xs.append(x[n_tr:]); test_ys.append(y[n_tr:])
        idx_map[k] = np.arange(tr_off, tr_off + n_tr)
        test_map[k] = np.arange(te_off, te_off + (n - n_tr))
        tr_off += n_tr; te_off += n - n_tr
    fd = FederatedData(
        train_x=np.concatenate(xs), train_y=np.concatenate(ys),
        test_x=np.concatenate(test_xs), test_y=np.concatenate(test_ys),
        train_idx_map=idx_map, test_idx_map=test_map, class_num=num_classes,
    )
    fd.synthetic_fallback = True  # dataset_source: generated, not read
    return fd


def synthetic_leaf_exact(
    alpha: float = 1.0,
    beta: float = 1.0,
    num_clients: int = 30,
    dim: int = 60,
    num_classes: int = 10,
    seed: int = 0,
    test_json: str | None = None,
    split_seed: int | None = None,
) -> FederatedData:
    """Draw-order-exact LEAF synthetic(alpha, beta) dataset.

    The reference generates this benchmark with a FIXED numpy seed
    (data/synthetic_1_1/generate_synthetic.py:19 `np.random.seed(0)`), so the
    full 30-user sample set is deterministic and reproducible offline; only
    its train/test membership came from an unseeded `random.shuffle` before
    the 90/10 split. This function reproduces the generation process (the
    public FedProx-paper synthetic(alpha,beta) recipe) with the exact legacy
    RandomState call sequence, so the produced rows are bit-identical to the
    reference's committed data.

    test_json: path to a LEAF `mytest.json` produced by the reference
    generator (e.g. the one committed at data/synthetic_1_1/test/mytest.json).
    When given, the reference's exact train/test split is RECONSTRUCTED by
    matching each committed test row back to its generated row — train rows
    are everything else — so accuracy numbers are measured on the reference's
    own test set. When None, a seeded per-user shuffle + 90/10 split is used
    instead (same proportions, deterministic).

    seed: the GENERATION seed — 0 is the reference's fixed value; any other
    value produces a different (non-reference) dataset. split_seed: seeds
    only the fallback 90/10 split (defaults to seed), so run-seed sweeps can
    vary the split without silently changing the benchmark data.
    """
    if split_seed is None:
        split_seed = seed
    rs = np.random.RandomState(seed)
    sizes = rs.lognormal(4, 2, num_clients).astype(int) + 50
    mean_W = rs.normal(0, alpha, num_clients)       # per-user model mean
    B = rs.normal(0, beta, num_clients)             # per-user input mean-mean
    cov = np.diag(np.power(np.arange(1, dim + 1, dtype=np.float64), -1.2))
    mean_x = np.stack([rs.normal(B[k], 1, dim) for k in range(num_clients)])

    per_user: list[tuple[np.ndarray, np.ndarray]] = []
    for k in range(num_clients):
        W = rs.normal(mean_W[k], 1, (dim, num_classes))
        b = rs.normal(mean_W[k], 1, num_classes)    # mean_b aliases mean_W
        x = rs.multivariate_normal(mean_x[k], cov, int(sizes[k]))
        y = np.argmax(x @ W + b, axis=1)            # argmax(softmax) = argmax
        per_user.append((x, y))

    test_rows: dict[int, np.ndarray] | None = None
    if test_json is not None:
        import json

        with open(test_json) as f:
            d = json.load(f)
        if len(d["users"]) != num_clients:
            raise ValueError(
                f"{test_json}: {len(d['users'])} users, expected {num_clients}")
        test_rows = {}
        for k, u in enumerate(sorted(d["users"])):  # f_00000.. numeric order
            gx, gy = per_user[k]
            xs = np.asarray(d["user_data"][u]["x"], dtype=np.float64)
            ys = np.asarray(d["user_data"][u]["y"])
            taken = np.zeros(len(gx), bool)
            rows = np.empty(len(xs), np.int64)
            for r in range(len(xs)):
                diff = np.abs(gx - xs[r]).max(axis=1)
                diff[taken] = np.inf
                j = int(np.argmin(diff))
                if diff[j] > 1e-9 or int(gy[j]) != int(ys[r]):
                    raise ValueError(
                        f"{test_json}: user {u} row {r} does not match any "
                        f"generated sample (min |dx|={diff[j]:.3g}) — wrong "
                        "(alpha, beta) or a differently-seeded file?")
                taken[j] = True
                rows[r] = j
            test_rows[k] = rows

    xs, ys, idx_map, test_xs, test_ys, test_map = [], [], {}, [], [], {}
    tr_off = te_off = 0
    for k in range(num_clients):
        x, y = per_user[k]
        if test_rows is not None:
            te = test_rows[k]
            tr = np.setdiff1d(np.arange(len(x)), te)
        else:
            perm = np.random.RandomState(
                (split_seed * 9973 + k + 1) % (2 ** 32)).permutation(len(x))
            n_tr = int(0.9 * len(x))  # generator's split ratio (:80)
            tr, te = perm[:n_tr], perm[n_tr:]
        xs.append(x[tr].astype(np.float32)); ys.append(y[tr].astype(np.int64))
        test_xs.append(x[te].astype(np.float32)); test_ys.append(y[te].astype(np.int64))
        idx_map[k] = np.arange(tr_off, tr_off + len(tr))
        test_map[k] = np.arange(te_off, te_off + len(te))
        tr_off += len(tr); te_off += len(te)
    fd = FederatedData(
        train_x=np.concatenate(xs), train_y=np.concatenate(ys),
        test_x=np.concatenate(test_xs), test_y=np.concatenate(test_ys),
        train_idx_map=idx_map, test_idx_map=test_map, class_num=num_classes,
    )
    fd.synthetic_fallback = True  # dataset_source: generated, not read
    return fd


def synthetic_images(
    num_clients: int,
    image_shape: tuple[int, ...],
    num_classes: int,
    samples_per_client: int = 100,
    test_samples: int = 1000,
    partition_method: str = "natural",
    partition_alpha: float = 0.5,
    seed: int = 0,
    size_lognormal: bool = True,
    as_uint8: bool = False,
    partition_fix_path: str | None = None,
) -> FederatedData:
    """Class-conditional Gaussian images, shape-compatible stand-in for
    MNIST/FEMNIST/CIFAR when real files are absent. Each class c has a fixed
    random mean image m_c; samples are m_c + noise. 'natural' partitioning
    gives each client a skewed label distribution + lognormal size (LEAF-like);
    'homo'/'hetero' delegate to the standard partitioners."""
    rng = np.random.RandomState(seed)
    means = rng.normal(0, 1, (num_classes,) + image_shape).astype(np.float32)

    if size_lognormal:
        sizes = np.clip(
            rng.lognormal(np.log(samples_per_client), 0.5, num_clients).astype(int),
            max(10, samples_per_client // 5),
            samples_per_client * 5,
        )
    else:
        sizes = np.full(num_clients, samples_per_client)
    total = int(sizes.sum())

    if partition_method == "natural":
        # each client draws labels from its own dirichlet class mix
        ys = []
        for k in range(num_clients):
            mix = rng.dirichlet(np.repeat(partition_alpha, num_classes))
            ys.append(rng.choice(num_classes, sizes[k], p=mix))
        y = np.concatenate(ys).astype(np.int64)
        offs = np.concatenate([[0], np.cumsum(sizes)])
        idx_map = {k: np.arange(offs[k], offs[k + 1]) for k in range(num_clients)}
    else:
        y = rng.choice(num_classes, total).astype(np.int64)
        idx_map = partition_data(y, num_clients, partition_method, partition_alpha,
                                 seed, fix_path=partition_fix_path)

    # noise from a shared pool: generating total*prod(shape) fresh gaussians
    # dominates wall-clock at 3400-client scale and adds nothing for learning
    pool = rng.normal(0, 1, (4096,) + image_shape).astype(np.float32)
    x = means[y] + 0.5 * pool[rng.randint(0, 4096, total)]
    ty = rng.choice(num_classes, test_samples).astype(np.int64)
    tx = means[ty] + 0.5 * pool[rng.randint(0, 4096, test_samples)]
    if as_uint8:
        # map the ~N(0,1.1) pixel field onto the uint8 grid; after the image
        # tasks' on-device /255 the model sees ~N(0.5, 0.125^2) — an affine
        # rescale of the float variant (standard [0,1] image normalization),
        # NOT the same raw scale, at 1/4 the host->device bytes. Real image
        # datasets are natively uint8, so this only affects the synthetic
        # stand-in; learning-rate-sensitive comparisons between the float
        # and uint8 synthetic variants are not scale-equivalent.
        q = lambda a: np.clip(a * 32.0 + 128.0, 0, 255).astype(np.uint8)
        x, tx = q(x), q(tx)
    fd = FederatedData(
        train_x=x if as_uint8 else x.astype(np.float32), train_y=y,
        test_x=tx if as_uint8 else tx.astype(np.float32), test_y=ty,
        train_idx_map=idx_map, test_idx_map=None, class_num=num_classes,
    )
    fd.synthetic_fallback = True
    return fd


def synthetic_segmentation(
    num_clients: int,
    image_shape: tuple[int, int, int] = (64, 64, 3),
    num_classes: int = 21,
    samples_per_client: int = 20,
    test_samples: int = 40,
    seed: int = 0,
    ignore_index: int = 255,
    partition_alpha: float = 0.5,
) -> FederatedData:
    """Blob-world segmentation stand-in for PASCAL VOC / COCO (FedSeg).

    Each image contains 1-3 axis-aligned rectangles of random foreground
    classes on a class-0 background; pixel labels follow the rectangles, with
    a 1-px ``ignore_index`` border around each object (mimicking VOC's void
    boundary pixels). Clients draw objects from a Dirichlet(partition_alpha)
    class mix -> non-IID, sharper as alpha shrinks (the LDA knob of
    cifar10/data_loader.py:172-196 applied to object classes).
    """
    rng = np.random.RandomState(seed)
    h, w, c = image_shape
    class_colors = rng.normal(0, 1, (num_classes, c)).astype(np.float32)

    def gen(n, class_probs):
        x = np.zeros((n, h, w, c), np.float32)
        y = np.zeros((n, h, w), np.int64)
        for i in range(n):
            x[i] = class_colors[0] + 0.3 * rng.normal(0, 1, (h, w, c))
            for _ in range(rng.randint(1, 4)):
                cls = 1 + int(rng.choice(num_classes - 1, p=class_probs))
                bh, bw = rng.randint(h // 4, h // 2), rng.randint(w // 4, w // 2)
                r0, c0 = rng.randint(0, h - bh), rng.randint(0, w - bw)
                x[i, r0:r0 + bh, c0:c0 + bw] = class_colors[cls] + \
                    0.3 * rng.normal(0, 1, (bh, bw, c))
                y[i, r0:r0 + bh, c0:c0 + bw] = cls
                # void boundary ring (all four edges)
                y[i, r0, c0:c0 + bw] = ignore_index
                y[i, r0 + bh - 1, c0:c0 + bw] = ignore_index
                y[i, r0:r0 + bh, c0] = ignore_index
                y[i, r0:r0 + bh, c0 + bw - 1] = ignore_index
        return x, y

    xs, ys, idx_map, off = [], [], {}, 0
    n_fg = num_classes - 1
    for k in range(num_clients):
        probs = rng.dirichlet(np.repeat(partition_alpha, n_fg))
        x, y = gen(samples_per_client, probs)
        xs.append(x); ys.append(y)
        idx_map[k] = np.arange(off, off + samples_per_client)
        off += samples_per_client
    tx, ty = gen(test_samples, np.full(n_fg, 1.0 / n_fg))
    fd = FederatedData(
        train_x=np.concatenate(xs), train_y=np.concatenate(ys),
        test_x=tx, test_y=ty,
        train_idx_map=idx_map, test_idx_map=None, class_num=num_classes,
    )
    fd.synthetic_fallback = True
    return fd


def synthetic_sequences(
    num_clients: int,
    seq_len: int,
    vocab_size: int,
    samples_per_client: int = 50,
    test_samples: int = 500,
    seed: int = 0,
    pad_id: int = 0,
) -> FederatedData:
    """Markov-chain token sequences, stand-in for Shakespeare/StackOverflow.

    x[t] is the context token, y[t] = x[t+1] (next-token target). Each client
    has its own transition sharpness -> non-IID. Sequences are full-length
    (no pad) except the synthetic raggedness left to per-sample masks.
    """
    rng = np.random.RandomState(seed)
    base = rng.dirichlet(np.ones(vocab_size - 1) * 0.3, vocab_size)  # rows: next-token dist

    def gen(n, sharp):
        seqs = np.zeros((n, seq_len + 1), dtype=np.int64)
        for i in range(n):
            t = rng.randint(1, vocab_size)
            for j in range(seq_len + 1):
                seqs[i, j] = t
                p = base[t] ** sharp
                p = p / p.sum()
                t = 1 + rng.choice(vocab_size - 1, p=p)
        return seqs

    xs, idx_map = [], {}
    off = 0
    for k in range(num_clients):
        sharp = 0.5 + rng.rand() * 1.5
        s = gen(samples_per_client, sharp)
        xs.append(s)
        idx_map[k] = np.arange(off, off + samples_per_client)
        off += samples_per_client
    seqs = np.concatenate(xs)
    test = gen(test_samples, 1.0)
    fd = FederatedData(
        train_x=seqs[:, :-1], train_y=seqs[:, 1:],
        test_x=test[:, :-1], test_y=test[:, 1:],
        train_idx_map=idx_map, test_idx_map=None, class_num=vocab_size,
    )
    fd.synthetic_fallback = True
    return fd


def synthetic_packed_population(path: str, num_clients: int, dim: int = 16,
                                num_classes: int = 5, seed: int = 0,
                                test_rows: int = 512,
                                size_lo: int = 6, size_hi: int = 25,
                                tail_size: int = 96,
                                tail_every: int = 200) -> str:
    """Write a deterministic SYNTHETIC packed-npy population straight to
    disk (core/client_source.PackedNpySource layout) without ever
    materializing it — the fixture for the flat-memory evidence (ci.sh
    streamed smoke, bench.py FEDML_BENCH_STREAM): lognormal-ish ragged
    client sizes with a heavy tail (the skew cohort bucketing exists
    for), labels planted from ONE pass over the feature rows actually
    written (x and y stream together — a second pass re-drawing x would
    store uncorrelated labels), and a held-out test split from the same
    planted mapping. Chunked writes keep the writer's RSS flat too."""
    import json as _json
    import os as _os

    _os.makedirs(path, exist_ok=True)
    rs = np.random.RandomState(seed)
    # size_lo/size_hi/tail_size parameterize the skew: the bf16+bucket
    # bench (FEDML_BENCH_FUSED) stretches the tail so the static batch
    # budget is priced by a client most cohorts never sample — the
    # FEMNIST-lognormal shape the bucket ladder exists for. Defaults are
    # the original fixture (byte-identical populations for old callers).
    sizes = rs.randint(size_lo, size_hi, num_clients).astype(np.int64)
    tail = max(num_clients // tail_every, 1)
    sizes[rs.choice(num_clients, tail, replace=False)] = tail_size
    offsets = np.zeros(num_clients + 1, np.int64)
    np.cumsum(sizes, out=offsets[1:])
    total = int(offsets[-1])
    W = rs.randn(dim, num_classes).astype(np.float32)
    with open(_os.path.join(path, "x.npy"), "wb") as fx, \
            open(_os.path.join(path, "y.npy"), "wb") as fy:
        np.lib.format.write_array_header_2_0(
            fx, {"descr": np.lib.format.dtype_to_descr(
                np.dtype(np.float32)),
                "fortran_order": False, "shape": (total, dim)})
        np.lib.format.write_array_header_2_0(
            fy, {"descr": np.lib.format.dtype_to_descr(np.dtype(np.int64)),
                 "fortran_order": False, "shape": (total,)})
        chunk = 1 << 18
        for s in range(0, total, chunk):
            m = min(chunk, total - s)
            x = rs.randn(m, dim).astype(np.float32)
            fx.write(x.tobytes())
            fy.write(np.argmax(x @ W, 1).astype(np.int64).tobytes())
    np.save(_os.path.join(path, "offsets.npy"), offsets)
    rs2 = np.random.RandomState(seed + 1)
    tx = rs2.randn(test_rows, dim).astype(np.float32)
    np.save(_os.path.join(path, "test_x.npy"), tx)
    np.save(_os.path.join(path, "test_y.npy"),
            np.argmax(tx @ W, 1).astype(np.int64))
    with open(_os.path.join(path, "meta.json"), "w") as f:
        _json.dump({"format": "fedml-packed-npy",
                    "num_clients": num_clients,
                    "class_num": num_classes, "source": "synthetic"}, f)
    return path
