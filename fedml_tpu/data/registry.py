"""Dataset registry — the load_data dispatch.

Mirror of the reference's load_data switch
(fedml_experiments/distributed/fedavg/main_fedavg.py:123-229) covering every
dataset family in fedml_api/data_preprocessing/. Each entry knows its
canonical client count, input shape, and class count; ``load_dataset`` tries
the real files under ``data_dir`` first (see fedml_tpu/data/files.py) and
falls back to deterministic shape-identical synthetic data.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from fedml_tpu.core.client_data import FederatedData
from fedml_tpu.core.partition import partition_data
from fedml_tpu.data import synthetic as syn


@dataclasses.dataclass(frozen=True)
class DatasetSpec:
    name: str
    num_clients: int          # canonical client count in the reference
    input_shape: tuple        # per-sample shape (images HWC; sequences (T,))
    num_classes: int
    task: str                 # 'classification' | 'sequence' | 'tags' | 'segmentation'
    partition: str            # 'natural' | 'lda'
    samples_per_client: int   # used by the synthetic fallback


# canonical client counts: MNIST 1000 (benchmark/README.md:12), FEMNIST 3400
# (:54), fed_cifar100 500 (:55), fed_shakespeare 715 (:56), stackoverflow
# 342477 (:57); cross-silo datasets use --client_num_in_total (default 10).
DATASETS: dict[str, DatasetSpec] = {
    "mnist": DatasetSpec("mnist", 1000, (28, 28, 1), 10, "classification", "natural", 60),
    "femnist": DatasetSpec("femnist", 3400, (28, 28, 1), 62, "classification", "natural", 110),
    "fed_cifar100": DatasetSpec("fed_cifar100", 500, (32, 32, 3), 100, "classification", "natural", 100),
    "shakespeare": DatasetSpec("shakespeare", 715, (80,), 90, "sequence", "natural", 50),
    "fed_shakespeare": DatasetSpec("fed_shakespeare", 715, (80,), 90, "sequence", "natural", 50),
    "stackoverflow_nwp": DatasetSpec("stackoverflow_nwp", 342477, (20,), 10004, "sequence", "natural", 30),
    "stackoverflow_lr": DatasetSpec("stackoverflow_lr", 342477, (10004,), 500, "tags", "natural", 30),
    "cifar10": DatasetSpec("cifar10", 10, (32, 32, 3), 10, "classification", "lda", 5000),
    "cifar100": DatasetSpec("cifar100", 10, (32, 32, 3), 100, "classification", "lda", 5000),
    "cinic10": DatasetSpec("cinic10", 10, (32, 32, 3), 10, "classification", "lda", 9000),
    "svhn": DatasetSpec("svhn", 10, (32, 32, 3), 10, "classification", "lda", 7000),
    "imagenet": DatasetSpec("imagenet", 100, (224, 224, 3), 1000, "classification", "natural", 100),
    "gld23k": DatasetSpec("gld23k", 233, (224, 224, 3), 203, "classification", "natural", 100),
    "gld160k": DatasetSpec("gld160k", 1262, (224, 224, 3), 2028, "classification", "natural", 130),
    "synthetic": DatasetSpec("synthetic", 30, (60,), 10, "classification", "natural", 200),
    # reference-exact synthetic(alpha,beta) variants (data/synthetic_*/
    # generate_synthetic.py; fixed np seed 0 -> reproducible offline)
    "synthetic_0_0": DatasetSpec("synthetic_0_0", 30, (60,), 10, "classification", "natural", 200),
    "synthetic_0.5_0.5": DatasetSpec("synthetic_0.5_0.5", 30, (60,), 10, "classification", "natural", 200),
    "synthetic_1_1": DatasetSpec("synthetic_1_1", 30, (60,), 10, "classification", "natural", 200),
    # FedSeg datasets (fedml_api/distributed/fedseg; PASCAL VOC 21 classes,
    # COCO mapped to the same 21-class VOC subset in the reference pipeline)
    "pascal_voc": DatasetSpec("pascal_voc", 4, (513, 513, 3), 21, "segmentation", "lda", 200),
    "coco": DatasetSpec("coco", 8, (513, 513, 3), 21, "segmentation", "lda", 300),
}


def _requantize_uint8(fd: FederatedData) -> FederatedData:
    """Convert [0,1]-normalized float pixel arrays back to uint8 for the fast
    transfer path (the image tasks re-normalize on device). No-op if already
    integer; refuses (with a log) if the float range isn't [0,1]-like, so
    uint8_pixels never silently corrupts unusual data."""
    import logging

    x = fd.train_x
    if np.issubdtype(x.dtype, np.integer):
        return fd
    if x.min() < -1e-3 or x.max() > 1.0 + 1e-3:
        logging.getLogger("fedml_tpu.data").warning(
            "uint8_pixels requested but pixel range [%.3f, %.3f] is not [0,1]; "
            "keeping float pixels", float(x.min()), float(x.max()),
        )
        return fd
    q = lambda a: np.clip(np.rint(a * 255.0), 0, 255).astype(np.uint8)
    return dataclasses.replace(fd, train_x=q(fd.train_x), test_x=q(fd.test_x))


def load_dataset(
    name: str,
    data_dir: str | None = None,
    client_num: int | None = None,
    partition_method: str | None = None,
    partition_alpha: float = 0.5,
    seed: int = 0,
    samples_per_client: int | None = None,
    test_samples: int | None = None,
    uint8_pixels: bool = False,
    partition_fix_path: str | None = None,
    image_size: int | None = None,
) -> FederatedData:
    fd = _load_dataset_impl(
        name, data_dir, client_num, partition_method, partition_alpha, seed,
        samples_per_client, test_samples, uint8_pixels, partition_fix_path,
        image_size,
    )
    if partition_fix_path is not None:
        # post-condition, whichever load route ran: the returned partition IS
        # the frozen map (a route that can't honor it — natural partitions,
        # sequence/segmentation synthetics — must fail loudly, not silently
        # train on a different partition; also catches a typo'd path)
        from fedml_tpu.core.partition import read_net_dataidx_map

        m = read_net_dataidx_map(partition_fix_path)
        ok = set(fd.train_idx_map) == set(m) and all(
            np.array_equal(np.asarray(fd.train_idx_map[k]), m[k]) for k in m
        )
        if not ok:
            raise ValueError(
                f"dataset {name!r} (partition_method={partition_method!r}) "
                f"did not honor partition_fix_path={partition_fix_path!r}; "
                "frozen maps apply to LDA-partitioned classification datasets "
                "with method 'hetero-fix'")
    return fd


def _load_dataset_impl(
    name: str,
    data_dir: str | None = None,
    client_num: int | None = None,
    partition_method: str | None = None,
    partition_alpha: float = 0.5,
    seed: int = 0,
    samples_per_client: int | None = None,
    test_samples: int | None = None,
    uint8_pixels: bool = False,
    partition_fix_path: str | None = None,
    image_size: int | None = None,
) -> FederatedData:
    """Load (or synthesize) a federated dataset by reference name.

    image_size: decode-time square resize for the folder/csv image readers
    (imagenet, gld23k/gld160k) — e.g. 224 for the reference-fidelity
    ImageNet resolution (ImageNet/data_loader.py trains 224x224); None
    keeps the study-scale default (64).

    client_num overrides the canonical count (the cross-silo datasets take it
    from --client_num_in_total in the reference; natural-partition datasets
    ignore it there but we allow subsetting for simulation scale).

    uint8_pixels: ship image pixels as uint8 and normalize ON DEVICE
    (classification/segmentation tasks cast integer inputs to f32/255 inside
    the jitted program) — 4x less host->device transfer, the dominant cost
    of a round at FEMNIST scale.
    """
    spec = DATASETS.get(name)
    if spec is None:
        raise ValueError(f"unknown dataset {name}; known: {sorted(DATASETS)}")
    n_clients = client_num or spec.num_clients
    if partition_fix_path is not None and partition_method is None:
        partition_method = "hetero-fix"  # a frozen map implies the method

    if data_dir is not None and os.path.isdir(data_dir):
        from fedml_tpu.data import files

        fd = files.try_load(spec, data_dir, n_clients, partition_method,
                            partition_alpha, seed,
                            partition_fix_path=partition_fix_path,
                            image_size=image_size)
        if fd is not None:
            if uint8_pixels:
                fd = _requantize_uint8(fd)
            return fd
    elif not name.startswith("synthetic"):
        import logging

        # no data_dir at all for a real-file dataset: the synthetic
        # stand-in is by design, but it must never be MISTAKEN for the
        # real thing — say so, and the telemetry run header records
        # dataset_source='synthetic' as the machine-readable twin
        logging.getLogger("fedml_tpu.data").warning(
            "dataset %r: no data_dir given — generating the synthetic "
            "shape-identical stand-in (run scripts/download_data.sh for "
            "the real files)", name)

    if name == "synthetic":
        return syn.synthetic_lr(num_clients=n_clients, seed=seed)
    if name.startswith("synthetic_"):
        a, b = (float(v) for v in name[len("synthetic_"):].split("_"))
        # honor a generator-produced test split when present under data_dir
        # (the reference commits one for (1,1)); else a seeded 90/10 split
        tj = None
        if data_dir is not None:
            cand = os.path.join(data_dir, "test", "mytest.json")
            tj = cand if os.path.isfile(cand) else None
        # generation seed is PINNED to the reference's fixed 0 (the name
        # promises reference-exact data); the run seed only varies the
        # fallback split. client_num flows through — synthetic_leaf_exact
        # raises if it disagrees with a provided test json's user count.
        return syn.synthetic_leaf_exact(alpha=a, beta=b,
                                        num_clients=n_clients, seed=0,
                                        split_seed=seed, test_json=tj)

    spc = samples_per_client or spec.samples_per_client
    ts = test_samples or min(2000, spc * n_clients // 10 + 100)
    if spec.task == "classification" and len(spec.input_shape) >= 2:
        pm = partition_method or ("hetero" if spec.partition == "lda" else "natural")
        return syn.synthetic_images(
            num_clients=n_clients,
            image_shape=spec.input_shape,
            num_classes=spec.num_classes,
            samples_per_client=spc,
            test_samples=ts,
            partition_method=pm,
            partition_alpha=partition_alpha,
            seed=seed,
            as_uint8=uint8_pixels,
            partition_fix_path=partition_fix_path,
        )
    if spec.task == "segmentation":
        # synthetic fallback at reduced resolution: full 513x513 blobs are
        # pure padding cost for a stand-in dataset
        h, w, c = spec.input_shape
        shape = (min(h, 64), min(w, 64), c)
        return syn.synthetic_segmentation(
            num_clients=n_clients, image_shape=shape,
            num_classes=spec.num_classes, samples_per_client=spc,
            test_samples=min(ts, 64), seed=seed,
            partition_alpha=partition_alpha,
        )
    if spec.task == "sequence":
        return syn.synthetic_sequences(
            num_clients=n_clients,
            seq_len=spec.input_shape[0],
            vocab_size=spec.num_classes,
            samples_per_client=spc,
            test_samples=ts,
            seed=seed,
        )
    if spec.task == "tags":
        # multi-hot bag-of-words in, multi-hot tags out
        rng = np.random.RandomState(seed)
        dim = spec.input_shape[0]
        n = spc * n_clients
        W = rng.normal(0, 1, (64, spec.num_classes))
        emb = rng.normal(0, 1, (dim, 64))

        def make(n):
            x = (rng.rand(n, dim) < (8.0 / dim)).astype(np.float32)
            logits = (x @ emb) @ W + rng.normal(0, 0.1, (n, spec.num_classes))
            y = (logits > np.quantile(logits, 0.98, axis=1, keepdims=True)).astype(np.float32)
            return x, y

        x, y = make(n)
        tx, ty = make(ts)
        idx = {k: np.arange(k * spc, (k + 1) * spc) for k in range(n_clients)}
        fd = FederatedData(x, y, tx, ty, idx, None, spec.num_classes)
        fd.synthetic_fallback = True
        return fd
    # tabular classification (e.g. synthetic fallback for 1-D inputs)
    pm = partition_method or "hetero"
    rng = np.random.RandomState(seed)
    dim = int(np.prod(spec.input_shape))
    n = spc * n_clients
    W = rng.normal(0, 1, (dim, spec.num_classes))
    x = rng.normal(0, 1, (n, dim)).astype(np.float32)
    y = np.argmax(x @ W + rng.normal(0, 0.5, (n, spec.num_classes)), 1).astype(np.int64)
    tx = rng.normal(0, 1, (ts, dim)).astype(np.float32)
    ty = np.argmax(tx @ W, 1).astype(np.int64)
    idx = partition_data(y, n_clients, pm, partition_alpha, seed,
                         fix_path=partition_fix_path)
    fd = FederatedData(x, y, tx, ty, idx, None, spec.num_classes)
    fd.synthetic_fallback = True
    return fd
