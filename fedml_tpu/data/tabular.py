"""Vertical-FL tabular datasets — NUS-WIDE, Lending Club, UCI.

Mirror of the reference's vertical-FL data layer (SURVEY.md §2.5):
fedml_api/data_preprocessing/NUS_WIDE/nus_wide_dataset.py (634 low-level
image features for one party + 1000 tag features for the other, binary
two-class selection), lending_club_loan/ (loan table split by feature
columns), and UCI/ (susy et al.). Each loader returns the party-sliced
arrays the VFL engine consumes:

    (x_guest [N, d_guest], x_hosts [H, N, d_host], y [N])

Real files are read when present under ``data_dir`` (csv with a label
column); otherwise a deterministic synthetic table with the same shapes is
generated, so every algorithm/test path runs without downloads (the repo-wide
data-fallback convention of fedml_tpu/data/registry.py).
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np


@dataclasses.dataclass(frozen=True)
class VerticalSpec:
    name: str
    guest_dim: int
    host_dims: tuple  # one entry per host party
    num_classes: int
    num_samples: int  # synthetic fallback size
    label_col: str    # csv label column for the real reader


VERTICAL_DATASETS: dict[str, VerticalSpec] = {
    # NUS-WIDE: guest = 1000-d tag features, host = 634-d low-level image
    # features (nus_wide_dataset.py two-party split)
    "nus_wide": VerticalSpec("nus_wide", 1000, (634,), 2, 4000, "label"),
    # Lending Club loan table: features split between the loan platform
    # (guest, holds default label) and a partner bank (host)
    "lending_club": VerticalSpec("lending_club", 48, (24,), 2, 6000, "loan_status"),
    # UCI SUSY: 18 kinematic features split 10/8, binary signal/background
    "uci_susy": VerticalSpec("uci_susy", 10, (8,), 2, 8000, "label"),
}


def _synthetic_vertical(spec: VerticalSpec, seed: int):
    """Linearly-separable-ish table: y from a random hyperplane over the
    CONCATENATED features, so neither party alone is sufficient — the VFL
    training signal requires the cross-party sum, like the real datasets."""
    rng = np.random.RandomState(seed * 131 + 7)
    n = spec.num_samples
    xg = rng.randn(n, spec.guest_dim).astype(np.float32)
    xh = np.stack(
        [rng.randn(n, d).astype(np.float32) for d in spec.host_dims]
    )
    wg = rng.randn(spec.guest_dim) / np.sqrt(spec.guest_dim)
    whs = [rng.randn(d) / np.sqrt(d) for d in spec.host_dims]
    score = xg @ wg + sum(xh[h] @ w for h, w in enumerate(whs))
    if spec.num_classes == 2:
        y = (score > np.median(score)).astype(np.int64)
    else:
        qs = np.quantile(score, np.linspace(0, 1, spec.num_classes + 1)[1:-1])
        y = np.digitize(score, qs).astype(np.int64)
    return xg, xh, y


def _read_csv_vertical(path: str, spec: VerticalSpec):
    """Real reader: one csv, label column by name, features split
    guest-first then host parties in column order (the reference fixes the
    split by column index the same way)."""
    import csv

    with open(path) as f:
        reader = csv.reader(f)
        header = next(reader)
        rows = [r for r in reader if r]
    li = header.index(spec.label_col)
    feat_cols = [i for i in range(len(header)) if i != li]
    need = spec.guest_dim + sum(spec.host_dims)
    if len(feat_cols) < need:
        raise ValueError(
            f"{spec.name}: csv has {len(feat_cols)} feature cols, need {need}"
        )
    mat = np.array([[float(r[i]) for i in feat_cols[:need]] for r in rows], np.float32)
    raw_y = [r[li] for r in rows]
    try:
        y = np.array([int(float(v)) for v in raw_y], np.int64)
    except ValueError:  # categorical labels
        uniq = {v: i for i, v in enumerate(sorted(set(raw_y)))}
        y = np.array([uniq[v] for v in raw_y], np.int64)

    xg = mat[:, : spec.guest_dim]
    hosts, off = [], spec.guest_dim
    for d in spec.host_dims:
        hosts.append(mat[:, off : off + d])
        off += d
    # hosts may have unequal dims; VFLAPI stacks equal-dim hosts — pad to max
    dmax = max(spec.host_dims)
    xh = np.zeros((len(spec.host_dims), len(rows), dmax), np.float32)
    for h, hm in enumerate(hosts):
        xh[h, :, : hm.shape[1]] = hm
    return xg, xh, y


def load_vertical(name: str, data_dir: str | None = None, seed: int = 0):
    """Load a vertical-FL dataset: real csv if ``data_dir/<name>.csv``
    exists, synthetic fallback otherwise.

    Returns (x_guest, x_hosts, y, spec).
    """
    spec = VERTICAL_DATASETS[name]
    if data_dir:
        path = os.path.join(data_dir, f"{name}.csv")
        if os.path.exists(path):
            xg, xh, y = _read_csv_vertical(path, spec)
            return xg, xh, y, spec
    xg, xh, y = _synthetic_vertical(spec, seed)
    return xg, xh, y, spec


def train_test_split_vertical(xg, xh, y, test_frac: float = 0.2, seed: int = 0):
    """Aligned split across every party (vertical FL requires row alignment)."""
    n = len(y)
    rng = np.random.RandomState(seed * 17 + 3)
    perm = rng.permutation(n)
    cut = int(n * (1 - test_frac))
    tr, te = perm[:cut], perm[cut:]
    return (xg[tr], xh[:, tr], y[tr]), (xg[te], xh[:, te], y[te])
