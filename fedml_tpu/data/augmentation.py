"""Data augmentation (reference: fedml_api/data_preprocessing/augmentation.py,
233 LoC — RandAugment-style policies applied in the torch dataloaders).

TPU re-design: augmentations are pure jax functions applied ON DEVICE inside
the jitted train step (vmapped over the batch), so the host data plane stays
a zero-copy array feed. The op set covers the reference's geometric +
photometric policies; magnitudes follow RandAugment conventions.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def random_crop(key, img, padding: int = 4):
    """Pad-and-random-crop (the CIFAR standard)."""
    H, W = img.shape[0], img.shape[1]
    padded = jnp.pad(img, ((padding, padding), (padding, padding), (0, 0)),
                     mode="reflect")
    kx, ky = jax.random.split(key)
    x0 = jax.random.randint(kx, (), 0, 2 * padding + 1)
    y0 = jax.random.randint(ky, (), 0, 2 * padding + 1)
    return jax.lax.dynamic_slice(padded, (x0, y0, 0), (H, W, img.shape[2]))


def random_flip(key, img):
    return jax.lax.cond(jax.random.bernoulli(key),
                        lambda x: x[:, ::-1, :], lambda x: x, img)


def brightness(key, img, max_delta: float = 0.2):
    return img + jax.random.uniform(key, (), minval=-max_delta, maxval=max_delta)


def contrast(key, img, max_factor: float = 0.3):
    f = 1.0 + jax.random.uniform(key, (), minval=-max_factor, maxval=max_factor)
    mean = jnp.mean(img, axis=(0, 1), keepdims=True)
    return (img - mean) * f + mean


def cutout(key, img, size: int = 8):
    """Zero a random square (the reference's Cutout policy)."""
    H, W = img.shape[0], img.shape[1]
    kx, ky = jax.random.split(key)
    cx = jax.random.randint(kx, (), 0, H)
    cy = jax.random.randint(ky, (), 0, W)
    yy, xx = jnp.mgrid[0:H, 0:W]
    mask = ((jnp.abs(yy - cx) > size // 2) | (jnp.abs(xx - cy) > size // 2))
    return img * mask[..., None]


def standard_cifar_augment(key, img):
    """crop + flip — the baseline train-time policy."""
    k1, k2 = jax.random.split(key)
    return random_flip(k2, random_crop(k1, img))


def rand_augment(key, img, num_ops: int = 2):
    """Pick ``num_ops`` random photometric/geometric ops per image. Uses
    lax.switch so the op choice is data-dependent but trace-static."""
    ops = [
        lambda k, x: random_crop(k, x),
        lambda k, x: random_flip(k, x),
        lambda k, x: brightness(k, x),
        lambda k, x: contrast(k, x),
        lambda k, x: cutout(k, x),
    ]

    def apply_one(i, carry):
        key, img = carry
        key, kop, kchoice = jax.random.split(key, 3)
        idx = jax.random.randint(kchoice, (), 0, len(ops))
        img = jax.lax.switch(idx, [partial(f, kop) for f in ops], img)
        return key, img

    _, img = jax.lax.fori_loop(0, num_ops, apply_one, (key, img))
    return img


def batch_augment(key, batch, fn=standard_cifar_augment):
    """vmap an augmentation over [bs, H, W, C]."""
    keys = jax.random.split(key, batch.shape[0])
    return jax.vmap(fn)(keys, batch)
