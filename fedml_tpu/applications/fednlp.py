"""FedNLP: federated NLP fine-tuning on the fedml_tpu engine.

The reference's applications/FedNLP is a pointer README to the external
FedNLP repo (applications/FedNLP/README.md), whose core workload is
federated fine-tuning of transformer text classifiers over naturally
non-IID text. This module is the in-tree equivalent, TPU-first:

- ``hf_text_classification_task``: wraps any HuggingFace **Flax**
  sequence-classification model (e.g. FlaxBertForSequenceClassification)
  into the framework's pure ``Task`` bundle, so the whole FedAvg engine —
  vmapped local fits, scanned round blocks, client-parallel meshes,
  DP/robust hooks — applies to transformer fine-tuning unchanged. The
  model's forward runs under jit like every other task; HBM-heavy configs
  compose with ``FedAvgConfig(remat=True)``.
- ``synthetic_text_classification``: class-conditional token-sequence
  generator (Dirichlet label skew across clients — the FedNLP paper's
  non-IID axis) used as the zero-egress stand-in; the real-data path is
  the same Task with HF-tokenized 20news/agnews arrays.

Offline by construction: models are built from a config (random init).
Where a network exists, ``from_pretrained`` weights drop into the same
``NetState.params`` slot — nothing else changes.
"""

from __future__ import annotations

import numpy as np

from fedml_tpu.core.client_data import FederatedData
from fedml_tpu.core.local import NetState, Task


def hf_text_classification_task(model, pad_id: int = 0) -> Task:
    """Task over a HuggingFace Flax *ForSequenceClassification model.

    x: [bs, seq] int token ids (pad_id-padded), y: [bs] int labels,
    mask: [bs] sample validity. The attention mask derives from pad_id on
    device. ``model`` is the HF wrapper (has .module and .params); its
    dropout rng collection is threaded from the engine's per-client keys.
    """
    import inspect

    import jax.numpy as jnp
    import optax

    module = model.module
    # HF Flax module signatures differ per family (BERT takes
    # token_type_ids/position_ids/head_mask, DistilBERT does not, RoBERTa
    # offsets positions past the pad id) — bind by NAME against the
    # module's own __call__ so any *ForSequenceClassification family works
    _accepts = set(inspect.signature(type(module).__call__).parameters)
    _roberta_style = "roberta" in type(module).__name__.lower()

    def _logits(params, x, rng, train):
        attn = (x != pad_id).astype(jnp.int32)
        kwargs = {"attention_mask": attn, "deterministic": not train}
        if "token_type_ids" in _accepts:
            kwargs["token_type_ids"] = jnp.zeros_like(x)
        if "position_ids" in _accepts:
            if _roberta_style:
                # RoBERTa numbering: pad positions stay at padding_idx,
                # real tokens count up from padding_idx + 1
                kwargs["position_ids"] = jnp.cumsum(attn, -1) * attn + pad_id
            else:
                kwargs["position_ids"] = jnp.broadcast_to(
                    jnp.arange(x.shape[-1]), x.shape)
        if "head_mask" in _accepts:
            kwargs["head_mask"] = None
        kwargs = {k: v for k, v in kwargs.items() if k in _accepts}
        rngs = {"dropout": rng} if train else {}
        out = module.apply({"params": params}, x, rngs=rngs, **kwargs)
        return out.logits if hasattr(out, "logits") else out[0]

    def init(rng, x_sample):
        del rng, x_sample  # HF materializes params at construction (seed=)
        return NetState(model.params, {})

    def loss(params, extra, x, y, mask, rng, train):
        logits = _logits(params, x, rng, train)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        n = jnp.maximum(jnp.sum(mask), 1.0)
        l = jnp.sum(per_ex * mask) / n
        metrics = {
            "loss_sum": jnp.sum(per_ex * mask),
            "correct": jnp.sum((jnp.argmax(logits, -1) == y) * mask),
            "count": jnp.sum(mask),
        }
        return l, extra, metrics

    def predict(params, extra, x):
        del extra
        return _logits(params, x, rng=None, train=False)

    def eval_batch(params, extra, x, y, mask):
        logits = _logits(params, x, rng=None, train=False)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        return {
            "loss_sum": jnp.sum(per_ex * mask),
            "correct": jnp.sum((jnp.argmax(logits, -1) == y) * mask),
            "count": jnp.sum(mask),
        }

    return Task(init, loss, predict, eval_batch)


def synthetic_text_classification(
    num_clients: int,
    num_classes: int = 4,
    vocab_size: int = 200,
    seq_len: int = 32,
    samples_per_client: int = 24,
    test_samples: int = 128,
    partition_alpha: float = 0.5,
    pad_id: int = 0,
    seed: int = 0,
) -> FederatedData:
    """Class-conditional token sequences with Dirichlet label skew.

    Each class owns a band of the vocabulary; a document is tokens drawn
    mostly from its class band plus uniform noise and a random pad tail —
    learnable by any sequence classifier, deterministic per seed, and
    non-IID across clients the way FedNLP partitions real corpora
    (label-Dirichlet over clients).
    """
    rng = np.random.RandomState(seed)
    band = (vocab_size - 1) // num_classes

    def draw(label: int, n: int) -> np.ndarray:
        lo = 1 + label * band
        toks = rng.randint(lo, lo + band, (n, seq_len))
        noise = rng.randint(1, vocab_size, (n, seq_len))
        keep = rng.rand(n, seq_len) < 0.7
        toks = np.where(keep, toks, noise)
        lengths = rng.randint(seq_len // 2, seq_len + 1, n)
        toks[np.arange(seq_len)[None, :] >= lengths[:, None]] = pad_id
        return toks.astype(np.int32)

    xs, ys, idx_map, off = [], [], {}, 0
    for k in range(num_clients):
        mix = rng.dirichlet(np.repeat(partition_alpha, num_classes))
        labels = rng.choice(num_classes, samples_per_client, p=mix)
        for c in labels:
            xs.append(draw(int(c), 1))
        ys.append(labels)
        idx_map[k] = np.arange(off, off + samples_per_client)
        off += samples_per_client
    ty = rng.choice(num_classes, test_samples)
    tx = np.concatenate([draw(int(c), 1) for c in ty])
    return FederatedData(
        train_x=np.concatenate(xs), train_y=np.concatenate(ys).astype(np.int64),
        test_x=tx, test_y=ty.astype(np.int64),
        train_idx_map=idx_map, test_idx_map=None, class_num=num_classes,
    )


def tiny_bert_classifier(num_classes: int, vocab_size: int = 200,
                         seq_len: int = 32, seed: int = 0):
    """A BERT-tiny-shaped FlaxBertForSequenceClassification built offline
    from a config (random init — no hub download). Swap in
    ``FlaxBertForSequenceClassification.from_pretrained(...)`` where a
    network exists; the Task is identical."""
    from transformers import BertConfig, FlaxBertForSequenceClassification

    cfg = BertConfig(
        vocab_size=vocab_size, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=128,
        max_position_embeddings=seq_len, num_labels=num_classes,
        pad_token_id=0,
    )
    return FlaxBertForSequenceClassification(cfg, seed=seed)
