"""Application layers on top of the core framework.

The reference ships applications as separate repos pointed at by stub
READMEs (applications/FedNLP/README.md is a 1-line URL). Here the worked
equivalents live in-tree: fednlp (federated text classification /
language modeling over HuggingFace Flax transformers and the native
TransformerLM).
"""
