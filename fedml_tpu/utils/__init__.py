from fedml_tpu.utils import tree
from fedml_tpu.utils.tree import (
    tree_weighted_mean,
    tree_stack,
    tree_unstack,
    tree_vectorize,
    tree_unvectorize,
    tree_zeros_like,
    tree_global_norm,
    tree_add,
    tree_sub,
    tree_scale,
)
