"""Pytree utilities — the tensor bookkeeping layer.

The reference does per-parameter dict loops on the host (e.g. the weighted sum
in FedAVGAggregator.aggregate, fedml_api/distributed/fedavg/FedAVGAggregator.py:58-87
and vectorize_weight in fedml_core/robustness/robust_aggregation.py:4-9). Here the
same operations are pure jax.tree transforms that stay on device and fuse under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tree_add(a, b):
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a, b):
    """a - b, leafwise. The FedOpt pseudo-gradient (w_old - w_avg)."""
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(t, s):
    return jax.tree.map(lambda x: x * s, t)


def tree_zeros_like(t):
    return jax.tree.map(jnp.zeros_like, t)


def tree_stack(trees):
    """Stack a list of pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def tree_unstack(tree, n):
    """Inverse of tree_stack: a stacked pytree -> list of n pytrees."""
    return [jax.tree.map(lambda x, i=i: x[i], tree) for i in range(n)]


def tree_weighted_mean(stacked, weights):
    """Weighted mean over the leading axis of a stacked pytree.

    ``stacked`` leaves have shape [K, ...]; ``weights`` has shape [K] and is
    normalized internally, so callers pass raw sample counts. This is the
    device-side equivalent of the server's per-key weighted averaging loop
    (reference FedAVGAggregator.py:72-80).
    """
    w = weights / jnp.maximum(jnp.sum(weights), 1e-12)
    return jax.tree.map(lambda x: jnp.tensordot(w, x, axes=([0], [0])), stacked)


def tree_vectorize(t):
    """Flatten a pytree into one 1-D vector (robust_aggregation.py:4-9 analogue)."""
    leaves = jax.tree.leaves(t)
    return jnp.concatenate([jnp.ravel(x) for x in leaves]) if leaves else jnp.zeros((0,))


def tree_unvectorize(vec, like):
    """Inverse of tree_vectorize given a template pytree ``like``."""
    leaves, treedef = jax.tree.flatten(like)
    out, i = [], 0
    for leaf in leaves:
        n = leaf.size
        out.append(jnp.reshape(vec[i : i + n], leaf.shape).astype(leaf.dtype))
        i += n
    return jax.tree.unflatten(treedef, out)


def tree_global_norm(t):
    """L2 norm over all leaves, computed without materializing the flat vector."""
    leaves = jax.tree.leaves(t)
    if not leaves:
        return jnp.zeros(())
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def tree_size(t) -> int:
    """Total number of scalars in a pytree (static)."""
    return sum(x.size for x in jax.tree.leaves(t))


def tree_cast(t, dtype):
    return jax.tree.map(lambda x: x.astype(dtype), t)
