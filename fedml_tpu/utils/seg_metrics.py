"""Segmentation evaluation — confusion-matrix scores (reference: Evaluator,
fedml_api/distributed/fedseg/utils.py:246-288).

The reference accumulates a numpy [C, C] confusion matrix batch-by-batch on
the host and derives Pixel_Accuracy / Pixel_Accuracy_Class / MIoU / FWIoU.
Here the accumulation is a jitted one-hot matmul (MXU-friendly, stays on
device across the whole eval scan); only the final [C, C] matrix crosses to
the host for the score formulas, which match the reference exactly
(including nanmean over absent classes).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def confusion_matrix(pred, label, num_classes: int, valid):
    """Batch confusion counts: conf[i, j] = #pixels with gt i predicted j.

    pred/label: integer arrays of identical shape; valid: float/bool mask of
    the same shape (0 for ignore_index pixels and padded samples — the
    reference drops gt outside [0, C) the same way, utils.py:277-281).
    """
    v = valid.reshape(-1).astype(jnp.float32)
    p = jnp.clip(pred.reshape(-1), 0, num_classes - 1)
    l = jnp.clip(label.reshape(-1), 0, num_classes - 1)
    idx = l * num_classes + p
    flat = jnp.zeros(num_classes * num_classes, jnp.float32).at[idx].add(v)
    return flat.reshape(num_classes, num_classes)


def seg_scores(conf: np.ndarray) -> dict:
    """Reference Evaluator formulas on a [C, C] confusion matrix."""
    conf = np.asarray(conf, np.float64)
    total = conf.sum()
    diag = np.diag(conf)
    row = conf.sum(axis=1)  # gt counts
    col = conf.sum(axis=0)  # pred counts
    with np.errstate(divide="ignore", invalid="ignore"):
        pixel_acc = diag.sum() / total if total > 0 else 0.0
        class_acc = float(np.nanmean(diag / row))
        iu = diag / (row + col - diag)
        miou = float(np.nanmean(iu))
        freq = row / total if total > 0 else row
        fwiou = float((freq[freq > 0] * iu[freq > 0]).sum())
    return {
        "pixel_acc": float(pixel_acc),
        "class_acc": class_acc,
        "mIoU": miou,
        "FWIoU": fwiou,
    }
