"""Compatibility graft for older jax runtimes.

The engine is written against current jax surface: ``jax.typeof`` (aval
inspection, incl. shard_map varying-manual-axes), ``jax.lax.pcast``
(replicated -> varying casts under shard_map), and top-level
``jax.shard_map`` with ``axis_names`` partial-manual mode. Containers that
bake an older jax (e.g. 0.4.x) lack those names while providing equivalent
machinery under ``jax.experimental.shard_map`` — and on them every engine
module would otherwise die at its first round with AttributeError.

``install()`` grafts the missing names onto the jax namespace, each gated
behind ``hasattr`` so it is a strict no-op on a current jax:

- ``jax.typeof``      -> ``jax.core.get_aval`` (old avals carry no ``vma``
  attribute; every caller already defends with ``getattr(..., "vma",
  frozenset())``, which is exactly right — old shard_map has no
  varying-manual-axes tracking to reconcile);
- ``jax.enable_x64``  -> ``jax.experimental.enable_x64`` (same context
  manager, pre-promotion name);
- ``jax.lax.axis_size`` -> ``jax.core.axis_frame`` (which on old jax IS the
  bound axis's static size — callers use it to build python-level ring
  permutations, so it must stay a python int);
- ``jax.lax.pcast``   -> identity (the cast only exists to satisfy the new
  vma type system; without vma tracking there is nothing to cast);
- ``jax.shard_map``   -> ``jax.experimental.shard_map.shard_map`` with
  ``check_rep=False`` (the old replication checker predates the vma model
  the callers are written for) and ``axis_names`` translated to the old
  ``auto`` complement. ``check_vma`` is accepted and ignored — the strict
  vma check does not exist on old jax, so strict-mode tests degrade to
  plain shard_map tests there.

Called from ``fedml_tpu/__init__``, so every entry point (tests, CLIs,
bench, launchers) runs on either jax generation without code changes.
"""

from __future__ import annotations


def jax_version() -> tuple[int, ...]:
    import jax

    return tuple(int(p) for p in jax.__version__.split(".")[:3] if p.isdigit())


def fed_tp_unsupported_reason() -> str | None:
    """Non-None (a skip reason) when this jax cannot COMPILE the federated
    tensor-parallel program — the ('clients', 'model') mesh with 'clients'
    manual (shard_map axis_names) and 'model' left to GSPMD.

    On the baked jax/jaxlib 0.4.3x CPU stack that program SIGABRTs inside
    ``backend_compile`` (a native XLA CHECK, not a python error — it kills
    the whole pytest process, which is why it must be gated BEFORE compile
    rather than caught). The partial-auto shard_map lowering it needs only
    became sound with the jax >= 0.5 vma/psum-transpose semantics, so the
    gate is a version check, not a feature probe (probing = crashing)."""
    v = jax_version()
    if v and v < (0, 5):
        import jax

        return (f"jax {jax.__version__}: federated-TP partial-auto "
                "shard_map SIGABRTs in XLA backend_compile; needs the "
                "jax>=0.5 vma/psum-transpose lowering")
    return None


def seq_oracle_unsupported_reason() -> str | None:
    """Non-None (a skip reason) when this jax cannot reproduce the
    seq-parallel ≡ single-device ORACLE equalities.

    The compat shard_map graft below runs with ``check_rep=False`` because
    old jax predates the vma model — and without vma tracking, old jax
    transposes ``psum`` back to ``psum`` instead of treating the cotangent
    as already-varying. Gradients that flow through the ring/grad-psum
    collectives come back with a systematic ~1e-2 relative deviation from
    the unsharded oracle (measured on jax 0.4.37: rel ≈ 0.012–0.017
    against the 1e-5 oracle tolerance). The ENGINE still runs and learns —
    only the exact-equality oracles are meaningless there, so they skip
    with this reason rather than fail forever on the old runtime."""
    v = jax_version()
    if v and v < (0, 5):
        import jax

        return (f"jax {jax.__version__}: pre-vma shard_map transposes psum "
                "to psum (not identity-on-varying), so seq-parallel grads "
                "deviate ~1e-2 from the single-device oracle; needs "
                "jax>=0.5 psum-transpose semantics")
    return None


def tp_oracle_unsupported_reason() -> str | None:
    """Non-None (a skip reason) when this jax cannot reproduce the
    centralized DP×TP / EP-MoE ≡ single-device ORACLE equalities.

    The tensor-parallel engine relies on the jax>=0.5 sharding-in-types
    machinery (``jax.set_mesh`` + layout propagation through the jitted
    train step). The compat graft degrades ``set_mesh`` to the legacy mesh
    context manager, under which GSPMD does not propagate the intended
    layouts through training — measured on jax 0.4.37 the DP×TP-trained
    model drifts to ~0.5 RELATIVE distance from the single-device oracle
    (not a tolerance nit; a different trajectory). Forward-pass layout
    tests still run; only the trained-equality oracles skip."""
    v = jax_version()
    if v and v < (0, 5):
        import jax

        return (f"jax {jax.__version__}: pre-sharding-in-types set_mesh "
                "shim does not propagate TP layouts through training "
                "(rel drift ~0.5 vs oracle); needs jax>=0.5")
    return None


def install() -> None:
    try:
        import jax
    except ImportError:  # pragma: no cover - jax is a hard dep everywhere else
        return

    if not hasattr(jax, "typeof"):
        import jax.core

        jax.typeof = jax.core.get_aval

    if not hasattr(jax, "enable_x64"):
        from jax.experimental import enable_x64

        jax.enable_x64 = enable_x64

    if not hasattr(jax.lax, "axis_size"):
        import jax.core as _core

        def axis_size(axis_name):
            # old jax: core.axis_frame(name) IS the static size (an int)
            if isinstance(axis_name, (tuple, list)):
                out = 1
                for a in axis_name:
                    out *= _core.axis_frame(a)
                return out
            return _core.axis_frame(axis_name)

        jax.lax.axis_size = axis_size

    if not hasattr(jax.lax, "pcast"):

        def pcast(x, axis_name=None, *, to=None):
            return x

        jax.lax.pcast = pcast

    if not hasattr(jax, "set_mesh"):
        # old Mesh is itself a context manager; `with jax.set_mesh(m):`
        # degrades to `with m:` (the pre-sharding-in-types idiom)
        jax.set_mesh = lambda mesh: mesh

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      axis_names=None, check_vma=None, **kw):
            auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
                    if axis_names else frozenset())
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False,
                              auto=auto)

        jax.shard_map = shard_map
