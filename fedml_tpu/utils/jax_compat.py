"""Compatibility graft for older jax runtimes.

The engine is written against current jax surface: ``jax.typeof`` (aval
inspection, incl. shard_map varying-manual-axes), ``jax.lax.pcast``
(replicated -> varying casts under shard_map), and top-level
``jax.shard_map`` with ``axis_names`` partial-manual mode. Containers that
bake an older jax (e.g. 0.4.x) lack those names while providing equivalent
machinery under ``jax.experimental.shard_map`` — and on them every engine
module would otherwise die at its first round with AttributeError.

``install()`` grafts the missing names onto the jax namespace, each gated
behind ``hasattr`` so it is a strict no-op on a current jax:

- ``jax.typeof``      -> ``jax.core.get_aval`` (old avals carry no ``vma``
  attribute; every caller already defends with ``getattr(..., "vma",
  frozenset())``, which is exactly right — old shard_map has no
  varying-manual-axes tracking to reconcile);
- ``jax.enable_x64``  -> ``jax.experimental.enable_x64`` (same context
  manager, pre-promotion name);
- ``jax.lax.axis_size`` -> ``jax.core.axis_frame`` (which on old jax IS the
  bound axis's static size — callers use it to build python-level ring
  permutations, so it must stay a python int);
- ``jax.lax.pcast``   -> identity (the cast only exists to satisfy the new
  vma type system; without vma tracking there is nothing to cast);
- ``jax.shard_map``   -> ``jax.experimental.shard_map.shard_map`` with
  ``check_rep=False`` (the old replication checker predates the vma model
  the callers are written for) and ``axis_names`` translated to the old
  ``auto`` complement. ``check_vma`` is accepted and ignored — the strict
  vma check does not exist on old jax, so strict-mode tests degrade to
  plain shard_map tests there.

Called from ``fedml_tpu/__init__``, so every entry point (tests, CLIs,
bench, launchers) runs on either jax generation without code changes.
"""

from __future__ import annotations


def install() -> None:
    try:
        import jax
    except ImportError:  # pragma: no cover - jax is a hard dep everywhere else
        return

    if not hasattr(jax, "typeof"):
        import jax.core

        jax.typeof = jax.core.get_aval

    if not hasattr(jax, "enable_x64"):
        from jax.experimental import enable_x64

        jax.enable_x64 = enable_x64

    if not hasattr(jax.lax, "axis_size"):
        import jax.core as _core

        def axis_size(axis_name):
            # old jax: core.axis_frame(name) IS the static size (an int)
            if isinstance(axis_name, (tuple, list)):
                out = 1
                for a in axis_name:
                    out *= _core.axis_frame(a)
                return out
            return _core.axis_frame(axis_name)

        jax.lax.axis_size = axis_size

    if not hasattr(jax.lax, "pcast"):

        def pcast(x, axis_name=None, *, to=None):
            return x

        jax.lax.pcast = pcast

    if not hasattr(jax, "set_mesh"):
        # old Mesh is itself a context manager; `with jax.set_mesh(m):`
        # degrades to `with m:` (the pre-sharding-in-types idiom)
        jax.set_mesh = lambda mesh: mesh

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      axis_names=None, check_vma=None, **kw):
            auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
                    if axis_names else frozenset())
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False,
                              auto=auto)

        jax.shard_map = shard_map
