"""jax.profiler bridges + compatibility alias for the span tracer.

The host-side span path now lives in ``fedml_tpu/obs/tracing.py`` (one
span path for everything: ``RoundTracer`` feeds the process metrics
registry and, via its ``sink``, the cross-rank distributed tracer).
``RoundTracer`` is re-exported here so seed-era imports keep working.

What genuinely lives here are the XLA-level profiler hooks:

- ``trace(logdir)``: context manager around jax.profiler for full XLA/TPU
  traces viewable in TensorBoard/Perfetto — opt-in because trace files are
  large;
- ``annotate(name)``: named region inside device traces.
"""

from __future__ import annotations

import contextlib

from fedml_tpu.obs.tracing import RoundTracer  # noqa: F401 — compat alias


@contextlib.contextmanager
def trace(logdir: str):
    """XLA-level trace via jax.profiler (TensorBoard 'profile' plugin /
    Perfetto). Wrap a handful of rounds, not a whole run."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region that shows up inside device traces
    (jax.profiler.TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
