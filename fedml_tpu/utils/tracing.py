"""Tracing / profiling hooks — a parity-plus subsystem.

The reference has no profiler integration; its only timing is ad-hoc
wall-clock prints ("aggregate time cost", FedAVGAggregator.py:59,85-86) —
SURVEY.md §5 flags jax.profiler hooks as the first-class improvement to add.

Two layers:
- ``RoundTracer``: lightweight host-side span timing (pack/compute/eval per
  round) with summary stats — always on, microsecond overhead.
- ``trace(logdir)``: context manager around jax.profiler for full XLA/TPU
  traces viewable in TensorBoard/Perfetto — opt-in because trace files are
  large.

Usage:
    tracer = RoundTracer()
    with tracer.span("pack"):   cb = ...
    with tracer.span("round"):  net = round_fn(...)
    tracer.next_round()
    print(tracer.summary())
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import numpy as np


class RoundTracer:
    """Per-round named span timing with aggregate statistics."""

    def __init__(self):
        self.rounds: list[dict[str, float]] = [{}]

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            cur = self.rounds[-1]
            cur[name] = cur.get(name, 0.0) + (time.perf_counter() - t0)

    def next_round(self):
        self.rounds.append({})

    def summary(self) -> dict[str, dict[str, float]]:
        """name -> {mean, p50, p95, max, total} over completed rounds."""
        per_name = defaultdict(list)
        for r in self.rounds:
            for k, v in r.items():
                per_name[k].append(v)
        out = {}
        for k, vs in per_name.items():
            a = np.asarray(vs)
            out[k] = {
                "mean": float(a.mean()),
                "p50": float(np.percentile(a, 50)),
                "p95": float(np.percentile(a, 95)),
                "max": float(a.max()),
                "total": float(a.sum()),
                "count": len(vs),
            }
        return out

    def totals(self) -> dict[str, float]:
        """name -> total seconds across all rounds (the bench span report)."""
        return {k: v["total"] for k, v in self.summary().items()}


@contextlib.contextmanager
def trace(logdir: str):
    """XLA-level trace via jax.profiler (TensorBoard 'profile' plugin /
    Perfetto). Wrap a handful of rounds, not a whole run."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region that shows up inside device traces
    (jax.profiler.TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
