"""Dataset condensation by gradient matching (fork addition).

Reference: fedml_api/utils/utils_condense.py:12-100+ (354 LoC) — synthesize a
small per-class image set whose network gradients match the real data's
(Zhao et al., Dataset Condensation with Gradient Matching); used by the
fork's FedDF path (_train_condense_server, feddf_api.py:534).

TPU form: the inner "match gradients" objective — cosine distance between
grad(real batch) and grad(synthetic set) — is a pure function of the
synthetic pixels, so the whole condensation loop is jitted with the synthetic
images updated by Adam. Layer-wise cosine matching as in the paper.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.local import Task


def _grad_match_loss(g_real, g_syn):
    """Sum over layers of (1 - cosine similarity) between gradient tensors."""
    total = 0.0
    for gr, gs in zip(jax.tree.leaves(g_real), jax.tree.leaves(g_syn)):
        gr_f, gs_f = jnp.ravel(gr), jnp.ravel(gs)
        denom = jnp.maximum(jnp.linalg.norm(gr_f) * jnp.linalg.norm(gs_f), 1e-8)
        total = total + (1.0 - jnp.dot(gr_f, gs_f) / denom)
    return total


def condense_dataset(
    task: Task,
    x: np.ndarray,
    y: np.ndarray,
    num_classes: int,
    images_per_class: int = 10,
    iters: int = 50,
    syn_lr: float = 0.1,
    batch_per_class: int = 64,
    seed: int = 0,
    net=None,
):
    """Return (x_syn [C*ipc, ...], y_syn [C*ipc]) matching class gradients.

    The synthetic set is initialized from real samples (the reference's
    'real' init mode) and optimized so that per-class gradients of the
    synthetic set match those of real class batches — at freshly-initialized
    networks by default, or at ``net`` (a NetState) when given: the
    reference's client.condense receives the CURRENT global weights
    (condense_api.py:170-178), so condensation adapts to the trained model.
    """
    rng = np.random.RandomState(seed)
    key = jax.random.PRNGKey(seed)

    # init synthetic images from random real samples per class
    xs, ys = [], []
    real_batches = []
    for c in range(num_classes):
        idx = np.where(np.asarray(y) == c)[0]
        if len(idx) == 0:
            continue
        pick = rng.choice(idx, images_per_class, replace=len(idx) < images_per_class)
        xs.append(np.asarray(x)[pick])
        ys.append(np.full(images_per_class, c, np.int64))
        rb = rng.choice(idx, min(batch_per_class, len(idx)), replace=False)
        pad = batch_per_class - len(rb)
        if pad:
            rb = np.concatenate([rb, rng.choice(idx, pad)])
        real_batches.append(np.asarray(x)[rb])
    x_syn = jnp.asarray(np.concatenate(xs), jnp.float32)
    y_syn = jnp.asarray(np.concatenate(ys))
    x_real = jnp.asarray(np.stack(real_batches))  # [C, B, ...]
    present = x_real.shape[0]

    tx = optax.adam(syn_lr)

    @jax.jit
    def run(x_syn, key):
        opt = tx.init(x_syn)

        def it(carry, k):
            x_syn, opt = carry
            if net is None:
                net_k = task.init(k, x_syn[: images_per_class])  # fresh random net
            else:
                net_k = net  # condition on the provided (global) weights

            def match_loss(xs_):
                total = 0.0
                for c in range(present):
                    sl = slice(c * images_per_class, (c + 1) * images_per_class)
                    yc = y_syn[sl]
                    m1 = jnp.ones(images_per_class)
                    g_syn = jax.grad(
                        lambda p: task.loss(p, net_k.extra, xs_[sl], yc, m1,
                                            k, False)[0]
                    )(net_k.params)
                    mb = jnp.ones(x_real.shape[1])
                    yb = jnp.full((x_real.shape[1],), yc[0])
                    g_real = jax.grad(
                        lambda p: task.loss(p, net_k.extra, x_real[c], yb, mb,
                                            k, False)[0]
                    )(net_k.params)
                    total = total + _grad_match_loss(
                        jax.lax.stop_gradient(g_real), g_syn)
                return total

            l, g = jax.value_and_grad(match_loss)(x_syn)
            upd, opt = tx.update(g, opt, x_syn)
            return (optax.apply_updates(x_syn, upd), opt), l

        keys = jax.random.split(key, iters)
        (x_syn, _), losses = jax.lax.scan(it, (x_syn, opt), keys)
        return x_syn, losses

    x_out, losses = run(x_syn, key)
    return np.asarray(x_out), np.asarray(y_syn), np.asarray(losses)
