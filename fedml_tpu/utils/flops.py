"""Model-FLOPs accounting for MFU reporting (BASELINE.md north-star rows).

Instead of hand-counting each architecture, ask XLA: the compiled forward's
``cost_analysis()["flops"]`` is the compiler's own FLOP count for the real
program on the real backend. Train-step FLOPs use the standard 3x-forward
accounting (fwd + 2 bwd matmul passes). MFU is quoted against the chip's
bf16 peak (same convention as bench.py: f32 runs still quote bf16 peak —
conservative, since XLA routes f32 contractions through the MXU).
"""

from __future__ import annotations

# public per-chip bf16 dense-matmul peaks, FLOPs/s (bench.py table; more
# specific keys first — substring match)
PEAK_BF16 = {"v5 lite": 1.97e14, "v5e": 1.97e14, "v5p": 4.59e14,
             "v6 lite": 9.18e14, "v6e": 9.18e14,
             "v4": 2.75e14, "v3": 1.23e14, "v2": 4.5e13}


def compiled_flops(fn, *args) -> float | None:
    """XLA's FLOP estimate for ``jit(fn)(*args)``; None when the backend
    does not expose cost analysis. Never raises — MFU is garnish."""
    try:
        import jax

        c = jax.jit(fn).lower(*args).compile()
        ca = c.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one dict per program
            ca = ca[0] if ca else {}
        f = float(ca.get("flops", 0.0))
        return f if f > 0 else None
    except Exception:  # noqa: BLE001
        return None


def bf16_peak() -> float | None:
    """This process's per-chip bf16 peak, or None off-TPU / on an unknown
    generation (a guessed peak would misreport, ADVICE r4)."""
    try:
        import jax

        d = jax.devices()[0]
        if d.platform != "tpu":
            return None
        kind = d.device_kind.lower()
        return next((v for k, v in PEAK_BF16.items() if k in kind), None)
    except Exception:  # noqa: BLE001
        return None


def train_mfu(samples_per_sec_per_chip: float,
              fwd_flops_per_sample: float) -> float | None:
    """MFU of a training loop: 3x-forward accounting vs bf16 peak."""
    peak = bf16_peak()
    if peak is None or not fwd_flops_per_sample:
        return None
    return samples_per_sec_per_chip * 3.0 * fwd_flops_per_sample / peak
