"""Metrics sink + logging (L-aux).

The reference's observability is wandb on rank 0 (main_fedavg.py:300-308,
FedAVGAggregator.py:136-162 wandb.log of Train/Acc etc.) plus rank-prefixed
python logging (fedml_api/utils/logger.py:8-33). In zero-egress TPU
environments wandb is unavailable, so the sink is local-first: an append-only
JSONL run log + in-memory summary (the wandb-summary.json analogue the
reference's CI consumes, CI-script-fedavg.sh:42-46). If wandb IS importable
and WANDB_MODE allows it, it mirrors transparently.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Persistent XLA compile cache: repeat runs of the same program skip
    the expensive first compile (~20-40 s per program on TPU through the
    remote-compile relay). Call AFTER jax is importable but before the
    first jit; failures are non-fatal (the cache is an optimization).
    Override the location with FEDML_COMPILE_CACHE."""
    try:
        import jax

        cache_dir = cache_dir or os.environ.get(
            "FEDML_COMPILE_CACHE",
            os.path.expanduser("~/.cache/fedml_tpu_xla"))
        # per-platform subdirectory: entries written through a REMOTE
        # compile service (e.g. a TPU relay) can carry host-feature flags
        # the local CPU rejects — sharing one dir makes every CPU child
        # iterate and discard them (slow startup + AOT-loader error spam).
        # JAX_PLATFORMS is readable without initializing any backend; when
        # it is unset, fall back to the backend jax has ALREADY initialized
        # (never initialize one here — that can dial a dead relay) so TPU
        # and CPU processes on the same host still get isolated subdirs.
        platform = (os.environ.get("JAX_PLATFORMS") or "").split(",")[0]
        if not platform:
            try:
                from jax._src import xla_bridge

                if xla_bridge._backends:
                    platform = jax.default_backend()
            except Exception:  # noqa: BLE001 — isolation is best-effort
                pass
        platform = platform or "default"
        cache_dir = os.path.join(
            cache_dir, "".join(c if c.isalnum() else "_" for c in platform))
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # noqa: BLE001
        logging.getLogger("fedml_tpu").warning(
            "compile cache unavailable (%s)", e)


def set_process_title(title: str) -> None:
    """Name the OS process (reference: setproctitle at main_fedavg.py:284-285)
    so ps/top show the role; silently skipped when setproctitle is absent."""
    try:
        import setproctitle

        setproctitle.setproctitle(title)
    except Exception:
        pass


def setup_logging(process_name: str = "fedml-tpu", level=logging.INFO,
                  log_dir: str | None = None):
    """Rank/process-prefixed format (logger.py:8-33 analogue)."""
    fmt = (f"[{process_name}] %(asctime)s %(levelname)s "
           "%(name)s:%(lineno)d %(message)s")
    handlers = [logging.StreamHandler()]
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        handlers.append(logging.FileHandler(
            os.path.join(log_dir, f"{process_name}.log")))
    logging.basicConfig(level=level, format=fmt, handlers=handlers, force=True)


class RunLogger:
    """wandb-compatible facade writing JSONL locally (and to wandb if live)."""

    def __init__(self, run_dir: str = "./runs", name: str | None = None,
                 config: dict | None = None, use_wandb: bool = False):
        self.name = name or time.strftime("run_%Y%m%d_%H%M%S")
        self.dir = os.path.join(run_dir, self.name)
        os.makedirs(self.dir, exist_ok=True)
        self.summary: dict[str, Any] = {}
        self._f = open(os.path.join(self.dir, "metrics.jsonl"), "a")
        if config:
            with open(os.path.join(self.dir, "config.json"), "w") as f:
                json.dump(config, f, indent=2, default=str)
        self._wandb = None
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb.init(project="fedml-tpu", name=self.name,
                                         config=config or {})
            except Exception:
                self._wandb = None

    def log(self, metrics: dict, step: int | None = None):
        rec = dict(metrics)
        if step is not None:
            rec["_step"] = step
        rec["_time"] = time.time()
        self._f.write(json.dumps(rec, default=float) + "\n")
        self._f.flush()
        self.summary.update(metrics)
        if self._wandb is not None:
            self._wandb.log(metrics, step=step)

    # reference metric names (FedAVGAggregator.py:136-162 wandb.log keys).
    # Train/Acc is the all-clients aggregate of the CURRENT model on train
    # splits (_local_test_on_all_clients) — train_all_* when the run produced
    # it, else the in-round sampled-client training metric as the closest
    # available analogue (listed later so the per-client aggregate wins).
    _WANDB_KEYS = (
        ("train_acc", "Train/Acc"), ("train_loss", "Train/Loss"),
        ("train_all_acc", "Train/Acc"), ("train_all_loss", "Train/Loss"),
        ("test_acc", "Test/Acc"), ("test_loss", "Test/Loss"),
        ("round", "round"),
    )

    def _wandb_summary(self) -> dict:
        out = dict(self.summary)
        for src, dst in self._WANDB_KEYS:
            if src in self.summary:
                out[dst] = self.summary[src]
        return out

    def finish(self):
        """Write the summary files: ``summary.json`` (raw keys) and a
        wandb-interop ``wandb-summary.json`` with the reference's metric
        names, also linked at ``<run_dir>/latest-run/files/wandb-summary.json``
        — the exact path shape the reference CI consumes
        (``wandb/latest-run/files/wandb-summary.json``,
        CI-script-fedavg.sh:42-46), so tooling written against the reference
        can point its ``wandb`` dir at ``run_dir`` unchanged."""
        with open(os.path.join(self.dir, "summary.json"), "w") as f:
            json.dump(self.summary, f, indent=2, default=float)
        wandb_summary = self._wandb_summary()
        with open(os.path.join(self.dir, "wandb-summary.json"), "w") as f:
            json.dump(wandb_summary, f, indent=2, default=float)
        latest = os.path.join(os.path.dirname(self.dir), "latest-run", "files")
        try:
            os.makedirs(latest, exist_ok=True)
            with open(os.path.join(latest, "wandb-summary.json"), "w") as f:
                json.dump(wandb_summary, f, indent=2, default=float)
        except OSError:
            pass  # read-only run_dir parent: the per-run copy above suffices
        self._f.close()
        if self._wandb is not None:
            self._wandb.finish()


def notify_sweep_done(path: str = "./tmp/fedml"):
    """Completion signal for sweep orchestrators — the reference writes into a
    named pipe (fedavg/utils.py:19-26); we write/touch a regular file if no
    fifo exists at ``path``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_NONBLOCK)
        os.write(fd, b"done\n")
        os.close(fd)
    except OSError:
        with open(path, "w") as f:
            f.write("done\n")
