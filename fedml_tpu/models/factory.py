"""Model factory — mirror of the reference's create_model dispatch
(fedml_experiments/distributed/fedavg/main_fedavg.py:232-267)."""

from __future__ import annotations


def create_model(model_name: str, output_dim: int = 10, **kwargs):
    """Return a flax module for the given reference model name."""
    from fedml_tpu.models.linear import LogisticRegression
    from fedml_tpu.models.cnn import CNNOriginalFedAvg, CNNDropOut
    from fedml_tpu.models.rnn import RNNOriginalFedAvg, RNNStackOverflow

    name = model_name.lower()
    if name == "lr":
        return LogisticRegression(num_classes=output_dim)
    if name == "cnn":
        return CNNOriginalFedAvg(only_digits=(output_dim == 10))
    if name == "cnn_dropout":
        return CNNDropOut(only_digits=(output_dim == 10))
    if name == "rnn":
        return RNNOriginalFedAvg(vocab_size=output_dim or 90)
    if name == "rnn_stackoverflow":
        return RNNStackOverflow()
    if name in ("resnet56", "resnet110"):
        from fedml_tpu.models.resnet import ResNetCIFAR

        depth = 56 if name == "resnet56" else 110
        return ResNetCIFAR(depth=depth, num_classes=output_dim)
    if name in ("resnet_wo_bn", "resnet56_wo_bn"):
        from fedml_tpu.models.resnet import ResNetCIFAR

        return ResNetCIFAR(depth=56, num_classes=output_dim, norm_type="none")
    if name == "resnet18_gn":
        from fedml_tpu.models.resnet_gn import ResNet18GN

        return ResNet18GN(num_classes=output_dim)
    if name == "mobilenet":
        from fedml_tpu.models.mobilenet import MobileNetV1

        return MobileNetV1(num_classes=output_dim)
    if name in ("mobilenet_v3", "mobilenet_v3_large"):
        from fedml_tpu.models.mobilenet import MobileNetV3

        if name.endswith("_large"):
            kwargs.setdefault("mode", "large")  # reference default model_mode
        return MobileNetV3(num_classes=output_dim, **kwargs)
    if name == "efficientnet":
        from fedml_tpu.models.efficientnet import EfficientNet

        return EfficientNet(num_classes=output_dim, **kwargs)
    if name in ("transformer", "transformer_flash"):
        from fedml_tpu.models.transformer import TransformerLM

        kwargs.setdefault("use_flash", name == "transformer_flash")
        kwargs.setdefault("vocab_size", output_dim)
        return TransformerLM(**kwargs)
    if name == "vgg11":
        from fedml_tpu.models.vgg import VGG

        return VGG(depth=11, num_classes=output_dim)
    if name == "vgg16":
        from fedml_tpu.models.vgg import VGG

        return VGG(depth=16, num_classes=output_dim)
    if name in ("darts", "darts_cifar", "darts_imagenet"):
        # the DERIVED fixed-genotype nets (the reference train stage,
        # model.py:111/:161); genotype= accepts a registry name, a search
        # result dict, or a json path. The search SUPERNET stays behind
        # FedNASAPI (it needs the bilevel engine, not plain FedAvg).
        from fedml_tpu.models.darts import (NetworkCIFAR, NetworkImageNet,
                                            as_genotype)

        if name == "darts_imagenet":
            kwargs.setdefault("genotype", "DARTS_V2")
            kwargs["genotype"] = as_genotype(kwargs["genotype"])  # fail fast
            return NetworkImageNet(num_classes=output_dim, **kwargs)
        kwargs.setdefault("genotype", "FedNAS_V1")
        kwargs["genotype"] = as_genotype(kwargs["genotype"])
        return NetworkCIFAR(num_classes=output_dim, **kwargs)
    raise ValueError(f"unknown model: {model_name}")
