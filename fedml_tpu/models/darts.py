"""DARTS search space for FedNAS (reference: fedml_api/model/cv/darts/
{model_search.py, operations.py, genotypes.py, architect.py}, ~1,700 LoC).

A differentiable-architecture supernet: each edge of a cell computes a
softmax(alpha)-weighted mixture of candidate ops. FedNAS federates the
bilevel search: clients optimize (weights w, alphas a) locally, the server
averages both (FedNASAggregator.__aggregate_weight/:71, __aggregate_alpha/:95).

Search-space parity with the reference:
  - the full 8-primitive set (genotypes.py:5-14), including the 5x5
    separable and dilated convs;
  - normal AND reduction cells (model_search.py Network: reduction at
    layers//3 and 2*layers//3 with channel doubling, stride-2 on the edges
    that touch the two input nodes, model_search.py:40-46,204-210);
  - separate ``alphas_normal`` / ``alphas_reduce`` tensors shared across
    cells of each type (model_search.py:233-241);
  - FactorizedReduce / ReLU-conv preprocessing of the two cell inputs and
    concat of the last ``multiplier`` nodes (operations.py, Cell.forward).

TPU re-design: the reference's MixedOp is a python loop over op modules; here
all candidate ops for an edge evaluate as a batched branch stack and the
alpha-softmax contraction is one tensordot — XLA fuses the mixture, and the
whole supernet vmaps over clients like any other model. Norms are GroupNorm
(affine-free BatchNorm in the reference): the supernet trains vmapped over
clients, where BN's mutable batch stats would silently leak across the
client axis. Alphas live in the same 'params' collection so the engine can
average them with the weights (parity) or split them out (bilevel search,
algorithms/fednas.py).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# reference genotypes.py:5-14, same order
PRIMITIVES = (
    "none",
    "max_pool_3x3",
    "avg_pool_3x3",
    "skip_connect",
    "sep_conv_3x3",
    "sep_conv_5x5",
    "dil_conv_3x3",
    "dil_conv_5x5",
)


def _norm(c: int, affine: bool = False):
    """Search-phase norm. affine=False everywhere except the stem — DARTS
    searches with affine-free norms so the alphas absorb scaling
    (operations.py OPS all pass affine=False; model_search.py's stem BN is
    the one affine norm). GroupNorm instead of BN: the supernet trains
    vmapped over clients, where BN's batch stats would leak across the
    client axis."""
    g = min(8, c)
    while c % g:  # GroupNorm needs groups | channels (e.g. stem 3*C)
        g -= 1
    return nn.GroupNorm(num_groups=g, use_scale=affine, use_bias=affine)


class _ReLUConvNorm(nn.Module):
    """ReLUConvBN analogue (operations.py) — 1x1 projection preprocessing.
    ``affine=True`` in derived (fixed-genotype) networks, False in search."""

    filters: int
    affine: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        return _norm(self.filters, self.affine)(x)


class FactorizedReduce(nn.Module):
    """Stride-2 channel-preserving reduction: two offset 1x1/s2 convs
    concatenated (operations.py FactorizedReduce). Assumes even H/W (same
    constraint as the reference's pad-0 convs)."""

    filters: int
    affine: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        h1 = nn.Conv(self.filters // 2, (1, 1), strides=(2, 2),
                     padding="VALID", use_bias=False)(x)
        h2 = nn.Conv(self.filters - self.filters // 2, (1, 1), strides=(2, 2),
                     padding="VALID", use_bias=False)(x[:, 1:, 1:, :])
        return _norm(self.filters, self.affine)(
            jnp.concatenate([h1, h2], axis=-1))


class _SepConv(nn.Module):
    """SepConv (operations.py): (ReLU, depthwise k/stride, pointwise, norm)
    applied twice — the second pass always stride 1."""

    filters: int
    kernel: int
    stride: int = 1
    affine: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        for s in (self.stride, 1):
            c = x.shape[-1]
            x = nn.relu(x)
            x = nn.Conv(c, (self.kernel, self.kernel), strides=(s, s),
                        padding="SAME", feature_group_count=c, use_bias=False)(x)
            x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
            x = _norm(self.filters, self.affine)(x)
        return x


class _DilConv(nn.Module):
    """DilConv (operations.py): ReLU, depthwise k/stride with dilation 2,
    pointwise, norm — applied once."""

    filters: int
    kernel: int
    stride: int = 1
    affine: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = x.shape[-1]
        x = nn.relu(x)
        x = nn.Conv(c, (self.kernel, self.kernel), strides=(self.stride,) * 2,
                    kernel_dilation=(2, 2), padding="SAME",
                    feature_group_count=c, use_bias=False)(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        return _norm(self.filters, self.affine)(x)


def _pool(x, kind: str, stride: int):
    window, s = (3, 3), (stride, stride)
    if kind == "max":
        return nn.max_pool(x, window, strides=s, padding="SAME")
    # count_include_pad=False: border outputs divide by the VALID element
    # count, matching the reference AvgPool2d (operations.py:6)
    return nn.avg_pool(x, window, strides=s, padding="SAME",
                       count_include_pad=False)


class MixedOp(nn.Module):
    """All 8 candidate ops evaluated, alpha-softmax-mixed in one contraction.
    ``stride=2`` on reduction-cell edges that read the two input nodes."""

    filters: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, weights, train: bool = False):
        # weights: [num_ops] softmaxed alphas for this edge
        s = self.stride
        down = x[:, ::2, ::2, :] if s == 2 else x
        outs = []
        for prim in PRIMITIVES:
            if prim == "none":
                outs.append(jnp.zeros_like(down))
            elif prim == "skip_connect":
                outs.append(FactorizedReduce(self.filters)(x, train)
                            if s == 2 else x)
            elif prim == "max_pool_3x3":
                # affine-free norm after pool, like the reference MixedOp's
                # BatchNorm2d(affine=False) (model_search.py:17-18)
                outs.append(_norm(x.shape[-1])(_pool(x, "max", s)))
            elif prim == "avg_pool_3x3":
                outs.append(_norm(x.shape[-1])(_pool(x, "avg", s)))
            elif prim == "sep_conv_3x3":
                outs.append(_SepConv(self.filters, 3, s)(x, train))
            elif prim == "sep_conv_5x5":
                outs.append(_SepConv(self.filters, 5, s)(x, train))
            elif prim == "dil_conv_3x3":
                outs.append(_DilConv(self.filters, 3, s)(x, train))
            elif prim == "dil_conv_5x5":
                outs.append(_DilConv(self.filters, 5, s)(x, train))
        stacked = jnp.stack(outs)  # [O, B, H', W', C]
        return jnp.tensordot(weights, stacked, axes=([0], [0]))


class Cell(nn.Module):
    """DARTS cell (model_search.py Cell): preprocess the two inputs, then
    ``steps`` intermediate nodes each summing mixed ops over all previous
    states; output = concat of the last ``multiplier`` nodes."""

    steps: int = 4
    multiplier: int = 4
    filters: int = 16
    reduction: bool = False
    reduction_prev: bool = False

    @nn.compact
    def __call__(self, s0, s1, alphas, train: bool = False):
        # alphas: [num_edges, num_ops] (already softmaxed rows)
        C = self.filters
        s0 = (FactorizedReduce(C)(s0, train) if self.reduction_prev
              else _ReLUConvNorm(C)(s0, train))
        s1 = _ReLUConvNorm(C)(s1, train)
        states = [s0, s1]
        offset = 0
        for i in range(self.steps):
            acc = 0.0
            for j, h in enumerate(states):
                stride = 2 if self.reduction and j < 2 else 1
                acc = acc + MixedOp(C, stride)(h, alphas[offset + j], train)
            offset += len(states)
            states.append(acc)
        return jnp.concatenate(states[-self.multiplier:], axis=-1)


def num_edges(steps: int = 4) -> int:
    return sum(2 + i for i in range(steps))


class DARTSNetwork(nn.Module):
    """Supernet (model_search.py Network): stem -> ``layers`` cells with
    reduction cells at layers//3 and 2*layers//3 (channels double there) ->
    global pool -> classifier. Two alpha tensors — ``alphas_normal`` and
    ``alphas_reduce`` — each shared across all cells of that type.

    ``nas_method="gdas"`` switches the edge mixture from softmax(alphas) to
    Gumbel straight-through hard selection (model_search_gdas.py:1-188
    get_gumbel_prob: sample gumbel noise onto the alphas, softmax at
    temperature tau, forward the one-hot argmax, backprop through the soft
    probs). Deviation: the reference anneals tau per epoch from the host
    (set_tau); here tau is a static module field — annealing means
    rebuilding the jitted program, so federated rounds hold it fixed
    WITHIN a stage. Annealing recipe: params (incl. alphas) are
    tau-independent, so run staged search — build a fresh FedNASAPI at
    each lower tau and carry ``net`` over (one recompile per stage, the
    honest cost model under jit; tested in test_nas_affinity_condense).

    Second GDAS deviation (ADVICE r5 item 2, documented deliberately): the
    gumbel noise is drawn ONCE per alphas tensor per forward and the
    resulting hard selection is shared across all cells of that type; the
    reference re-samples inside the per-cell forward loop
    (model_search_gdas.py:127-129), giving each cell an independent draw
    (more exploration per step). Here the edge weights are computed once
    before the cell loop precisely so the mixture is a single fused op
    under vmap-over-clients — per-cell draws would rebuild the mixture
    inside every cell at K-clients width. The shared draw is still an
    unbiased sample of the same categorical; it only correlates the cells'
    exploration within one step, and successive steps (fresh dropout rng
    per batch) decorrelate across time. Callers who want reference-exact
    exploration can raise ``layers``-many supernets — nothing in the
    search API assumes the shared draw."""

    num_classes: int = 10
    layers: int = 8
    steps: int = 4
    multiplier: int = 4
    init_filters: int = 16
    stem_multiplier: int = 3
    nas_method: str = "darts"
    tau: float = 10.0

    def _edge_weights(self, alphas, train: bool):
        if self.nas_method != "gdas":
            return jax.nn.softmax(alphas, -1)
        logits = alphas
        if train:  # eval selects deterministically (no gumbel noise)
            u = jax.random.uniform(self.make_rng("dropout"), alphas.shape,
                                   minval=1e-10, maxval=1.0)
            logits = alphas - jnp.log(-jnp.log(u))
        probs = jax.nn.softmax(logits / self.tau, -1)
        hard = jax.nn.one_hot(jnp.argmax(probs, -1), alphas.shape[-1],
                              dtype=probs.dtype)
        # straight-through: forward the hard one-hot, grad via the probs
        return hard + probs - jax.lax.stop_gradient(probs)

    @nn.compact
    def __call__(self, x, train: bool = False):
        E = num_edges(self.steps)
        a_init = lambda k: 1e-3 * jax.random.normal(k, (E, len(PRIMITIVES)))
        aw_normal = self._edge_weights(self.param("alphas_normal", a_init),
                                       train)
        aw_reduce = self._edge_weights(self.param("alphas_reduce", a_init),
                                       train)

        C_curr = self.stem_multiplier * self.init_filters
        s = nn.Conv(C_curr, (3, 3), padding="SAME", use_bias=False)(x)
        s0 = s1 = _norm(C_curr, affine=True)(s)

        C_curr = self.init_filters
        reduction_prev = False
        # reference: reduction at layers//3 and 2*layers//3. The -{0} guard
        # only matters for layers<3 (shallow test nets), where a reduction
        # cell at layer 0 would leave no normal cell and starve
        # alphas_normal of gradient; real configs (layers>=6) are unaffected.
        reduce_at = {self.layers // 3, 2 * self.layers // 3} - {0}
        for i in range(self.layers):
            reduction = i in reduce_at
            if reduction:
                C_curr *= 2
            cell = Cell(self.steps, self.multiplier, C_curr,
                        reduction, reduction_prev)
            s0, s1 = s1, cell(s0, s1, aw_reduce if reduction else aw_normal,
                              train)
            reduction_prev = reduction
        y = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


def _parse_alphas(probs: np.ndarray, steps: int) -> list[tuple[str, int]]:
    """The reference's genotype _parse (model_search.py:263-291): per node,
    top-2 incoming edges ranked by their best non-'none' op weight; per
    chosen edge, that best op. Flat [(op, predecessor), ...] — 2 per node."""
    none_idx = PRIMITIVES.index("none")
    gene: list[tuple[str, int]] = []
    offset = 0
    for i in range(steps):
        n_in = 2 + i
        W = probs[offset : offset + n_in]
        masked = np.delete(W, none_idx, axis=1)
        best_per_edge = masked.max(-1)
        edges = np.argsort(-best_per_edge, kind="stable")[:2]  # ranked, like the reference sort
        for j in (int(e) for e in edges):
            ops = [(w, k) for k, w in enumerate(W[j]) if k != none_idx]
            gene.append((PRIMITIVES[max(ops)[1]], j))
        offset += n_in
    return gene


def extract_genotype(params, steps: int = 4, multiplier: int = 4) -> dict:
    """Discretize both alpha tensors into the reference's Genotype structure
    (normal/normal_concat/reduce/reduce_concat, genotypes.py:3;
    FedNASAggregator.record_model_global_architecture, FedNASAggregator.py:173)."""

    def softmax_np(a):
        e = np.exp(a - a.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    concat = list(range(2 + steps - multiplier, steps + 2))
    return {
        "normal": _parse_alphas(softmax_np(np.asarray(params["alphas_normal"])), steps),
        "normal_concat": concat,
        "reduce": _parse_alphas(softmax_np(np.asarray(params["alphas_reduce"])), steps),
        "reduce_concat": concat,
    }


# ---------------------------------------------------------------- derived net
# The reference's "train" stage (main_fednas.py:44-45 --stage train) builds a
# FIXED-genotype network (model.py:111 NetworkCIFAR) and federatedly trains
# it: drop-path regularization on non-identity edges, optional auxiliary
# head at 2/3 depth (aux loss weight args.auxiliary_weight).

# Published genotypes (reference genotypes.py:74-91) + the FedNAS result.
GENOTYPES: dict[str, dict] = {
    "FedNAS_V1": {
        "normal": [("sep_conv_3x3", 1), ("sep_conv_3x3", 0),
                   ("sep_conv_3x3", 2), ("sep_conv_5x5", 0),
                   ("sep_conv_3x3", 1), ("sep_conv_5x5", 3),
                   ("dil_conv_5x5", 3), ("sep_conv_3x3", 4)],
        "normal_concat": [2, 3, 4, 5],
        "reduce": [("max_pool_3x3", 0), ("skip_connect", 1),
                   ("max_pool_3x3", 0), ("max_pool_3x3", 2),
                   ("max_pool_3x3", 0), ("dil_conv_5x5", 1),
                   ("max_pool_3x3", 0), ("dil_conv_5x5", 2)],
        "reduce_concat": [2, 3, 4, 5],
    },
    "DARTS_V2": {
        "normal": [("sep_conv_3x3", 0), ("sep_conv_3x3", 1),
                   ("sep_conv_3x3", 0), ("sep_conv_3x3", 1),
                   ("sep_conv_3x3", 1), ("skip_connect", 0),
                   ("skip_connect", 0), ("dil_conv_3x3", 2)],
        "normal_concat": [2, 3, 4, 5],
        "reduce": [("max_pool_3x3", 0), ("max_pool_3x3", 1),
                   ("skip_connect", 2), ("max_pool_3x3", 1),
                   ("max_pool_3x3", 0), ("skip_connect", 2),
                   ("skip_connect", 2), ("max_pool_3x3", 1)],
        "reduce_concat": [2, 3, 4, 5],
    },
}


def as_genotype(g) -> dict:
    """Normalize a genotype source: a registry name ("FedNAS_V1"), a dict
    (extract_genotype output / parsed json), or a json file path."""
    if isinstance(g, str):
        if g in GENOTYPES:
            return GENOTYPES[g]
        import json
        import os

        if os.path.exists(g):
            with open(g) as f:
                # recurse so file input gets the same (op, int)
                # normalization and fail-fast validation as dict input —
                # a file with float/string node indices must error HERE,
                # not later inside DerivedCell (ADVICE r5 item 4)
                return as_genotype(json.load(f))
        raise ValueError(f"unknown genotype {g!r} (registry: "
                         f"{sorted(GENOTYPES)} or a json file path)")
    g = dict(g)
    for k in ("normal", "reduce"):
        g[k] = [(str(op), int(j)) for op, j in g[k]]
        g[f"{k}_concat"] = [int(i) for i in g[f"{k}_concat"]]
    return g


def _drop_path(x, drop_prob: float, rng):
    """Per-sample stochastic branch drop (darts/utils.py:82-88): zero the
    whole branch for a bernoulli(drop_prob) subset of the batch, rescale
    survivors by 1/keep."""
    keep = 1.0 - drop_prob
    mask = jax.random.bernoulli(rng, keep, (x.shape[0], 1, 1, 1))
    return jnp.where(mask, x / keep, 0.0)


class DerivedCell(nn.Module):
    """Fixed-genotype cell (model.py Cell): two ops per node, chosen
    predecessors, drop-path on non-identity branches during training.
    All norms affine (operations.py OPS called with affine=True at
    model.py:37)."""

    gene: tuple  # ((op_name, predecessor_idx), ...), 2 per node
    concat: tuple  # state indices concatenated as the cell output
    filters: int
    reduction: bool = False
    reduction_prev: bool = False
    drop_path_prob: float = 0.0

    @nn.compact
    def __call__(self, s0, s1, train: bool = False):
        C = self.filters
        s0 = (FactorizedReduce(C, affine=True)(s0, train)
              if self.reduction_prev
              else _ReLUConvNorm(C, affine=True)(s0, train))
        s1 = _ReLUConvNorm(C, affine=True)(s1, train)
        states = [s0, s1]
        for i in range(len(self.gene) // 2):
            hs = []
            for name, j in self.gene[2 * i: 2 * i + 2]:
                stride = 2 if self.reduction and j < 2 else 1
                h = states[j]
                identity = False
                if name == "none":
                    # true Zero op (operations.py Zero): contributes nothing,
                    # at the op's output spatial extent. Discretized
                    # genotypes never pick it, but user-supplied json may.
                    h = jnp.zeros_like(h[:, ::stride, ::stride, :])
                elif name == "skip_connect":
                    if stride == 2:
                        h = FactorizedReduce(C, affine=True)(h, train)
                    else:
                        identity = True  # Identity: no drop-path (model.py:55)
                elif name == "max_pool_3x3":
                    h = _pool(h, "max", stride)  # derived pools carry no norm
                elif name == "avg_pool_3x3":
                    h = _pool(h, "avg", stride)
                elif name == "sep_conv_3x3":
                    h = _SepConv(C, 3, stride, affine=True)(h, train)
                elif name == "sep_conv_5x5":
                    h = _SepConv(C, 5, stride, affine=True)(h, train)
                elif name == "dil_conv_3x3":
                    h = _DilConv(C, 3, stride, affine=True)(h, train)
                elif name == "dil_conv_5x5":
                    h = _DilConv(C, 5, stride, affine=True)(h, train)
                else:
                    raise ValueError(f"unknown op {name!r} in genotype")
                if train and self.drop_path_prob > 0.0 and not identity:
                    h = _drop_path(h, self.drop_path_prob,
                                   self.make_rng("dropout"))
                hs.append(h)
            states.append(hs[0] + hs[1])
        return jnp.concatenate([states[i] for i in self.concat], axis=-1)


class AuxiliaryHeadCIFAR(nn.Module):
    """Aux classifier at 2/3 depth, 8x8 input (model.py:64-84): ReLU,
    avg-pool 5x5/s3 (-> 2x2), 1x1 conv 128, norm, ReLU, 2x2 conv 768,
    norm, ReLU, linear."""

    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.shape[1] < 8 or x.shape[2] < 8:
            raise ValueError(
                f"auxiliary head needs >=8x8 features, got {x.shape[1:3]} — "
                "input too small for this depth (model.py:66 assumes 8x8 at "
                "2/3 of the layers; use a 32x32 input or auxiliary=False)")
        x = nn.relu(x)
        x = nn.avg_pool(x, (5, 5), strides=(3, 3), padding="VALID")
        x = nn.Conv(128, (1, 1), use_bias=False)(x)
        x = nn.relu(_norm(128, affine=True)(x))
        x = nn.Conv(768, (2, 2), padding="VALID", use_bias=False)(x)
        x = nn.relu(_norm(768, affine=True)(x))
        return nn.Dense(self.num_classes)(x.reshape(x.shape[0], -1))


def genotype_to_dot(genotype, cell: str = "normal") -> str:
    """Graphviz DOT source for one cell of a genotype — the reference's
    visualize.py (fedml_api/model/cv/darts/visualize.py) renders the same
    DAG via the graphviz binary; emitting portable DOT text keeps the
    utility dependency-free (pipe into `dot -Tpng` to render)."""
    g = as_genotype(genotype)
    gene, concat = g[cell], g[f"{cell}_concat"]
    steps = len(gene) // 2
    lines = [f'digraph {cell} {{', '  rankdir=LR;',
             '  node [shape=box, style=rounded];',
             '  "c_{k-2}"; "c_{k-1}";']

    def state_name(j: int) -> str:
        return ('"c_{k-2}"' if j == 0 else '"c_{k-1}"' if j == 1
                else f'"{j - 2}"')

    for i in range(steps):
        lines.append(f'  "{i}" [shape=circle];')
        for op, j in gene[2 * i: 2 * i + 2]:
            lines.append(f'  {state_name(j)} -> "{i}" [label="{op}"];')
    lines.append('  "c_{k}" [shape=box];')
    for c in concat:
        lines.append(f'  {state_name(c)} -> "c_{{k}}";')
    lines.append("}")
    return "\n".join(lines)


class AuxiliaryHeadImageNet(nn.Module):
    """ImageNet aux classifier, 14x14 input (model.py:87-108): ReLU,
    avg-pool 5x5/s2 (-> 5x5... 2x2 at 14x14? the reference assumes 14x14 ->
    2x2 via the torch pool arithmetic), 1x1 conv 128, norm, ReLU, 2x2 conv
    768, ReLU, linear. NOTE: the reference deliberately OMITS the second
    norm ('omitted in my earlier implementation due to a typo... for
    consistency with the paper', model.py:100-102) — reproduced here, and
    required for exact param parity."""

    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        x = nn.avg_pool(x, (5, 5), strides=(2, 2), padding="VALID")
        x = nn.Conv(128, (1, 1), use_bias=False)(x)
        x = nn.relu(_norm(128, affine=True)(x))
        x = nn.Conv(768, (2, 2), padding="VALID", use_bias=False)(x)
        x = nn.relu(x)  # no norm here (reference model.py:100-102)
        # deviation: the reference flattens (model.py:106) into a
        # Linear(768,·) — which cannot run at its own stated 14x14 input
        # (4x4x768 features remain); global-pool the residual extent so the
        # head matches the 768-feature classifier AND executes
        return nn.Dense(self.num_classes)(jnp.mean(x, axis=(1, 2)))


class NetworkImageNet(nn.Module):
    """Derived ImageNet network (model.py:161-216 NetworkImageNet): 3-conv
    double stem (each stride 2; cells start from 1/4 and 1/8 resolution
    with reduction_prev=True), ``layers`` DerivedCells, optional
    AuxiliaryHeadImageNet after cell 2*layers//3, global pool, classifier.

    Param parity with the torch construction: C=48, layers=14, 1000
    classes, DARTS_V2 -> 4,718,752 (5,979,528 with the auxiliary head) —
    pinned in tests/test_param_parity.py."""

    genotype: object = "DARTS_V2"
    num_classes: int = 1000
    layers: int = 14
    init_filters: int = 48
    auxiliary: bool = False
    drop_path_prob: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        g = as_genotype(self.genotype)
        C = self.init_filters
        # stem0: 3 -> C//2 (s2) -> C (s2); stem1: C -> C (s2)
        h = nn.Conv(C // 2, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False)(x)
        h = nn.relu(_norm(C // 2, affine=True)(h))
        h = nn.Conv(C, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False)(h)
        s0 = _norm(C, affine=True)(h)
        h = nn.Conv(C, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False)(nn.relu(s0))
        s1 = _norm(C, affine=True)(h)

        C_curr = C
        # -{0}: tiny-layer deviation from model.py (see NetworkCIFAR note)
        reduce_at = {self.layers // 3, 2 * self.layers // 3} - {0}
        reduction_prev = True  # stem1 already reduced (model.py:187)
        aux_in = None
        for i in range(self.layers):
            reduction = i in reduce_at
            if reduction:
                C_curr *= 2
            gene, concat = ((g["reduce"], g["reduce_concat"]) if reduction
                            else (g["normal"], g["normal_concat"]))
            cell = DerivedCell(gene=tuple(tuple(e) for e in gene),
                               concat=tuple(concat), filters=C_curr,
                               reduction=reduction,
                               reduction_prev=reduction_prev,
                               drop_path_prob=self.drop_path_prob)
            s0, s1 = s1, cell(s0, s1, train)
            reduction_prev = reduction
            if i == 2 * self.layers // 3:
                aux_in = s1
        logits_aux = None
        if self.auxiliary and aux_in is not None:
            logits_aux = AuxiliaryHeadImageNet(self.num_classes)(aux_in, train)
        y = jnp.mean(s1, axis=(1, 2))  # AvgPool2d(7) == global mean at 224
        logits = nn.Dense(self.num_classes)(y)
        if train and self.auxiliary:
            return logits, logits_aux  # tuple only when the head exists
        return logits


class NetworkCIFAR(nn.Module):
    """Derived (fixed-genotype) CIFAR network — the reference's train-stage
    model (model.py:111-159 NetworkCIFAR): stem, ``layers`` DerivedCells
    with reductions at layers//3 and 2*layers//3 (channels double there),
    optional auxiliary head after cell 2*layers//3 (training only),
    global pool, classifier. Returns bare logits at eval AND in train mode
    without the head (so plain classification_task / create_model work);
    the (logits, logits_aux) tuple only when ``auxiliary`` during training.

    Param parity with the torch construction: C=16, layers=8, 10 classes,
    FedNAS_V1 -> 337,626 params (773,092 with the auxiliary head) —
    pinned in tests/test_param_parity.py. Norms are affine GroupNorm for
    the same reason as the supernet (vmapped-over-clients training)."""

    genotype: object = "FedNAS_V1"
    num_classes: int = 10
    layers: int = 8
    init_filters: int = 16
    stem_multiplier: int = 3
    auxiliary: bool = False
    drop_path_prob: float = 0.5  # reference fixed value (model.py:118)

    @nn.compact
    def __call__(self, x, train: bool = False):
        g = as_genotype(self.genotype)
        C_curr = self.stem_multiplier * self.init_filters
        s = nn.Conv(C_curr, (3, 3), padding="SAME", use_bias=False)(x)
        s0 = s1 = _norm(C_curr, affine=True)(s)

        C_curr = self.init_filters
        # reference model.py:130 places a reduction at cell 0 when
        # layers < 3; the -{0} exclusion is a deliberate deviation (ADVICE
        # r5 item 3) shared with the supernet: a reduction at layer 0 would
        # leave a <3-layer net with no normal cell. Real configs
        # (layers >= 6) are unaffected — layers//3 >= 2.
        reduce_at = {self.layers // 3, 2 * self.layers // 3} - {0}
        reduction_prev = False
        aux_in = None
        for i in range(self.layers):
            reduction = i in reduce_at
            if reduction:
                C_curr *= 2
            gene, concat = ((g["reduce"], g["reduce_concat"]) if reduction
                            else (g["normal"], g["normal_concat"]))
            cell = DerivedCell(gene=tuple(tuple(e) for e in gene),
                               concat=tuple(concat), filters=C_curr,
                               reduction=reduction,
                               reduction_prev=reduction_prev,
                               drop_path_prob=self.drop_path_prob)
            s0, s1 = s1, cell(s0, s1, train)
            reduction_prev = reduction
            if i == 2 * self.layers // 3:
                aux_in = s1
        logits_aux = None
        if self.auxiliary and aux_in is not None:
            # built unconditionally so init(train=False) creates the head's
            # params; only RETURNED during training (model.py:153-155)
            logits_aux = AuxiliaryHeadCIFAR(self.num_classes)(aux_in, train)
        y = jnp.mean(s1, axis=(1, 2))
        logits = nn.Dense(self.num_classes)(y)
        if train and self.auxiliary:
            # tuple ONLY when the head exists: without it the net is a
            # plain classifier usable by classification_task / create_model
            return logits, logits_aux
        return logits
