"""DARTS search space for FedNAS (reference: fedml_api/model/cv/darts/
{model_search.py, operations.py, genotypes.py, architect.py}, ~1,700 LoC).

A differentiable-architecture supernet: each edge of a cell computes a
softmax(alpha)-weighted mixture of candidate ops. FedNAS federates the
bilevel search: clients optimize (weights w, alphas a) locally, the server
averages both (FedNASAggregator.__aggregate_weight/:71, __aggregate_alpha/:95).

TPU re-design: the reference's MixedOp is a python loop over op modules; here
all candidate ops for an edge evaluate as a batched branch stack and the
alpha-softmax contraction is one einsum — XLA fuses the mixture, and the
whole supernet vmaps over clients like any other model. Alphas live in a
separate 'arch' param collection so the engine can average them with the
weights (parity) or expose them separately (FedNAS genotype extraction).
"""

from __future__ import annotations


import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

PRIMITIVES = (
    "none",
    "skip_connect",
    "max_pool_3x3",
    "avg_pool_3x3",
    "sep_conv_3x3",
    "dil_conv_3x3",
)


class _SepConv(nn.Module):
    filters: int
    dilation: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = x.shape[-1]
        x = nn.Conv(c, (3, 3), padding="SAME", feature_group_count=c,
                    kernel_dilation=(self.dilation, self.dilation),
                    use_bias=False)(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        x = nn.GroupNorm(num_groups=min(8, self.filters))(x)
        return nn.relu(x)


class MixedOp(nn.Module):
    """All candidate ops evaluated, alpha-softmax-mixed in one contraction."""

    filters: int

    @nn.compact
    def __call__(self, x, weights, train: bool = False):
        # weights: [num_ops] softmaxed alphas for this edge
        outs = []
        for prim in PRIMITIVES:
            if prim == "none":
                outs.append(jnp.zeros_like(x))
            elif prim == "skip_connect":
                outs.append(x)
            elif prim == "max_pool_3x3":
                outs.append(nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME"))
            elif prim == "avg_pool_3x3":
                outs.append(nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME"))
            elif prim == "sep_conv_3x3":
                outs.append(_SepConv(self.filters)(x, train))
            elif prim == "dil_conv_3x3":
                outs.append(_SepConv(self.filters, dilation=2)(x, train))
        stacked = jnp.stack(outs)  # [O, B, H, W, C]
        return jnp.tensordot(weights, stacked, axes=([0], [0]))


class Cell(nn.Module):
    """DARTS cell: ``steps`` intermediate nodes, each summing mixed ops over
    all previous nodes; output = concat of intermediate nodes."""

    steps: int = 4
    filters: int = 16

    @nn.compact
    def __call__(self, s0, s1, alphas, train: bool = False):
        # alphas: [num_edges, num_ops] (already softmaxed rows)
        states = [s0, s1]
        offset = 0
        for i in range(self.steps):
            acc = 0.0
            for j, h in enumerate(states):
                acc = acc + MixedOp(self.filters)(h, alphas[offset + j], train)
            offset += len(states)
            states.append(acc)
        return jnp.concatenate(states[-self.steps:], axis=-1)


def num_edges(steps: int = 4) -> int:
    return sum(2 + i for i in range(steps))


class DARTSNetwork(nn.Module):
    """Supernet: stem -> ``layers`` cells -> classifier. Alphas are a single
    'arch'-collection param shared across cells (normal cells only — the
    reference's reduced search space for FedNAS)."""

    num_classes: int = 10
    layers: int = 4
    steps: int = 4
    init_filters: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        C = self.init_filters
        E = num_edges(self.steps)
        alphas = self.param(
            "alphas_normal",
            lambda k: 1e-3 * jax.random.normal(k, (E, len(PRIMITIVES))),
        )
        aw = jax.nn.softmax(alphas, axis=-1)
        s = nn.Conv(C, (3, 3), padding="SAME", use_bias=False)(x)
        s = nn.GroupNorm(num_groups=min(8, C))(s)
        s0 = s1 = s
        for l in range(self.layers):
            s0, s1 = s1, Cell(self.steps, C)(s0, s1, aw, train)
            # project concat back to C channels to keep the supernet slim
            s1 = nn.Conv(C, (1, 1), use_bias=False)(s1)
        y = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


def extract_genotype(params, steps: int = 4) -> list[list[tuple[str, int]]]:
    """Discretize alphas -> per-node top-2 (op, predecessor) pairs — the
    reference's genotype recording (FedNASAggregator.record_model_global_
    architecture, FedNASAggregator.py:173)."""
    alphas = np.asarray(params["alphas_normal"])
    probs = np.exp(alphas) / np.exp(alphas).sum(-1, keepdims=True)
    geno, offset = [], 0
    for i in range(steps):
        n_in = 2 + i
        edges = probs[offset : offset + n_in]
        # best non-'none' op per edge, then top-2 edges by that op's prob
        best_op = edges[:, 1:].argmax(-1) + 1
        best_p = edges[np.arange(n_in), best_op]
        top2 = np.argsort(-best_p)[:2]
        geno.append([(PRIMITIVES[best_op[j]], int(j)) for j in top2])
        offset += n_in
    return geno
