"""FedAvg-paper CNNs (reference: fedml_api/model/cv/cnn.py:26-163).

CNN_OriginalFedAvg: conv5x5(32) -> maxpool -> conv5x5(64) -> maxpool ->
dense 512 -> softmax head; 1,663,370 params for femnist (62 classes).
CNN_DropOut: the TFF/LEAF variant with 3x3 convs and dropout.

Input layout is NHWC [bs, 28, 28, 1] (TPU-native; torch reference is NCHW).
"""

from __future__ import annotations

import flax.linen as nn


class CNNOriginalFedAvg(nn.Module):
    """McMahan et al. CNN (cnn.py:26-97). only_digits=False -> 62 classes."""

    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(10 if self.only_digits else 62)(x)


class CNNDropOut(nn.Module):
    """TFF-style dropout CNN (cnn.py:100-163)."""

    only_digits: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else 62)(x)
