"""FedAvg-paper CNNs (reference: fedml_api/model/cv/cnn.py:26-163).

CNN_OriginalFedAvg: conv5x5(32) -> maxpool -> conv5x5(64) -> maxpool ->
dense 512 -> softmax head; 1,663,370 params with only_digits=True,
1,690,046 for femnist (62 classes) — both exactly the reference counts
(pinned in tests/test_param_parity.py).
CNN_DropOut: the TFF/LEAF variant with 3x3 convs and dropout.

Input layout is NHWC [bs, 28, 28, 1] (TPU-native; torch reference is NCHW).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class CNNOriginalFedAvg(nn.Module):
    """McMahan et al. CNN (cnn.py:26-97). only_digits=False -> 62 classes.

    ``dtype=jnp.bfloat16`` runs the convs/matmuls in bf16 on the MXU
    (PARAMS stay float32 — flax casts per-op and the head below returns
    f32 logits), the standard TPU mixed-precision recipe. Default float32
    keeps exact reference-comparable numerics."""

    only_digits: bool = False
    dtype: Any = None  # activation/compute dtype; None = float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        dt = self.dtype
        if dt is not None:
            x = x.astype(dt)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=dt)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=dt)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=dt)(x))
        # head in f32: loss/softmax numerics stay full-precision
        return nn.Dense(10 if self.only_digits else 62)(x.astype(jnp.float32))


class CNNDropOut(nn.Module):
    """TFF-style dropout CNN (cnn.py:100-163)."""

    only_digits: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else 62)(x)
