"""CIFAR ResNets — resnet56/resnet110 (reference: fedml_api/model/cv/resnet.py:1-268).

The reference uses the classic 3-stage basic-block CIFAR ResNet (He et al.)
with BatchNorm. TPU notes: NHWC layout, bfloat16-friendly conv widths
(16/32/64 channels), BatchNorm running stats live in the 'batch_stats'
collection and are federated-averaged with the params (the reference
averages the full state_dict including BN buffers, FedAVGAggregator.py:72-80).
``norm='group'`` swaps in GroupNorm — BN-free variant for non-IID robustness.
``norm='none'`` is the normalization-FREE ResNet (reference
fedml_api/model/cv/resnet_wo_bn.py, used in robust-FL experiments where BN
buffers poison the average): Fixup-style blocks — zero-init on each residual
branch's last conv plus learned scalar scale/bias — keep it trainable
without any norm layer, and aggregation touches only true parameters.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    norm: Callable = nn.BatchNorm
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        y = self.norm(use_running_average=not train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = self.norm(use_running_average=not train)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm(use_running_average=not train)(residual)
        return nn.relu(y + residual)


class ResNetCIFAR(nn.Module):
    """depth = 6n+2 (56 -> n=9, 110 -> n=18); 3 stages of n basic blocks.

    ``dtype=jnp.bfloat16`` runs convs in bf16 on the MXU with f32 params
    and f32 norm statistics (flax norm layers keep reductions in f32) —
    the standard TPU mixed-precision recipe, halving activation HBM for
    the cross-silo vmapped-10-client program."""

    depth: int = 56
    num_classes: int = 10
    norm_type: str = "batch"  # 'batch' | 'group'
    dtype: Any = None  # activation/compute dtype; None = float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        assert (self.depth - 2) % 6 == 0, "depth must be 6n+2"
        n = (self.depth - 2) // 6
        dt = self.dtype
        if dt is not None:
            x = x.astype(dt)
        if self.norm_type == "batch":
            norm = partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5, dtype=dt)
        else:
            norm = partial(_GN, num_groups=8, dtype=dt)

        y = nn.Conv(16, (3, 3), padding="SAME",
                    use_bias=(self.norm_type == "none"), dtype=dt)(x)
        if self.norm_type == "batch":
            y = norm(use_running_average=not train)(y)
        elif self.norm_type == "group":
            y = norm()(y)
        y = nn.relu(y)
        for stage, (filters, stride) in enumerate([(16, 1), (32, 2), (64, 2)]):
            for i in range(n):
                s = (stride, stride) if i == 0 else (1, 1)
                if self.norm_type == "batch":
                    y = BasicBlock(filters, s, norm, dtype=dt)(y, train)
                elif self.norm_type == "group":
                    y = _GNBasicBlock(filters, s, dtype=dt)(y, train)
                else:
                    y = _FixupBasicBlock(filters, s, dtype=dt)(y, train)
        # upcast BEFORE the pool: the spatial mean must accumulate in f32,
        # and the pooled output is tiny so this costs no HBM
        y = jnp.mean(y.astype(jnp.float32), axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


class _GN(nn.Module):
    """GroupNorm shim accepting (and ignoring) use_running_average."""

    num_groups: int = 8
    dtype: Any = None

    @nn.compact
    def __call__(self, x, use_running_average: bool = True):
        return nn.GroupNorm(num_groups=min(self.num_groups, x.shape[-1]),
                            dtype=self.dtype)(x)


class _FixupBasicBlock(nn.Module):
    """Norm-free basic block (resnet_wo_bn parity): residual branch is
    conv-relu-conv with the second conv zero-initialized and a learned
    scalar scale + bias, so the block starts as identity and training stays
    stable without normalization."""

    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        # Fixup scalars are stored f32 (param_dtype default) but applied in
        # the compute dtype so bf16 activations are not promoted back to f32
        cd = self.dtype or x.dtype
        residual = x
        b1 = self.param("bias1", nn.initializers.zeros, (1,))
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=True, dtype=self.dtype)(x + b1.astype(cd))
        y = nn.relu(y)
        b2 = self.param("bias2", nn.initializers.zeros, (1,))
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=True,
                    kernel_init=nn.initializers.zeros,
                    dtype=self.dtype)(y + b2.astype(cd))
        scale = self.param("scale", nn.initializers.ones, (1,))
        y = y * scale.astype(cd)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=True, dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class _GNBasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        gn = lambda c: nn.GroupNorm(num_groups=min(8, c), dtype=self.dtype)
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        y = gn(self.filters)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = gn(self.filters)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = gn(self.filters)(residual)
        return nn.relu(y + residual)
