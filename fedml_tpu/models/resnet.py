"""CIFAR ResNets — resnet56/resnet110 (reference: fedml_api/model/cv/resnet.py:1-268).

The reference uses the classic 3-stage basic-block CIFAR ResNet (He et al.)
with BatchNorm. TPU notes: NHWC layout, bfloat16-friendly conv widths
(16/32/64 channels), BatchNorm running stats live in the 'batch_stats'
collection and are federated-averaged with the params (the reference
averages the full state_dict including BN buffers, FedAVGAggregator.py:72-80).
``norm='group'`` swaps in GroupNorm — BN-free variant for non-IID robustness.
``norm='none'`` is the normalization-FREE ResNet (reference
fedml_api/model/cv/resnet_wo_bn.py, used in robust-FL experiments where BN
buffers poison the average): Fixup-style blocks — zero-init on each residual
branch's last conv plus learned scalar scale/bias — keep it trainable
without any norm layer, and aggregation touches only true parameters.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    norm: Callable = nn.BatchNorm

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False)(x)
        y = self.norm(use_running_average=not train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        y = self.norm(use_running_average=not train)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False)(residual)
            residual = self.norm(use_running_average=not train)(residual)
        return nn.relu(y + residual)


class ResNetCIFAR(nn.Module):
    """depth = 6n+2 (56 -> n=9, 110 -> n=18); 3 stages of n basic blocks."""

    depth: int = 56
    num_classes: int = 10
    norm_type: str = "batch"  # 'batch' | 'group'

    @nn.compact
    def __call__(self, x, train: bool = False):
        assert (self.depth - 2) % 6 == 0, "depth must be 6n+2"
        n = (self.depth - 2) // 6
        if self.norm_type == "batch":
            norm = partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5)
        else:
            norm = partial(_GN, num_groups=8)

        y = nn.Conv(16, (3, 3), padding="SAME",
                    use_bias=(self.norm_type == "none"))(x)
        if self.norm_type == "batch":
            y = norm(use_running_average=not train)(y)
        elif self.norm_type == "group":
            y = norm()(y)
        y = nn.relu(y)
        for stage, (filters, stride) in enumerate([(16, 1), (32, 2), (64, 2)]):
            for i in range(n):
                s = (stride, stride) if i == 0 else (1, 1)
                if self.norm_type == "batch":
                    y = BasicBlock(filters, s, norm)(y, train)
                elif self.norm_type == "group":
                    y = _GNBasicBlock(filters, s)(y, train)
                else:
                    y = _FixupBasicBlock(filters, s)(y, train)
        y = jnp.mean(y, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes)(y)


class _GN(nn.Module):
    """GroupNorm shim accepting (and ignoring) use_running_average."""

    num_groups: int = 8

    @nn.compact
    def __call__(self, x, use_running_average: bool = True):
        return nn.GroupNorm(num_groups=min(self.num_groups, x.shape[-1]))(x)


class _FixupBasicBlock(nn.Module):
    """Norm-free basic block (resnet_wo_bn parity): residual branch is
    conv-relu-conv with the second conv zero-initialized and a learned
    scalar scale + bias, so the block starts as identity and training stays
    stable without normalization."""

    filters: int
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        b1 = self.param("bias1", nn.initializers.zeros, (1,))
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=True)(x + b1)
        y = nn.relu(y)
        b2 = self.param("bias2", nn.initializers.zeros, (1,))
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=True,
                    kernel_init=nn.initializers.zeros)(y + b2)
        scale = self.param("scale", nn.initializers.ones, (1,))
        y = y * scale
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=True)(residual)
        return nn.relu(y + residual)


class _GNBasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool = False):
        gn = lambda c: nn.GroupNorm(num_groups=min(8, c))
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False)(x)
        y = gn(self.filters)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        y = gn(self.filters)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False)(residual)
            residual = gn(self.filters)(residual)
        return nn.relu(y + residual)
