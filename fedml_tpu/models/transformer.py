"""Decoder-only transformer with pluggable sequence-parallel attention.

Not in the reference (its NLP models are tiny LSTMs, model/nlp/rnn.py) — this
is the long-context capability the TPU framework treats as first-class: with
``seq_mesh`` set, self-attention runs as ring attention over the 'seq' axis
(fedml_tpu.parallel.ring_attention) so sequence length scales with the mesh.
Usable as an FL model through the standard sequence_task wrapper.
"""

from __future__ import annotations


import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from fedml_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ring_attention_flash,
    ulysses_attention,
)


class SelfAttention(nn.Module):
    num_heads: int
    head_dim: int
    causal: bool = True
    seq_axis: str | None = None  # set to shard attention over a mesh axis
    use_flash: bool = False      # Pallas blockwise kernel (fedml_tpu.ops)
    seq_impl: str = "ring"       # 'ring' | 'ulysses' (all-to-all head scatter)

    @nn.compact
    def __call__(self, x, train: bool = False):
        C = x.shape[-1]
        H, D = self.num_heads, self.head_dim
        # Head-aligned projections, Megatron-style: DenseGeneral keeps the
        # head dim a REAL kernel dim ([C, H, D], not a flattened [C, 3HD]
        # column block), so tensor parallelism shards heads whole
        # (P(None,'model',None), parallel/tensor_parallel.py) and the
        # attention core runs fully sharded — the only TP collective is the
        # psum o_proj's row-parallel contraction inserts. The explicit
        # names are the TP spec-matching contract (rename-robust: specs key
        # on these leaf names, not flax auto-numbering).
        q = nn.DenseGeneral((H, D), use_bias=False, name="q_proj")(x)
        k = nn.DenseGeneral((H, D), use_bias=False, name="k_proj")(x)
        v = nn.DenseGeneral((H, D), use_bias=False, name="v_proj")(x)
        if self.seq_axis is not None:
            if self.seq_impl == "ulysses":
                o = ulysses_attention(q, k, v, self.seq_axis,
                                      causal=self.causal,
                                      use_flash=self.use_flash)
            elif self.seq_impl == "ring":
                # flash is vma-clean under strict shard_map: Mosaic kernels
                # carry vma-typed out_shapes on TPU, and off-TPU the op
                # dispatches to its jnp twin (ops/flash_attention._mode)
                o = (ring_attention_flash(q, k, v, self.seq_axis,
                                          causal=self.causal)
                     if self.use_flash else
                     ring_attention(q, k, v, self.seq_axis, causal=self.causal))
            else:
                raise ValueError(
                    f"unknown seq_impl {self.seq_impl!r} (ring | ulysses)")
        elif self.use_flash:
            from fedml_tpu.ops import flash_attention

            o = flash_attention(q, k, v, self.causal)
        else:
            o = full_attention(q, k, v, causal=self.causal)
        # row-parallel over heads: kernel [H, D, C]; contracting the sharded
        # H dim is the single Megatron all-reduce per attention layer
        return nn.DenseGeneral(C, axis=(-2, -1), use_bias=False,
                               name="o_proj")(o)


class MoEMLP(nn.Module):
    """Switch-style top-1 mixture-of-experts MLP, written as expert-stacked
    einsums: all experts are materialized as one [E, ...] kernel and the
    token->expert dispatch is a one-hot combine. That formulation is what
    makes EXPERT PARALLELISM a pure layout choice — shard the leading E dim
    over a mesh axis (parallel/tensor_parallel.py's *_experts rule) and
    GSPMD turns the combine into a psum over the expert shards, each device
    computing only its experts. Top-1 gate scales its expert's output by
    the gate value (Switch Transformer convention); no capacity dropping —
    dense dispatch keeps the math exactly equal to an unsharded run (the
    EP ≡ single-device oracle in test_tensor_parallel.py)."""

    num_experts: int
    mlp_ratio: int = 4

    @nn.compact
    def __call__(self, x):
        B, T, C = x.shape
        E, H = self.num_experts, self.mlp_ratio * C
        w_gate = self.param("w_gate", nn.initializers.normal(0.02), (C, E))
        w_in = self.param("w_in_experts",
                          nn.initializers.lecun_normal(), (E, C, H))
        w_out = self.param("w_out_experts",
                           nn.initializers.lecun_normal(), (E, H, C))
        gates = jax.nn.softmax(x @ w_gate)                  # [B,T,E]
        top1 = jnp.argmax(gates, axis=-1)
        combine = jax.nn.one_hot(top1, E, dtype=x.dtype) * gates
        h = nn.gelu(jnp.einsum("btc,ech->bteh", x, w_in))
        y = jnp.einsum("bteh,ehc->btec", h, w_out)
        return jnp.einsum("btec,bte->btc", y, combine)


class Block(nn.Module):
    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    causal: bool = True
    seq_axis: str | None = None
    use_flash: bool = False
    seq_impl: str = "ring"
    moe_experts: int = 0  # >0: replace the MLP with a switch MoE

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.LayerNorm()(x)
        x = x + SelfAttention(self.num_heads, self.head_dim, self.causal,
                              self.seq_axis, self.use_flash,
                              self.seq_impl)(h, train)
        h = nn.LayerNorm()(x)
        C = x.shape[-1]
        if self.moe_experts > 0:
            return x + MoEMLP(self.moe_experts, self.mlp_ratio)(h)
        # explicit names = the TP spec contract: mlp_in column-parallel,
        # mlp_out row-parallel (parallel/tensor_parallel.py)
        m = nn.Dense(self.mlp_ratio * C, name="mlp_in")(h)
        m = nn.gelu(m)
        x = x + nn.Dense(C, name="mlp_out")(m)
        return x


class PipelineLM(nn.Module):
    """Decoder-only LM with the block stack run as a GPipe PIPELINE over a
    'stage' mesh axis (parallel/pipeline.py): depth/S consecutive
    transformer Blocks per stage (depth must be a multiple of the stage
    count S), stacked into a single [depth, ...] param tree; microbatches
    flow stage-to-stage via ppermute and jax.grad yields the reverse
    schedule. With ``mesh=None`` the same stacked params are applied
    sequentially (lax.scan over blocks) — the equivalence oracle for the
    pipeline (test_pipeline_parallel.py). Embedding/head are replicated
    (cheap, and keeps the pipelined region homogeneous)."""

    vocab_size: int = 256
    dim: int = 128
    depth: int = 4  # total Blocks; must be a multiple of the stage count
    num_heads: int = 4
    max_len: int = 2048
    causal: bool = True
    mesh: Mesh | None = None
    stage_axis: str = "stage"
    num_microbatches: int = 2
    data_axis: str | None = None  # DP x PP: batch stays sharded over this

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        B, T = tokens.shape
        x = nn.Embed(self.vocab_size, self.dim)(tokens)
        pos = self.param("pos_emb",
                         nn.initializers.normal(0.02), (self.max_len, self.dim))
        x = x + pos[:T][None]

        blk = Block(self.num_heads, self.dim // self.num_heads,
                    causal=self.causal)

        def init_stages(rng):
            dummy = jnp.zeros((1, 1, self.dim), jnp.float32)
            return jax.vmap(
                lambda r: blk.init(r, dummy)["params"]
            )(jax.random.split(rng, self.depth))

        stages = self.param("stages", init_stages)

        def stage_fn(p, h):
            return blk.apply({"params": p}, h)

        if self.mesh is not None:
            from fedml_tpu.parallel.pipeline import (
                gpipe,
                microbatch,
                unmicrobatch,
            )

            S = int(self.mesh.shape[self.stage_axis])
            if self.depth % S:
                raise ValueError(
                    f"depth={self.depth} must be a multiple of the "
                    f"'{self.stage_axis}' mesh size {S} (equal Blocks per "
                    "pipeline stage)")
            k = self.depth // S
            # stage s runs blocks [s*k, (s+1)*k): group the stacked blocks
            # [depth, ...] into [S, k, ...] and scan the k sub-blocks
            # inside each stage — sequential order is preserved
            staged = jax.tree.map(
                lambda t: t.reshape((S, k) + t.shape[1:]), stages)

            def staged_fn(p, h):
                return jax.lax.scan(
                    lambda hh, pp: (stage_fn(pp, hh), None), h, p)[0]

            y = unmicrobatch(gpipe(staged_fn, staged,
                                   microbatch(x, self.num_microbatches),
                                   self.stage_axis, self.mesh,
                                   data_axis=self.data_axis))
        else:
            y, _ = jax.lax.scan(lambda h, p: (stage_fn(p, h), None), x, stages)
        y = nn.LayerNorm()(y)
        return nn.Dense(self.vocab_size, name="lm_head")(y)


class TransformerLM(nn.Module):
    vocab_size: int = 256
    dim: int = 128
    depth: int = 2
    num_heads: int = 4
    max_len: int = 2048
    causal: bool = True
    seq_axis: str | None = None
    use_flash: bool = False
    seq_impl: str = "ring"
    moe_experts: int = 0  # >0: every block's MLP is a switch MoE

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        B, T = tokens.shape
        x = nn.Embed(self.vocab_size, self.dim)(tokens)
        pos = self.param("pos_emb",
                         nn.initializers.normal(0.02), (self.max_len, self.dim))
        if self.seq_axis is not None:
            # inside shard_map T is the LOCAL block; offset into the global
            # position table by this shard's ring position
            offset = jax.lax.axis_index(self.seq_axis) * T
            x = x + jax.lax.dynamic_slice_in_dim(pos, offset, T)[None]
        else:
            x = x + pos[:T][None]
        for _ in range(self.depth):
            x = Block(self.num_heads, self.dim // self.num_heads,
                      causal=self.causal, seq_axis=self.seq_axis,
                      use_flash=self.use_flash, seq_impl=self.seq_impl,
                      moe_experts=self.moe_experts)(x, train)
        x = nn.LayerNorm()(x)
        return nn.Dense(self.vocab_size, name="lm_head")(x)
