"""Vertical-FL party towers (reference: fedml_api/model/finance/
vfl_models_standalone.py:1-72 — small dense feature extractors + a linear
classifier whose outputs the guest sums)."""

from __future__ import annotations

import flax.linen as nn


class DenseTower(nn.Module):
    """Feature-slice -> per-class logit contribution."""

    hidden: int = 32
    num_classes: int = 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.num_classes)(x)


class LinearTower(nn.Module):
    """Logistic-regression party model (the reference's LR guest/host)."""

    num_classes: int = 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dense(self.num_classes)(x.reshape((x.shape[0], -1)))
