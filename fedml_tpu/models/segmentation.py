"""Semantic-segmentation models for FedSeg (reference:
fedml_api/distributed/fedseg/ — the reference trains DeepLabV3+-style
encoder/decoder torch models; see FedSegAPI.py:19-38 where the torch model is
injected into MyModelTrainer).

TPU-first design notes:
- NHWC throughout; every conv static-shaped so XLA tiles onto the MXU.
- Atrous (dilated) convs via ``kernel_dilation`` — no im2col tricks needed.
- Upsampling via ``jax.image.resize`` (bilinear), which XLA lowers to
  gather-free convolutions on TPU.
- GroupNorm instead of BatchNorm by default: FL clients have small local
  batches and BN running stats are a known source of non-IID drift (the
  reference ships SynchronizedBatchNorm workarounds, model/cv/batchnorm_utils.py).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def _gn(x, groups: int = 8):
    return nn.GroupNorm(num_groups=min(groups, x.shape[-1]))(x)


class ConvBlock(nn.Module):
    filters: int
    kernel: tuple[int, int] = (3, 3)
    strides: tuple[int, int] = (1, 1)
    dilation: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.filters, self.kernel, self.strides,
                    kernel_dilation=self.dilation, padding="SAME",
                    use_bias=False)(x)
        x = _gn(x)
        return nn.relu(x)


class ResStage(nn.Module):
    """Two-block residual stage with optional stride/dilation."""

    filters: int
    strides: tuple[int, int] = (1, 1)
    dilation: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        y = ConvBlock(self.filters, strides=self.strides, dilation=self.dilation)(x)
        y = nn.Conv(self.filters, (3, 3), kernel_dilation=self.dilation,
                    padding="SAME", use_bias=False)(y)
        y = _gn(y)
        if x.shape != y.shape:
            x = nn.Conv(self.filters, (1, 1), self.strides, use_bias=False)(x)
            x = _gn(x)
        return nn.relu(x + y)


class ASPP(nn.Module):
    """Atrous spatial pyramid pooling: parallel dilated branches + image pool."""

    filters: int = 128
    rates: Sequence[int] = (1, 6, 12, 18)

    @nn.compact
    def __call__(self, x):
        branches = []
        for r in self.rates:
            k = (1, 1) if r == 1 else (3, 3)
            branches.append(ConvBlock(self.filters, kernel=k, dilation=(r, r))(x))
        # image-level pooling branch
        pooled = jnp.mean(x, axis=(1, 2), keepdims=True)
        pooled = ConvBlock(self.filters, kernel=(1, 1))(pooled)
        pooled = jnp.broadcast_to(pooled, x.shape[:3] + (self.filters,))
        branches.append(pooled)
        y = jnp.concatenate(branches, axis=-1)
        return ConvBlock(self.filters, kernel=(1, 1))(y)


class DeepLabLite(nn.Module):
    """DeepLabV3+-style encoder/decoder, compact enough for federated silos.

    Encoder: 4 residual stages (output stride 16, last stage dilated);
    ASPP head; decoder fuses the stride-4 low-level features; bilinear
    upsample back to input resolution. Output: [bs, H, W, num_classes].
    """

    num_classes: int = 21
    width: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train  # GroupNorm everywhere — no train-time mutable state
        h, w = x.shape[1], x.shape[2]
        y = ConvBlock(self.width, strides=(2, 2))(x)           # /2
        y = ResStage(self.width * 2, strides=(2, 2))(y)        # /4
        low = y
        y = ResStage(self.width * 4, strides=(2, 2))(y)        # /8
        y = ResStage(self.width * 8, strides=(2, 2))(y)        # /16
        y = ResStage(self.width * 8, dilation=(2, 2))(y)       # /16, dilated
        y = ASPP(self.width * 4)(y)

        # decoder: upsample to /4, fuse low-level features
        y = jax.image.resize(y, (y.shape[0], low.shape[1], low.shape[2],
                                 y.shape[-1]), "bilinear")
        low = ConvBlock(self.width, kernel=(1, 1))(low)
        y = jnp.concatenate([y, low], axis=-1)
        y = ConvBlock(self.width * 4)(y)
        y = nn.Conv(self.num_classes, (1, 1))(y)
        return jax.image.resize(y, (y.shape[0], h, w, self.num_classes),
                                "bilinear")


class UNetLite(nn.Module):
    """Small U-Net — the lighter FedSeg option for low-resource silos."""

    num_classes: int = 21
    width: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        w = self.width
        e1 = ConvBlock(w)(ConvBlock(w)(x))
        e2 = ConvBlock(w * 2)(nn.max_pool(e1, (2, 2), (2, 2)))
        e3 = ConvBlock(w * 4)(nn.max_pool(e2, (2, 2), (2, 2)))
        b = ConvBlock(w * 8)(nn.max_pool(e3, (2, 2), (2, 2)))

        def up(y, skip, f):
            y = jax.image.resize(
                y, (y.shape[0], skip.shape[1], skip.shape[2], y.shape[-1]),
                "bilinear")
            y = jnp.concatenate([y, skip], axis=-1)
            return ConvBlock(f)(y)

        d3 = up(b, e3, w * 4)
        d2 = up(d3, e2, w * 2)
        d1 = up(d2, e1, w)
        return nn.Conv(self.num_classes, (1, 1))(d1)
