"""ResNet-18 with GroupNorm — the fed_cifar100 model.

Reference: fedml_api/model/cv/resnet_gn.py:1-235 — ImageNet-style ResNet-18
with GroupNorm replacing BatchNorm (per the Adaptive Federated Optimization
paper: BN's running stats are ill-defined under client drift, GN is stateless).
TPU: NHWC, no mutable collections at all (pure params pytree -> cheaper
aggregation: no 'extra' to average).

Parameter accounting: with small_input=False this is EXACTLY torchvision's
resnet18 count (11,689,512 @ 1000 classes; pinned in
tests/test_param_parity.py) using the GN paper's per-CHANNEL affine. The
reference's custom GroupNorm2d (group_normalization.py) carries per-GROUP
affine instead — 9,300 fewer params across the net — a deviation from
standard GroupNorm that we deliberately do not copy.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def _gn(c: int):
    return nn.GroupNorm(num_groups=min(32, c))


class GNBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME",
                    use_bias=False)(x)
        y = _gn(self.filters)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        y = _gn(self.filters)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False)(residual)
            residual = _gn(self.filters)(residual)
        return nn.relu(y + residual)


class ResNet18GN(nn.Module):
    num_classes: int = 100
    # CIFAR-style stem (3x3, no maxpool) since fed_cifar100 is 24x24 crops
    small_input: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.small_input:
            y = nn.Conv(64, (3, 3), padding="SAME", use_bias=False)(x)
        else:
            y = nn.Conv(64, (7, 7), (2, 2), padding="SAME", use_bias=False)(x)
        y = _gn(64)(y)
        y = nn.relu(y)
        if not self.small_input:
            y = nn.max_pool(y, (3, 3), strides=(2, 2), padding="SAME")
        for filters, stride in [(64, 1), (64, 1), (128, 2), (128, 1),
                                (256, 2), (256, 1), (512, 2), (512, 1)]:
            y = GNBlock(filters, (stride, stride))(y, train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)
