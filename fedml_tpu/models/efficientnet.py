"""EfficientNet-B0..B7 (reference: fedml_api/model/cv/efficientnet.py +
efficientnet_utils.py, 988 LoC).

MBConv blocks with SE, swish activation, compound width/depth scaling.
TPU: NHWC; stochastic depth as dropout on the residual branch.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax.numpy as jnp

# (width_mult, depth_mult, resolution, dropout)
_PARAMS = {
    "b0": (1.0, 1.0, 224, 0.2), "b1": (1.0, 1.1, 240, 0.2),
    "b2": (1.1, 1.2, 260, 0.3), "b3": (1.2, 1.4, 300, 0.3),
    "b4": (1.4, 1.8, 380, 0.4), "b5": (1.6, 2.2, 456, 0.4),
    "b6": (1.8, 2.6, 528, 0.5), "b7": (2.0, 3.1, 600, 0.5),
}

# base blocks: (expand, filters, repeats, kernel, stride)
_BLOCKS = [
    (1, 16, 1, 3, 1), (6, 24, 2, 3, 2), (6, 40, 2, 5, 2), (6, 80, 3, 3, 2),
    (6, 112, 3, 5, 1), (6, 192, 4, 5, 2), (6, 320, 1, 3, 1),
]


def _round_filters(f, mult):
    f *= mult
    new = max(8, int(f + 4) // 8 * 8)
    if new < 0.9 * f:
        new += 8
    return int(new)


def _round_repeats(r, mult):
    return int(math.ceil(r * mult))


class _MBConv(nn.Module):
    expand: int
    filters: int
    kernel: int
    strides: int
    drop_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        inp = x
        c_in = x.shape[-1]
        c_mid = c_in * self.expand
        if self.expand != 1:
            x = nn.Conv(c_mid, (1, 1), use_bias=False)(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
            x = nn.swish(x)
        x = nn.Conv(c_mid, (self.kernel, self.kernel),
                    (self.strides, self.strides), padding="SAME",
                    feature_group_count=c_mid, use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.swish(x)
        # squeeze-excite at ratio 0.25 of input channels
        s = jnp.mean(x, axis=(1, 2))
        s = nn.swish(nn.Dense(max(1, c_in // 4))(s))
        s = nn.sigmoid(nn.Dense(c_mid)(s))
        x = x * s[:, None, None, :]
        x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        if self.strides == 1 and c_in == self.filters:
            if self.drop_rate > 0:
                x = nn.Dropout(self.drop_rate, deterministic=not train,
                               broadcast_dims=(1, 2, 3))(x)
            x = x + inp
        return x


class EfficientNet(nn.Module):
    variant: str = "b0"
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        wm, dm, _res, drop = _PARAMS[self.variant]
        y = nn.Conv(_round_filters(32, wm), (3, 3), (2, 2), padding="SAME",
                    use_bias=False)(x)
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9)(y)
        y = nn.swish(y)
        total = sum(_round_repeats(r, dm) for (_, _, r, _, _) in _BLOCKS)
        bidx = 0
        for expand, filters, repeats, kernel, stride in _BLOCKS:
            f = _round_filters(filters, wm)
            for i in range(_round_repeats(repeats, dm)):
                s = stride if i == 0 else 1
                y = _MBConv(expand, f, kernel, s,
                            drop_rate=0.2 * bidx / total)(y, train)
                bidx += 1
        y = nn.Conv(_round_filters(1280, wm), (1, 1), use_bias=False)(y)
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9)(y)
        y = nn.swish(y)
        y = jnp.mean(y, axis=(1, 2))
        y = nn.Dropout(drop, deterministic=not train)(y)
        return nn.Dense(self.num_classes)(y)
