"""MobileNet V1 and V3 (reference: fedml_api/model/cv/mobilenet.py and
mobilenet_v3.py, 466 LoC — cross-silo CV models).

TPU notes: depthwise convs use feature_group_count; NHWC; hard-swish /
hard-sigmoid as in V3. Widths kept at the reference's defaults.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def _hard_sigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


def _hard_swish(x):
    return x * _hard_sigmoid(x)


class _ConvBN(nn.Module):
    filters: int
    kernel: tuple = (3, 3)
    strides: tuple = (1, 1)
    groups: int = 1
    act: str = "relu"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.filters, self.kernel, self.strides, padding="SAME",
                    feature_group_count=self.groups, use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        if self.act == "relu":
            x = nn.relu(x)
        elif self.act == "hswish":
            x = _hard_swish(x)
        return x


class MobileNetV1(nn.Module):
    """Depthwise-separable stack (mobilenet.py)."""

    num_classes: int = 10
    width: float = 1.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = lambda c: max(8, int(c * self.width))
        x = _ConvBN(w(32), strides=(2, 2))(x, train)
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
               (1024, 1)]
        for filters, stride in cfg:
            in_c = x.shape[-1]
            x = _ConvBN(in_c, (3, 3), (stride, stride), groups=in_c)(x, train)  # depthwise
            x = _ConvBN(w(filters), (1, 1))(x, train)  # pointwise
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class _SEBlock(nn.Module):
    reduce: int = 4

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(max(8, c // self.reduce))(s))
        s = _hard_sigmoid(nn.Dense(c)(s))
        return x * s[:, None, None, :]


class _V3Block(nn.Module):
    expand: int
    filters: int
    kernel: int = 3
    strides: int = 1
    se: bool = False
    act: str = "relu"

    @nn.compact
    def __call__(self, x, train: bool = False):
        inp = x
        x = _ConvBN(self.expand, (1, 1), act=self.act)(x, train)
        x = _ConvBN(self.expand, (self.kernel, self.kernel),
                    (self.strides, self.strides), groups=self.expand,
                    act=self.act)(x, train)
        if self.se:
            x = _SEBlock()(x)
        x = _ConvBN(self.filters, (1, 1), act="none")(x, train)
        if self.strides == 1 and inp.shape[-1] == self.filters:
            x = x + inp
        return x


# (expand, out, kernel, stride, se, act) — the paper's Table 1/2 configs
_V3_SMALL = [
    (16, 16, 3, 2, True, "relu"),
    (72, 24, 3, 2, False, "relu"),
    (88, 24, 3, 1, False, "relu"),
    (96, 40, 5, 2, True, "hswish"),
    (240, 40, 5, 1, True, "hswish"),
    (240, 40, 5, 1, True, "hswish"),
    (120, 48, 5, 1, True, "hswish"),
    (144, 48, 5, 1, True, "hswish"),
    (288, 96, 5, 2, True, "hswish"),
    (576, 96, 5, 1, True, "hswish"),
    (576, 96, 5, 1, True, "hswish"),
]
_V3_LARGE = [
    (16, 16, 3, 1, False, "relu"),
    (64, 24, 3, 2, False, "relu"),
    (72, 24, 3, 1, False, "relu"),
    (72, 40, 5, 2, True, "relu"),
    (120, 40, 5, 1, True, "relu"),
    (120, 40, 5, 1, True, "relu"),
    (240, 80, 3, 2, False, "hswish"),
    (200, 80, 3, 1, False, "hswish"),
    (184, 80, 3, 1, False, "hswish"),
    (184, 80, 3, 1, False, "hswish"),
    (480, 112, 3, 1, True, "hswish"),
    (672, 112, 3, 1, True, "hswish"),
    (672, 160, 5, 2, True, "hswish"),
    (960, 160, 5, 1, True, "hswish"),
    (960, 160, 5, 1, True, "hswish"),
]


class MobileNetV3(nn.Module):
    """MobileNetV3 (mobilenet_v3.py; the reference defaults to
    model_mode='LARGE', mobilenet_v3.py:138). ``mode`` selects the paper's
    Small or Large stack; both end in the hswish 1x1 + pooled classifier."""

    num_classes: int = 10
    mode: str = "small"  # 'small' | 'large'

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.mode not in ("small", "large"):
            raise ValueError(f"mode={self.mode!r} (small|large)")
        x = _ConvBN(16, strides=(2, 2), act="hswish")(x, train)
        cfg = _V3_SMALL if self.mode == "small" else _V3_LARGE
        for e, f, k, s, se, act in cfg:
            x = _V3Block(e, f, k, s, se, act)(x, train)
        last, head = (576, 1024) if self.mode == "small" else (960, 1280)
        x = _ConvBN(last, (1, 1), act="hswish")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = _hard_swish(nn.Dense(head)(x))
        x = nn.Dropout(0.2, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
