"""Flax model zoo (L3a) — re-designs of fedml_api/model/* for TPU.

All modules accept ``train: bool = False`` in __call__ and use channels-last
NHWC layout (TPU-native; the torch reference is NCHW). The factory
``create_model`` mirrors the reference's dispatch
(fedml_experiments/distributed/fedavg/main_fedavg.py:232-267).
"""

from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.models.cnn import CNNOriginalFedAvg, CNNDropOut
from fedml_tpu.models.rnn import RNNOriginalFedAvg, RNNStackOverflow
from fedml_tpu.models.factory import create_model
