"""Linear models (reference: fedml_api/model/linear/lr.py:4-11)."""

from __future__ import annotations

import flax.linen as nn


class LogisticRegression(nn.Module):
    """Single dense layer over flattened input; logits out.

    Reference lr.py applies sigmoid in forward; we return logits and fold the
    nonlinearity into the loss (numerically better, same optimum).
    """

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes)(x)
