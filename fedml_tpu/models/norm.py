"""Cross-device synchronized BatchNorm + GroupNorm helper.

Mirror of fedml_api/model/cv/batchnorm_utils.py (DataParallelWithCallback +
SynchronizedBatchNorm, 462 LoC of CUDA-stream choreography) and
group_normalization.py. On TPU the whole mechanism collapses: flax's
BatchNorm already reduces batch statistics over a named mesh axis when
``axis_name`` is set — inside shard_map/pmap the mean/var become a psum
over the axis, which is exactly sync-BN, scheduled by XLA over ICI.

``sync_batchnorm("clients")`` inside a client-sharded model makes BN behave
as if the global batch (all devices) were normalized together — the
single-process DataParallel semantics the reference's utility recreates.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn


def sync_batchnorm(axis_name: str, momentum: float = 0.9, epsilon: float = 1e-5):
    """BatchNorm constructor whose statistics sync over ``axis_name``.

    Use inside shard_map/pmap bodies; outside any mapped axis, construct
    plain ``nn.BatchNorm`` instead (flax raises on unbound axis names).
    """
    return partial(
        nn.BatchNorm, momentum=momentum, epsilon=epsilon, axis_name=axis_name
    )


def group_norm(num_groups: int = 8):
    """GroupNorm helper (model/cv/group_normalization.py analogue) — the
    stateless alternative recommended for federated averaging (no running
    stats to aggregate)."""
    return partial(nn.GroupNorm, num_groups=num_groups)
