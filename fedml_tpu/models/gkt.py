"""FedGKT split ResNets (reference: fedml_api/model/cv/resnet56_gkt/ — the
client runs a small ResNet-8 feature extractor + tiny classifier head; the
server runs the large trunk (ResNet-55/49) that consumes the client's
stage-1 feature maps)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.resnet import BasicBlock, _GN, _GNBasicBlock
from functools import partial


class GKTClientExtractor(nn.Module):
    """Stem + one stage of basic blocks -> [H, W, 16] feature maps.

    norm_type 'group' swaps stateless GroupNorm in for BatchNorm — required
    when the extractor runs under a params-only engine (FedGKTAPI keeps no
    mutable collections, matching its vmapped per-client stacking).
    """

    blocks: int = 3  # ResNet-8: 3 blocks in one 16-channel stage
    norm_type: str = "batch"  # 'batch' | 'group'

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.norm_type == "group":
            y = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
            y = _GN()(y)
            y = nn.relu(y)
            for _ in range(self.blocks):
                y = _GNBasicBlock(16, (1, 1))(y, train)
            return y
        norm = partial(nn.BatchNorm, momentum=0.9)
        y = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        y = norm(use_running_average=not train)(y)
        y = nn.relu(y)
        for _ in range(self.blocks):
            y = BasicBlock(16, (1, 1), norm)(y, train)
        return y


class GKTClientHead(nn.Module):
    """Tiny classifier on pooled client features (the client-side logits
    shipped to the server for KD)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, feats, train: bool = False):
        y = jnp.mean(feats, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


class GKTServerModel(nn.Module):
    """Large trunk: stages 2-3 of a CIFAR ResNet consuming 16-ch features."""

    blocks_per_stage: int = 9  # ResNet-56 geometry minus the client stage
    num_classes: int = 10
    norm_type: str = "batch"  # 'group' for params-only engines (FedGKTAPI)

    @nn.compact
    def __call__(self, feats, train: bool = False):
        y = feats
        if self.norm_type == "group":
            for filters, stride in [(32, 2), (64, 2)]:
                for i in range(self.blocks_per_stage):
                    s = (stride, stride) if i == 0 else (1, 1)
                    y = _GNBasicBlock(filters, s)(y, train)
            y = jnp.mean(y, axis=(1, 2))
            return nn.Dense(self.num_classes)(y)
        norm = partial(nn.BatchNorm, momentum=0.9)
        for filters, stride in [(32, 2), (64, 2)]:
            for i in range(self.blocks_per_stage):
                s = (stride, stride) if i == 0 else (1, 1)
                y = BasicBlock(filters, s, norm)(y, train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


class SplitLowerNet(nn.Module):
    """SplitNN default lower cut (client side): norm-free conv features.

    The reference cuts an arbitrary torch model between client and server
    (split_nn/client.py holds the lower layers); SplitNNAPI keeps only
    trainable params per side, so the default cut avoids mutable
    normalization state.
    """

    width: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat features
            return nn.relu(nn.Dense(self.width * 4)(x))
        y = nn.relu(nn.Conv(self.width, (3, 3), (2, 2), padding="SAME")(x))
        y = nn.relu(nn.Conv(self.width * 2, (3, 3), (2, 2), padding="SAME")(y))
        return y


class SplitUpperNet(nn.Module):
    """SplitNN default upper cut (server side): activations -> logits."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, acts, train: bool = False):
        y = acts.reshape((acts.shape[0], -1))
        y = nn.relu(nn.Dense(128)(y))
        return nn.Dense(self.num_classes)(y)
