"""FedGKT split ResNets (reference: fedml_api/model/cv/resnet56_gkt/ — the
client runs a small ResNet-8 feature extractor + tiny classifier head; the
server runs the large trunk (ResNet-55/49) that consumes the client's
stage-1 feature maps)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.resnet import BasicBlock, _GN, _GNBasicBlock
from functools import partial


class GKTClientExtractor(nn.Module):
    """Stem + one stage of basic blocks -> [H, W, 16] feature maps.

    norm_type 'group' swaps stateless GroupNorm in for BatchNorm — required
    when the extractor runs under a params-only engine (FedGKTAPI keeps no
    mutable collections, matching its vmapped per-client stacking).
    """

    blocks: int = 3  # ResNet-8: 3 blocks in one 16-channel stage
    norm_type: str = "batch"  # 'batch' | 'group'

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.norm_type == "group":
            y = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
            y = _GN()(y)
            y = nn.relu(y)
            for _ in range(self.blocks):
                y = _GNBasicBlock(16, (1, 1))(y, train)
            return y
        norm = partial(nn.BatchNorm, momentum=0.9)
        y = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        y = norm(use_running_average=not train)(y)
        y = nn.relu(y)
        for _ in range(self.blocks):
            y = BasicBlock(16, (1, 1), norm)(y, train)
        return y


class GKTClientHead(nn.Module):
    """Tiny classifier on pooled client features (the client-side logits
    shipped to the server for KD)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, feats, train: bool = False):
        y = jnp.mean(feats, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


class GKTServerModel(nn.Module):
    """Large trunk: stages 2-3 of a CIFAR ResNet consuming 16-ch features."""

    blocks_per_stage: int = 9  # ResNet-56 geometry minus the client stage
    num_classes: int = 10
    norm_type: str = "batch"  # 'group' for params-only engines (FedGKTAPI)

    @nn.compact
    def __call__(self, feats, train: bool = False):
        y = feats
        if self.norm_type == "group":
            for filters, stride in [(32, 2), (64, 2)]:
                for i in range(self.blocks_per_stage):
                    s = (stride, stride) if i == 0 else (1, 1)
                    y = _GNBasicBlock(filters, s)(y, train)
            y = jnp.mean(y, axis=(1, 2))
            return nn.Dense(self.num_classes)(y)
        norm = partial(nn.BatchNorm, momentum=0.9)
        for filters, stride in [(32, 2), (64, 2)]:
            for i in range(self.blocks_per_stage):
                s = (stride, stride) if i == 0 else (1, 1)
                y = BasicBlock(filters, s, norm)(y, train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


class Bottleneck(nn.Module):
    """Reference bottleneck (resnet56_gkt/resnet_{client,server}.py):
    1x1(planes) -> 3x3(planes, stride) -> 1x1(4*planes), projection
    shortcut on shape change."""

    planes: int
    strides: tuple[int, int] = (1, 1)
    norm_type: str = "batch"

    @nn.compact
    def __call__(self, x, train: bool = False):
        def norm(y):
            if self.norm_type == "group":
                return nn.GroupNorm(num_groups=min(8, y.shape[-1]))(y)
            return nn.BatchNorm(momentum=0.9,
                                use_running_average=not train)(y)

        out_c = 4 * self.planes
        residual = x
        y = nn.Conv(self.planes, (1, 1), use_bias=False)(x)
        y = nn.relu(norm(y))
        y = nn.Conv(self.planes, (3, 3), self.strides, padding="SAME",
                    use_bias=False)(y)
        y = nn.relu(norm(y))
        y = nn.Conv(out_c, (1, 1), use_bias=False)(y)
        y = norm(y)
        if residual.shape != y.shape:
            residual = nn.Conv(out_c, (1, 1), self.strides,
                               use_bias=False)(residual)
            residual = norm(residual)
        return nn.relu(y + residual)


class GKTClientNetRef(nn.Module):
    """The reference's exact client model (resnet8_56: Bottleneck x2 on the
    16-plane stage). forward -> (logits, extracted_features): features are
    the POST-STEM 16-ch maps (resnet_client.py:78-92) — what travels to the
    server — while the local head continues through layer1 + fc for the
    client-side CE/KD logits. 10,586 params @ 10 classes, matching the
    reference count exactly (pinned)."""

    num_classes: int = 10
    norm_type: str = "batch"

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        if self.norm_type == "group":
            y = nn.GroupNorm(num_groups=8)(y)
        else:
            y = nn.BatchNorm(momentum=0.9, use_running_average=not train)(y)
        feats = nn.relu(y)
        y = feats
        for _ in range(2):
            y = Bottleneck(16, norm_type=self.norm_type)(y, train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes)(y), feats


class GKTServerNetRef(nn.Module):
    """The reference's exact server trunk (resnet56_server: Bottleneck
    [6,6,6] over planes 16/32/64 consuming the client's 16-ch stem
    features; the reference also constructs a stem it never runs —
    resnet_server.py:73-85 — which we do not reproduce, so our count is
    the forward-used 590,858 of its 591,322)."""

    num_classes: int = 10
    norm_type: str = "batch"

    @nn.compact
    def __call__(self, feats, train: bool = False):
        y = feats
        for planes, stride in [(16, 1), (32, 2), (64, 2)]:
            for i in range(6):
                s = (stride, stride) if i == 0 else (1, 1)
                y = Bottleneck(planes, s, self.norm_type)(y, train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


class SplitLowerNet(nn.Module):
    """SplitNN default lower cut (client side): norm-free conv features.

    The reference cuts an arbitrary torch model between client and server
    (split_nn/client.py holds the lower layers); SplitNNAPI keeps only
    trainable params per side, so the default cut avoids mutable
    normalization state.
    """

    width: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat features
            return nn.relu(nn.Dense(self.width * 4)(x))
        y = nn.relu(nn.Conv(self.width, (3, 3), (2, 2), padding="SAME")(x))
        y = nn.relu(nn.Conv(self.width * 2, (3, 3), (2, 2), padding="SAME")(y))
        return y


class SplitUpperNet(nn.Module):
    """SplitNN default upper cut (server side): activations -> logits."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, acts, train: bool = False):
        y = acts.reshape((acts.shape[0], -1))
        y = nn.relu(nn.Dense(128)(y))
        return nn.Dense(self.num_classes)(y)
