"""FedGKT split ResNets (reference: fedml_api/model/cv/resnet56_gkt/ — the
client runs a small ResNet-8 feature extractor + tiny classifier head; the
server runs the large trunk (ResNet-55/49) that consumes the client's
stage-1 feature maps)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.resnet import BasicBlock
from functools import partial


class GKTClientExtractor(nn.Module):
    """Stem + one stage of basic blocks -> [H, W, 16] feature maps."""

    blocks: int = 3  # ResNet-8: 3 blocks in one 16-channel stage

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, momentum=0.9)
        y = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        y = norm(use_running_average=not train)(y)
        y = nn.relu(y)
        for _ in range(self.blocks):
            y = BasicBlock(16, (1, 1), norm)(y, train)
        return y


class GKTClientHead(nn.Module):
    """Tiny classifier on pooled client features (the client-side logits
    shipped to the server for KD)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, feats, train: bool = False):
        y = jnp.mean(feats, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


class GKTServerModel(nn.Module):
    """Large trunk: stages 2-3 of a CIFAR ResNet consuming 16-ch features."""

    blocks_per_stage: int = 9  # ResNet-56 geometry minus the client stage
    num_classes: int = 10

    @nn.compact
    def __call__(self, feats, train: bool = False):
        norm = partial(nn.BatchNorm, momentum=0.9)
        y = feats
        for filters, stride in [(32, 2), (64, 2)]:
            for i in range(self.blocks_per_stage):
                s = (stride, stride) if i == 0 else (1, 1)
                y = BasicBlock(filters, s, norm)(y, train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)
