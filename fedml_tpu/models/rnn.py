"""Character/word LSTMs (reference: fedml_api/model/nlp/rnn.py).

RNN_OriginalFedAvg (rnn.py:4-36): embedding(vocab 90 -> 8), 2x LSTM(256),
dense to vocab — Shakespeare next-char.
RNN_StackOverFlow (rnn.py:39-70): embedding(10004 -> 96), 1x LSTM(670),
dense 96 -> dense 10004 — next-word prediction.

TPU notes: the torch versions run cuDNN LSTM on [bs, T]; here the recurrence
is an nn.RNN (flax scan over an OptimizedLSTMCell), which XLA unrolls into
fused matmuls on the MXU. Input: int tokens [bs, T]; output: logits
[bs, T, vocab] predicting the NEXT token at each position.
"""

from __future__ import annotations

import flax.linen as nn


class RNNOriginalFedAvg(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        return nn.Dense(self.vocab_size)(h)


class RNNStackOverflow(nn.Module):
    vocab_size: int = 10004  # 10000 words + pad/bos/eos/oov
    embedding_dim: int = 96
    hidden_size: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        h = nn.Dense(self.embedding_dim)(h)
        return nn.Dense(self.vocab_size)(h)
