"""VGG (reference: fedml_api/model/cv/vgg.py, 158 LoC — VGG-11/16 baselines).

Two heads:
  - imagenet_head=True: the reference's torchvision-style classifier —
    adaptive-pool to 7x7, 4096-4096-classes MLP with dropout (vgg.py:23-32;
    vgg16 @ 1000 classes = 138,357,544 params, pinned in
    tests/test_param_parity.py).
  - imagenet_head=False (default): the CIFAR-style head (global pool +
    512-unit MLP) — right-sized for the 32x32 federated configs.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512,
         512, "M", 512, 512, 512, 512, "M"],
}


def _adaptive_avg_pool(x, out_hw: int):
    """AdaptiveAvgPool2d analogue with torch's exact bin semantics: output
    bin i averages rows floor(i*H/out) .. ceil((i+1)*H/out)-1 (variable-size
    bins; degenerates to replication when H < out). Shapes are static under
    jit, so the bins unroll at trace time."""
    def pool_axis(x, axis, size):
        segs = []
        for i in range(out_hw):
            lo = (i * size) // out_hw
            hi = -(-((i + 1) * size) // out_hw)
            sl = [slice(None)] * x.ndim
            sl[axis] = slice(lo, max(hi, lo + 1))
            segs.append(x[tuple(sl)].mean(axis=axis, keepdims=True))
        return jnp.concatenate(segs, axis=axis)

    x = pool_axis(x, 1, x.shape[1])
    return pool_axis(x, 2, x.shape[2])


class VGG(nn.Module):
    depth: int = 11
    num_classes: int = 10
    batch_norm: bool = True
    imagenet_head: bool = False  # reference torchvision classifier (see module doc)

    @nn.compact
    def __call__(self, x, train: bool = False):
        for v in _CFGS[self.depth]:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME")(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9)(x)
                x = nn.relu(x)
        if self.imagenet_head:
            x = _adaptive_avg_pool(x, 7)
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(4096)(x))
            x = nn.Dropout(0.5, deterministic=not train)(x)
            x = nn.relu(nn.Dense(4096)(x))
            x = nn.Dropout(0.5, deterministic=not train)(x)
            return nn.Dense(self.num_classes)(x)
        x = jnp.mean(x, axis=(1, 2))  # adaptive pool to 1x1 (CIFAR-sized inputs)
        x = nn.relu(nn.Dense(512)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
