"""VGG (reference: fedml_api/model/cv/vgg.py, 158 LoC — VGG-11/16 baselines)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512,
         512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    depth: int = 11
    num_classes: int = 10
    batch_norm: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        for v in _CFGS[self.depth]:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME")(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9)(x)
                x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # adaptive pool to 1x1 (CIFAR-sized inputs)
        x = nn.relu(nn.Dense(512)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
