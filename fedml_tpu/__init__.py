"""fedml_tpu — a TPU-native federated learning framework.

A from-scratch re-design of the capabilities of FedML (forestnoobie/FedML,
reference layer map in SURVEY.md) for TPU hardware:

- The reference's message-passing round (MPI/gRPC/MQTT point-to-point sends,
  ``fedml_core/distributed/communication/``) becomes ONE SPMD program over a
  ``jax.sharding.Mesh``: local client training is a jitted/`shard_map`-ped
  train step, aggregation is a weighted ``jax.lax.psum`` over ICI.
- The reference's per-process ClientManager/ServerManager/Trainer machinery
  (``fedml_core/distributed/{client,server}/``) becomes a thin host-side
  round driver around jitted collectives.
- Models are flax.linen modules (reference: torch.nn, ``fedml_api/model/``),
  optimizers are optax, checkpointing is orbax.

Subpackages
-----------
mesh        device mesh + sharding helpers                    (L0)
collectives tested collective wrappers = the "comm backend"   (L1)
core        client state, local update, round engine, sampler,
            partitioner, robust aggregation, topology         (L2)
models      flax model zoo                                    (L3a)
data        partitioned dataset loaders (8-tuple contract)    (L3b)
algorithms  FedAvg, FedOpt, FedProx, FedNova, hierarchical,
            decentralized, robust, FedDF, SplitNN, VFL,
            TurboAggregate, FedGKT, FedNAS                    (L4)
experiments unified CLI launcher                              (L5)
"""

__version__ = "0.1.0"
