"""fedml_tpu — a TPU-native federated learning framework.

A from-scratch re-design of the capabilities of FedML (forestnoobie/FedML,
reference layer map in SURVEY.md) for TPU hardware:

- The reference's message-passing round (MPI/gRPC/MQTT point-to-point sends,
  ``fedml_core/distributed/communication/``) becomes ONE SPMD program over a
  ``jax.sharding.Mesh``: local client training is a jitted/`shard_map`-ped
  train step, aggregation is a weighted ``jax.lax.psum`` over ICI.
- The reference's per-process ClientManager/ServerManager/Trainer machinery
  (``fedml_core/distributed/{client,server}/``) becomes a thin host-side
  round driver around jitted collectives.
- Models are flax.linen modules (reference: torch.nn, ``fedml_api/model/``),
  optimizers are optax, checkpointing is orbax.

Subpackages
-----------
mesh        device mesh + sharding helpers                    (L0)
collectives tested collective wrappers (on-TPU "comm backend") (L1)
comm        cross-process transports: loopback | gRPC | MQTT,
            Message/Observer/manager pattern                  (L1)
core        client state, local update, round engine, sampler,
            partitioner, robust aggregation, topology,
            checkpointing, schedules                          (L2)
models      flax model zoo (+ sync-BN, norm-free ResNet)      (L3a)
data        partitioned dataset loaders (8-tuple contract),
            vertical tabular, poisoning, augmentation         (L3b)
algorithms  FedAvg, FedOpt, FedProx, FedNova, hierarchical,
            decentralized, robust, FedDF, SplitNN, VFL,
            TurboAggregate, FedGKT, FedNAS, FedSeg            (L4)
distributed cross-process 6-file runtimes over ``comm``       (L4)
parallel    ring / Ulysses sequence parallelism
ops         Pallas TPU kernels (flash attention)
native      C++ host data plane (ctypes)
experiments unified CLI + multi-process launcher              (L5)
utils       pytree ops, metrics, tracing, condensation
"""

__version__ = "0.1.0"

# graft missing new-jax names (jax.typeof / jax.lax.pcast / jax.shard_map)
# onto older jax runtimes — a no-op on current jax (see utils/jax_compat).
# Imports jax, which is acceptable at package-import time: every fedml_tpu
# subpackage needs jax within a few lines anyway, and importing jax does
# NOT initialize a backend (so this cannot hang on a dead accelerator
# relay — the thing the light-import entry points guard against).
from fedml_tpu.utils.jax_compat import install as _jax_compat_install

_jax_compat_install()
del _jax_compat_install
