"""Finite-field arithmetic for secure aggregation (TurboAggregate).

The reference's MPC layer (fedml_api/distributed/turboaggregate/mpc_function.py)
does modular inverses (:4-18), Lagrange coefficient generation (:38-59) and
BGW/Shamir share encoding (:62-76) in numpy int64 on the host. Here the same
math runs in JAX int32/int64 so coded shares can be psum'd over ICI without
leaving the device.

The field is GF(p) with p = 2**31 - 1 (Mersenne prime, fits int64 products
after mod reduction at each step). All public functions run under a local
``jax.enable_x64()`` scope so int64 is available regardless of the global
x64 flag; returned arrays are int64.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

P_DEFAULT = 2**31 - 1


def _x64(fn):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.enable_x64():
            return fn(*args, **kwargs)

    return wrapped


@_x64
def mod_pow(base, exp: int, p: int = P_DEFAULT):
    """base**exp mod p via square-and-multiply (exp is a static python int)."""
    base = jnp.asarray(base, jnp.int64) % p
    result = jnp.ones_like(base)
    e = int(exp)
    while e > 0:
        if e & 1:
            result = (result * base) % p
        base = (base * base) % p
        e >>= 1
    return result


@_x64
def mod_inv(a, p: int = P_DEFAULT):
    """Modular inverse by Fermat's little theorem: a^(p-2) mod p.

    Replaces the extended-Euclid loop of the reference (mpc_function.py:4-18)
    with a fixed-depth exponentiation — data-independent control flow, so it
    jits and vmaps.
    """
    return mod_pow(a, p - 2, p)


@_x64
def lagrange_coeffs(alpha_s, beta_s, p: int = P_DEFAULT):
    """L[i, j] = prod_{k != j} (alpha_i - beta_k) / (beta_j - beta_k)  (mod p).

    Vectorized port of gen_Lagrange_coeffs (mpc_function.py:38-59).
    alpha_s: [A] eval points; beta_s: [B] interpolation points. Returns [A, B].
    """
    alpha_s = jnp.asarray(alpha_s, jnp.int64) % p
    beta_s = jnp.asarray(beta_s, jnp.int64) % p
    B = beta_s.shape[0]
    # den[j] = prod_{k != j} (beta_j - beta_k), reduced mod p at every step so
    # intermediate products stay inside int64
    diff_b = (beta_s[:, None] - beta_s[None, :]) % p  # [B, B]
    diff_b = jnp.where(jnp.eye(B, dtype=bool), 1, diff_b)

    def prod_mod(m):  # rowwise product mod p, m: [R, C] -> [R]
        init = jnp.ones(m.shape[0], jnp.int64)
        out, _ = lax.scan(lambda c, col: ((c * col) % p, None), init, m.T)
        return out

    den = prod_mod(diff_b)
    # num[i, j] = prod_{k != j} (alpha_i - beta_k)
    diff_a = (alpha_s[:, None] - beta_s[None, :]) % p  # [A, B]
    def num_row(da):  # da: [B]
        m = jnp.where(jnp.eye(B, dtype=bool), 1, jnp.broadcast_to(da[None, :], (B, B)))
        return prod_mod(m)
    num = jax.vmap(num_row)(diff_a)  # [A, B]
    return (num * mod_inv(den, p)[None, :]) % p


@_x64
def shamir_encode(x, key, n_shares: int, t: int, p: int = P_DEFAULT):
    """Shamir/BGW share encoding (port of BGW_encoding, mpc_function.py:62-76).

    x: int64 array (already field-encoded secret), shape [...]. Returns
    shares of shape [n_shares, ...]: s_i = x + sum_m r_m * alpha_i^m with
    random coefficients r_1..r_t drawn from GF(p).
    """
    x = jnp.asarray(x, jnp.int64) % p
    alphas = jnp.arange(1, n_shares + 1, dtype=jnp.int64)
    coeffs = jax.random.randint(key, (t,) + x.shape, 0, p - 1, dtype=jnp.int64)

    def share(alpha):
        acc = x
        apow = jnp.asarray(1, jnp.int64)
        for m in range(t):
            apow = (apow * alpha) % p
            acc = (acc + coeffs[m] * apow) % p
        return acc

    return jax.vmap(share)(alphas)


@_x64
def shamir_decode(shares, alphas, t: int, p: int = P_DEFAULT):
    """Reconstruct the secret from >= t+1 shares by Lagrange interpolation at 0."""
    shares = jnp.asarray(shares, jnp.int64) % p
    k = t + 1
    L = lagrange_coeffs(jnp.zeros((1,), jnp.int64), alphas[:k], p)[0]  # [k]
    acc = jnp.zeros(shares.shape[1:], jnp.int64)
    for j in range(k):
        acc = (acc + L[j] * shares[j]) % p
    return acc


def assert_field_capacity(n_terms: int, quant_scale: float,
                          max_abs: float = 1.0, p: int = P_DEFAULT) -> float:
    """Loud guard against silent mod-p wraparound in aggregation sums.

    Summing ``n_terms`` field-encoded values whose pre-quantization
    magnitudes are bounded by ``max_abs`` produces signed magnitudes up to
    ``n_terms * quant_scale * max_abs``; the signed decode range is
    (-p/2, p/2), so the sum stays decodable iff

        n_terms * 2 * quant_scale * max_abs < p.

    Large cohorts or a generous ``quant_scale`` can cross this silently —
    the decoded aggregate would wrap to garbage with no error anywhere —
    so aggregators must call this at CONSTRUCTION, not discover it at
    round N. Returns the fraction of the field the worst-case sum uses
    (the headroom diagnostic); raises ValueError at or past capacity.
    """
    if n_terms < 1:
        raise ValueError(f"n_terms={n_terms} must be >= 1")
    if quant_scale <= 0 or max_abs <= 0:
        raise ValueError(
            f"quant_scale={quant_scale} and max_abs={max_abs} must be > 0")
    need = 2.0 * float(n_terms) * float(quant_scale) * float(max_abs)
    if need >= p:
        raise ValueError(
            f"field capacity exceeded: {n_terms} terms * 2 * quant_scale="
            f"{quant_scale:g} * max_abs={max_abs:g} = {need:.4g} >= p={p} "
            "— the aggregated sum would wrap mod p and decode to garbage; "
            "lower quant_scale (costs precision), shrink the cohort, or "
            "tighten the clip bound feeding max_abs")
    return need / p


@_x64
def field_encode(x, scale: float = 2**16, p: int = P_DEFAULT):
    """Quantize float array into GF(p): round(x * scale) mod p (negatives wrap)."""
    q = jnp.round(jnp.asarray(x, jnp.float64) * scale).astype(jnp.int64)
    return q % p


@_x64
def field_decode(z, scale: float = 2**16, p: int = P_DEFAULT):
    """Inverse of field_encode; values above p/2 decode as negative."""
    z = jnp.asarray(z, jnp.int64)
    signed = jnp.where(z > p // 2, z - p, z)
    return signed.astype(jnp.float64) / scale
