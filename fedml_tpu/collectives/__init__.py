from fedml_tpu.collectives.ops import (
    weighted_psum_tree,
    weighted_mean_tree,
    all_gather_tree,
    ppermute_tree,
    mix_with_topology,
    psum_tree,
)
from fedml_tpu.collectives import finite_field
