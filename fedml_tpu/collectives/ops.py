"""Collective wrappers (L1) — the TPU-native "communication backend".

Direct replacement of fedml_core/distributed/communication/ (MPI pickled
point-to-point sends, mpi/mpi_send_thread.py:27; gRPC JSON messages,
gRPC/grpc_comm_manager.py:53-74; MQTT pub/sub). The reference implements
aggregation as N uploads + N downloads of serialized state_dicts through a
polling receive loop (mpi/com_manager.py:71-78). Here a round's entire
communication is XLA collectives over ICI, emitted inside shard_map:

  model download (S2C_SYNC)  -> params are replicated; nothing moves
  model upload + aggregate   -> weighted_psum_tree
  gossip to neighbors        -> ppermute_tree / mix_with_topology
  secure aggregation         -> finite_field.psum of coded shares

All functions here take/return pytrees and must be called inside shard_map
(they use a named mesh axis).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def psum_tree(tree, axis_name: str = "clients"):
    return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)


def weighted_psum_tree(tree, weight, axis_name: str = "clients"):
    """Sum of ``weight * tree`` over the mesh axis; returns (sum_tree, sum_weight).

    ``weight`` is this shard's scalar weight (e.g. local sample count). The
    caller divides to get the weighted mean — kept separate so hierarchical /
    multi-level aggregation can psum numerator and denominator independently.
    """
    num = jax.tree.map(lambda x: lax.psum(x * weight, axis_name), tree)
    den = lax.psum(weight, axis_name)
    return num, den


def weighted_mean_tree(tree, weight, axis_name: str = "clients"):
    """Sample-weighted average over the mesh axis.

    The SPMD form of the server's weighted model average
    (reference FedAVGAggregator.aggregate, FedAVGAggregator.py:58-87).
    """
    num, den = weighted_psum_tree(tree, weight, axis_name)
    den = jnp.maximum(den, 1e-12)
    return jax.tree.map(lambda x: x / den, num)


def all_gather_tree(tree, axis_name: str = "clients", axis: int = 0, tiled: bool = False):
    """Gather every shard's pytree along a new (or existing, if tiled) axis."""
    return jax.tree.map(lambda x: lax.all_gather(x, axis_name, axis=axis, tiled=tiled), tree)


def ppermute_tree(tree, perm, axis_name: str = "clients"):
    """Point-to-point ring/graph exchange: ``perm`` is [(src, dst), ...].

    The TPU replacement for the decentralized framework's
    send_result_to_neighbors (decentralized_worker_manager.py:41-46): a
    topology edge list becomes a ppermute schedule riding ICI.
    """
    return jax.tree.map(lambda x: lax.ppermute(x, axis_name, perm), tree)


def mix_with_topology(tree, mixing_row, axis_name: str = "clients"):
    """Weighted neighbor mixing: out_i = sum_j W[i,j] * tree_j.

    ``mixing_row`` is this device's row of the (row-normalized) mixing matrix W
    produced by a TopologyManager (reference
    fedml_core/distributed/topology/symmetric_topology_manager.py:21-52).
    Implemented as all_gather + local contraction — on a small 'clients' axis
    this is one ICI all-gather, and XLA fuses the contraction. For sparse
    rings prefer ppermute_tree per edge.
    """
    def mix(x):
        allx = lax.all_gather(x, axis_name, axis=0)  # [n, ...]
        return jnp.tensordot(mixing_row, allx, axes=([0], [0]))

    return jax.tree.map(mix, tree)
