from fedml_tpu.core.client_data import ClientBatch, FederatedData, pack_clients
from fedml_tpu.core.partition import (
    dirichlet_partition,
    homo_partition,
    partition_data,
    record_data_stats,
)
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.core.local import LocalSpec, make_local_update, make_eval_fn
from fedml_tpu.core.robust import norm_diff_clipping, add_gaussian_noise
from fedml_tpu.core.partition_rules import (
    ServerStatePartitioner,
    match_partition_rules,
    rules_from_json,
    rules_to_json,
)
