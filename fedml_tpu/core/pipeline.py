"""Pipelined round execution — keep the accelerator fed while the host works.

The per-round driver is a three-stage pipeline; each stage here is a small,
engine-agnostic primitive the FedAvg engine (and the cross-process managers)
compose:

- :class:`Prefetcher` — a background packer thread that prepares work item
  r+1 (sample ids, pack the ``IndexBatch``/``ClientBatch``, issue its
  ``device_put``) while round r executes on device, through a bounded ring
  buffer. FedJAX (arXiv:2108.02117) gets its simulation throughput from
  exactly this overlap: the host's pack loop and the device's round program
  run concurrently instead of strictly alternating.
- :class:`InflightRing` — the drain half: dispatched round OUTPUTS (device
  arrays of metrics + quarantine codes) are held in a ring and materialized
  ``lag`` rounds behind dispatch, so the host never blocks on the round it
  just launched and JAX async dispatch stays >= ``lag`` rounds deep.
  Telemetry/quarantine records flush in submission order at drain time —
  the ledger is bit-identical to the synchronous driver's (test-enforced).
- :class:`AsyncSender` — a FIFO sender worker for the cross-process client:
  uplink frame encoding (tree flatten + buffer copies + CRC32 + optional
  deflate) and transmission move off the training thread, the client-side
  analogue of the Smart-NIC FL-server ingest offload (arXiv:2307.06561).
- :func:`compile_concurrently` — the AOT warm-up executor: pre-lowered
  round-program variants compile on a thread pool (XLA releases the GIL),
  with fresh-compile / persistent-cache-hit accounting from
  ``obs/perf_instrument.py``.

Safety invariants the primitives rely on (and the engine upholds):

- *Determinism*: packing round r is a pure function of (seed, round,
  sampled ids) — the prefetch thread computes exactly what the synchronous
  driver would, so prefetch on/off is bitwise identical.
- *Donation safety*: packers allocate FRESH host buffers every round (the
  numpy pack paths already do); the round program donates only the model/
  optimizer buffers, never the batch, so a prefetched batch can sit in the
  ring while an earlier round still reads its own.
- *Thread ownership*: the producer thread only packs and places; all
  engine-state mutation (rng chain, net/opt, ledger, telemetry) stays on
  the driver thread. Drains run inline in ``push``/``drain_all`` — also on
  the driver thread.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable

from fedml_tpu.obs import perf_instrument as _perf

log = logging.getLogger("fedml_tpu.pipeline")


class Prefetcher:
    """Background producer over a deterministic key schedule.

    ``produce(key)`` runs on the packer thread for each key in order;
    results are handed to :meth:`get` through a ring buffer bounded at
    ``depth`` items (double-buffering = depth 2: one batch in flight on
    device, one staged, one being packed).

    ``get`` must be called with the same keys in the same order — the
    pipeline is a FIFO, not a cache. A producer exception is re-raised by
    the next ``get`` (never swallowed into a hang). ``on_event`` (tests/
    instrumentation) observes ``("produced", key)`` on the packer thread
    and ``("got", key)`` on the consumer thread.
    """

    def __init__(self, produce: Callable[[Any], Any], keys: Iterable[Any],
                 depth: int = 2, on_event: Callable | None = None,
                 name: str = "fedml-prefetch"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._produce = produce
        self._keys = list(keys)
        self._q: "queue.Queue[tuple[Any, Any]]" = queue.Queue(maxsize=depth)
        self._err: BaseException | None = None
        self._stop = threading.Event()
        self._on_event = on_event
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            for key in self._keys:
                if self._stop.is_set():
                    return
                item = self._produce(key)
                if self._on_event is not None:
                    self._on_event("produced", key)
                while not self._stop.is_set():
                    try:
                        self._q.put((key, item), timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 — surfaced via get()
            self._err = e
            log.exception("prefetch producer died")

    def get(self, key: Any) -> tuple[Any, float]:
        """Next produced item (must match ``key``) plus the seconds this
        call stalled waiting for it — observed into
        ``fed_prefetch_stall_seconds``."""
        t0 = time.perf_counter()
        while True:
            if self._err is not None and self._q.empty():
                raise RuntimeError(
                    f"prefetch producer failed before key {key!r}"
                ) from self._err
            try:
                k, item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if not self._thread.is_alive() and self._q.empty() \
                        and self._err is None:
                    raise RuntimeError(
                        f"prefetch schedule exhausted before key {key!r}")
                continue
        stall = time.perf_counter() - t0
        _perf.record_prefetch_stall(stall)
        if k != key:
            raise RuntimeError(
                f"prefetch out of order: wanted {key!r}, got {k!r}")
        if self._on_event is not None:
            self._on_event("got", key)
        return item, stall

    def close(self) -> None:
        """Stop the producer and reclaim the thread (idempotent). Items
        still in the ring are discarded — close only after the last get."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=10.0)


class InflightRing:
    """Ring of dispatched-but-undrained round outputs.

    ``push(key, entry)`` appends and drains (via ``drain_fn(key, entry)``,
    inline on the caller's thread, in submission order) everything deeper
    than ``lag``; returns the drained results. ``drain_all`` flushes the
    rest (end of run, or an eval round that needs its own metrics). The
    ring length after each push feeds the ``fed_dispatch_depth`` gauge.
    """

    def __init__(self, lag: int, drain_fn: Callable[[Any, Any], Any],
                 on_event: Callable | None = None):
        if lag < 0:
            raise ValueError(f"drain lag must be >= 0, got {lag}")
        self._lag = lag
        self._drain = drain_fn
        self._on_event = on_event
        self._ring: deque = deque()

    def __len__(self) -> int:
        return len(self._ring)

    def _pop(self):
        key, entry = self._ring.popleft()
        out = self._drain(key, entry)
        if self._on_event is not None:
            self._on_event("drained", key)
        return out

    def push(self, key: Any, entry: Any) -> list:
        self._ring.append((key, entry))
        _perf.set_dispatch_depth(len(self._ring))
        out = []
        while len(self._ring) > self._lag:
            out.append(self._pop())
        return out

    def drain_all(self) -> list:
        out = []
        while self._ring:
            out.append(self._pop())
        _perf.set_dispatch_depth(0)
        return out


class AsyncSender:
    """FIFO sender worker — encode+transmit off the caller's thread.

    One daemon thread drains a queue of messages through ``send``; order is
    preserved (the chaos layer's per-link sequence numbers, the gRPC seq
    stream, and the server's round tags all assume FIFO per sender). A send
    failure is logged with traceback, stops the worker (remaining queued
    messages are dropped — the peer's elastic round deadline handles the
    gap), fires ``on_error`` on the worker thread, and re-raises from the
    next ``submit``/``close`` so the owning manager dies visibly instead of
    hanging silently — the same contract as ``BaseCommManager._notify``.
    ``on_error`` matters for owners that may never call submit/close again
    (a client blocked waiting for a broadcast its failed upload forfeited):
    it is their hook to shut down instead of hanging.
    """

    _STOP = object()

    def __init__(self, send: Callable[[Any], None], name: str = "fedml-sender",
                 on_error: Callable[[BaseException], None] | None = None):
        self._send = send
        self._on_error = on_error
        self._q: queue.Queue = queue.Queue()
        self._err: BaseException | None = None
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            msg = self._q.get()
            if msg is self._STOP:
                return
            try:
                self._send(msg)
            except BaseException as e:  # noqa: BLE001 — surfaced on submit
                self._err = e
                log.exception("async sender: send failed; worker stopping")
                if self._on_error is not None:
                    try:
                        self._on_error(e)
                    except BaseException:  # noqa: BLE001 — teardown hook
                        log.exception("async sender: on_error hook raised")
                return

    def submit(self, msg: Any) -> None:
        if self._err is not None:
            raise RuntimeError("async sender worker died") from self._err
        self._q.put(msg)

    def close(self, timeout: float = 60.0) -> None:
        """Flush queued sends and stop the worker. Raises if the worker
        died on an earlier send OR failed to flush within ``timeout`` —
        a wedged transport must not read as a clean exit."""
        self._q.put(self._STOP)
        if threading.current_thread() is not self._thread:
            # (an on_error hook may close() from the worker itself — a
            # thread cannot join itself, and the error is already set)
            self._thread.join(timeout)
            if self._err is None and self._thread.is_alive():
                raise RuntimeError(
                    f"async sender did not flush within {timeout}s "
                    "(transport wedged mid-send?)")
        if self._err is not None:
            raise RuntimeError("async sender worker died") from self._err


def compile_concurrently(lowered: dict, max_workers: int | None = None) -> dict:
    """Compile pre-lowered jit programs on a thread pool (XLA compiles
    release the GIL, so the <=4 bucket variants + block fn genuinely
    overlap), with compile accounting from obs/perf_instrument.

    Returns a report: ``variants`` (names compiled), ``seconds`` (wall
    clock of the whole pass), ``fresh_compiles`` (persistent-cache misses
    when the cache was consulted, raw backend passes otherwise — a repeat
    run against a warm cache must show 0; the acceptance tests assert it),
    ``cache_hits``/``cache_misses`` deltas, ``per_variant`` ({name:
    {"seconds": wall}} — locally timed, so it reports even when
    jax.monitoring is absent), and ``instrumented`` (False when
    jax.monitoring is unavailable, in which case every delta reads 0
    vacuously).

    Each variant compiles inside ``_perf.attribute_compiles(name)`` (the
    compile observatory's per-jit-name attribution) and its executable's
    XLA cost analysis is cached under the same name
    (``goodput.record_variant_cost``) — which is how a warmed-up engine's
    round records later carry FLOPs/s without re-deriving cost at
    dispatch time.
    """
    from concurrent.futures import ThreadPoolExecutor

    from fedml_tpu.obs import goodput as _goodput

    instrumented = _perf.install()
    c0, h0, m0, r0 = (_perf.compiles_total(), _perf.cache_hits_total(),
                      _perf.cache_misses_total(),
                      _perf.cache_requests_total())
    t0 = time.perf_counter()
    names = list(lowered)
    per_variant: dict = {}

    def _one(n):
        tv = time.perf_counter()
        with _perf.attribute_compiles(n):
            exe = lowered[n].compile()
        per_variant[n] = {"seconds": time.perf_counter() - tv}
        _goodput.record_variant_cost(n, exe)
        return exe

    if names:
        with ThreadPoolExecutor(
                max_workers=max_workers or min(len(names), 8)) as ex:
            compiled = list(ex.map(_one, names))
    else:
        compiled = []
    requests = int(_perf.cache_requests_total() - r0)
    misses = int(_perf.cache_misses_total() - m0)
    passes = int(_perf.compiles_total() - c0)
    return {
        "variants": names,
        "executables": dict(zip(names, compiled)),
        "seconds": time.perf_counter() - t0,
        "per_variant": per_variant,
        # with the persistent cache consulted, a cache HIT deserializes —
        # only a MISS pays XLA; without it every backend pass is fresh
        "fresh_compiles": misses if requests else passes,
        "cache_hits": int(_perf.cache_hits_total() - h0),
        "cache_misses": misses,
        "instrumented": instrumented,
    }
