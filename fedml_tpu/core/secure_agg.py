"""Pairwise-masked secure aggregation over GF(2^31 - 1) (the SecAgg mold).

Protocol (Bonawitz et al., CCS'17, adapted to this repo's star topology
and determinism discipline):

- every client quantizes its weighted update into GF(p)
  (``collectives.finite_field.field_encode``) and adds
  (1) **cancelling pairwise masks** — for each cohort pair (i, j) a mask
  vector expanded by a jitted counter-PRG from a seed only i and j share
  (a Diffie-Hellman exchange in GF(p): ``s_ij = pk_j^sk_i = pk_i^sk_j``,
  ``pk = g^sk``), added by the lower slot and subtracted by the higher so
  the masks vanish from the cohort SUM; and
  (2) a **self-mask** ``PRG(b_i)`` whose seed ``b_i`` is Shamir-shared
  across the cohort (``collectives.finite_field.shamir_encode``) — the
  server can only strip it with shares from >= t+1 cohort members.

- the server's per-upload cost is ONE streaming add mod p
  (``fold_masked``): masking must stay a cheap fold at fan-in, never a
  per-client host reconstruction (the Smart-NIC server lesson,
  arXiv:2307.06561).

- **dropout tolerance**: when clients die mid-round the pairwise masks
  between each survivor i and each dead slot j no longer cancel.
  Survivors reveal exactly the seeds that repair the sum — their own
  ``s_ij`` for the DEAD slots only (a pairwise secret masks nothing else
  once j's contribution is gone) — and the server strips the live
  clients' self-masks from the Shamir shares the survivor slots hold.
  Below ``threshold_t + 1`` survivors nothing is recoverable and the
  round must shed loudly.

Determinism note (the fedlint contract): every secret here derives from
the session seed via sha256 (``derive_secret``) — no ``os.urandom``, no
``secrets`` module — so a chaos run replays bit-for-bit. That choice is
what makes dropout recovery a *simulated configuration* (FL_PyTorch,
arXiv:2202.03099) rather than a bolt-on: the privacy property is carried
by the protocol shape (who sends what to whom), while the key material is
replayable by construction. A production deployment swaps
``derive_secret`` for real entropy plus an advertise round-trip for the
public keys; every other line — masking arithmetic, share thresholds,
recovery rule — ships unchanged.
"""

from __future__ import annotations

import functools
import hashlib
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fedml_tpu.collectives import finite_field as ff

P_DEFAULT = ff.P_DEFAULT

# primitive root of GF(2^31 - 1) (the Lehmer/MINSTD generator base): its
# powers cover the whole multiplicative group, so pk = g^sk loses no key
# bits and the DH pair seeds s_ij range over the full field
GENERATOR = 7


def _x64(fn):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.enable_x64():
            return fn(*args, **kwargs)

    return wrapped


# ------------------------------------------------------------------ secrets
def derive_secret(seed: int, round_idx: int, tag: str, slot: int,
                  p: int = P_DEFAULT) -> int:
    """One per-(round, slot) secret in [1, p-1), sha256 counter-mode from
    the session seed — the replayable stand-in for client entropy (see
    module docstring)."""
    key = f"secagg|{seed}|{round_idx}|{tag}|{slot}".encode()
    h = hashlib.sha256(key).digest()
    return int.from_bytes(h[:8], "little") % (p - 2) + 1


def secret_key(seed: int, round_idx: int, slot: int,
               p: int = P_DEFAULT) -> int:
    """The slot's DH secret exponent for this round."""
    return derive_secret(seed, round_idx, "sk", slot, p)


def self_mask_seed(seed: int, round_idx: int, slot: int,
                   p: int = P_DEFAULT) -> int:
    """The slot's self-mask PRG seed b_i (Shamir-shared via
    :func:`self_mask_shares`)."""
    return derive_secret(seed, round_idx, "self", slot, p)


def public_key(sk: int, p: int = P_DEFAULT) -> int:
    """pk = g^sk mod p (advertised in a deployment; derived here)."""
    return pow(GENERATOR, sk, p)


def public_keys(seed: int, round_idx: int, cohort: int,
                p: int = P_DEFAULT) -> list[int]:
    """Every slot's public key for the round (the simulated advertise
    phase — each party computes the same list from the session seed)."""
    return [public_key(secret_key(seed, round_idx, s, p), p)
            for s in range(cohort)]


def pair_seed(sk_own: int, pk_peer: int, p: int = P_DEFAULT) -> int:
    """The shared pairwise mask seed: pk_peer^sk_own = g^(sk_i * sk_j),
    symmetric in (i, j) — only the two endpoints can compute it."""
    return pow(pk_peer, sk_own, p)


# ---------------------------------------------------------------------- PRG
# Counter-mode splitmix64: mask[k] = mix(seed + (k+1) * gamma) mod p. The
# modular reduction's bias is ~2^-33 per element — irrelevant for masking
# (the masks cancel exactly; only their distribution matters) and kept for
# a branch-free jittable expansion. prg_expand_np is the numpy oracle the
# tests pin the jitted path against.
_GAMMA = 0x9E3779B97F4A7C15
_MIX1 = 0xBF58476D1CE4E5B9
_MIX2 = 0x94D049BB133111EB


def _prg_body(seed, n: int, p: int):
    k = jnp.arange(1, n + 1, dtype=jnp.uint64)
    z = seed + k * jnp.uint64(_GAMMA)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(_MIX1)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(_MIX2)
    z = z ^ (z >> jnp.uint64(31))
    return (z % jnp.uint64(p)).astype(jnp.int64)


# module-level jitted entry points: jax caches executables per callable
# object, so these must be created ONCE (a jax.jit inside the function
# body would recompile the kernel on every call)
_prg_jit = jax.jit(_prg_body, static_argnums=(1, 2))


@_x64
def prg_expand(seed: int, n: int, p: int = P_DEFAULT):
    """Expand one seed into n field elements (jitted counter-PRG)."""
    return _prg_jit(jnp.asarray(seed, jnp.uint64), n, p)


def prg_expand_np(seed: int, n: int, p: int = P_DEFAULT) -> np.ndarray:
    """Numpy twin of :func:`prg_expand` — the replay oracle."""
    k = np.arange(1, n + 1, dtype=np.uint64)
    with np.errstate(over="ignore"):
        z = np.uint64(seed) + k * np.uint64(_GAMMA)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(_MIX1)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(_MIX2)
        z = z ^ (z >> np.uint64(31))
    return (z % np.uint64(p)).astype(np.int64)


def _mask_fold_body(vec, seeds, signs, n: int, p: int):
    """vec + sum_m signs[m] * PRG(seeds[m]) mod p, one fused scan."""

    def body(acc, sd_sign):
        sd, sg = sd_sign
        return (acc + sg * _prg_body(sd, n, p)) % p, None

    out, _ = lax.scan(body, vec % p, (seeds, signs))
    return out


_mask_fold_jit = jax.jit(_mask_fold_body, static_argnums=(3, 4))


@_x64
def apply_masks(vec, seeds, signs, p: int = P_DEFAULT):
    """Add (sign +1) / subtract (sign -1) the PRG expansions of ``seeds``
    onto an int64 field vector — the one jitted kernel both masking (the
    client) and unmasking (the server's recovery pass) run."""
    vec = jnp.asarray(vec, jnp.int64)
    seeds = jnp.asarray(seeds, jnp.uint64)
    signs = jnp.asarray(signs, jnp.int64)
    if seeds.shape[0] == 0:
        return vec % p
    return _mask_fold_jit(vec, seeds, signs, int(vec.shape[0]), p)


# ------------------------------------------------------------------- config
def default_threshold_t(cohort: int) -> int:
    """The adaptive Shamir-threshold default both runtimes share: t = 2
    where the cohort can carry it, degrading to t = 1 for 2-slot cohorts
    (t must stay <= cohort - 1 or nothing could ever reconstruct). One
    definition — the standalone engine and the cross-process tier must
    not fork it, or their recovery semantics silently diverge."""
    return max(1, min(2, int(cohort) - 1))


@dataclass(frozen=True)
class SecAggConfig:
    """One cohort's masking parameters.

    ``cohort``       K slots (== client_num_per_round);
    ``threshold_t``  Shamir degree t — stripping any self-mask (and hence
                     decoding any round, full or partial) needs shares
                     from >= t+1 cohort slots, so t+1 is also the
                     dropout-recovery threshold: fewer survivors => the
                     round sheds;
    ``quant_scale``  fixed-point scale for field_encode;
    ``max_abs``      loud capacity bound — every masked coordinate is
                     promised <= max_abs before quantization, and
                     construction verifies cohort * 2 * quant_scale *
                     max_abs < p (finite_field.assert_field_capacity) so
                     the summed field values cannot silently wrap.
    """

    cohort: int
    threshold_t: int = 2
    quant_scale: float = 2**16
    max_abs: float = 4.0
    p: int = P_DEFAULT

    def __post_init__(self):
        if not 1 <= self.threshold_t <= self.cohort - 1:
            # t=0 would put the secret verbatim in every share; t+1 >
            # cohort could never reconstruct even from a full round
            raise ValueError(
                f"threshold_t={self.threshold_t} needs t in [1, cohort-1="
                f"{self.cohort - 1}]: recovery reconstructs from t+1 "
                "survivor shares")
        ff.assert_field_capacity(self.cohort, self.quant_scale,
                                 self.max_abs, self.p)

    @property
    def recovery_min(self) -> int:
        """Minimum survivors for a decodable round."""
        return self.threshold_t + 1


# ------------------------------------------------------------- client side
def pair_masks_for(seed: int, round_idx: int, slot: int, cfg: SecAggConfig,
                   peers=None) -> tuple[np.ndarray, np.ndarray]:
    """(seeds, signs) of slot's pairwise masks against every other cohort
    slot: + for the lower slot of each pair, - for the higher, so the
    cohort sum cancels exactly.

    ``peers`` restricts the pair partners to the listed GLOBAL slot ids
    (default: the whole cohort). The hierarchical tier passes each edge
    block's slots, so masks cancel within a block and every edge can fold
    its block to an unmasked field partial locally; slot ids, keys, and
    seeds stay cohort-global, so a block-scoped round decodes to exactly
    the bits a flat round would."""
    sk = secret_key(seed, round_idx, slot, cfg.p)
    pks = public_keys(seed, round_idx, cfg.cohort, cfg.p)
    partners = range(cfg.cohort) if peers is None \
        else sorted(int(j) for j in peers)
    seeds, signs = [], []
    for j in partners:
        if j == slot:
            continue
        seeds.append(pair_seed(sk, pks[j], cfg.p))
        signs.append(1 if slot < j else -1)
    return (np.asarray(seeds, np.uint64), np.asarray(signs, np.int64))


def mask_update(vec, weight: float, slot: int, seed: int, round_idx: int,
                cfg: SecAggConfig, peers=None) -> np.ndarray:
    """Quantize ``vec * weight`` into GF(p) and add this slot's self +
    pairwise masks. Returns the int64 wire payload — the only thing a
    client ever uploads about its update. Enforces the capacity promise
    HERE, in the one function every engine masks through: a coordinate
    past ``cfg.max_abs`` would wrap the cohort sum mod p and decode to
    garbage with no error anywhere downstream."""
    scaled = np.asarray(vec, np.float64) * float(weight)
    peak = float(np.max(np.abs(scaled))) if scaled.size else 0.0
    if peak > cfg.max_abs:
        raise ValueError(
            f"masked update coordinate {peak:.4g} exceeds the capacity "
            f"promise max_abs={cfg.max_abs:g} — the cohort sum would "
            "wrap GF(p) silently (raise the max_abs promise / lower "
            "quant_scale, or clip the update)")
    with jax.enable_x64():
        q = jnp.asarray(
            ff.field_encode(jnp.asarray(scaled, jnp.float64),
                            cfg.quant_scale, cfg.p), jnp.int64)
    seeds, signs = pair_masks_for(seed, round_idx, slot, cfg, peers=peers)
    seeds = np.concatenate(
        [np.asarray([self_mask_seed(seed, round_idx, slot, cfg.p)],
                    np.uint64), seeds])
    signs = np.concatenate([np.asarray([1], np.int64), signs])
    return np.asarray(apply_masks(q, seeds, signs, cfg.p), np.int64)


def self_mask_shares(seed: int, round_idx: int, slot: int,
                     cfg: SecAggConfig) -> np.ndarray:
    """Shamir shares of this slot's self-mask seed, one per cohort slot
    (share k is addressed to slot k; a deployment encrypts it for k —
    the star relay ships it via the server, which can use at most the
    shares the survivor slots reveal)."""
    b = self_mask_seed(seed, round_idx, slot, cfg.p)
    key = jax.random.PRNGKey(
        derive_secret(seed, round_idx, "shamir", slot, cfg.p))
    with jax.enable_x64():
        shares = ff.shamir_encode(jnp.asarray([b], jnp.int64), key,
                                  cfg.cohort, cfg.threshold_t, cfg.p)
        return np.asarray(shares[:, 0], np.int64)


# ------------------------------------------------------------- server side
def fold_masked(acc, masked, p: int = P_DEFAULT):
    """The server's whole per-upload cost: one streaming add mod p."""
    masked = np.asarray(masked, np.int64)
    if acc is None:
        return masked % p
    return (acc + masked) % p


def _fold_masked_body(acc, masked, p: int):
    return (acc + masked) % p


_fold_masked_jit = jax.jit(_fold_masked_body, static_argnums=(2,))


@_x64
def fold_masked_device(acc, masked, p: int = P_DEFAULT):
    """Device-resident twin of :func:`fold_masked` — the ``fused_agg``
    treatment applied to masked ingest. The accumulator stays an int64
    device array and each arrival is one jitted add mod p, so the host
    never round-trips the vector per upload. Integer mod-p addition is
    exact and associative, so the result is bitwise identical to the host
    fold (the tests pin it)."""
    masked = jnp.asarray(masked, jnp.int64)
    if acc is None:
        return masked % p
    return _fold_masked_jit(acc, masked, p)


def recover_self_seed(holder_slots, shares, t: int,
                      p: int = P_DEFAULT) -> int:
    """Reconstruct one self-mask seed from the shares the listed holder
    slots revealed (>= t+1 required; Lagrange at 0 over alphas slot+1)."""
    holder_slots = [int(s) for s in holder_slots]
    if len(holder_slots) < t + 1:
        raise ValueError(
            f"self-mask recovery needs >= {t + 1} shares, got "
            f"{len(holder_slots)}")
    with jax.enable_x64():
        alphas = jnp.asarray([s + 1 for s in holder_slots], jnp.int64)
        sh = jnp.asarray(shares, jnp.int64).reshape(len(holder_slots), 1)
        return int(ff.shamir_decode(sh, alphas, t, p)[0])


def unmask_partial(acc, survivors, dead, self_seeds: dict[int, int],
                   pair_seeds_by_survivor: dict[int, dict[int, int]],
                   cfg: SecAggConfig) -> np.ndarray:
    """Strip the masks a partial (or full) sum still carries, staying in
    GF(p):

    - every SURVIVOR's self-mask PRG(b_i) (seeds reconstructed from the
      revealed Shamir shares);
    - for every (survivor i, dead j) pair the orphaned pairwise mask,
      with i's sign (the dead side never arrived).

    ``pair_seeds_by_survivor[i][j]`` is survivor i's revealed s_ij; a
    full round passes ``dead=[]`` and ``{}``.
    Returns the int64 FIELD vector — still additive, so edge partials
    unmasked here fold mod p at the root before one final decode (the
    hierarchical tier's whole trick: decode once, at the top)."""
    survivors, dead = sorted(int(s) for s in survivors), sorted(
        int(d) for d in dead)
    seeds, signs = [], []
    for i in survivors:
        seeds.append(self_seeds[i])
        signs.append(-1)
    for i in survivors:
        for j in dead:
            seeds.append(pair_seeds_by_survivor[i][j])
            signs.append(-1 if i < j else 1)  # undo i's + / - side
    return np.asarray(
        apply_masks(np.asarray(acc, np.int64),
                    np.asarray(seeds, np.uint64),
                    np.asarray(signs, np.int64), cfg.p), np.int64)


def field_decode_sum(acc, cfg: SecAggConfig) -> np.ndarray:
    """Decode an unmasked GF(p) sum to float64 (the one decode a round
    performs, flat or tree)."""
    with jax.enable_x64():
        return np.asarray(
            ff.field_decode(jnp.asarray(acc, jnp.int64), cfg.quant_scale,
                            cfg.p), np.float64)


def unmask_sum(acc, survivors, dead, self_seeds: dict[int, int],
               pair_seeds_by_survivor: dict[int, dict[int, int]],
               cfg: SecAggConfig) -> np.ndarray:
    """:func:`unmask_partial` + :func:`field_decode_sum`: the flat-cohort
    path — strip every mask, decode once, return the float64 weighted SUM
    over the survivors."""
    return field_decode_sum(
        unmask_partial(acc, survivors, dead, self_seeds,
                       pair_seeds_by_survivor, cfg), cfg)
