"""Buffered asynchronous rounds — staleness policies, the bounded staging
buffer, and a virtual-clock async simulator (FedBuff-style, arXiv:2106.06639
via PAPERS.md; server-side acceleration composes through the engine's
server_update hook, FedAc arXiv:2006.08950; ingest-overlap server design
after arXiv:2307.06561).

The synchronous server is a round barrier: one straggling or crashed rank
owns the round's critical path (PR 3's attribution proves exactly where).
This module removes the barrier:

- clients train and upload **continuously** against possibly-stale globals;
- the server aggregates as soon as a buffer of K sanitized arrivals fills
  (or a deadline fires), weighting each update by a pluggable **staleness
  discount** (constant / polynomial / exponential — all jittable, each with
  a numpy oracle twin, test-enforced);
- **admission control** rejects-and-requeues updates staler than a bound
  and skips dispatching to ranks whose ``fed_last_heartbeat_age_seconds``
  marks them suspect;
- **backpressure**: the staging buffer is bounded — overflow sheds the
  stalest pending update (counted in ``fed_async_shed_total{reason}``),
  never blocks dispatch.

Degenerate contract (test-enforced): ``K = cohort`` with staleness bound 0
reduces **bitwise** to the synchronous path — model bits AND quarantine
ledger — because every composition point (per-client local fit, the PR-4
``gated_aggregate`` gate, ``_update_from_aggregate``, the rng chain) is the
same code the sync driver runs, just invoked from the event loop instead of
the barrier.

Two consumers share these pieces:

- :class:`VirtualClockAsyncRunner` — a discrete-event simulator over a
  ``FedAvgAPI`` engine. The clock is virtual (each dispatch takes
  ``base_duration_s`` plus any chaos straggle delay scheduled for its
  (rank, wave)), so async-vs-sync wall-clock claims are deterministic,
  tier-1-testable, and replay bit-for-bit;
- the cross-process ``FedAvgServerManager(async_buffer_k=...)`` — the same
  :class:`AsyncBuffer`/:class:`StalenessPolicy` driving the real
  event-driven wire loop (distributed/fedavg/server_manager.py).
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.obs import perf_instrument as _perf

log = logging.getLogger("fedml_tpu.async_buffer")

STALENESS_KINDS = ("constant", "polynomial", "exponential")

# shed-reason vocabulary for fed_async_shed_total{reason}; admission and
# backpressure verdicts share it so dashboards see one family ('suspect'
# is the cross-process server's heartbeat-admission skip; 'undecodable' is
# an encoded uplink — top-k / delta / quantized, comm/delta.py — whose
# payload was structural garbage: quarantined at decode, requeued). Note
# encoded uplinks also shed 'stale' when their versioned base was evicted
# from the server's bounded broadcast stash.
# 'server_restart' is the crash-recovery shed (docs/ROBUSTNESS.md §Server
# crash recovery): work that was in flight when the server died — the
# WAL-journaled buffer entries lost with the process, and post-restart
# arrivals whose echoed restart_epoch predates the recovery.
# 'offline' is SCHEDULED unavailability (chaos/churn.py ChurnTrace): the
# slot/rank is away by the trace, not dead — skipped silently with no
# suspect bookkeeping or reprobe churn, counted here so the export still
# shows where round capacity went.
SHED_REASONS = ("stale", "overflow", "nonfinite", "crash", "suspect",
                "undecodable", "server_restart", "offline")


# ------------------------------------------------------ staleness discounts
def make_staleness_fn(kind: str, a: float = 0.5) -> Callable:
    """Jittable discount ``s -> weight multiplier`` over an int/float
    staleness array (s = server version at aggregation minus the version
    the update trained against). The FedBuff/FedAsync menu:

    - ``constant``:    1 (staleness-blind — the FedBuff paper's default);
    - ``polynomial``:  (1 + s)^-a  (FedAsync's poly discount);
    - ``exponential``: exp(-a * s).

    ``constant`` multiplies by exactly 1.0, so the staleness-0 weights are
    BITWISE the synchronous sample weights (the degenerate-parity
    contract's weight half).
    """
    if kind not in STALENESS_KINDS:
        raise ValueError(f"unknown staleness kind {kind!r} "
                         f"(one of {STALENESS_KINDS})")
    a = float(a)
    if kind == "constant":
        return lambda s: jnp.ones_like(jnp.asarray(s, jnp.float32))
    if kind == "polynomial":
        return lambda s: (1.0 + jnp.asarray(s, jnp.float32)) ** (-a)
    return lambda s: jnp.exp(-a * jnp.asarray(s, jnp.float32))


def staleness_oracle(kind: str, a: float = 0.5) -> Callable:
    """Numpy twin of :func:`make_staleness_fn` — the test oracle, and what
    the cross-process server uses host-side (weights are [K] scalars; a jit
    round-trip per arrival would be pure overhead)."""
    if kind not in STALENESS_KINDS:
        raise ValueError(f"unknown staleness kind {kind!r} "
                         f"(one of {STALENESS_KINDS})")
    a = float(a)
    if kind == "constant":
        return lambda s: np.ones_like(np.asarray(s, np.float32))
    if kind == "polynomial":
        return lambda s: (1.0 + np.asarray(s, np.float32)) ** (-a)
    return lambda s: np.exp(-a * np.asarray(s, np.float32)).astype(np.float32)


@dataclasses.dataclass(frozen=True)
class StalenessPolicy:
    """Discount kind + parameter + admission bound, with the CLI spec
    parser (``--staleness``): 'constant' | 'poly:0.5' | 'exp:0.3'.

    ``bound``: an arriving update with staleness > bound is REJECTED and
    its rank requeued with the fresh model (admission control); None = any
    staleness admitted (discount-only). ``bound == 0`` additionally parks
    uploaded ranks until the next flush — work started pre-flush would be
    born stale and rejected, so bound-0 IS the synchronous barrier
    expressed in the async machinery (the degenerate-parity mode).
    """

    kind: str = "constant"
    a: float = 0.5
    bound: int | None = None

    def __post_init__(self):
        if self.kind not in STALENESS_KINDS:
            raise ValueError(f"unknown staleness kind {self.kind!r} "
                             f"(one of {STALENESS_KINDS})")
        if self.bound is not None and self.bound < 0:
            raise ValueError(f"staleness bound must be >= 0, got {self.bound}")

    @classmethod
    def from_spec(cls, spec, bound: int | None = None) -> "StalenessPolicy":
        """'constant' | 'poly:A' | 'polynomial:A' | 'exp:A' |
        'exponential:A' (A = the discount's decay parameter), or an
        already-built policy (passed through; ``bound`` then overrides
        only when given)."""
        if isinstance(spec, StalenessPolicy):
            if bound is None:
                return spec
            return dataclasses.replace(spec, bound=bound)
        name, _, arg = str(spec or "constant").partition(":")
        name = {"poly": "polynomial", "exp": "exponential"}.get(
            name.strip().lower(), name.strip().lower())
        return cls(kind=name, a=float(arg) if arg else 0.5, bound=bound)

    def discount(self) -> Callable:
        return make_staleness_fn(self.kind, self.a)

    def discount_np(self) -> Callable:
        return staleness_oracle(self.kind, self.a)

    def admits(self, staleness: int) -> bool:
        return self.bound is None or staleness <= self.bound

    @property
    def synchronous(self) -> bool:
        """bound == 0: park-until-flush (see class docstring)."""
        return self.bound == 0


# --------------------------------------------------------------- the buffer
@dataclasses.dataclass
class BufferedUpdate:
    """One sanitized arrival staged for the next buffered aggregate.
    ``payload`` is runtime-shaped: staged wire leaves cross-process, a
    per-client NetState in the simulator. ``version`` is the global model
    version the update trained against (staleness at flush = current
    version - this)."""

    rank: int          # 1-based worker rank (sim: slot + 1)
    client: int        # the client id this dispatch trained
    version: int
    wave: int          # the rank's dispatch counter (sampling key)
    payload: object
    nsamp: float
    seq: int           # global arrival sequence (deterministic tie-break)
    t_arrival: float


class AsyncBuffer:
    """Bounded staging buffer between ingest and the buffered aggregate.

    ``add`` never blocks: past ``capacity`` the STALEST pending update
    (lowest trained-against version, oldest arrival on ties) is shed and
    returned to the caller to count (``fed_async_shed_total{overflow}``) —
    backpressure degrades the oldest information first instead of stalling
    the dispatch path. NOTE the inline-flush drivers (the simulator and
    the async server both flush the moment ``ready`` trips, inside the
    same lock/loop that staged the arrival) keep ``len`` structurally at
    or below ``flush_threshold`` <= ``capacity``, so for them the bound is
    enforced by immediate flushing and the shed path is the backstop for
    any driver that defers flushes (a future queue-the-flush server).
    ``drain`` returns entries sorted by (rank, seq): a deterministic
    stacking order — at K = cohort exactly the sync engine's slot order,
    which is half of the bitwise-parity contract.

    Not thread-safe by itself: the cross-process server mutates it under
    its round lock; the simulator is single-threaded.
    """

    def __init__(self, k: int, capacity: int | None = None, journal=None):
        k = int(k)
        if k < 1:
            raise ValueError(f"async buffer k must be >= 1, got {k}")
        self.k = k
        self.capacity = int(capacity) if capacity is not None else 2 * k
        if self.capacity < 1:
            raise ValueError(f"buffer capacity must be >= 1, "
                             f"got {self.capacity}")
        # crash-recovery journal hook (docs/ROBUSTNESS.md §Server crash
        # recovery): callable(event, entry) invoked on 'admit'/'shed' so
        # the server's WAL records buffer membership — a restarted server
        # ledgers exactly the entries that died with the process. None =
        # the pre-WAL behavior, zero extra work.
        self.journal = journal
        self._entries: list[BufferedUpdate] = []

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def flush_threshold(self) -> int:
        """K, clamped by capacity (a capacity below K must still flush)."""
        return min(self.k, self.capacity)

    @property
    def ready(self) -> bool:
        return len(self._entries) >= self.flush_threshold

    def first_arrival_t(self) -> float | None:
        return min((e.t_arrival for e in self._entries), default=None)

    def add(self, entry: BufferedUpdate) -> list[BufferedUpdate]:
        """Stage one arrival; returns the entries shed to stay within
        capacity (stalest first), possibly including the new entry itself
        when it is the stalest of the lot."""
        self._entries.append(entry)
        if self.journal is not None:
            self.journal("admit", entry)
        shed: list[BufferedUpdate] = []
        while len(self._entries) > self.capacity:
            victim = min(self._entries, key=lambda e: (e.version, e.seq))
            self._entries.remove(victim)
            shed.append(victim)
            if self.journal is not None:
                self.journal("shed", victim)
        return shed

    def drain(self) -> list[BufferedUpdate]:
        entries, self._entries = self._entries, []
        return sorted(entries, key=lambda e: (e.rank, e.seq))


# ------------------------------------------------- virtual-clock simulator
def straggle_delay_s(plan, rank: int, wave: int) -> float:
    """Total chaos straggle delay for a (rank, wave) dispatch under a
    FaultPlan — the virtual clock's duration model. Matches rules with the
    injector's own ``matches_link`` on the UPLINK (direction 'send',
    rank -> server 0 — exactly the link the wire injector sleeps on), so
    a plan written for the wire runtime means the same schedule here; a
    'recv'-direction rule never applies. ``link_seq`` := wave, so
    probabilistic rules stay a pure function of (seed, rule, rank, wave)
    and the simulated run replays bit-for-bit."""
    if plan is None:
        return 0.0
    total = 0.0
    for i, rule in enumerate(plan.rules):
        if rule.fault != "straggle" or not rule.in_window(wave):
            continue
        if not rule.matches_link("send", rank, 0):
            continue
        if plan.fires(i, "send", rank, 0, wave):
            total += rule.delay_s
    return total


def crashed_in_wave(plan, rank: int, wave: int) -> bool:
    if plan is None:
        return False
    return any(r.fault == "crash" and rank in (r.ranks or ())
               and r.in_window(wave) for r in plan.rules)


def sync_virtual_wallclock(plan, n_ranks: int, num_rounds: int,
                           base_duration_s: float = 1.0) -> float:
    """The synchronous barrier's virtual wall-clock under the same duration
    model the async simulator uses: each round costs the MAX over the
    cohort's dispatch durations (the straggler owns the round — PR 3's
    critical-path attribution, now a closed form). The async-beats-sync
    acceptance compares the simulator's clock against this."""
    total = 0.0
    for r in range(num_rounds):
        total += max(base_duration_s + straggle_delay_s(plan, rank, r)
                     for rank in range(1, n_ranks + 1))
    return total


class VirtualClockAsyncRunner:
    """Discrete-event buffered-async driver over a ``FedAvgAPI`` engine.

    Worker slots (one per cohort position, mirroring the cross-process
    worker ranks) train continuously: slot j's wave-w dispatch trains
    client ``engine._sampled_ids(w)[j]`` with the SAME
    ``fold_in(fold_in(seed, wave), client)`` key chain as both runtimes,
    against a snapshot of the global model at dispatch time. Arrivals pass
    admission (staleness bound -> requeue; non-finite -> quarantined,
    NEVER buffered) into the :class:`AsyncBuffer`; a full buffer (or a
    virtual deadline) flushes: staleness-discounted ``gated_aggregate``
    (the engine's own gate/estimator settings), then the engine's
    ``_update_from_aggregate`` — the ONE server-side composition, so
    FedOpt/FedAc server momentum and post-aggregate hooks apply on top of
    the buffered aggregate exactly as they do synchronously.

    Everything is a pure function of (engine seed, chaos plan, policy), so
    a seeded async chaos run replays bit-for-bit (test-enforced).
    """

    def __init__(self, engine, buffer_k: int, staleness="constant",
                 staleness_bound: int | None = None,
                 deadline_s: float | None = None,
                 capacity: int | None = None,
                 chaos_plan=None, adversary_plan=None,
                 base_duration_s: float = 1.0):
        if engine.mesh is not None:
            raise ValueError("the async simulator is a standalone "
                             "(single-device) driver; run the cross-process "
                             "runtime for meshed/sharded async")
        if engine.client_result_hook is not None or \
                engine._adversary is not None:
            raise ValueError(
                "the async simulator composes adversaries per-arrival "
                "(adversary_plan=) and has no per-client hook path — build "
                "the engine without client_result_hook/adversary_plan")
        self.engine = engine
        self.policy = StalenessPolicy.from_spec(staleness,
                                                bound=staleness_bound)
        self.buffer = AsyncBuffer(buffer_k, capacity=capacity)
        self.deadline_s = deadline_s
        self.chaos_plan = chaos_plan
        self.adversary_plan = adversary_plan
        self.base_duration_s = float(base_duration_s)
        self._fit = jax.jit(engine.local_update)
        self._flush_fn = self._build_flush_fn()
        _perf.ensure_async_shed_families()
        self.version = 0
        self.clock = 0.0
        self.shed_counts = {r: 0 for r in SHED_REASONS}
        self.staleness_seen: list[int] = []
        self.history: list[dict] = []
        self._seq = 0
        self._epoch = 0  # buffer epoch: stale deadline events are ignored
        n = engine.cfg.client_num_per_round
        self._wave = [0] * n
        self._parked: list[int] = []  # bound-0 mode: slots awaiting a flush

    # ------------------------------------------------------------- programs
    def _build_flush_fn(self):
        """The buffered-aggregate program: staleness-discounted weights
        (in-graph, via the jittable discount) -> the engine's gate/
        estimator -> ``_update_from_aggregate``. Compiled once per buffer
        size; at K = cohort / bound 0 its inputs and every op match the
        sync ``_aggregate_and_update`` composition, which is why the
        degenerate mode is bitwise."""
        from fedml_tpu.algorithms.fedavg import agg_weights
        from fedml_tpu.core.robust_agg import gated_aggregate
        from fedml_tpu.utils.tree import tree_weighted_mean

        engine = self.engine
        discount = self.policy.discount()

        @jax.jit
        def flush(stacked, net, opt, nsamp, stale, kp):
            w = agg_weights(nsamp, engine.uniform_avg) * discount(stale)
            if engine._needs_stacked:
                avg, _, reasons = gated_aggregate(
                    stacked, net, w, robust_fn=engine._robust_agg,
                    norm_mult=engine._sanitize_mult)
            else:
                avg = tree_weighted_mean(stacked, w)
                reasons = jnp.zeros(nsamp.shape, jnp.int32)
            new_net, new_opt = engine._update_from_aggregate(
                net, avg, opt, kp)
            return new_net, new_opt, reasons

        return flush

    # ---------------------------------------------------------------- queue
    def _dispatch(self, heap, slot: int, t: float):
        """Slot becomes free at virtual time ``t``: assign its next wave's
        client, snapshot the current global, schedule the arrival."""
        wave = self._wave[slot]
        self._wave[slot] += 1
        dur = self.base_duration_s + straggle_delay_s(
            self.chaos_plan, slot + 1, wave)
        self._seq += 1
        ids = self.engine._sampled_ids(wave)
        if slot >= len(ids):
            # scheduled-offline (churn trace): this wave's available
            # cohort is smaller than the slot count — the slot idles
            # through the wave and retries the next one. Deliberately NOT
            # the dead path: no suspect bookkeeping, just the 'offline'
            # shed counter so stats() show where wave capacity went
            heapq.heappush(heap, (t + dur, self._seq, "arrival",
                                  {"slot": slot, "wave": wave,
                                   "offline": True}))
            return
        item = {
            "slot": slot, "wave": wave,
            "client": int(ids[slot]),
            "version": self.version,
            "net": self.engine.net,  # snapshot ref (immutable jax arrays)
            "dead": crashed_in_wave(self.chaos_plan, slot + 1, wave),
        }
        heapq.heappush(heap, (t + dur, self._seq, "arrival", item))

    def _compute_arrival(self, item):
        """The arrival's local fit — the same per-client program the
        cross-process trainer jits (vmapped-row ≡ single-client equality
        is already test-enforced by the loopback ≡ standalone suite)."""
        from fedml_tpu.core.client_data import pack_clients

        eng = self.engine
        cid, wave = item["client"], item["wave"]
        cb = pack_clients(eng.data, [cid], eng.cfg.batch_size,
                          max_batches=eng.num_batches, seed=eng.cfg.seed,
                          round_idx=wave)
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(eng.cfg.seed), wave), cid)
        net_k, metrics = self._fit(key, item["net"], cb.x[0], cb.y[0],
                                   cb.mask[0])
        if self.adversary_plan is not None:
            from fedml_tpu.chaos.adversary import perturb_leaves
            from fedml_tpu.comm.message import pack_pytree, unpack_pytree

            leaves = perturb_leaves(
                self.adversary_plan, pack_pytree(net_k),
                pack_pytree(item["net"]), item["slot"] + 1, wave)
            net_k = unpack_pytree(net_k, leaves)
        return net_k, float(cb.num_samples[0]), metrics

    def _shed(self, reason: str):
        self.shed_counts[reason] = self.shed_counts.get(reason, 0) + 1
        _perf.record_async_shed(reason)

    @staticmethod
    def _finite(net) -> bool:
        return all(np.isfinite(np.asarray(v)).all()
                   for v in jax.tree.leaves(net)
                   if np.issubdtype(np.asarray(v).dtype, np.floating))

    # ---------------------------------------------------------------- flush
    def _flush(self, t: float):
        eng = self.engine
        entries = self.buffer.drain()
        self._epoch += 1
        if not entries:
            return
        stale = [self.version - e.version for e in entries]
        self.staleness_seen.extend(stale)
        for s in stale:
            _perf.record_update_staleness(s)
        first_t = min(e.t_arrival for e in entries)
        _perf.record_buffer_fill(t - first_t)

        stacked = jax.tree.map(lambda *vs: jnp.stack(vs),
                               *[e.payload for e in entries])
        nsamp = jnp.asarray([e.nsamp for e in entries], jnp.float32)
        stale_v = jnp.asarray(stale, jnp.int32)
        # the sync driver's exact rng chain (one split per global update;
        # round_fn's internal 3-way split mirrored for the hook key)
        eng.rng, rk = jax.random.split(eng.rng)
        _, _, kp = jax.random.split(rk, 3)
        old_net = eng.net
        eng.net, eng.server_opt_state, reasons = self._flush_fn(
            stacked, eng.net, eng.server_opt_state, nsamp, stale_v, kp)
        if eng._needs_stacked:
            eng.quarantine.record_codes(
                self.version, np.asarray(reasons),
                clients=[e.client for e in entries],
                ranks=[e.rank for e in entries])
        rec = {
            "update": self.version, "t": round(t, 6), "k": len(entries),
            "staleness": stale, "buffer_fill_s": round(t - first_t, 6),
            "shed": dict(self.shed_counts),
            "clients": [e.client for e in entries],
        }
        self.history.append(rec)
        if eng.telemetry is not None:
            upd_sq = sum(
                float(np.sum((np.asarray(a) - np.asarray(b)) ** 2))
                for a, b in zip(jax.tree.leaves(eng.net.params),
                                jax.tree.leaves(old_net.params)))
            q = eng.quarantine.for_round(self.version)
            eng.telemetry.emit_round(
                self.version, clients=[e.client for e in entries],
                metrics={"update_norm": float(np.sqrt(upd_sq)),
                         "num_samples": float(np.sum(np.asarray(nsamp)))},
                **{"async": {"k": len(entries), "staleness": stale,
                             "buffer_fill_s": round(t - first_t, 6),
                             "shed": dict(self.shed_counts)}},
                **({"quarantine": q} if q else {}))
        self.version += 1

    # ------------------------------------------------------------------ run
    def run(self, num_updates: int):
        """Drive the event loop until ``num_updates`` buffered aggregates
        landed; returns the engine's NetState. ``self.clock`` is the
        virtual wall-clock of the last flush — compare against
        :func:`sync_virtual_wallclock` for the async-beats-sync claim."""
        eng = self.engine
        heap: list = []
        for slot in range(eng.cfg.client_num_per_round):
            self._dispatch(heap, slot, 0.0)
        events_since_flush = 0
        while self.version < num_updates:
            if not heap:
                raise RuntimeError(
                    "async simulator starved: every slot is parked and the "
                    "buffer cannot fill (k > cohort with bound 0?)")
            if events_since_flush > 10_000:
                # no-progress guard: e.g. a rank crashed for the whole run
                # holds the buffer below K forever with no deadline to
                # flush partial — fail loudly instead of spinning
                raise RuntimeError(
                    f"async simulator made no progress over "
                    f"{events_since_flush} events (buffer {len(self.buffer)}"
                    f"/{self.buffer.flush_threshold}, shed "
                    f"{self.shed_counts}) — a dark rank can hold the buffer "
                    "below K forever; lower buffer_k or set deadline_s")
            events_since_flush += 1
            t, _, kind, item = heapq.heappop(heap)
            if kind == "deadline":
                if item["epoch"] == self._epoch and len(self.buffer):
                    self._flush(t)
                    self.clock = t
                    events_since_flush = 0
                    for slot in self._drain_parked():
                        self._dispatch(heap, slot, t)
                continue
            slot = item["slot"]
            if item.get("offline"):
                # scheduled-offline wave: retry at the next wave's cohort
                self._shed("offline")
                self._dispatch(heap, slot, t)
                continue
            if item["dead"]:
                # a crashed rank's dispatch produces nothing; the slot
                # burns the wave and re-dispatches (rejoin after window)
                self._shed("crash")
                self._dispatch(heap, slot, t)
                continue
            staleness = self.version - item["version"]
            if not self.policy.admits(staleness):
                # admission control: reject-and-requeue with a fresh model
                self._shed("stale")
                self._dispatch(heap, slot, t)
                continue
            net_k, nsamp, _metrics = self._compute_arrival(item)
            if not self._finite(net_k):
                # PR-4 quarantine at the door: a non-finite arrival never
                # enters the buffer (the in-buffer gate still covers norm
                # outliers, where the verdict needs the cohort's median)
                eng.quarantine.record(self.version, slot + 1, "nonfinite",
                                      client=item["client"])
                from fedml_tpu.obs import comm_instrument as _obs

                _obs.record_update_rejected("nonfinite")
                self._shed("nonfinite")
                self._dispatch(heap, slot, t)
                continue
            self._seq += 1
            if len(self.buffer) == 0 and self.deadline_s is not None:
                heapq.heappush(heap, (t + self.deadline_s, self._seq,
                                      "deadline", {"epoch": self._epoch}))
                self._seq += 1
            for _victim in self.buffer.add(BufferedUpdate(
                    rank=slot + 1, client=item["client"],
                    version=item["version"], wave=item["wave"],
                    payload=net_k, nsamp=nsamp, seq=self._seq,
                    t_arrival=t)):
                # counting is all a victim needs — its slot already got its
                # park-or-redispatch when the shed entry was consumed (the
                # inline flush below keeps this a deferred-flush backstop:
                # len never exceeds flush_threshold <= capacity here)
                self._shed("overflow")
            if self.policy.synchronous:
                # bound 0 = the barrier: work dispatched now would be born
                # stale post-flush — park the slot until the flush lands
                self._parked.append(slot)
            else:
                self._dispatch(heap, slot, t)
            if self.buffer.ready:
                self._flush(t)
                self.clock = t
                events_since_flush = 0
                for s in self._drain_parked():
                    self._dispatch(heap, s, t)
        return eng.net

    def _drain_parked(self) -> list[int]:
        parked, self._parked = self._parked, []
        return parked

    def stats(self) -> dict:
        st = self.staleness_seen
        return {
            "updates": self.version,
            "wallclock": round(self.clock, 6),
            "shed": dict(self.shed_counts),
            "staleness_mean": float(np.mean(st)) if st else 0.0,
            "staleness_max": int(max(st)) if st else 0,
        }
