"""Topology managers for decentralized FL (L2).

Re-design of fedml_core/distributed/topology/: ring-with-random-links
topologies and row-normalized mixing matrices
(symmetric_topology_manager.py:21-52, asymmetric_topology_manager.py) and the
standalone variant (fedml_api/standalone/decentralized/topology_manager.py:5-142).
The reference builds networkx graphs; here topologies are plain numpy mixing
matrices W plus ppermute edge schedules, the two forms the TPU collectives
consume (collectives.ops.mix_with_topology / ppermute_tree).
"""

from __future__ import annotations

import numpy as np


class SymmetricTopologyManager:
    """Undirected ring + random symmetric extra links, equal-weight rows.

    ``neighbor_num`` counts ring neighbors per side like the reference's
    Watts-Strogatz base (k nearest neighbors); ``undirected_neighbor_num``
    adds random symmetric links.
    """

    def __init__(self, n: int, neighbor_num: int = 2, seed: int = 0):
        self.n = n
        self.neighbor_num = min(neighbor_num, max(n - 1, 0))
        self.seed = seed
        self.topology: np.ndarray | None = None

    def generate_topology(self):
        n, k = self.n, self.neighbor_num
        rng = np.random.RandomState(self.seed)
        A = np.eye(n, dtype=np.float64)
        # ring lattice: connect each node to k nearest neighbors (both sides)
        for i in range(n):
            for d in range(1, k // 2 + 1):
                A[i, (i + d) % n] = 1.0
                A[i, (i - d) % n] = 1.0
        # random symmetric rewiring/additions (WS-style randomness)
        extra = rng.rand(n, n) < (k / max(n, 1)) * 0.5
        extra = np.triu(extra, 1)
        A = np.clip(A + extra + extra.T, 0, 1)
        # row-normalize to a doubly-stochastic-ish mixing matrix
        W = A / A.sum(axis=1, keepdims=True)
        self.topology = W
        return W

    def get_in_neighbor_idx_list(self, node: int) -> list[int]:
        W = self.topology
        return [j for j in range(self.n) if W[node, j] > 0 and j != node]

    def get_out_neighbor_idx_list(self, node: int) -> list[int]:
        W = self.topology
        return [j for j in range(self.n) if W[j, node] > 0 and j != node]

    def get_in_neighbor_weights(self, node: int) -> np.ndarray:
        return self.topology[node]

    def get_out_neighbor_weights(self, node: int) -> np.ndarray:
        return self.topology[:, node]


class AsymmetricTopologyManager(SymmetricTopologyManager):
    """Directed topology: ring base + random directed extra edges, so the
    mixing matrix is row-stochastic but not symmetric (the PushSum setting)."""

    def generate_topology(self):
        n, k = self.n, self.neighbor_num
        rng = np.random.RandomState(self.seed)
        A = np.eye(n, dtype=np.float64)
        for i in range(n):
            for d in range(1, k // 2 + 1):
                A[i, (i + d) % n] = 1.0
        A = np.clip(A + (rng.rand(n, n) < (k / max(n, 1)) * 0.5), 0, 1)
        W = A / A.sum(axis=1, keepdims=True)
        self.topology = W
        return W


def ring_permutation(n: int, shift: int = 1) -> list[tuple[int, int]]:
    """ppermute schedule for a directed ring: device i -> i+shift (mod n)."""
    return [(i, (i + shift) % n) for i in range(n)]


def topology_to_ppermutes(W: np.ndarray) -> list[list[tuple[int, int]]]:
    """Decompose a sparse topology into a minimal set of ppermute schedules.

    Each schedule is a partial permutation (each src/dst used at most once);
    edges are greedily packed so dense rings need 1-2 schedules instead of
    one all_gather. Self-loops are excluded (local term is added separately).
    """
    n = W.shape[0]
    edges = [(i, j) for i in range(n) for j in range(n) if i != j and W[j, i] > 0]
    # edge (src=i, dst=j) delivers i's value to j (W[j, i] weights arrivals at j)
    schedules: list[list[tuple[int, int]]] = []
    remaining = edges
    while remaining:
        used_src, used_dst, batch, rest = set(), set(), [], []
        for (s, d) in remaining:
            if s not in used_src and d not in used_dst:
                batch.append((s, d)); used_src.add(s); used_dst.add(d)
            else:
                rest.append((s, d))
        schedules.append(batch)
        remaining = rest
    return schedules
