"""Robust aggregation defenses (L2).

Port of fedml_core/robustness/robust_aggregation.py: norm-difference clipping
(:38-49) and weak-DP Gaussian noise (:51-55), as pure pytree functions that
run on device inside the aggregation program instead of host-side torch ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.utils.tree import tree_global_norm


def norm_diff_clipping(local_net, global_net, norm_bound: float):
    """Clip the client->server update (w_local - w_global) to an L2 ball of
    radius norm_bound, then re-add the global weights
    (robust_aggregation.py:38-49)."""
    diff = jax.tree.map(jnp.subtract, local_net, global_net)
    norm = tree_global_norm(diff)
    scale = jnp.minimum(1.0, norm_bound / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g, d: g + d * scale, global_net, diff)


def add_gaussian_noise(rng, net, stddev: float):
    """Weak differential privacy: add N(0, stddev^2) to every weight
    (robust_aggregation.py:51-55)."""
    leaves, treedef = jax.tree.flatten(net)
    keys = jax.random.split(rng, len(leaves))
    noisy = [
        x + stddev * jax.random.normal(k, x.shape, x.dtype)
        for x, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noisy)
