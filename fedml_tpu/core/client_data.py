"""Fixed-shape packing of ragged per-client data (the XLA-friendly data plane).

The reference hands each client a python DataLoader over its own index subset
(train_data_local_dict, e.g. fedml_api/data_preprocessing/cifar10/data_loader.py:433+),
so clients naturally have ragged sample counts. XLA wants static shapes, so a
round's sampled clients are packed into one dense array block:

  x    [K, B, bs, ...]   K clients, B batches each, bs samples per batch
  y    [K, B, bs, ...]
  mask [K, B, bs]        1.0 for real samples, 0.0 for padding

Clients with fewer than B*bs samples are padded; the mask zeroes padded
samples out of the loss, and a zero-gradient SGD step is a no-op, so a padded
client takes exactly as many *effective* steps as its real batch count —
matching the reference's "iterate your own dataloader" semantics for plain
SGD. True sample counts ride along for exact sample-weighted aggregation.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ClientBatch:
    """One round's packed client data. Leaves are arrays with leading dim K."""

    x: Any          # [K, B, bs, ...]
    y: Any          # [K, B, bs, ...]
    mask: Any       # [K, B, bs] float32
    num_samples: Any  # [K] float32 — true (unpadded) counts

    @property
    def num_clients(self) -> int:
        return self.x.shape[0]

    @property
    def num_batches(self) -> int:
        return self.x.shape[1]

    @property
    def batch_size(self) -> int:
        return self.x.shape[2]


@dataclasses.dataclass
class FederatedData:
    """Host-side federated dataset: global arrays + the client index map.

    Mirrors the reference 8-tuple loader contract
    (train_data_num, test_data_num, train_data_global, test_data_global,
    train_data_local_num_dict, train_data_local_dict, test_data_local_dict,
    class_num — e.g. cifar10/data_loader.py:468) in one structure.
    """

    train_x: np.ndarray
    train_y: np.ndarray
    test_x: np.ndarray
    test_y: np.ndarray
    train_idx_map: dict[int, np.ndarray]   # client -> indices into train_*
    test_idx_map: dict[int, np.ndarray] | None
    class_num: int

    @property
    def num_clients(self) -> int:
        return len(self.train_idx_map)

    @property
    def train_data_local_num_dict(self) -> dict[int, int]:
        return {c: len(ix) for c, ix in self.train_idx_map.items()}

    def as_eight_tuple(self):
        """The reference's 8-tuple, for API parity."""
        return (
            len(self.train_x),
            len(self.test_x),
            (self.train_x, self.train_y),
            (self.test_x, self.test_y),
            self.train_data_local_num_dict,
            self.train_idx_map,
            self.test_idx_map,
            self.class_num,
        )


def subset_clients(data: FederatedData, client_ids) -> FederatedData:
    """Rank-local view holding ONLY the given clients' train rows — the
    analogue of the reference's ``load_partition_data_distributed_<ds>``
    variants that load just that rank's shard (e.g.
    FederatedEMNIST/data_loader.py:70+, cifar10/data_loader.py:433).

    Client ids keep their GLOBAL numbering (the server's sampled index is
    looked up unchanged); accessing a client outside the subset raises
    KeyError — loudly, instead of silently training on absent data. The
    global test set is kept whole (every rank evaluates the same way the
    reference's distributed loaders do)."""
    client_ids = [int(c) for c in client_ids]
    rows = [np.asarray(data.train_idx_map[c], np.int64) for c in client_ids]
    flat = np.concatenate(rows) if rows else np.zeros((0,), np.int64)
    new_map: dict[int, np.ndarray] = {}
    off = 0
    for c, r in zip(client_ids, rows):
        new_map[c] = np.arange(off, off + len(r), dtype=np.int64)
        off += len(r)
    test_map = None
    if data.test_idx_map is not None:
        # test rows stay global-array-indexed; keep only subset keys
        test_map = {c: data.test_idx_map[c] for c in client_ids
                    if c in data.test_idx_map}
    return dataclasses.replace(
        data,
        train_x=data.train_x[flat],
        train_y=data.train_y[flat],
        train_idx_map=new_map,
        test_idx_map=test_map,
    )


_U64 = (1 << 64) - 1


def _splitmix_shuffle(idx: np.ndarray, seed: int) -> None:
    """In-place Fisher-Yates with splitmix64 — bit-identical to the C++
    packer's shuffle (native/packer.cpp pack_one_client).

    The splitmix state at step t is the affine seed + t*GOLDEN, so all mixed
    outputs (and hence all swap targets j) are computed vectorized; only the
    inherently-sequential swap sweep stays in Python."""
    n = len(idx)
    if n <= 1:
        return
    with np.errstate(over="ignore"):
        t = np.arange(1, n, dtype=np.uint64)
        z = np.uint64(seed) + t * np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        z = z ^ (z >> np.uint64(31))
        i_vals = np.arange(n - 1, 0, -1, dtype=np.uint64)
        j = (z % (i_vals + np.uint64(1))).astype(np.int64)
    lst = idx.tolist()  # python-list swaps are ~3x faster than ndarray ones
    for t_, i in enumerate(range(n - 1, 0, -1)):
        jj = j[t_]
        lst[i], lst[jj] = lst[jj], lst[i]
    idx[:] = lst


def client_shuffle_seeds(client_ids, seed: int, round_idx: int) -> np.ndarray:
    """Per-client shuffle seeds keyed by (seed, round, CLIENT ID) — the ONE
    definition of the grouping-invariance chain shared by pack_clients,
    pack_client_indices, and (via the seeds argument) the native packer."""
    base = (seed * 7_919 + round_idx + 1) & _U64
    return np.array(
        [(base * 0x9E3779B97F4A7C15 + int(c) + 1) & _U64 for c in client_ids],
        dtype=np.uint64,
    )


def _shuffled_client_rows(data: "FederatedData", cid: int, cseed: int, cap: int):
    """Client cid's row indices for this round: splitmix shuffle, truncate."""
    idx = np.array(data.train_idx_map[int(cid)])
    _splitmix_shuffle(idx, int(cseed))
    return idx[:cap]


def pack_clients(
    data: FederatedData,
    client_ids: np.ndarray,
    batch_size: int,
    max_batches: int | None = None,
    seed: int = 0,
    round_idx: int = 0,
    use_native: bool | None = None,
) -> ClientBatch:
    """Pack the sampled clients' train data into a dense ClientBatch.

    Each client's indices are shuffled per-round (the DataLoader shuffle
    analogue), then laid into [B, bs] with zero padding. B is the max batch
    count among sampled clients unless ``max_batches`` caps it (the cap
    matches reference behavior only when no client overflows it).

    The shuffle is splitmix64 Fisher-Yates seeded by (seed, round, CLIENT
    ID) — identical in the native and numpy paths, and independent of which
    other clients are packed in the same call. That grouping-invariance is
    what makes the cross-process distributed runtime (one client per rank,
    fedml_tpu/distributed) bit-identical to the SPMD simulation (all clients
    in one block) — the distributed ≡ standalone equivalence oracle.

    ``use_native``: True forces the C++ packer (fedml_tpu.native), False the
    numpy loop, None auto-selects native when available.
    """
    counts = [len(data.train_idx_map[int(c)]) for c in client_ids]
    b_needed = max(int(np.ceil(n / batch_size)) for n in counts)
    B = b_needed if max_batches is None else min(max_batches, b_needed)
    K = len(client_ids)
    bs = batch_size
    seeds = client_shuffle_seeds(client_ids, seed, round_idx)

    if B == 0:
        # every sampled client is empty (e.g. an empty held-out stream) —
        # a degenerate but legal batch; the native packer rejects
        # capacity==0, so build the empty block directly
        return ClientBatch(
            x=np.zeros((K, 0, bs) + data.train_x.shape[1:], data.train_x.dtype),
            y=np.zeros((K, 0, bs) + data.train_y.shape[1:], data.train_y.dtype),
            mask=np.zeros((K, 0, bs), np.float32),
            num_samples=np.zeros((K,), np.float32),
        )

    if use_native is not False:
        from fedml_tpu import native

        if native.native_available():
            idx_lists = [np.asarray(data.train_idx_map[int(c)], np.int64)
                         for c in client_ids]
            x, y, mask, num = native.pack_clients_native(
                data.train_x, data.train_y, idx_lists, B * bs, seeds)
            return ClientBatch(
                x=x.reshape((K, B, bs) + data.train_x.shape[1:]),
                y=y.reshape((K, B, bs) + data.train_y.shape[1:]),
                mask=mask.reshape(K, B, bs),
                num_samples=num,
            )
        if use_native:
            raise RuntimeError("native packer requested but unavailable")

    xshape = data.train_x.shape[1:]
    yshape = data.train_y.shape[1:]
    x = np.zeros((K, B, bs) + xshape, dtype=data.train_x.dtype)
    y = np.zeros((K, B, bs) + yshape, dtype=data.train_y.dtype)
    mask = np.zeros((K, B, bs), dtype=np.float32)
    num = np.zeros((K,), dtype=np.float32)

    for k, cid in enumerate(client_ids):
        idx = _shuffled_client_rows(data, cid, seeds[k], B * bs)
        n = len(idx)
        num[k] = n
        flat_x = data.train_x[idx]
        flat_y = data.train_y[idx]
        x[k].reshape(B * bs, *xshape)[:n] = flat_x
        y[k].reshape(B * bs, *yshape)[:n] = flat_y
        mask[k].reshape(B * bs)[:n] = 1.0
    return ClientBatch(x=x, y=y, mask=mask, num_samples=num)


def pad_batches(cb: "ClientBatch", num_batches: int) -> "ClientBatch":
    """Zero-pad a ClientBatch along the batch axis (axis 1) up to
    ``num_batches``. Padded batches carry mask 0, so they are provable
    no-ops in every engine; both the SPMD FedGKT engine and the
    cross-process worker pad through HERE so their blocks stay
    bit-identical (the padded rows feed the KD teacher next round)."""
    pad = num_batches - cb.x.shape[1]
    if pad <= 0:
        return cb
    z = lambda a: np.concatenate(
        [a, np.zeros((a.shape[0], pad) + a.shape[2:], a.dtype)], 1)
    return ClientBatch(x=z(cb.x), y=z(cb.y), mask=z(cb.mask),
                       num_samples=cb.num_samples)


def pad_index_batches(ib: "IndexBatch", num_batches: int) -> "IndexBatch":
    """Index-plane analogue of pad_batches: zero-pad idx/mask along the
    batch axis up to ``num_batches`` (padded slots carry mask 0 = provable
    no-ops). Every engine pads through HERE so the per-round and block
    data planes cannot desynchronize."""
    pad = num_batches - ib.idx.shape[1]
    if pad <= 0:
        return ib
    z = lambda a: np.concatenate(
        [a, np.zeros((a.shape[0], pad) + a.shape[2:], a.dtype)], 1)
    return IndexBatch(idx=z(ib.idx), mask=z(ib.mask),
                      num_samples=ib.num_samples)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class IndexBatch:
    """Device-resident data plane: one round's client sample INDICES.

    Instead of gathering/copying sample rows on the host and DMA-ing a dense
    [K, B, bs, ...] block every round (pack_clients), the full train set
    lives in HBM once and a round ships only this index block (~KBs); the
    row gather happens inside the jitted round program, where HBM bandwidth
    dwarfs the host link. Same per-client-id splitmix shuffle as
    pack_clients, so the two data planes produce identical batches.
    """

    idx: Any          # [K, B, bs] int32 into train_x/train_y; 0 where padded
    mask: Any         # [K, B, bs] float32
    num_samples: Any  # [K] float32


def pack_client_indices(
    data: FederatedData,
    client_ids: np.ndarray,
    batch_size: int,
    max_batches: int | None = None,
    seed: int = 0,
    round_idx: int = 0,
) -> IndexBatch:
    """Index-only variant of pack_clients (same shuffle, same layout)."""
    counts = [len(data.train_idx_map[int(c)]) for c in client_ids]
    b_needed = max(int(np.ceil(n / batch_size)) for n in counts)
    B = b_needed if max_batches is None else min(max_batches, b_needed)
    K, bs = len(client_ids), batch_size
    seeds = client_shuffle_seeds(client_ids, seed, round_idx)
    idx_out = np.zeros((K, B * bs), np.int32)
    mask = np.zeros((K, B * bs), np.float32)
    num = np.zeros((K,), np.float32)
    for k, cid in enumerate(client_ids):
        idx = _shuffled_client_rows(data, cid, seeds[k], B * bs)
        n = len(idx)
        idx_out[k, :n] = idx
        mask[k, :n] = 1.0
        num[k] = n
    return IndexBatch(
        idx=idx_out.reshape(K, B, bs), mask=mask.reshape(K, B, bs), num_samples=num
    )


def batch_global(x: np.ndarray, y: np.ndarray, batch_size: int):
    """Pad-and-batch a global dataset into [B, bs, ...] + mask, for eval."""
    n = len(x)
    B = int(np.ceil(n / batch_size))
    xb = np.zeros((B, batch_size) + x.shape[1:], dtype=x.dtype)
    yb = np.zeros((B, batch_size) + y.shape[1:], dtype=y.dtype)
    mb = np.zeros((B, batch_size), dtype=np.float32)
    xb.reshape(B * batch_size, *x.shape[1:])[:n] = x
    yb.reshape(B * batch_size, *y.shape[1:])[:n] = y
    mb.reshape(B * batch_size)[:n] = 1.0
    return xb, yb, mb
