"""Standard Task builders for flax modules.

The reference ships one ModelTrainer per task family:
my_model_trainer_classification.py (cross-entropy),
my_model_trainer_nwp.py (next-word prediction with pad masking),
my_model_trainer_tag_prediction.py (multi-label BCE) under
fedml_api/standalone/fedavg/. These builders are the equivalents: they wrap a
flax.linen module (which must accept ``train: bool``) into the pure
(init, loss, predict, eval_batch) bundle consumed by core.local.

Conventions:
- modules may carry 'dropout' rngs and mutable collections (batch_stats);
  both are handled generically.
- x: [bs, ...], y: [bs] int labels (classification) / [bs, seq] int tokens
  (sequence) / [bs, C] multi-hot (tags). mask: [bs] sample-validity.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core.local import NetState, Task


def _split_variables(variables) -> NetState:
    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}
    return NetState(params, extra)


def _apply_train(module, params, extra, x, rng):
    out = module.apply(
        {"params": params, **extra},
        x,
        train=True,
        mutable=list(extra.keys()),
        rngs={"dropout": rng},
    )
    logits, mutated = out
    new_extra = dict(extra)
    new_extra.update(mutated)
    return logits, new_extra


def _apply_eval(module, params, extra, x):
    return module.apply({"params": params, **extra}, x, train=False)


def _as_float_image(x):
    """Integer pixel blocks (the uint8 fast transfer path — see
    fedml_tpu/data/registry.py uint8_pixels) normalize to f32/255 ON DEVICE;
    float inputs pass through untouched. Trace-time dtype check, zero cost
    under jit."""
    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer):
        return jnp.asarray(x, jnp.float32) / 255.0
    return x


def classification_task(module) -> Task:
    """Softmax cross-entropy over integer labels."""

    def init(rng, x_sample):
        p_rng, d_rng = jax.random.split(rng)
        variables = module.init(
            {"params": p_rng, "dropout": d_rng}, _as_float_image(x_sample), train=False
        )
        return _split_variables(variables)

    def loss(params, extra, x, y, mask, rng, train):
        x = _as_float_image(x)
        if train:
            logits, new_extra = _apply_train(module, params, extra, x, rng)
        else:
            logits, new_extra = _apply_eval(module, params, extra, x), extra
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        n = jnp.maximum(jnp.sum(mask), 1.0)
        l = jnp.sum(per_ex * mask) / n
        correct = jnp.sum((jnp.argmax(logits, -1) == y) * mask)
        metrics = {"loss_sum": jnp.sum(per_ex * mask), "correct": correct, "count": jnp.sum(mask)}
        return l, new_extra, metrics

    def predict(params, extra, x):
        return _apply_eval(module, params, extra, _as_float_image(x))

    def eval_batch(params, extra, x, y, mask):
        logits = _apply_eval(module, params, extra, _as_float_image(x))
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        return {
            "loss_sum": jnp.sum(per_ex * mask),
            "correct": jnp.sum((jnp.argmax(logits, -1) == y) * mask),
            "count": jnp.sum(mask),
        }

    return Task(init, loss, predict, eval_batch)


def sequence_task(module, pad_id: int = 0, count_pad_in_acc: bool = False,
                  seq_axis: str | None = None) -> Task:
    """Next-token prediction: module maps tokens [bs, T] -> logits [bs, T, V];
    labels are the inputs shifted by the module itself or provided as y
    [bs, T]. Tokens equal to ``pad_id`` are masked out of loss and accuracy
    (the reference masks PAD in nwp, my_model_trainer_nwp.py).

    seq_axis: sequence-parallel mode — x/y carry this device's sequence
    slice (the module runs ring/Ulysses attention over the axis), so the
    loss normalizer and the metric sums are psum-ed over it: every seq shard
    then holds the identical GLOBAL loss/metrics. No explicit gradient
    collective is needed: differentiating this psum-ed loss w.r.t.
    seq-invariant params makes shard_map's vma-aware transpose insert the
    gradient psum itself (see the NOTE in core/local.py), so the gradient
    equals the unsharded gradient exactly."""

    def init(rng, x_sample):
        p_rng, d_rng = jax.random.split(rng)
        variables = module.init({"params": p_rng, "dropout": d_rng}, x_sample, train=False)
        return _split_variables(variables)

    def _tok_mask(y, mask):
        tm = (y != pad_id).astype(jnp.float32)
        return tm * mask[:, None]

    def _seq_sum(v):
        return jax.lax.psum(v, seq_axis) if seq_axis is not None else v

    def loss(params, extra, x, y, mask, rng, train):
        if train:
            logits, new_extra = _apply_train(module, params, extra, x, rng)
        else:
            logits, new_extra = _apply_eval(module, params, extra, x), extra
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        tm = _tok_mask(y, mask)
        n = jnp.maximum(_seq_sum(jnp.sum(tm)), 1.0)
        l = _seq_sum(jnp.sum(per_tok * tm)) / n
        correct = _seq_sum(jnp.sum((jnp.argmax(logits, -1) == y) * tm))
        metrics = {"loss_sum": _seq_sum(jnp.sum(per_tok * tm)),
                   "correct": correct, "count": _seq_sum(jnp.sum(tm))}
        return l, new_extra, metrics

    def predict(params, extra, x):
        return _apply_eval(module, params, extra, x)

    def eval_batch(params, extra, x, y, mask):
        logits = _apply_eval(module, params, extra, x)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        tm = _tok_mask(y, mask)
        return {
            "loss_sum": jnp.sum(per_tok * tm),
            "correct": jnp.sum((jnp.argmax(logits, -1) == y) * tm),
            "count": jnp.sum(tm),
        }

    return Task(init, loss, predict, eval_batch)


def segmentation_task(
    module,
    ignore_index: int = 255,
    loss_mode: str = "ce",
    focal_gamma: float = 2.0,
    focal_alpha: float = 0.5,
) -> Task:
    """Pixel-wise segmentation: module maps [bs, H, W, C] -> logits
    [bs, H, W, num_classes]; y is [bs, H, W] int labels with ``ignore_index``
    marking void pixels (reference SegmentationLosses, fedseg/utils.py:66-110:
    CrossEntropyLoss(ignore_index=255) and FocalLoss). The focal variant here
    is the standard per-pixel (1-pt)^gamma weighting; the reference applies
    the transform to the batch-mean CE (utils.py:97-110), which collapses to
    a scalar reweighting — per-pixel is the published form.

    Metrics count *valid pixels* (not samples): loss_sum/correct/count are
    summed over non-ignored pixels of non-padded samples, so the engine's
    weighted aggregation stays exact.
    """

    def init(rng, x_sample):
        p_rng, d_rng = jax.random.split(rng)
        variables = module.init(
            {"params": p_rng, "dropout": d_rng}, _as_float_image(x_sample), train=False
        )
        return _split_variables(variables)

    def _pixel_metrics(logits, y, mask):
        valid = (y != ignore_index).astype(jnp.float32) * mask[:, None, None]
        y_safe = jnp.where(y == ignore_index, 0, y)
        per_px = optax.softmax_cross_entropy_with_integer_labels(logits, y_safe)
        if loss_mode == "focal":
            pt = jnp.exp(-per_px)
            per_px = focal_alpha * jnp.power(1.0 - pt, focal_gamma) * per_px
        correct = jnp.sum((jnp.argmax(logits, -1) == y) * valid)
        return per_px, valid, correct

    def loss(params, extra, x, y, mask, rng, train):
        x = _as_float_image(x)
        if train:
            logits, new_extra = _apply_train(module, params, extra, x, rng)
        else:
            logits, new_extra = _apply_eval(module, params, extra, x), extra
        per_px, valid, correct = _pixel_metrics(logits, y, mask)
        n = jnp.maximum(jnp.sum(valid), 1.0)
        l = jnp.sum(per_px * valid) / n
        metrics = {"loss_sum": jnp.sum(per_px * valid), "correct": correct, "count": jnp.sum(valid)}
        return l, new_extra, metrics

    def predict(params, extra, x):
        return _apply_eval(module, params, extra, _as_float_image(x))

    def eval_batch(params, extra, x, y, mask):
        logits = _apply_eval(module, params, extra, _as_float_image(x))
        per_px, valid, correct = _pixel_metrics(logits, y, mask)
        return {"loss_sum": jnp.sum(per_px * valid), "correct": correct, "count": jnp.sum(valid)}

    return Task(init, loss, predict, eval_batch)


def tag_prediction_task(module, threshold: float = 0.5) -> Task:
    """Multi-label (tag) prediction with sigmoid BCE; y is multi-hot [bs, C].
    Accuracy = micro-F1-style exact element accuracy over real samples."""

    def init(rng, x_sample):
        p_rng, d_rng = jax.random.split(rng)
        variables = module.init({"params": p_rng, "dropout": d_rng}, x_sample, train=False)
        return _split_variables(variables)

    def _metrics(logits, y, mask):
        per_ex = jnp.sum(optax.sigmoid_binary_cross_entropy(logits, y), axis=-1)
        pred = (jax.nn.sigmoid(logits) > threshold).astype(y.dtype)
        correct = jnp.sum(jnp.all(pred == y, axis=-1) * mask)
        return per_ex, correct

    def loss(params, extra, x, y, mask, rng, train):
        if train:
            logits, new_extra = _apply_train(module, params, extra, x, rng)
        else:
            logits, new_extra = _apply_eval(module, params, extra, x), extra
        per_ex, correct = _metrics(logits, y, mask)
        n = jnp.maximum(jnp.sum(mask), 1.0)
        l = jnp.sum(per_ex * mask) / n
        metrics = {"loss_sum": jnp.sum(per_ex * mask), "correct": correct, "count": jnp.sum(mask)}
        return l, new_extra, metrics

    def predict(params, extra, x):
        return _apply_eval(module, params, extra, x)

    def eval_batch(params, extra, x, y, mask):
        logits = _apply_eval(module, params, extra, x)
        per_ex, correct = _metrics(logits, y, mask)
        return {"loss_sum": jnp.sum(per_ex * mask), "correct": correct, "count": jnp.sum(mask)}

    return Task(init, loss, predict, eval_batch)


def aux_classification_task(module, aux_weight: float = 0.4) -> Task:
    """Cross-entropy with an auxiliary-head term for modules that return
    ``(logits, logits_aux)`` during training (DARTS derived nets,
    models/darts.NetworkCIFAR): train loss adds ``aux_weight *
    CE(logits_aux)`` when the head is present (reference
    FedNASTrainer.local_train, FedNASTrainer.py:179-183; standard DARTS
    auxiliary weight 0.4). Eval is plain classification on the main head —
    init/predict/eval_batch delegate to classification_task; only the
    train loss differs."""

    base = classification_task(module)

    def loss(params, extra, x, y, mask, rng, train):
        if not train:
            return base.loss(params, extra, x, y, mask, rng, train)
        x = _as_float_image(x)
        out, new_extra = _apply_train(module, params, extra, x, rng)
        logits, logits_aux = out if isinstance(out, tuple) else (out, None)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        # metrics track the MAIN head (the reference logs prec1 of logits)
        n = jnp.maximum(jnp.sum(mask), 1.0)
        metrics = {"loss_sum": jnp.sum(per_ex * mask),
                   "correct": jnp.sum((jnp.argmax(logits, -1) == y) * mask),
                   "count": jnp.sum(mask)}
        if logits_aux is not None:
            per_ex = per_ex + aux_weight * \
                optax.softmax_cross_entropy_with_integer_labels(logits_aux, y)
        return jnp.sum(per_ex * mask) / n, new_extra, metrics

    return Task(base.init, loss, base.predict, base.eval_batch)
