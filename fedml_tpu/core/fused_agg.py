"""Fused on-device server aggregation — decode → gate → pairwise partials.

The stacked server path (distributed/fedavg/aggregator._aggregate_core)
densifies every encoded upload to host f32 (``server_manager._decode_upload``
runs zlib + numpy per rank), re-stacks the whole cohort per leaf, and only
then hands the gagg jit a ``[K, ...]`` stack — at fan-in 100+ the
decode→gate→sum chain on the server is the round bottleneck (the Smart-NIC
aggregation lesson, arXiv:2307.06561). This module is the fused alternative
(docs/PERFORMANCE.md §Fused aggregation):

- uploads stage to device AS THEIR RAW QUANTIZED LEAVES (deflated int8 is
  inflated host-side to int8 — zlib cannot run in a jit, and int8 is 4x
  smaller than the f32 tree the stacked path materializes; packed sign
  BYTES and sparse idx/val go up verbatim);
- ONE jitted ingest per arrival runs decode → densify against the
  device-resident broadcast stash → the unconditional non-finite gate
  (:func:`make_fused_ingest`), so a per-client f32 tree never exists on
  host;
- arrivals accumulate into the CANONICAL pairwise partial sums — the
  :class:`PairwiseAccumulator` is a binary counter whose nodes are exactly
  the aligned-block internal nodes of ``robust_agg.pairwise_sum``'s
  balanced tree, so peak device memory is O(log fan-in) partials on the
  in-order path instead of the full ``[K, ...]`` stack (out-of-order
  arrivals pend until the slot cursor reaches them — the worst case decays
  to O(K) single-slot nodes, never worse than the stack);
- flush merges the counter, divides ONCE through the shared
  ``robust_agg.pairwise_finalize`` (zero surviving weight keeps the global
  model), and the new global model lands device-resident.

Bitwise contract: the fused result is BIT-IDENTICAL to the stacked route
under ``sum_assoc='pairwise'`` for the same arrived slots — gate reasons
and quarantine ledger included (test-enforced). Three pieces make that
hold across jit boundaries:

- the per-arrival decode replays the host decoders' exact f32 ops
  (``comm/delta._q8_leaf_decode`` / ``_sign_leaf_decode`` /
  ``apply_delta`` / ``sparse.topk_decode``) and the gate is the per-slot
  half of ``sanitize_updates`` (``norm_mult=inf``) — the only gate the
  FOLD-AT-ARRIVAL path supports: the norm-outlier rule is a cohort
  statistic computed at flush, AFTER arrivals were already folded;
- the accumulator's LEVEL-1 combine compiles the identical
  ``c0*w0 + c1*w1`` expression ``pairwise_weighted_stats`` evaluates per
  aligned slot pair (XLA contracts that multiply+add to an fma — which is
  exactly why the stacked fold pre-pads its slot axis to even length:
  uniform level-1 expressions are what make the fold reproducible pair by
  pair from a different jit);
- levels >= 2 are plain adds of materialized partials on both routes.

Robust estimators and armed sanitize ride the STAGED fused mode instead
(docs/PERFORMANCE.md §Fused aggregation): the per-arrival jit
(:func:`make_fused_robust_ingest`) decodes and emits the slot's evidence
row — update norm, finite flag, count-sketch via
``robust_agg.update_evidence``, whose ops are all per-row reductions so a
``[1, ...]`` row is bitwise the stacked cohort's row — and the RAW
densified update stays device-resident per slot (cohort verdicts need the
full survivor set, so the fold can't happen at arrival; device-staged
bytes ≈ the stacked route's stack bytes, but there is no host densify, no
barrier H2D, and decode overlaps the wire wait). Flush runs ONE jit
(:func:`make_fused_robust_flush`): stack the staged slots in sorted-slot
order, concatenate the evidence rows, then the shared
``robust_agg.verdict_flush`` — the very composition ``gated_aggregate``'s
verdict branch calls — so fused×{median, trimmed_mean, krum, multi_krum,
geometric_median, armed sanitize} is bitwise the stacked result, model
bits AND reason codes, by construction.

Sharded server state composes as a layout property (GSPMD,
arXiv:2004.13336): a ``stage_fn`` pins each ingested slot's leaves to the
partitioner's rule-table placement, so accumulator partials / staged
slots already carry the sharded layout and XLA lowers the flush's folds
into reduce-scatters landing in-place — no gather-then-reshard. Sharding
moves bytes, not values; the bitwise contract is unchanged.

Poison policy is inherited unchanged: a NaN scale decodes non-finite ON
DEVICE and dies at the in-graph gate; structural garbage never reaches the
device (``comm/delta.inflate_update`` raises ``CorruptPayload`` host-side,
quarantined ``undecodable`` exactly like the stacked path).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from fedml_tpu.core.robust_agg import (
    REASON_NONFINITE,
    REASON_OK,
    pairwise_finalize,
    update_evidence,
    verdict_flush,
)

FUSED_KINDS = ("dense", "delta", "delta-int8", "delta-sign1", "topk")

# one jitted partial-sum add serves every level >= 2 combine (jit caches by
# structure: (wsum leaves, weight total) tuples all share one trace)
_tree_add = jax.jit(lambda a, b: jax.tree.map(jnp.add, a, b))

_finalize = jax.jit(pairwise_finalize)


@jax.jit
def _pair_combine(c0, w0, c1, w1):
    """Level-1 combine of two RAW slots: the exact per-pair expression of
    ``pairwise_weighted_stats``'s first fold level (slot-axis pre-padded to
    even, so every aligned pair evaluates ``c0*w0 + c1*w1`` — bit-for-bit
    the same contraction here and there)."""
    term = [a.astype(jnp.float32) * w0 + b.astype(jnp.float32) * w1
            for a, b in zip(c0, c1)]
    return term, w0 + w1


class PairwiseAccumulator:
    """Streaming canonical pairwise fold — ``pairwise_sum``'s association,
    one slot at a time.

    A binary counter over push order: level 0 holds (at most) one RAW
    ``(clean_leaves, weight)`` slot, level ``l >= 1`` one complete ALIGNED
    partial of ``2**l`` consecutive slots. Pushing carry-propagates exactly
    the adjacent combines the stacked fold performs — the level-1 combine
    multiplies weights in (``_pair_combine``), higher levels add partials —
    so after K in-order pushes the live nodes ARE the canonical tree's
    internal nodes (O(log K) of them). :meth:`merge` pads the count to the
    next power of two with exact-zero raw slots, which is bitwise the
    stacked fold's zero-padding (its even pre-pad + per-level odd-tail
    pads; unrolled, leaf-padding to the next power of two)."""

    def __init__(self, zero_fn):
        self._zero_fn = zero_fn  # () -> an exact-zero RAW (clean, w) slot
        self._levels: dict[int, object] = {}
        self._count = 0
        self.peak_nodes = 0  # live-node high-water mark (memory evidence)

    def __len__(self) -> int:
        return self._count

    @property
    def live_nodes(self) -> int:
        return len(self._levels)

    def push(self, raw) -> None:
        """Append one RAW ``(clean_leaves, weight)`` slot and carry."""
        if 0 not in self._levels:
            self._levels[0] = raw
        else:
            c0, w0 = self._levels.pop(0)
            c1, w1 = raw
            node, lvl = _pair_combine(c0, w0, c1, w1), 1
            while lvl in self._levels:
                node = _tree_add(self._levels.pop(lvl), node)
                lvl += 1
            self._levels[lvl] = node
        self._count += 1
        self.peak_nodes = max(self.peak_nodes, len(self._levels))

    def merge(self):
        """Collapse to the single root ``(wsum_leaves, total)`` partial
        (None when nothing was pushed). The accumulator is spent after."""
        if self._count == 0:
            return None
        target = 1 << max(self._count - 1, 0).bit_length()
        if target == 1:
            target = 2  # the stacked fold pre-pads a lone slot to a pair
        while self._count < target:
            self.push(self._zero_fn())
        (node,) = self._levels.values()
        self._levels = {}
        return node


def _leaf_meta(leaves) -> tuple:
    """Static (shape, dtype) per leaf — the decode functions specialize on
    it (non-float leaves ship dense and REPLACE, float leaves densify)."""
    return tuple((tuple(np.shape(v)), np.dtype(jnp.asarray(v).dtype))
                 for v in leaves)


def term_nbytes(meta) -> int:
    """Bytes of ONE partial/slot (every leaf f32 in the fold) — the unit
    of the fed_agg_stack_bytes{mode=fused} accounting."""
    return int(sum(4 * int(np.prod(shape, dtype=np.int64)) if shape else 4
                   for shape, _ in meta))


def _unpack_sign_bits(packed, n: int):
    """Device twin of ``np.unpackbits``: MSB-first bits of each byte,
    truncated to ``n`` — bit-exact (the values are 0/1)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[:, None] >> shifts[None, :]) & jnp.uint8(1)
    return bits.reshape(-1)[:n]


def _densify(kind: str, meta, payload, scales, base_leaves):
    """Traceable: one upload's raw wire payload -> the client's effective
    model leaves, replicating the HOST decode path's f32 ops bit for bit
    (``comm/delta`` decoders + ``apply_delta``; ``comm/sparse.topk_decode``).
    Non-float leaves ship dense and replace (the shared leaf convention)."""
    out = []
    if kind == "dense":
        for p, (shape, dtype) in zip(payload, meta):
            out.append(jnp.asarray(p).reshape(shape))
        return out
    if kind == "topk":
        idx_list, val_list = payload
        for g, sel, vals, (shape, dtype) in zip(base_leaves, idx_list,
                                                val_list, meta):
            if not np.issubdtype(dtype, np.floating):
                out.append(jnp.asarray(vals).reshape(shape))
                continue
            flat = jnp.asarray(g, jnp.float32).reshape(-1)
            flat = flat.at[jnp.asarray(sel)].add(
                jnp.asarray(vals, jnp.float32))
            out.append(flat.reshape(shape).astype(dtype))
        return out
    for i, (p, g, (shape, dtype)) in enumerate(zip(payload, base_leaves,
                                                   meta)):
        if not np.issubdtype(dtype, np.floating):
            out.append(jnp.asarray(p).reshape(shape))
            continue
        s = jnp.asarray(scales[i], jnp.float32)
        if kind == "delta":
            d = jnp.asarray(p, jnp.float32).reshape(shape)
        elif kind == "delta-int8":
            d = (jnp.asarray(p).astype(jnp.float32) * s).reshape(shape)
        else:  # delta-sign1
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            bits = _unpack_sign_bits(jnp.asarray(p), n)
            d = jnp.where(bits.astype(bool), s, -s) \
                .astype(jnp.float32).reshape(shape)
        out.append((jnp.asarray(g, jnp.float32) + d).astype(dtype))
    return out


def make_fused_ingest(kind: str, meta):
    """Build the jitted per-arrival composition for payload ``kind`` over a
    model with leaf ``meta``: decode → densify → non-finite gate. Returns
    ``fn(payload, scales, base, global, w) ->
    (clean_leaves, surviving_weight, reason)`` replicating — slot for
    slot, bit for bit — the per-slot half of ``sanitize_updates``
    (``norm_mult=inf``) inside the stacked route."""
    if kind not in FUSED_KINDS:
        raise ValueError(f"unknown fused payload kind {kind!r} "
                         f"(one of {FUSED_KINDS})")

    @jax.jit
    def ingest(payload, scales, base_leaves, global_leaves, w):
        eff = _densify(kind, meta, payload, scales, base_leaves)
        finite = jnp.ones((), bool)
        for e in eff:
            finite &= jnp.all(jnp.isfinite(e))
        # the per-slot half of sanitize_updates: replace a non-finite
        # upload with the global model (a zero WEIGHT alone would still
        # poison 0 * nan) and zero its weight; report nonfinite only for
        # participating (w > 0) slots — identical reason codes to the gate
        clean = [jnp.where(finite, e, g.astype(e.dtype))
                 for e, g in zip(eff, global_leaves)]
        w = jnp.asarray(w, jnp.float32)
        w_out = jnp.where(finite, w, jnp.float32(0.0))
        reason = jnp.where(
            w > 0,
            jnp.where(finite, REASON_OK, REASON_NONFINITE),
            REASON_OK).astype(jnp.int32)
        return clean, w_out, reason

    return ingest


def make_fused_densify(kind: str, meta):
    """Build the jitted arrival-side decode for the ASYNC fused path:
    densify only, plus the door's finiteness verdict. The gate's global-
    model replacement and the evidence row are deliberately NOT computed
    here — they reference the FLUSH-time global model (the drain
    re-ingests the dense leaves against it, exactly when the stacked
    route gates its staged entries), while the buffer may outlive the
    arrival-time broadcast. One scalar readback replaces the stacked
    door's host ``isfinite`` pass over the full tree. Returns
    ``fn(payload, scales, base_leaves) -> (dense_leaves, finite)``."""
    if kind not in FUSED_KINDS:
        raise ValueError(f"unknown fused payload kind {kind!r} "
                         f"(one of {FUSED_KINDS})")

    @jax.jit
    def densify(payload, scales, base_leaves):
        eff = _densify(kind, meta, payload, scales, base_leaves)
        finite = jnp.ones((), bool)
        for e in eff:
            finite &= jnp.all(jnp.isfinite(e))
        return eff, finite

    return densify


def make_fused_robust_ingest(kind: str, meta, sketch_dim: int):
    """Build the jitted per-arrival composition for the STAGED (robust)
    fused mode: decode → densify → evidence row. Returns
    ``fn(payload, scales, base, global, w) -> (raw_leaves, evidence)``
    where ``raw_leaves`` is the slot's densified update (RAW — the
    verdict composition feeds raw slots into ``update_evidence`` and
    ``apply_verdicts``, exactly like ``gated_aggregate``'s verdict
    branch) and ``evidence`` is the slot's one-row PR-13 dict
    (``{"norm", "finite", "sketch", "weight"}``, leading axis 1). Every
    evidence op is a per-row reduction, so the row is bitwise the row the
    stacked path computes for this slot inside the whole-cohort
    ``update_evidence`` call (the same property the edge tier's
    ``e2s_evidence`` frames rely on)."""
    if kind not in FUSED_KINDS:
        raise ValueError(f"unknown fused payload kind {kind!r} "
                         f"(one of {FUSED_KINDS})")

    @jax.jit
    def ingest(payload, scales, base_leaves, global_leaves, w):
        eff = _densify(kind, meta, payload, scales, base_leaves)
        ev = update_evidence([e[None] for e in eff], list(global_leaves),
                             jnp.asarray(w, jnp.float32)[None],
                             sketch_dim=sketch_dim)
        return eff, ev

    return ingest


def make_fused_robust_flush(verdict_fn, norm_mult: float | None = None,
                            out_shardings=None):
    """Build the one-jit flush for the STAGED fused mode: stack the
    staged slots (sorted-slot order — the stacked route's compacted
    layout), concatenate the per-arrival evidence rows, then the shared
    ``robust_agg.verdict_flush`` (``evidence_verdicts`` →
    ``apply_verdicts`` → canonical pairwise fold). Build it ONCE per
    aggregator (it retraces per distinct realized K, like the stacked
    gagg jit — warmup covers both).

    ``out_shardings`` (mesh-sharded server state only): a
    ``(leaf_shardings_list, rep, rep)`` pin so the new model lands in
    the partitioner's rule-table placement — with staged slots already
    carrying the sharded layout, XLA lowers the fold into
    reduce-scatters; no gather-then-reshard round trip.

    Returns ``fn(slot_leaves, slot_evidence, global_leaves) ->
    (new_global_leaves, verdict_weights, reasons)``."""
    def flush(slot_leaves, slot_evidence, global_leaves):
        stacked = [jnp.stack(col) for col in zip(*slot_leaves)]
        # every evidence field carries a leading slot axis of 1, so the
        # cohort dict is a plain axis-0 concatenate per field
        ev = {key: jnp.concatenate([e[key] for e in slot_evidence])
              for key in ("norm", "finite", "weight", "sketch")}
        return verdict_flush(stacked, list(global_leaves), ev, verdict_fn,
                             norm_mult=norm_mult)

    if out_shardings is None:
        return jax.jit(flush)
    return jax.jit(flush, out_shardings=out_shardings)


class FusedRoundIngest:
    """One round's device-resident fused ingest state.

    PLAIN mode (``staged=False``): slots are worker indices; arrivals
    push into the accumulator strictly in SLOT order (a cursor:
    out-of-order arrivals pend device-resident until every lower slot
    arrived or the flush skips the holes) — so the fold is the canonical
    pairwise association over the COMPACTED sorted arrival set, exactly
    the layout ``_aggregate_core`` stacks, and fused ≡ stacked stays
    bitwise whatever order the wire delivered.

    STAGED mode (``staged=True`` — robust estimators / armed sanitize):
    cohort verdicts need the full survivor set, so nothing folds at
    arrival; each slot's RAW densified update and its evidence row stay
    device-resident until :meth:`flush_robust` runs the one-jit verdict
    composition. Peak memory is O(K) staged slots — the stacked route's
    stack bytes, reported honestly as ``fed_agg_stack_bytes{mode=
    fused_staged}`` — but there is no host densify and decode overlaps
    the wire wait.

    ``stage_fn`` (mesh-sharded server state only): applied to each
    ingested slot's leaves, pinning them to the partitioner's rule-table
    placement so the flush's folds lower into reduce-scatters."""

    def __init__(self, global_leaves, meta, *, staged: bool = False,
                 stage_fn=None):
        self._global = [jnp.asarray(v) for v in global_leaves]
        self._meta = meta
        zero = ([jnp.zeros(shape, dtype) for shape, dtype in meta],
                jnp.zeros((), jnp.float32))
        self._acc = PairwiseAccumulator(lambda: zero)
        self._pending: dict[int, tuple] = {}
        self._staged: dict[int, tuple] = {}  # staged mode: slot->(raw, ev)
        self.staged_mode = bool(staged)
        self._stage_fn = stage_fn
        self._reasons: dict[int, jax.Array] = {}
        self._cursor = 0
        self.slots: set[int] = set()
        self.peak_terms = 0

    def add(self, slot: int, ingest_fn, payload, scales, base_leaves,
            weight: float) -> None:
        """Run the per-arrival jit for one upload and fold (plain) or
        stage (staged mode) the result. ``ingest_fn`` is the matching
        builder's product: :func:`make_fused_ingest` in plain mode,
        :func:`make_fused_robust_ingest` in staged mode."""
        if slot in self.slots:
            # exactly-once folding: a chaos duplicate that survived the
            # upstream dedup gates must not double-count (the stacked
            # path's dict overwrite is idempotent for identical content)
            return
        entry = ingest_fn(
            payload,
            jnp.zeros((0,), jnp.float32) if scales is None
            else jnp.asarray(scales, jnp.float32),
            self._global if base_leaves is None else list(base_leaves),
            self._global, jnp.float32(weight))
        self.add_staged(slot, entry)

    def add_staged(self, slot: int, entry) -> None:
        """Fold/stage one PRE-INGESTED entry — the async drain path: the
        arrival-time jit already ran (decode + gate/evidence with the
        staleness-discounted weight, knowable at arrival because the
        round index is static between flushes) and its result rode the
        buffer, so the drain folds at the door with no decode burst.
        Plain-mode entries are ``(clean_leaves, w_out, reason)``; staged
        (robust) mode entries are ``(raw_leaves, evidence_row)``."""
        if slot in self.slots:
            return
        if self.staged_mode:
            raw, ev = entry
            if self._stage_fn is not None:
                raw = self._stage_fn(raw)
            self.slots.add(slot)
            self._staged[slot] = (raw, ev)
            self.peak_terms = max(self.peak_terms, len(self._staged))
            return
        clean, w_out, reason = entry
        if self._stage_fn is not None:
            clean = self._stage_fn(clean)
        self.slots.add(slot)
        self._reasons[slot] = reason
        self._pending[slot] = (clean, w_out)
        while self._cursor in self._pending:
            self._acc.push(self._pending.pop(self._cursor))
            self._cursor += 1
        self.peak_terms = max(self.peak_terms,
                              self._acc.live_nodes + len(self._pending))

    def block_until_ready(self) -> None:
        """Synchronize on every live device node (counter partials +
        pending out-of-order slots + staged robust slots) — the
        measurement seam benches use to separate ingest work from the
        flush without reaching into the accumulator's internals."""
        for node in list(self._acc._levels.values()) \
                + list(self._pending.values()) \
                + list(self._staged.values()):
            jax.block_until_ready(node)

    def flush(self):
        """Merge → finalize: returns ``(new_global_leaves, reasons)`` with
        ``reasons`` the ``[K']`` int32 codes over the sorted arrived slots
        (the stacked route's compacted layout). The all-rejected round
        keeps the global model via the shared ``pairwise_finalize``."""
        for slot in sorted(self._pending):  # straggler holes: skip, like
            self._acc.push(self._pending.pop(slot))  # the stacked compact
        node = self._acc.merge()
        if node is None:
            return None, None
        wsum, total = node
        new_leaves = _finalize(wsum, total, self._global)
        reasons = jnp.stack([self._reasons[s] for s in sorted(self.slots)])
        return new_leaves, reasons

    def flush_robust(self, flush_fn):
        """STAGED-mode flush: the ONE verdict jit (from
        :func:`make_fused_robust_flush`) over the sorted staged slots —
        the stacked route's compacted layout, so elastic rounds (only
        some slots arrived) see the identical realized cohort. Returns
        ``(new_global_leaves, verdict_weights, reasons)``; all-None when
        nothing was staged."""
        order = sorted(self._staged)
        if not order:
            return None, None, None
        slot_leaves = [self._staged[s][0] for s in order]
        slot_ev = [self._staged[s][1] for s in order]
        return flush_fn(slot_leaves, slot_ev, self._global)

    # ----------------------------------------------------- edge tier
    def flush_block_partial(self, block_size: int):
        """Edge-tier flush (plain mode): collapse the block WITHOUT the
        final divide, filling missing locals with the global model at
        zero weight AT POSITION — the ``_stack_block`` fill. A zero-
        weight term folds as an exact-zero f32 product either way, and
        holes must keep their aligned place for the block partial to be
        the canonical tree's internal node (root combine ≡ flat fold).
        Returns ``(wsum_leaves, total, reasons)``; ``reasons`` covers ALL
        block positions (holes report OK, exactly like the stacked gate
        does for zero-weight slots)."""
        hole = (self._global, jnp.zeros((), jnp.float32))
        for local in range(self._cursor, block_size):
            self._acc.push(self._pending.pop(local, hole))
        wsum, total = self._acc.merge()  # count == block_size, a power
        ok = jnp.zeros((), jnp.int32)    # of two: merge pads nothing
        reasons = jnp.stack([self._reasons.get(s, ok)
                             for s in range(block_size)])
        return wsum, total, reasons

    def block_evidence(self, block_size: int, sketch_dim: int):
        """Edge-tier evidence assembly (STAGED mode): the block's
        ``[block_size, ...]`` evidence arrays from the per-arrival rows,
        hole positions zero-filled — bitwise the rows the stacked edge's
        ``update_evidence`` computes over the ``_stack_block`` fill (a
        global-model slot's norm, sketch buckets and weight are all
        exact ``+0.0``: ``g - g`` is ``+0.0`` for finite ``g`` and every
        reduction preserves it; its finite flag is True)."""
        zero_row = {"norm": jnp.zeros((1,), jnp.float32),
                    "finite": jnp.ones((1,), bool),
                    "sketch": jnp.zeros((1, max(sketch_dim, 0)),
                                        jnp.float32),
                    "weight": jnp.zeros((1,), jnp.float32)}
        rows = [self._staged[s][1] if s in self._staged else zero_row
                for s in range(block_size)]
        return {key: jnp.concatenate([r[key] for r in rows])
                for key in ("norm", "finite", "weight", "sketch")}

    def block_stacked(self, block_size: int):
        """Edge-tier verdict-receipt stack (STAGED mode): the block's
        RAW ``[block_size, ...]`` leaves with the ``_stack_block`` hole
        fill (global model at position), ready for the shared
        ``apply_verdicts`` jit the stacked edge already runs."""
        return [jnp.stack([self._staged[s][0][i]
                           if s in self._staged else g
                           for s in range(block_size)])
                for i, g in enumerate(self._global)]
