"""Byzantine-robust aggregation — pure jittable functions over STACKED updates.

The reference's only poisoning defenses are norm-diff clipping and weak-DP
noise (core/robust.py); neither survives a single Byzantine client that
uploads NaNs or a scaled sign-flipped update — both aggregation paths
(``tree_weighted_mean`` in the SPMD engine, ``FedAvgAggregator._wavg`` in
the cross-process runtime) would average hostility straight into the global
model. This module supplies the classical robust estimators as drop-in
replacements for the weighted mean, all over the SAME data layout both
runtimes already produce: a pytree whose leaves carry one leading client
axis ``[K, ...]`` plus a ``[K]`` weight vector (sample counts; 0 =
excluded slot — zero-sample padding and gate-rejected clients alike).

Aggregators (each ``fn(stacked, weights) -> (tree, info)``, jit-safe):

- ``mean``               the exact ``tree_weighted_mean`` baseline;
- ``median``             coordinate-wise weighted (lower) median —
                         breakdown point f < n/2;
- ``trimmed_mean``       coordinate-wise weighted trimmed mean: the outer
                         ``trim`` fraction of total weight is discarded at
                         EACH end per coordinate (winsorized-interval
                         weights, exact for uniform weights and integral
                         trim counts) — breakdown f/n < trim;
- ``krum`` / ``multi_krum``  Krum (Blanchard et al., NeurIPS'17): score
                         each client by the sum of its n-f-2 smallest
                         pairwise squared distances on the flattened
                         update; pick the minimizer (krum) or average the
                         ``m`` best by sample weight (multi_krum).
                         Requires n >= 2f+3;
- ``geometric_median``   fixed-iteration (jit-static) Weiszfeld loop on
                         the flattened updates — the smoothed L1 point
                         estimate, breakdown f < n/2.

The **sanitation gate** (``sanitize_updates``) runs BEFORE any aggregator:
it rejects non-finite updates and norm outliers (update norm beyond
``norm_mult`` x the UNWEIGHTED median norm of the finite participants —
one vote per client, because sample counts are client-reported and a
weighted baseline would let an attacker claiming the weight majority
become its own reference), replaces a rejected client's
update with the global model (a neutral value — a zero WEIGHT alone would
still poison sorts/distances with NaNs), and zeroes its weight. Because
every aggregator normalizes by the SURVIVING weight mass (the same
reweighting elastic partial aggregation relies on), the result stays the
exact estimator over the survivors — no post-hoc correction needed
(test-enforced against a numpy oracle).

Attribution comes out as per-slot int32 reason codes (``REASONS``), which
the engines turn into a :class:`QuarantineLedger` — the replayable
artifact both runtimes must agree on (the chaos ledger's model-space
sibling).
"""

from __future__ import annotations

import threading
from functools import partial

import jax
import jax.numpy as jnp

from fedml_tpu.utils.tree import tree_weighted_mean

# per-slot quarantine reason codes (int32 in-graph; names in ledgers).
# 'undecodable' is ledger-only (no in-graph code): the server records it
# when an encoded uplink's payload is structurally garbage — a chaos
# bit-flip that survived CRC, a truncated deflate stream — and the upload
# never reaches the stacked aggregate at all (docs/PERFORMANCE.md §Wire
# efficiency). 'edge_lost' is ledger-only too: the hierarchical root
# records it for every cohort slot of an edge block whose partial never
# arrived (crashed/partitioned edge rank — the round degrades to an
# elastic zero-term partial, docs/ROBUSTNESS.md §Cross-tier robust
# gating). 'secagg_dropout' and 'secagg_shed' are ledger-only codes of
# the masked secure-aggregation tier (docs/ROBUSTNESS.md §Secure
# aggregation): 'secagg_dropout' marks a cohort slot whose masked upload
# never arrived on a round the survivors RECOVERED (mask recovery
# stripped its orphaned pairwise masks); 'secagg_shed' marks every slot
# of a round that fell below the t+1 recovery threshold (or lost a
# reveal) and was shed + re-broadcast instead of wedging. Appended AFTER
# the in-graph codes so 0..3 stay stable.
REASONS = ("ok", "nonfinite", "norm_outlier", "suspected", "undecodable",
           "edge_lost", "secagg_dropout", "secagg_shed", "server_restart")
REASON_OK, REASON_NONFINITE, REASON_NORM_OUTLIER, REASON_SUSPECTED = range(4)

# sanitation default: reject ||update|| > 4x the weighted-median norm.
# Benign client norms on non-IID data spread ~2-3x; the classic scaled
# attacks (sign_flip/scale with factor >= 5) land well past 4x.
DEFAULT_NORM_MULT = 4.0

AGGREGATORS = ("mean", "median", "trimmed_mean", "krum", "multi_krum",
               "geometric_median")

# Estimators whose math is per-coordinate (sorts/cumsums along the client
# axis only — no arithmetic reduction whose grouping a resharding could
# change): under a mesh-sharded server state these run SHARD-LOCAL after an
# all-to-all from client-sharded to param-sharded stacked layout
# (gated_aggregate's ``reshard_fn``), bit-identical to the gathered path.
# krum / multi_krum / geometric_median need full flattened per-client
# vectors (pairwise distances, Weiszfeld) and keep the gathered path; the
# plain mean is excluded too — resharding would regroup its weighted-sum
# reduction and cost the bitwise replicated≡sharded parity contract.
COORDINATEWISE = frozenset({"median", "trimmed_mean"})


def _wshape(w, leaf):
    """[K] weights broadcast-shaped against a [K, ...] leaf."""
    return w.reshape((w.shape[0],) + (1,) * (leaf.ndim - 1))


def _sorted_with_weights(x, w):
    """Per-coordinate ascending sort of a [K, ...] leaf with the [K]
    weights carried along each coordinate's order."""
    order = jnp.argsort(x, axis=0)
    xs = jnp.take_along_axis(x, order, axis=0)
    wb = jnp.broadcast_to(_wshape(w, x), x.shape)
    ws = jnp.take_along_axis(wb, order, axis=0)
    return xs, ws


def weighted_median(stacked, weights):
    """Coordinate-wise weighted (lower) median over the leading client
    axis: the smallest value whose cumulative weight reaches half the
    total. Zero-weight slots contribute nothing; with uniform weights and
    an odd survivor count this is the exact coordinate-wise median."""
    w = jnp.asarray(weights, jnp.float32)

    def med(x):
        xs, ws = _sorted_with_weights(x, w)
        cum = jnp.cumsum(ws, axis=0)
        half = jnp.maximum(cum[-1:], 1e-12) * 0.5
        idx = jnp.argmax(cum >= half, axis=0)
        return jnp.take_along_axis(xs, idx[None], axis=0)[0]

    return jax.tree.map(med, stacked)


def weighted_trimmed_mean(stacked, weights, trim: float = 0.2):
    """Coordinate-wise weighted trimmed mean: each coordinate's sorted
    weight intervals are clipped to the central ``[trim*W, (1-trim)*W]``
    band of total weight ``W`` and averaged with the clipped widths. For
    uniform weights and integral trim counts this IS the classical trimmed
    mean; zero-weight slots have zero interval width and vanish."""
    if not 0.0 <= trim < 0.5:
        raise ValueError(f"trim must be in [0, 0.5), got {trim}")
    w = jnp.asarray(weights, jnp.float32)

    def tmean(x):
        xs, ws = _sorted_with_weights(x, w)
        cum = jnp.cumsum(ws, axis=0)
        total = cum[-1:]
        lo, hi = trim * total, (1.0 - trim) * total
        eff = jnp.clip(jnp.minimum(cum, hi) - jnp.maximum(cum - ws, lo),
                       0.0, None)
        return (jnp.sum(xs * eff, axis=0)
                / jnp.maximum(jnp.sum(eff, axis=0), 1e-12))

    return jax.tree.map(tmean, stacked)


def _flatten_clients(stacked):
    """[K, D] matrix of per-client flattened updates (every leaf raveled
    past the client axis and concatenated — float32 so distances in one
    dtype regardless of mixed leaves)."""
    leaves = jax.tree.leaves(stacked)
    return jnp.concatenate(
        [leaf.reshape(leaf.shape[0], -1).astype(jnp.float32)
         for leaf in leaves], axis=1)


def krum_scores(stacked, weights, f: int):
    """Krum scores: for each valid client, the sum of its ``n - f - 2``
    smallest squared distances to OTHER valid clients (n = number of
    positive-weight slots, a traced scalar). Invalid slots (weight 0)
    score +inf and are never anyone's neighbor."""
    v = _flatten_clients(stacked)
    k = v.shape[0]
    valid = jnp.asarray(weights, jnp.float32) > 0
    sq = jnp.sum(v * v, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (v @ v.T)
    d2 = jnp.maximum(d2, 0.0)  # clamp fp cancellation below zero
    inf = jnp.full_like(d2, jnp.inf)
    d2 = jnp.where(jnp.eye(k, dtype=bool) | ~valid[None, :], inf, d2)
    n = jnp.sum(valid.astype(jnp.int32))
    n_neighbors = jnp.maximum(n - f - 2, 1)
    ds = jnp.sort(d2, axis=1)
    take = jnp.arange(k)[None, :] < n_neighbors
    score = jnp.sum(jnp.where(take, ds, 0.0), axis=1)
    return jnp.where(valid, score, jnp.inf)


def _krum_suspected(score, valid, f: int):
    """The ``f`` worst-scoring VALID slots (ties broken by slot order) —
    the aggregator-level attribution the quarantine ledger records.
    Invalid slots sort LAST in the from-worst order (+inf) so a
    gate-rejected slot is never re-reported as krum-suspected. Shared by
    the stacked estimator and the evidence-phase verdict estimator so the
    two ledgers cannot drift."""
    if f <= 0:
        return jnp.zeros(score.shape, bool)
    rank_from_worst = jnp.argsort(jnp.argsort(
        jnp.where(valid, -score, jnp.inf)))
    return valid & (rank_from_worst < jnp.minimum(
        f, jnp.sum(valid.astype(jnp.int32))))


def krum(stacked, weights, f: int, m: int = 1):
    """(Multi-)Krum: ``m=1`` returns the single client minimizing the Krum
    score; ``m>1`` sample-weight-averages the ``m`` best-scoring clients.
    ``info['suspected']`` flags the ``f`` WORST-scoring valid clients —
    the aggregator-level attribution the quarantine ledger records.

    ``f`` and ``m`` are static (they shape the program); the number of
    valid clients is traced, so gate rejections need no recompile."""
    score = krum_scores(stacked, weights, f)
    k = score.shape[0]
    valid = jnp.isfinite(score)
    if m <= 1:
        win = jnp.argmin(score)
        agg = jax.tree.map(lambda x: jnp.take(x, win, axis=0), stacked)
    else:
        _, sel = jax.lax.top_k(-score, min(m, k))
        w = jnp.asarray(weights, jnp.float32)
        sel_w = jnp.where(jnp.isfinite(score[sel]), w[sel], 0.0)
        sel_tree = jax.tree.map(lambda x: jnp.take(x, sel, axis=0), stacked)
        agg = tree_weighted_mean(sel_tree, sel_w)
    return agg, {"suspected": _krum_suspected(score, valid, f)}


def geometric_median(stacked, weights, iters: int = 8, eps: float = 1e-8):
    """Weighted geometric median by a fixed-iteration Weiszfeld loop
    (jit-static ``iters``), initialized at the weighted mean. Zero-weight
    slots drop out of every reweighting."""
    v = _flatten_clients(stacked)
    w = jnp.asarray(weights, jnp.float32)
    z0 = (w @ v) / jnp.maximum(jnp.sum(w), 1e-12)

    def step(_, z):
        d = jnp.sqrt(jnp.sum((v - z[None, :]) ** 2, axis=1))
        beta = w / jnp.maximum(d, eps)
        return (beta @ v) / jnp.maximum(jnp.sum(beta), 1e-12)

    z = jax.lax.fori_loop(0, iters, step, z0)
    # unflatten back into the stacked tree's per-client leaf structure
    leaves = jax.tree.leaves(stacked)
    treedef = jax.tree.structure(stacked)
    out, off = [], 0
    for leaf in leaves:
        n = int(leaf.size // leaf.shape[0])
        out.append(z[off:off + n].reshape(leaf.shape[1:]).astype(leaf.dtype))
        off += n
    return jax.tree.unflatten(treedef, out)


def make_robust_aggregator(name: str, n: int, f: int | None = None,
                           trim: float | None = None, m: int | None = None,
                           iters: int = 8):
    """Build ``fn(stacked, weights) -> (tree, info)`` for aggregator
    ``name`` over ``n`` client slots. ``f`` is the Byzantine budget
    (default ``(n-3)//2``, Krum's maximum); ``trim`` the per-end trim
    fraction (default ``max(f/n, 0.1)``); ``m`` multi-Krum's selection
    count (default ``n - f - 2``)."""
    if name not in AGGREGATORS:
        raise ValueError(f"unknown aggregator {name!r} (one of {AGGREGATORS})")
    if f is None:
        f = max((n - 3) // 2, 0)
    if not 0 <= f < n:
        raise ValueError(f"f={f} must be in [0, {n})")
    if name == "mean":
        return lambda s, w: (tree_weighted_mean(s, w), {})
    if name == "median":
        return lambda s, w: (weighted_median(s, w), {})
    if name == "trimmed_mean":
        t = max(f / n, 0.1) if trim is None else trim
        return lambda s, w: (weighted_trimmed_mean(s, w, trim=t), {})
    if name in ("krum", "multi_krum"):
        if n < 2 * f + 3:
            raise ValueError(f"krum needs n >= 2f+3 (n={n}, f={f})")
        mm = 1 if name == "krum" else (max(n - f - 2, 1) if m is None
                                       else int(m))
        return partial(krum, f=f, m=mm)
    return lambda s, w: (geometric_median(s, w, iters=iters), {})


# -------------------------------------------------- pairwise association
# Canonical balanced-binary summation — the hierarchical-aggregation
# contract (docs/ROBUSTNESS.md §Hierarchical tiers). IEEE float addition
# is not associative, so a tree of edge aggregators that forwards partial
# weighted sums can only be BITWISE-identical to a flat aggregation if
# both reduce with the SAME association. The pairwise fold below is that
# association: at every level adjacent pairs are added (odd tails padded
# with exact-zero terms), so the fold over K slots is a complete binary
# tree aligned at every power-of-two boundary. An edge tier whose blocks
# are contiguous, power-of-two-sized slot ranges computes exactly the
# internal nodes of that tree — root combine ≡ flat fold, bit for bit.
# Opt-in (``gated_aggregate(pairwise=True)`` / the cross-process
# aggregator's ``sum_assoc='pairwise'``): the default weighted mean keeps
# its historical tensordot association, so existing bitwise contracts
# (sharded ≡ replicated, async ≡ sync, ...) are untouched.

def pairwise_sum(x):
    """Fold a [N, ...] array over axis 0 with the canonical pairwise
    association. Composable: folding contiguous power-of-two-sized blocks
    and then folding the block partials is bitwise the same as folding
    everything at once (property-tested)."""
    n = x.shape[0]
    if n == 0:
        return jnp.zeros(x.shape[1:], x.dtype)
    while n > 1:
        if n % 2:
            x = jnp.concatenate([x, jnp.zeros_like(x[:1])], axis=0)
            n += 1
        x = x[0::2] + x[1::2]
        n //= 2
    return x[0]


def pairwise_weighted_stats(stacked, weights):
    """(weighted-sum tree, total weight) over the leading client axis with
    the canonical association: terms ``w_k * u_k`` are formed per slot
    (f32) and pairwise-folded; the weight total folds the same way. The
    mean is ``wsum / total`` — division happens ONCE, at the final
    consumer (``pairwise_finalize``), which is what lets an edge tier ship
    raw partials without a lossy divide-then-remultiply round trip.

    The slot axis is zero-padded to EVEN length BEFORE the term multiply.
    XLA contracts the multiply into the first fold level as an fma
    (verified on CPU; ``optimization_barrier`` does not block the LLVM-
    level contraction), but only when the first level needs no zero-pad
    concatenate — so without this pre-pad the fold's BITS depended on the
    slot count's PARITY. Padding up front makes level 1 the same
    ``t[2i] = s[2i]*w[2i] + s[2i+1]*w[2i+1]`` expression for every K,
    which is what lets the streaming fused server ingest
    (core/fused_agg.py) reproduce the fold pair by pair across jit
    boundaries, bit for bit (its pair-combine jit compiles the identical
    expression). The pad slot is an exact-zero term (0 * 0), so values
    are unchanged; only odd-K bit patterns moved (from the accidental
    plain-multiply form to the canonical fma form)."""
    w = jnp.asarray(weights, jnp.float32)
    if w.shape[0] % 2:
        w = jnp.concatenate([w, jnp.zeros((1,), jnp.float32)])
        stacked = jax.tree.map(
            lambda s: jnp.concatenate([s, jnp.zeros_like(s[:1])]), stacked)
    wsum = jax.tree.map(
        lambda s: pairwise_sum(s.astype(jnp.float32) * _wshape(w, s)),
        stacked)
    return wsum, pairwise_sum(w)


def pairwise_finalize(wsum, total, global_tree):
    """wsum / total with the all-rejected fallback: zero surviving weight
    keeps the global model (the same rule gated_aggregate applies). The
    ONE division site shared by the flat pairwise path and the
    hierarchical root, so the two cannot drift."""
    alive = total > 0
    den = jnp.maximum(total, 1e-12)
    return jax.tree.map(
        lambda s, g: jnp.where(alive, s / den, g.astype(s.dtype)),
        wsum, global_tree)


def nonfinite_gate(stacked, global_tree, weights):
    """The per-slot half of :func:`sanitize_updates` — non-finite
    rejection only. Verdicts depend on nothing but the slot itself, so an
    edge aggregator gating its OWN children reaches exactly the verdicts
    a flat server would for those slots. This is the SINGLE-PHASE tree
    mode's whole defense; the cohort statistics (norm-outlier rule,
    robust estimators) compose across tiers via the two-phase
    evidence/verdict protocol instead (docs/ROBUSTNESS.md §Cross-tier
    robust gating)."""
    w = jnp.asarray(weights, jnp.float32)
    k = w.shape[0]
    finite = jnp.ones((k,), bool)
    for s in jax.tree.leaves(stacked):
        finite &= jnp.all(jnp.isfinite(s), axis=tuple(range(1, s.ndim)))
    reasons = jnp.where(finite, REASON_OK, REASON_NONFINITE)
    reasons = jnp.where(w > 0, reasons, REASON_OK).astype(jnp.int32)
    new_w = jnp.where(finite, w, 0.0)
    clean = jax.tree.map(
        lambda s, g: jnp.where(_wshape(~finite, s),
                               jnp.broadcast_to(g[None], s.shape)
                               .astype(s.dtype), s),
        stacked, global_tree)
    return clean, new_w, reasons


def edge_partial(stacked, global_tree, weights):
    """One edge aggregator's jittable round step: non-finite gate over its
    children, then the canonical pairwise partial — returns
    ``(wsum_tree, total_weight, reasons)``. The wsum/total pair is what
    rides the E2S uplink (one pre-aggregated update + weight: root fan-in
    is O(edges)); reasons carry the per-child quarantine verdicts so the
    root's ledger matches a flat run entry-for-entry."""
    clean, w, reasons = nonfinite_gate(stacked, global_tree, weights)
    wsum, total = pairwise_weighted_stats(clean, w)
    return wsum, total, reasons


def combine_edge_partials(partial_stack, totals, global_tree):
    """The root's combine: pairwise-fold the stacked edge partials
    ``[E, ...]`` and the ``[E]`` totals, then the shared finalize. With
    contiguous power-of-two edge blocks this is bitwise the flat pairwise
    aggregation over all K children (test-enforced)."""
    wsum = jax.tree.map(pairwise_sum, partial_stack)
    total = pairwise_sum(jnp.asarray(totals, jnp.float32))
    return pairwise_finalize(wsum, total, global_tree), total


# ----------------------------------------- two-phase robust (evidence/verdict)
# The cross-tier protocol (docs/ROBUSTNESS.md §Cross-tier robust gating):
# once aggregation is distributed over edge tiers to keep root fan-in
# bounded, any defense that needs the full stacked cohort at one rank
# re-creates the very bottleneck the tree removed (the Smart-NIC lesson,
# arXiv:2307.06561). The split below keeps the DATA at the edges and
# moves only VERDICT-SUFFICIENT evidence to the root:
#
#   phase 1  update_evidence   per-slot sanitation evidence — update norm,
#            (edge-local)      non-finite flag, and a fixed-size chunked-
#                              Rademacher sketch of the flattened update
#                              (sign-masked bucket sums: a count-sketch
#                              whose pairwise distances estimate the full-
#                              vector ones). Every operation is a per-row
#                              reduction, so edge-computed evidence is
#                              bitwise what a flat server would compute
#                              for the same slots.
#   phase 2  evidence_verdicts cohort-global math at ONE rank (the root,
#            (root)            or a flat server): the sanitation gate's
#                              norm-median rule (gate_verdicts — the SAME
#                              scalar half sanitize_updates runs) plus an
#                              estimator-selection pass over the sketches,
#                              emitting per-slot VERDICT WEIGHTS + reason
#                              codes.
#   phase 3  apply_verdicts    survivor fold: rejected/unselected slots
#            (edge-local)      are replaced by the global model and carry
#                              zero weight (the PR-4 survivor-reweighting
#                              rule), survivors fold with the canonical
#                              pairwise association — so an edge tier's
#                              block partials combine to the flat result
#                              bit for bit (pairwise_sum composition).
#
# Estimator selection (make_verdict_estimator) recasts each PR-4
# aggregator as a per-slot weighting over the evidence — the tiered form:
#   mean            gate-surviving sample weights (the weighted mean);
#   krum            the slot minimizing the Krum score over SKETCH
#                   distances, verdict weight 1.0 (x * 1.0 / 1.0 is
#                   exact, so the winner's update survives bitwise);
#   multi_krum      sample weights on the m best-scoring slots;
#   median          the weighted MEDOID over sketches — the slot
#                   minimizing the weighted sum of distances to the
#                   others (the selection form of the median; an exact
#                   coordinate-wise median needs the full cohort at one
#                   rank, which is the bottleneck this protocol exists to
#                   avoid);
#   trimmed_mean    winsorized interval weights over the DISTANCE-TO-
#                   CENTER order (the farthest 2*trim of total weight is
#                   trimmed — both coordinate "ends" collapse to large
#                   distance in update space);
#   geometric_median  a fixed-iteration Weiszfeld loop in sketch space;
#                   the verdict weights are the final iteration's
#                   ``w_k / max(d_k, eps)`` reweighting, so the full-
#                   space fold IS the smoothed-L1 estimate driven by
#                   sketch distances.
#
# A flat run opts into the identical composition via
# ``gated_aggregate(verdict_fn=...)`` (the cross-process aggregator's
# ``sum_assoc='pairwise'`` + ``aggregator=``), which is what makes
# tree ≡ flat bitwise — model bits AND ledger — for every estimator.

EVIDENCE_SKETCH_DIM = 64  # f32 scalars per client the sketch budget ships
_SKETCH_SEED = 0x5EDC0FFE  # fixed: both runtimes must draw the same signs


def update_sketch(stacked, global_tree, sketch_dim: int = EVIDENCE_SKETCH_DIM):
    """``[K, sketch_dim]`` chunked-Rademacher sketch of the flattened
    updates ``u_k = s_k - g``: coordinates are sign-flipped by a fixed
    seeded ±1 pattern and summed in ``sketch_dim`` contiguous buckets.
    Distance-preserving in expectation (the one-hash count-sketch), and —
    unlike a dense Gaussian projection — computed with per-row elementwise
    ops and trailing-axis reductions only, so an edge's block sketch is
    bitwise the flat cohort's rows. Non-finite entries are masked to zero
    (those slots are already dead at the gate)."""
    if sketch_dim <= 0:
        # sketchless mode (the mean/sanitize-only verdict estimator reads
        # no distances): ship zero evidence bytes instead of dead payload
        k = jax.tree.leaves(stacked)[0].shape[0]
        return jnp.zeros((k, 0), jnp.float32)
    rows = []
    for s, g in zip(jax.tree.leaves(stacked), jax.tree.leaves(global_tree)):
        d = s.astype(jnp.float32) - g.astype(jnp.float32)[None]
        d = jnp.where(jnp.isfinite(d), d, 0.0)
        rows.append(d.reshape(d.shape[0], -1))
    flat = jnp.concatenate(rows, axis=1)
    k, dsz = flat.shape
    chunk = -(-dsz // sketch_dim)  # ceil: bucket width
    pad = sketch_dim * chunk - dsz
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((k, pad), jnp.float32)], axis=1)
    signs = jax.random.rademacher(
        jax.random.PRNGKey(_SKETCH_SEED), (sketch_dim * chunk,),
        jnp.float32)
    return (flat * signs[None, :]).reshape(k, sketch_dim, chunk).sum(axis=-1)


def update_evidence(stacked, global_tree, weights,
                    sketch_dim: int = EVIDENCE_SKETCH_DIM):
    """Phase 1: the per-slot evidence dict an edge forwards in ONE compact
    ``e2s_evidence`` frame — ``sketch_dim + 3`` scalars per client
    (norm, finite, weight, sketch row), never the updates themselves."""
    finite, norm = _slot_evidence(stacked, global_tree)
    return {"norm": norm, "finite": finite,
            "sketch": update_sketch(stacked, global_tree, sketch_dim),
            "weight": jnp.asarray(weights, jnp.float32)}


def make_verdict_estimator(name: str, n: int, f: int | None = None,
                           trim: float | None = None, m: int | None = None,
                           iters: int = 8):
    """Build the evidence-phase estimator ``fn(sketch, gate_w) ->
    (verdict_weights, suspected)`` for aggregator ``name`` over ``n``
    cohort slots — the tiered form of :func:`make_robust_aggregator`
    (same budget defaults and validation: ``f`` defaults to ``(n-3)//2``,
    krum needs ``n >= 2f+3``, ``trim`` defaults to ``max(f/n, 0.1)``)."""
    if name not in AGGREGATORS:
        raise ValueError(f"unknown aggregator {name!r} (one of {AGGREGATORS})")
    if f is None:
        f = max((n - 3) // 2, 0)
    if not 0 <= f < n:
        raise ValueError(f"f={f} must be in [0, {n})")

    if name == "mean":
        return lambda sk, w: (w, None)

    if name in ("krum", "multi_krum"):
        if n < 2 * f + 3:
            raise ValueError(f"krum needs n >= 2f+3 (n={n}, f={f})")
        mm = 1 if name == "krum" else (max(n - f - 2, 1) if m is None
                                       else int(m))

        def krum_verdicts(sk, w):
            score = krum_scores([sk], w, f)
            valid = jnp.isfinite(score)
            if mm <= 1:
                # weight EXACTLY 1.0 on the winner: x * 1.0 / 1.0 is
                # bitwise x, so single-krum's take-the-winner semantics
                # survive the weighted fold; an all-invalid cohort keeps
                # zero weight everywhere (the global-model fallback)
                vw = jnp.zeros_like(w).at[jnp.argmin(score)].set(1.0)
                vw = jnp.where(jnp.any(valid), vw, 0.0)
            else:
                # bound by the REALIZED slot count, not the construction-n:
                # a flat elastic round stacks only the arrived uploads and
                # top_k refuses k > minor dim
                _, sel = jax.lax.top_k(-score, min(mm, score.shape[0]))
                selected = jnp.zeros((score.shape[0],), bool).at[sel].set(True)
                vw = jnp.where(selected & valid, w, 0.0)
            return vw, _krum_suspected(score, valid, f)

        return krum_verdicts

    if name == "median":
        def medoid_verdicts(sk, w):
            # the weighted MEDOID: argmin_i sum_j w_j ||sk_i - sk_j||
            valid = w > 0
            sq = jnp.sum(sk * sk, axis=1)
            d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (sk @ sk.T),
                             0.0)
            cost = jnp.sqrt(d2) @ jnp.where(valid, w, 0.0)
            cost = jnp.where(valid, cost, jnp.inf)
            vw = jnp.zeros_like(w).at[jnp.argmin(cost)].set(1.0)
            return jnp.where(jnp.any(valid), vw, 0.0), None

        return medoid_verdicts

    if name == "trimmed_mean":
        t = max(f / n, 0.1) if trim is None else trim
        if not 0.0 <= t < 0.5:
            raise ValueError(f"trim must be in [0, 0.5), got {t}")

        def trimmed_verdicts(sk, w):
            # winsorized interval weights over the distance-to-center
            # order: the farthest 2*trim of total weight is trimmed (both
            # per-coordinate "ends" collapse to large update-space
            # distance); boundary slots keep their fractional width, the
            # same clipped-interval rule weighted_trimmed_mean applies
            total = jnp.sum(w)
            center = (w @ sk) / jnp.maximum(total, 1e-12)
            dist = jnp.sqrt(jnp.sum((sk - center[None, :]) ** 2, axis=1))
            dist = jnp.where(w > 0, dist, jnp.inf)
            order = jnp.argsort(dist)
            ws = w[order]
            cum = jnp.cumsum(ws)
            hi = (1.0 - 2.0 * t) * total
            eff = jnp.clip(jnp.minimum(cum, hi) - (cum - ws), 0.0, None)
            return jnp.zeros_like(w).at[order].set(eff), None

        return trimmed_verdicts

    def weiszfeld_verdicts(sk, w):
        z0 = (w @ sk) / jnp.maximum(jnp.sum(w), 1e-12)

        def step(_, z):
            d = jnp.sqrt(jnp.sum((sk - z[None, :]) ** 2, axis=1))
            beta = w / jnp.maximum(d, 1e-8)
            return (beta @ sk) / jnp.maximum(jnp.sum(beta), 1e-12)

        # iters-1 refinement steps, then the final reweighting BECOMES the
        # verdict: the fold sum(beta_k u_k)/sum(beta_k) is exactly the
        # last Weiszfeld iterate, lifted to full update space
        z = jax.lax.fori_loop(0, max(iters - 1, 0), step, z0)
        d = jnp.sqrt(jnp.sum((sk - z[None, :]) ** 2, axis=1))
        return w / jnp.maximum(d, 1e-8), None

    return weiszfeld_verdicts


def evidence_verdicts(evidence, verdict_fn, norm_mult: float | None = None):
    """Phase 2 — the ONE cohort-global verdict composition (the root runs
    it over gathered edge evidence, a flat server over its own): gate
    (``gate_verdicts`` — the exact scalar half of ``sanitize_updates``,
    so the ledgers agree by construction) -> estimator selection ->
    merge ``suspected`` into the gate's reasons (gate reasons win).
    Returns ``(verdict_weights, reasons)``, both ``[K]``."""
    w = jnp.asarray(evidence["weight"], jnp.float32)
    mult = float("inf") if norm_mult is None else norm_mult
    _, gate_w, reasons = gate_verdicts(
        jnp.asarray(evidence["norm"], jnp.float32),
        jnp.asarray(evidence["finite"], bool), w, mult)
    vw, suspected = verdict_fn(
        jnp.asarray(evidence["sketch"], jnp.float32), gate_w)
    if suspected is not None:
        reasons = jnp.where((reasons == REASON_OK) & suspected,
                            REASON_SUSPECTED, reasons)
    return vw, reasons


def apply_verdicts(stacked, global_tree, vweights):
    """Phase 3 — the survivor fold an edge runs over its block (and a flat
    server over the whole cohort): zero-verdict slots are REPLACED by the
    global model (a NaN under a zero weight would still poison ``0 * nan``)
    and fold as exact zero terms; survivors fold with the canonical
    pairwise association. Returns ``(wsum_tree, total_weight)`` — the same
    partial shape the single-phase ``edge_partial`` ships, so the root's
    ``combine_edge_partials`` serves both protocols."""
    vw = jnp.asarray(vweights, jnp.float32)
    keep = vw > 0
    clean = jax.tree.map(
        lambda s, g: jnp.where(
            keep.reshape((keep.shape[0],) + (1,) * (s.ndim - 1)),
            s, jnp.broadcast_to(g[None], s.shape).astype(s.dtype)),
        stacked, global_tree)
    return pairwise_weighted_stats(clean, vw)


def verdict_flush(stacked, global_tree, evidence, verdict_fn,
                  norm_mult: float | None = None):
    """The flush half of the two-phase composition, defined ONCE:
    ``evidence_verdicts`` -> ``apply_verdicts`` -> ``pairwise_finalize``
    over PRECOMPUTED evidence rows. :func:`gated_aggregate` calls this
    with evidence it just computed from the stacked cohort; the fused
    ingest plane (core/fused_agg.py) calls it with evidence rows emitted
    one arrival at a time (per-row reductions, so the rows are bitwise
    the cohort's — see :func:`_slot_evidence`). Sharing the composition
    is what makes fused×robust bitwise the stacked path by construction,
    model bits AND reason codes, rather than by parallel implementations.

    Returns ``(avg_tree, verdict_weights, reasons)``."""
    vw, reasons = evidence_verdicts(evidence, verdict_fn,
                                    norm_mult=norm_mult)
    wsum, total = apply_verdicts(stacked, global_tree, vw)
    return pairwise_finalize(wsum, total, global_tree), vw, reasons


# ------------------------------------------------------------------ gate
def _slot_evidence(stacked, global_tree):
    """Per-slot sanitation evidence over the full tree: ``(finite, norm)``
    where ``finite[k]`` is the all-leaves-finite flag and ``norm[k]`` is
    ``||u_k - g||`` with non-finite entries masked out of the sum. Every
    operation is a PER-ROW reduction (trailing axes only), so the values
    are bitwise independent of how many slots share the leading axis —
    which is what lets an edge aggregator compute its block's evidence
    locally and a flat server compute the whole cohort's, and the two
    agree slot-for-slot (docs/ROBUSTNESS.md §Cross-tier robust gating)."""
    k = jax.tree.leaves(stacked)[0].shape[0]
    finite = jnp.ones((k,), bool)
    norm_sq = jnp.zeros((k,), jnp.float32)
    for s, g in zip(jax.tree.leaves(stacked), jax.tree.leaves(global_tree)):
        axes = tuple(range(1, s.ndim))
        finite &= jnp.all(jnp.isfinite(s), axis=axes)
        d = (s.astype(jnp.float32)
             - g.astype(jnp.float32)[None])
        # non-finite entries would NaN the norm; they are already
        # rejected by the finite flag, so mask them out of the sum
        norm_sq += jnp.sum(jnp.where(jnp.isfinite(d), d, 0.0) ** 2,
                           axis=axes)
    return finite, jnp.sqrt(norm_sq)


def gate_verdicts(norm, finite, weights, norm_mult: float):
    """The cohort-global scalar half of :func:`sanitize_updates`: given
    per-slot evidence (``norm``, ``finite``) and weights, decide
    ``(replace, new_weights, reasons)``. Factored out so the hierarchical
    root can run EXACTLY the flat gate's math over evidence gathered from
    edges — the two ledgers agree by construction, not by parallel
    implementations."""
    w = jnp.asarray(weights, jnp.float32)
    # unweighted median of the finite, participating slots' norms (one
    # vote per client — see the sanitize_updates docstring)
    med_w = (finite & (w > 0)).astype(jnp.float32)
    med = weighted_median(norm, med_w)
    outlier = finite & (w > 0) & (norm > norm_mult * jnp.maximum(med, 1e-12))
    replace = ~finite | outlier
    reasons = jnp.where(~finite, REASON_NONFINITE,
                        jnp.where(outlier, REASON_NORM_OUTLIER, REASON_OK))
    reasons = jnp.where(w > 0, reasons, REASON_OK).astype(jnp.int32)
    new_w = jnp.where(replace, 0.0, w)
    return replace, new_w, reasons


def sanitize_updates(stacked, global_tree, weights,
                     norm_mult: float = DEFAULT_NORM_MULT):
    """The sanitation gate, in-graph: per slot decide ok / nonfinite /
    norm-outlier, then neutralize rejects.

    Returns ``(clean_stacked, new_weights, reasons)`` where ``reasons`` is
    an int32 ``[K]`` of ``REASONS`` codes. A rejected slot's update is
    REPLACED by the broadcast global model and its weight zeroed — both
    matter: weights alone leave NaNs free to poison sorts, distances, and
    ``0 * nan`` products; values alone leave the reject counted in the
    weight mass. Survivor weights are untouched, so any downstream
    aggregator's internal normalization IS the elastic partial-aggregation
    reweighting — exact over the survivors.

    Non-finite is checked over every leaf (the wire's float path performs
    no clamping by design — comm/message.py ships f32 bits verbatim, so
    this gate is where a NaN upload must die). The norm rule compares each
    slot's update norm ``||u_k - g||`` (over the full tree) to the
    UNWEIGHTED median norm of the finite participating slots: reject
    beyond ``norm_mult * median``. Unweighted on purpose: sample counts
    are client-REPORTED (a Byzantine client can claim any weight), so a
    weighted baseline would let an attacker holding — or fabricating —
    more than half the weight mass become its own reference norm. The
    gate's breakdown is therefore by client COUNT (f < n/2), the standard
    Byzantine model; the aggregators behind it stay sample-weighted.
    ``norm_mult=inf`` disables the norm rule but keeps the non-finite one.
    """
    w = jnp.asarray(weights, jnp.float32)
    finite, norm = _slot_evidence(stacked, global_tree)

    # value replacement covers EVERY non-finite/outlier slot (even
    # zero-weight padding — a stray NaN there would still poison sorts and
    # pairwise distances); the REPORTED reasons cover only participating
    # (w > 0) slots, so padding never shows up in the ledger.
    replace, new_w, reasons = gate_verdicts(norm, finite, w, norm_mult)
    clean = jax.tree.map(
        lambda s, g: jnp.where(_wshape(replace, s),
                               jnp.broadcast_to(g[None], s.shape)
                               .astype(s.dtype), s),
        stacked, global_tree)
    return clean, new_w, reasons


def gated_aggregate(stacked, global_tree, weights, robust_fn=None,
                    norm_mult: float | None = None, reshard_fn=None,
                    pairwise: bool = False, verdict_fn=None,
                    sketch_dim: int = EVIDENCE_SKETCH_DIM):
    """The full verdict composition, jittable, defined ONCE for both
    runtimes (their quarantine ledgers must agree entry-for-entry, so the
    composition rule must not exist in two dialects):

    gate (``norm_mult`` armed; None = off) -> estimator (``robust_fn`` or
    the weighted mean) -> merge the estimator's ``suspected`` verdicts
    into the gate's reason codes (gate reasons win) -> if EVERY slot was
    rejected, fall back to the global model instead of averaging an empty
    survivor set.

    ``reshard_fn`` (mesh-sharded server state only): a layout constraint
    applied to the gated stacked updates AFTER the gate and BEFORE the
    estimator — the sharded engines pass the partitioner's
    ``stacked_constrainer(net)`` for COORDINATEWISE estimators so their
    per-coordinate sorts run shard-local (client-sharded -> param-sharded
    all-to-all). A pure resharding: bits move, values don't, and the gate
    itself always sees the estimator's input in the same layout both
    paths produce.

    ``pairwise`` replaces the weighted-mean estimator's tensordot with
    the canonical balanced-binary association (see :func:`pairwise_sum`)
    — the flat twin of a hierarchical edge tier, bitwise-comparable to
    any 2-tier topology over the same cohort. Mean only; ROBUST
    estimators get their tiered form via ``verdict_fn`` instead.

    ``verdict_fn`` (from :func:`make_verdict_estimator`) switches to the
    two-phase composition — update_evidence -> evidence_verdicts ->
    apply_verdicts -> pairwise_finalize — the flat twin of the cross-tier
    robust protocol (docs/ROBUSTNESS.md §Cross-tier robust gating): a
    flat run with ``verdict_fn`` is bitwise a 2-tier robust run over the
    same cohort, model bits AND reason codes. The gate arms through the
    same ``norm_mult``; ``robust_fn``/``pairwise`` must stay unset (one
    composition per call).

    Returns ``(avg_tree, surviving_weights, reasons)``; ``reasons`` is
    None only when the gate is off AND the estimator reported nothing.
    """
    if pairwise and robust_fn is not None:
        raise ValueError("pairwise association is the weighted-mean "
                         "contract — robust estimators' tiered form is "
                         "verdict_fn (make_verdict_estimator)")
    if verdict_fn is not None:
        if robust_fn is not None or pairwise:
            raise ValueError("verdict_fn IS the two-phase composition — "
                             "it does not stack with robust_fn/pairwise")
        ev = update_evidence(stacked, global_tree, weights,
                             sketch_dim=sketch_dim)
        return verdict_flush(stacked, global_tree, ev, verdict_fn,
                             norm_mult=norm_mult)
    w = jnp.asarray(weights, jnp.float32)
    reasons = None
    agg_in = stacked
    if norm_mult is not None:
        agg_in, w, reasons = sanitize_updates(stacked, global_tree, w,
                                              norm_mult=norm_mult)
    if reshard_fn is not None:
        agg_in = reshard_fn(agg_in)
    if pairwise:
        wsum, total = pairwise_weighted_stats(agg_in, w)
        return pairwise_finalize(wsum, total, global_tree), w, reasons
    if robust_fn is not None:
        avg, info = robust_fn(agg_in, w)
        sus = info.get("suspected")
        if sus is not None:
            base = (reasons if reasons is not None
                    else jnp.zeros(sus.shape, jnp.int32))
            reasons = jnp.where((base == REASON_OK) & sus,
                                REASON_SUSPECTED, base)
    else:
        avg = tree_weighted_mean(agg_in, w)
    if reasons is not None:
        alive = jnp.sum(w) > 0
        avg = jax.tree.map(lambda a, g: jnp.where(alive, a, g), avg,
                           global_tree)
    return avg, w, reasons


# ---------------------------------------------------------------- ledger
class QuarantineLedger:
    """Thread-safe record of per-round gate/aggregator verdicts — the
    model-space sibling of the chaos FaultLedger, and the artifact the
    standalone and cross-process runtimes must AGREE on for the same
    adversary plan (test-enforced). ``rank`` is the 1-based worker rank,
    which in the standalone engine is the stacked slot index + 1 (the same
    client the loopback runtime's rank trains)."""

    def __init__(self):
        self._entries: list[dict] = []
        self._lock = threading.Lock()
        # crash-recovery journal hook (docs/ROBUSTNESS.md §Server crash
        # recovery): callable(entry_dict) invoked per verdict so the
        # server's WAL carries a forensic trail of mid-round quarantines;
        # the ledger's commit-time authority stays quarantine.json. None =
        # no journaling, zero extra work.
        self.journal = None

    def record(self, round_idx: int, rank: int, reason: str,
               client=None) -> None:
        if reason not in REASONS or reason == "ok":
            raise ValueError(f"unrecordable quarantine reason {reason!r}")
        entry = {
            "round": int(round_idx), "rank": int(rank),
            "reason": reason,
            "client": None if client is None else int(client),
        }
        with self._lock:
            self._entries.append(entry)
        if self.journal is not None:
            self.journal(dict(entry))

    def record_codes(self, round_idx: int, reasons, clients=None,
                     ranks=None) -> None:
        """Fold a round's in-graph ``[K]`` reason-code vector into ledger
        entries; also feeds the metric families. Slot ``i`` maps to worker
        rank ``i + 1`` unless ``ranks`` gives the explicit slot->rank map
        (elastic partial rounds aggregate a rank subset)."""
        from fedml_tpu.obs import comm_instrument as _obs

        for slot, code in enumerate(reasons):
            code = int(code)
            if code == REASON_OK:
                continue
            reason = REASONS[code]
            client = None if clients is None else clients[slot]
            rank = (slot + 1) if ranks is None else int(ranks[slot])
            self.record(round_idx, rank, reason, client=client)
            _obs.record_update_rejected(reason)
            _obs.record_suspected_rank(rank)

    def entries(self) -> list[dict]:
        """Copy of the raw entries in record order — what the server
        checkpoints alongside the model (quarantine.json) so a restarted
        process reports the SAME ledger an uninterrupted run would
        (docs/ROBUSTNESS.md §Server crash recovery)."""
        with self._lock:
            return [dict(e) for e in self._entries]

    def restore(self, entries) -> None:
        """Re-install checkpointed/WAL-replayed entries (crash recovery).
        Routed through :meth:`record` so the reason vocabulary stays
        validated; metric families are NOT re-fed — the restarted
        process's counters track what IT observed, the ledger tracks the
        run — and the journal hook is suppressed (restored entries are
        already durable; re-journaling them would grow the WAL per
        boot)."""
        j, self.journal = self.journal, None
        try:
            for e in entries:
                self.record(int(e["round"]), int(e["rank"]), e["reason"],
                            client=e.get("client"))
        finally:
            self.journal = j

    def canonical(self) -> list[tuple]:
        with self._lock:
            return sorted((e["round"], e["rank"], e["reason"], e["client"])
                          for e in self._entries)

    def for_round(self, round_idx: int) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._entries
                    if e["round"] == round_idx]

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        with self._lock:
            for e in self._entries:
                out[e["reason"]] = out.get(e["reason"], 0) + 1
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
