"""Learning-rate schedules (reference: LR_Scheduler,
fedml_api/distributed/fedseg/utils.py:113-170).

The reference mutates optimizer.param_groups per iteration with three modes —
step (``base * 0.1^(epoch // lr_step)``), cos
(``0.5 * base * (1 + cos(pi * T / N))``) and poly
(``base * (1 - T/N)^0.9``) — plus a linear warmup over the first
``warmup_epochs`` epochs. Here the same curves are pure step->lr functions
plugged straight into optax (``optax.sgd(schedule)``), so the schedule is
traced into the jitted local-update program instead of touched from Python.
"""

from __future__ import annotations

import jax.numpy as jnp


def make_lr_schedule(
    mode: str,
    base_lr: float,
    total_steps: int,
    *,
    warmup_steps: int = 0,
    steps_per_epoch: int = 1,
    lr_step: int = 0,
    power: float = 0.9,
):
    """Return ``schedule(step) -> lr`` matching the reference's modes.

    total_steps = N = num_epochs * iters_per_epoch; ``step`` is the global
    iteration T. ``constant`` is also accepted (no reference analogue needed
    for FedAvg-family algorithms).
    """
    if mode == "step" and not lr_step:
        raise ValueError("mode='step' requires lr_step")

    def schedule(step):
        t = jnp.asarray(step, jnp.float32)
        n = jnp.asarray(max(total_steps, 1), jnp.float32)
        if mode == "constant":
            lr = jnp.asarray(base_lr, jnp.float32)
        elif mode == "cos":
            lr = 0.5 * base_lr * (1.0 + jnp.cos(jnp.pi * t / n))
        elif mode == "poly":
            lr = base_lr * jnp.power(jnp.clip(1.0 - t / n, 0.0, 1.0), power)
        elif mode == "step":
            epoch = jnp.floor(t / steps_per_epoch)
            lr = base_lr * jnp.power(0.1, jnp.floor(epoch / lr_step))
        else:
            raise ValueError(f"unknown lr schedule mode {mode!r}")
        if warmup_steps > 0:
            lr = jnp.where(t < warmup_steps, lr * t / warmup_steps, lr)
        return lr

    return schedule
