"""Round checkpointing (parity-plus: the reference has NO checkpoint/resume in
its FL loop — SURVEY.md §5 — only FedNAS genotype logging; we add orbax-style
round checkpoints of server params + optimizer state + round idx + RNG key).
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax
import numpy as np

from fedml_tpu.core.wal import durable_open, durable_replace, durable_write


class TornCheckpoint(Exception):
    """A checkpoint file that cannot even be LOADED (truncated zip, short
    read, crash mid-write) — distinct from a structure mismatch, which is
    a configuration error and stays loud. ``restore_latest`` skips (and
    counts) torn files; direct ``restore_round`` callers see the raise."""


def _gather_leaf(v):
    """Gather-on-save for mesh-partitioned server state: a sharded leaf is
    assembled to one full host array before serialization. Without this a
    partitioned pytree either crashes the npz fallback or round-trips a
    layout tied to one mesh shape; gathered checkpoints are shard-agnostic
    — a state saved from an 8-way sharded run restores onto 4 devices, 1
    device, or a different rule table (the engine re-partitions at
    ``load_state``). Single-process only, like everything in this module:
    every shard is addressable, so ``device_get`` assembles exactly."""
    if isinstance(v, jax.Array) and not v.is_fully_replicated:
        return np.asarray(jax.device_get(v))
    return v


def save_round(ckpt_dir: str, round_idx: int, net, server_opt_state, rng,
               history: list | None = None, keep: int = 3,
               extra_state: dict | None = None):
    """Save a round checkpoint via orbax (falls back to npz if orbax breaks).

    Sharded server state (FedAvgAPI(shard_server_state=True)) is gathered
    on save — see :func:`_gather_leaf`.

    ``extra_state``: additional top-level entries (e.g. the DP accountant's
    RDP totals) — restore templates must declare the same keys."""
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"round_{round_idx:06d}")
    state = {
        "net": net,
        "server_opt_state": server_opt_state,
        "rng": rng,
        "round": np.asarray(round_idx, np.int64),
    }
    if extra_state:
        state.update(extra_state)
    state = jax.tree.map(_gather_leaf, state)
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), state, force=True)
        ckptr.wait_until_finished()
    except Exception:
        leaves, treedef = jax.tree.flatten(state)
        # atomic + durable: write under a tmp name that _completed_rounds
        # ignores, fsync, then rename (+ dir fsync) — a crash mid-save must
        # not leave a loadable-looking file, and a crash right after the
        # rename must not lose the rename (core/wal.py durability helpers;
        # the fedlint fsync-discipline rule pins this path)
        tmp = path + ".npz.tmp"
        try:
            with durable_open(tmp, "wb") as f:
                np.savez(f, treedef=str(treedef),
                         **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})
            durable_replace(tmp, path + ".npz")
        finally:
            if os.path.exists(tmp):  # don't let an orphan eat a _prune slot
                os.unlink(tmp)
    if history is not None:
        import json

        durable_write(os.path.join(ckpt_dir, "history.json"),
                      json.dumps(history).encode())
    _prune(ckpt_dir, keep)
    return path


class AsyncCheckpointer:
    """Round checkpoints written OFF the training thread.

    The caller pays only the device→host snapshot; serialization + disk
    I/O + pruning overlap with the following rounds' compute (the orbax
    async pattern, without requiring orbax). The snapshot must happen on
    the calling thread BEFORE handoff: jax arrays are immutable, but
    engines running with ``donate=True`` hand their buffers to the next
    round's program, which invalidates them — a background thread reading
    them later would crash (or worse, on some backends, read garbage).

    One save in flight at a time: a second ``save()`` first waits for the
    previous write (backpressure instead of a snapshot queue growing
    unboundedly when disk is slower than training). ``wait()``/``close()``
    flush; a failed background write surfaces on the next call rather
    than being dropped.
    """

    def __init__(self, ckpt_dir: str, keep: int = 3):
        from concurrent.futures import ThreadPoolExecutor

        self.ckpt_dir = ckpt_dir
        self.keep = keep
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ckpt")
        self._inflight = None

    def save(self, round_idx: int, net, server_opt_state, rng,
             history: list | None = None,
             extra_state: dict | None = None) -> None:
        # snapshot on the caller's thread (see class docstring)
        host = jax.device_get(
            {"net": net, "server_opt_state": server_opt_state, "rng": rng,
             "extra": extra_state})
        self.wait()  # backpressure + surface a previous write's failure
        self._inflight = self._pool.submit(
            save_round, self.ckpt_dir, round_idx, host["net"],
            host["server_opt_state"], host["rng"],
            list(history) if history is not None else None, self.keep,
            host["extra"])

    def wait(self) -> None:
        if self._inflight is not None:
            fut, self._inflight = self._inflight, None
            fut.result()  # re-raises a failed write

    def close(self) -> None:
        try:
            self.wait()
        finally:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
            return
        # already unwinding (e.g. a training crash): a failed background
        # write must not REPLACE the real exception as the propagating
        # error — log it and let the original failure surface
        try:
            self.close()
        except Exception:  # noqa: BLE001
            import logging

            logging.getLogger("fedml_tpu.checkpoint").exception(
                "async checkpoint write failed while unwinding %r", exc)


_ROUND_RE = re.compile(r"^round_(\d{6})(\.npz)?$")


def _completed_rounds(ckpt_dir: str) -> list[int]:
    """Only COMPLETED checkpoints: 'round_NNNNNN' dirs or '.npz' files —
    orbax in-progress temp dirs (round_NNNNNN.orbax-checkpoint-tmp-*) and
    half-written '.npz.tmp' files from a crash mid-save must not be offered
    for resume."""
    return [int(m.group(1))
            for d in os.listdir(ckpt_dir) if (m := _ROUND_RE.match(d))]


def latest_round(ckpt_dir: str) -> int | None:
    if not os.path.isdir(ckpt_dir):
        return None
    rounds = _completed_rounds(ckpt_dir)
    return max(rounds) if rounds else None


def restore_round(ckpt_dir: str, round_idx: int, template: Any):
    """Restore a checkpoint into the same pytree structure as ``template``
    (a dict with net/server_opt_state/rng/round built like in save_round).

    Raises :class:`TornCheckpoint` when the file cannot be LOADED (a crash
    mid-write left a truncated container) — structure/shape mismatches
    against the template stay ValueError (a configuration error, never a
    torn artifact)."""
    path = os.path.join(ckpt_dir, f"round_{round_idx:06d}")
    if os.path.isdir(path):
        try:
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            return ckptr.restore(os.path.abspath(path), target=template)
        except (OSError, EOFError) as e:
            raise TornCheckpoint(f"unreadable checkpoint dir {path}: {e}")
    try:
        npz = np.load(path + ".npz", allow_pickle=False)
    except (OSError, EOFError, ValueError) as e:
        # zipfile.BadZipFile is an OSError subclass... no — it subclasses
        # Exception; name-match it so this module needs no zipfile import
        raise TornCheckpoint(f"unloadable checkpoint {path}.npz: {e}")
    except Exception as e:  # noqa: BLE001 — np.load raises BadZipFile /
        # zlib.error on truncation; anything else load-phase is torn too
        if type(e).__name__ not in ("BadZipFile", "error"):
            raise
        raise TornCheckpoint(f"unloadable checkpoint {path}.npz: {e}")
    leaves, treedef = jax.tree.flatten(template)
    # the npz fallback maps leaves to the template purely by index, so a
    # template whose structure differs from the saved one (e.g. a dp run's
    # checkpoint — which carries a dp_rdp leaf that sorts FIRST — resumed
    # without dp) would silently shift every leaf by one and install the
    # RDP totals as model weights; fail loudly instead
    n_saved = sum(1 for k in npz.files if k.startswith("leaf_"))
    if n_saved != len(leaves) or str(npz["treedef"]) != str(treedef):
        raise ValueError(
            f"checkpoint structure mismatch at {path}.npz: saved "
            f"{n_saved} leaves / treedef {npz['treedef']}, template has "
            f"{len(leaves)} leaves / treedef {treedef} — was the run "
            "configuration (e.g. --defense_type) changed across resume?")
    try:
        # members decompress lazily — a mid-file truncation that spared
        # the zip directory still surfaces here, as torn, not as a crash
        restored = [npz[f"leaf_{i}"] for i in range(len(leaves))]
    except Exception as e:  # noqa: BLE001 — BadZipFile/zlib.error/EOFError
        raise TornCheckpoint(f"truncated checkpoint member in {path}.npz: {e}")
    for i, (t, r) in enumerate(zip(leaves, restored)):
        if np.shape(t) != np.shape(r):
            raise ValueError(
                f"checkpoint leaf {i} shape mismatch at {path}.npz: "
                f"saved {np.shape(r)}, template {np.shape(t)}")
    return jax.tree.unflatten(treedef, restored)


def restore_latest(ckpt_dir: str, template: Any):
    """Restore the newest RESTORABLE checkpoint: a torn newest file (crash
    mid-save that still published a name, or bit rot) is skipped — counted
    on ``fed_ckpt_torn_total`` and warned — and recovery falls back to the
    previous round instead of crashing the restart loop. Returns
    ``(round_idx, state)`` or ``None`` when nothing is restorable."""
    import logging

    if not os.path.isdir(ckpt_dir):
        return None
    log = logging.getLogger("fedml_tpu.checkpoint")
    for r in sorted(_completed_rounds(ckpt_dir), reverse=True):
        try:
            return r, restore_round(ckpt_dir, r, template)
        except TornCheckpoint as e:
            from fedml_tpu.obs import perf_instrument as _perf

            _perf.record_ckpt_torn()
            log.warning("skipping torn checkpoint round %d: %s "
                        "(falling back to the previous round)", r, e)
    return None


def _prune(ckpt_dir: str, keep: int):
    import shutil

    rounds = sorted(
        d for d in os.listdir(ckpt_dir)
        if d.startswith("round_") and not d.endswith(".tmp")
    )
    for d in rounds[:-keep] if keep else []:
        p = os.path.join(ckpt_dir, d)
        shutil.rmtree(p, ignore_errors=True) if os.path.isdir(p) else os.remove(p)
