"""Per-round client sampling.

Reference semantics (FedAVGAggregator.client_sampling,
fedml_api/distributed/fedavg/FedAVGAggregator.py:89-97): seed numpy with the
round index, then np.random.choice(num_clients, n, replace=False); full
participation when client_num_per_round == client_num_in_total. We reproduce
the same *semantics* (deterministic per-round subset, uniform without
replacement) with numpy seeded by (seed, round) so host-side data packing can
use it, and provide a jax.random variant for on-device sampling.
"""

from __future__ import annotations

import numpy as np
import jax.random as jrandom


def sample_clients(
    round_idx: int,
    client_num_in_total: int,
    client_num_per_round: int,
    seed: int = 0,
    p=None,
) -> np.ndarray:
    """Host-side deterministic sampler (numpy RandomState(seed + round));
    ``p`` optionally weights the draw (shared seeding/sort/dtype contract
    for the uniform and weighted variants)."""
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total, dtype=np.int64)
    rng = np.random.RandomState(seed * 1_000_003 + round_idx)
    return np.sort(
        rng.choice(client_num_in_total, client_num_per_round, replace=False,
                   p=p)
    ).astype(np.int64)


def sample_clients_weighted(
    round_idx: int,
    client_sizes,
    client_num_per_round: int,
    seed: int = 0,
) -> np.ndarray:
    """Size-weighted sampler in the spirit of the FedAvg paper's second
    sampling scheme (P(client k) ∝ n_k, paired with a UNIFORM aggregate —
    FedAvgConfig.sampling='size_weighted'; the reference only implements
    uniform).

    Honesty note on the unbiasedness argument: the paper samples WITH
    replacement, where P∝n_k + uniform averaging is exactly unbiased.
    This draws ``np.random.choice(replace=False, p=...)``, which selects
    sequentially — inclusion probabilities are then NOT exactly ∝ n_k
    (large clients saturate), so the uniform-average estimator carries a
    small bias unless m << N. Without replacement is kept deliberately:
    duplicate client fits would waste round compute, and for the m << N
    cross-device regime this targets, the approximation error is far below
    sampling noise.

    Degenerate sizes are handled rather than crashed on: zero-size clients
    get a vanishing (not zero) probability so a skewed partition with
    fewer nonzero clients than the round needs still draws a full round;
    all-zero sizes fall back to uniform."""
    sizes = np.asarray(client_sizes, np.float64)
    return sample_clients(round_idx, len(sizes), client_num_per_round, seed,
                          p=_size_probs(sizes))


def _size_probs(sizes: np.ndarray):
    """The size_weighted probability vector (None = uniform fallback) —
    shared by the full-population and churn-restricted samplers."""
    if not np.any(sizes > 0):
        return None
    floor = sizes[sizes > 0].min() * 1e-9
    p = np.maximum(sizes, floor)
    return p / p.sum()


def sample_available(cfg, round_idx: int, trace, client_sizes=None
                     ) -> np.ndarray:
    """Churn-aware per-round draw: restrict the population to the trace's
    scheduled-available cohort for this round's window, then run the SAME
    seeded RandomState stream over the restricted index space. Returns
    ``min(client_num_per_round, available)`` sorted ids — under a diurnal
    trough the cohort legitimately shrinks (the acceptance test asserts
    cohort sizes follow the curve); the trace's min-one floor keeps it
    nonempty. Deterministic: availability draws live on ChurnTrace's
    sha256 stream, the subset draw on sample_clients' numpy stream, so
    churn composes with chaos/adversary plans without draw coupling."""
    avail = trace.available_clients(trace.window(round_idx),
                                    cfg.client_num_in_total)
    n = min(cfg.client_num_per_round, len(avail))
    if n == len(avail):
        return avail
    p = None
    if cfg.sampling == "size_weighted":
        if client_sizes is None:
            raise ValueError("size_weighted sampling needs the per-client "
                             "sizes — pass prepare_sampling(cfg, data)")
        p = _size_probs(np.asarray(client_sizes, np.float64)[avail])
    idx = sample_clients(round_idx, len(avail), n, cfg.seed, p=p)
    return np.sort(avail[idx]).astype(np.int64)


def prepare_sampling(cfg, data) -> np.ndarray | None:
    """Construction-time half of the sampling dispatch: validate
    ``cfg.sampling`` (fail fast, not at the first round after an
    expensive engine build) and precompute what the per-round draw needs
    — per-client sizes for size_weighted, nothing for uniform."""
    if cfg.sampling == "size_weighted":
        if hasattr(data, "client_sizes"):
            # streamed ClientDataSource: sizes are metadata, no payload read
            return np.asarray(data.client_sizes)[: cfg.client_num_in_total]
        return np.asarray([len(data.train_idx_map[c])
                           for c in range(cfg.client_num_in_total)])
    if cfg.sampling != "uniform":
        raise ValueError(f"unknown sampling {cfg.sampling!r} "
                         "(uniform | size_weighted)")
    return None


def sample_for(cfg, round_idx: int, client_sizes=None) -> np.ndarray:
    """Per-round half of the dispatch — the shared entry for every engine
    that honors the flag (uniform | size_weighted; the weighted scheme
    needs prepare_sampling's sizes and must pair with a uniform
    aggregate). An active ``cfg.churn_trace`` restricts every draw to the
    trace's scheduled-available cohort for the round's window."""
    if cfg.sampling not in ("uniform", "size_weighted"):
        raise ValueError(f"unknown sampling {cfg.sampling!r} "
                         "(uniform | size_weighted)")
    trace = getattr(cfg, "churn_trace", None)
    if trace is not None:
        return sample_available(cfg, round_idx, trace, client_sizes)
    if cfg.sampling == "size_weighted":
        if client_sizes is None:
            raise ValueError("size_weighted sampling needs the per-client "
                             "sizes — pass prepare_sampling(cfg, data)")
        return sample_clients_weighted(
            round_idx, client_sizes, cfg.client_num_per_round, cfg.seed)
    return sample_clients(round_idx, cfg.client_num_in_total,
                          cfg.client_num_per_round, cfg.seed)


def sample_clients_device(key, round_idx, client_num_in_total: int, client_num_per_round: int):
    """On-device sampler: fold the round index into the key and take a
    without-replacement choice. Shapes are static; usable under jit."""
    k = jrandom.fold_in(key, round_idx)
    return jrandom.choice(
        k, client_num_in_total, (client_num_per_round,), replace=False
    )
