"""Regex partition-rule table over a param pytree -> per-leaf NamedShardings.

The server plane's scaling problem (ROADMAP item 2): a replicated global
model plus stack-and-average aggregation costs HBM and FLOPs proportional
to model size x cohort on EVERY device. "Automatic Cross-Replica Sharding
of Weight Update in Data-Parallel Training" (arXiv:2004.13336) shows the
weight-update step can instead be sharded across replicas — reduce-scatter
the update sum, apply the server step shard-locally, all-gather only when
the full weights are needed — at no convergence cost. XLA implements that
rewrite automatically once the state carries sharded layouts; this module
supplies the layouts.

Shape (after the ``match_partition_rules`` + partitioner idiom the LLM/FL
training stacks converged on — SNIPPETS.md [1]/[3]): an ordered table of
``(regex, rule)`` pairs is matched against each leaf's ``/``-joined tree
path (first match wins); the winning rule resolves to a
``PartitionSpec`` given the leaf's shape and the mesh axis being sharded
over. Rules:

- ``"replicated"`` / ``None``  — ``P()`` (every device holds the leaf);
- ``"auto"``                   — shard the LARGEST dim divisible by the
                                 mesh-axis size (ties: lowest dim index);
                                 nothing divisible -> replicated;
- ``int d``                    — shard dim ``d`` (must divide; loud error
                                 otherwise — an explicit rule that cannot
                                 apply is a config bug, not a fallback);
- ``[e0, e1, ...]``            — an explicit per-dim spec entry list
                                 (``None`` or the axis name), i.e. a raw
                                 ``PartitionSpec``.

Scalars and single-element leaves are never partitioned (the snippet's
guard), whatever the table says. ``default`` covers leaves no rule
matches: a rule value (applied), or ``None`` to make an unmatched leaf a
hard error (the strict mode of SNIPPETS.md [1]).

The default table — ``((".*", "auto"),)`` — is the pure data-parallel
server plane: every large tensor sharded over the one server axis, biases
and scalars replicated. Model-specific tables (e.g. keep embeddings
replicated, shard attention kernels on the head dim) are plain data:
``rules_to_json`` / ``rules_from_json`` round-trip them through configs.
"""

from __future__ import annotations

import math
import re
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_RULES: tuple = ((r".*", "auto"),)


def _key_str(entry) -> str:
    """One tree-path entry -> its name segment (DictKey / GetAttrKey /
    SequenceKey / FlattenedIndexKey all carry exactly one payload attr)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def leaf_names(tree, sep: str = "/") -> list[str]:
    """The ``sep``-joined tree path of every leaf, in ``jax.tree.leaves``
    order — the strings the rule regexes match against. A NetState param
    leaf reads like ``params/Dense_0/kernel``; an optax state leaf like
    ``0/mu/Dense_0/kernel`` — so kernel/bias-style rules hit the optimizer
    moments exactly as they hit the params they mirror."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [sep.join(_key_str(k) for k in path) for path, _ in flat]


def match_partition_rules(rules, tree, default: Any = "replicated",
                          sep: str = "/") -> dict[str, Any]:
    """``{leaf path: raw rule value}`` (still unresolved — see
    :meth:`ServerStatePartitioner.resolve`) matched leaf-by-leaf: first
    ``re.search`` hit in ``rules`` wins; ``default`` covers misses
    (``default=None`` -> unmatched leaves raise). Scalar / single-element
    leaves always resolve to ``"replicated"``. Returned as a name-keyed
    dict rather than the snippet's rule pytree: explicit-spec rule values
    are python tuples, which ``jax.tree`` would silently traverse as
    subtrees."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out: dict[str, Any] = {}
    for path, leaf in flat:
        name = sep.join(_key_str(k) for k in path)
        shape = np.shape(leaf)
        if len(shape) == 0 or math.prod(shape) == 1:
            out[name] = "replicated"
            continue
        for pattern, rule in rules:
            if re.search(pattern, name) is not None:
                out[name] = rule
                break
        else:
            if default is None:
                raise ValueError(
                    f"no partition rule matches leaf {name!r} and strict "
                    "mode is on (default=None)")
            out[name] = default
    return out


def rules_to_json(rules) -> list:
    """Rule table -> a json-able ``[[pattern, rule], ...]`` (tuples become
    lists; everything else is already a json scalar)."""
    return [[p, list(r) if isinstance(r, (tuple, list)) else r]
            for p, r in rules]


def rules_from_json(obj) -> tuple:
    """Inverse of :func:`rules_to_json` (also accepts a json string)."""
    if isinstance(obj, str):
        import json

        obj = json.loads(obj)
    out = []
    for p, r in obj:
        out.append((str(p), tuple(r) if isinstance(r, list) else r))
    return tuple(out)


def tree_bytes(tree) -> int:
    """Total payload bytes of a pytree (host or device leaves)."""
    tot = 0
    for leaf in jax.tree.leaves(tree):
        shape = np.shape(leaf)
        dt = np.dtype(getattr(leaf, "dtype", np.float32))
        tot += math.prod(shape) * dt.itemsize
    return tot


class ServerStatePartitioner:
    """Mesh placement of the server plane (global model + server optimizer
    state) driven by a partition-rule table — the
    ``DataParallelPartitioner``/``SPMDPartitioner`` shape of SNIPPETS.md
    [3], specialized to the FL server axis.

    ``axis`` defaults to the mesh's FIRST axis — in the FedAvg engines
    that is the ``'clients'`` axis, which doubles as the server-shard
    axis: during local fits it indexes client slots, between rounds it
    indexes server-state shards (the same device set, two roles).
    """

    def __init__(self, mesh: Mesh, axis: str | None = None,
                 rules=None, default: Any = "auto"):
        self.mesh = mesh
        self.axis = axis or mesh.axis_names[0]
        if self.axis not in mesh.axis_names:
            raise ValueError(f"axis {self.axis!r} not in mesh axes "
                             f"{mesh.axis_names}")
        self.ndev = int(mesh.shape[self.axis])
        self.rules = tuple(rules) if rules is not None else DEFAULT_RULES
        self.default = default

    # ------------------------------------------------------------ resolve
    def resolve(self, rule, shape) -> P:
        """One raw rule value + a leaf shape -> the concrete
        ``PartitionSpec`` (see the module docstring for the rule forms)."""
        if rule is None or rule == "replicated":
            return P()
        if len(shape) == 0 or math.prod(shape) == 1:
            return P()
        if rule == "auto":
            dims = sorted(range(len(shape)), key=lambda d: (-shape[d], d))
            for d in dims:
                if shape[d] >= self.ndev and shape[d] % self.ndev == 0:
                    return P(*([None] * d + [self.axis]))
            return P()
        if isinstance(rule, int):
            if not 0 <= rule < len(shape):
                raise ValueError(f"rule dim {rule} out of range for shape "
                                 f"{shape}")
            if shape[rule] % self.ndev != 0:
                raise ValueError(
                    f"dim {rule} of shape {shape} not divisible by the "
                    f"'{self.axis}' mesh size {self.ndev}")
            return P(*([None] * rule + [self.axis]))
        if isinstance(rule, (tuple, list)):
            if len(rule) > len(shape):
                raise ValueError(
                    f"explicit spec {tuple(rule)} has {len(rule)} entries "
                    f"but the leaf has shape {shape} — rule table and "
                    "model disagree")
            for d, e in enumerate(rule):
                if e is None:
                    continue
                # explicit specs may name ANY mesh axis (or several), not
                # just the partitioner's own — validate the names here (a
                # typo'd axis must fail loudly at table-resolve time, not
                # deep inside jit) and check divisibility against the size
                # of the axes the entry actually names
                size = self._entry_axis_size(e)
                if shape[d] % size != 0:
                    raise ValueError(
                        f"dim {d} of shape {shape} not divisible by mesh "
                        f"axes {e!r} (total size {size})")
            return P(*rule)
        raise ValueError(f"unknown partition rule {rule!r}")

    def _entry_axis_size(self, entry) -> int:
        """Total device count behind one PartitionSpec entry (an axis name
        or a tuple of axis names), validated against the mesh."""
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        for n in names:
            if n not in self.mesh.axis_names:
                raise ValueError(f"spec axis {n!r} not in mesh axes "
                                 f"{self.mesh.axis_names}")
        return math.prod(int(self.mesh.shape[n]) for n in names)

    def specs(self, tree):
        """Pytree of concrete ``PartitionSpec`` per leaf (``PartitionSpec``
        is a registered pytree LEAF, so the result maps safely)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        matched = match_partition_rules(self.rules, tree,
                                        default=self.default)
        names = list(matched)
        return jax.tree.unflatten(treedef, [
            self.resolve(matched[n], np.shape(leaf))
            for n, (_, leaf) in zip(names, flat)])

    def shardings(self, tree):
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            self.specs(tree),
                            is_leaf=lambda x: isinstance(x, P))

    def describe(self, tree) -> dict[str, str]:
        """``{leaf path: spec}`` — the human-readable rule-table outcome
        (docs/PERFORMANCE.md's HBM model is written against this)."""
        names = leaf_names(tree)
        specs = jax.tree.leaves(
            self.specs(tree), is_leaf=lambda x: isinstance(x, P))
        return {n: str(s) for n, s in zip(names, specs)}

    # ------------------------------------------------------------- place
    def shard(self, tree):
        """Device placement per the rule table (eager ``device_put``)."""
        return jax.tree.map(
            lambda v, sh: jax.device_put(v, sh), tree, self.shardings(tree))

    def replicate(self, tree):
        """Gather: every leaf replicated over the mesh (the broadcast
        layout; exact — resharding moves bits, never rounds them)."""
        rep = NamedSharding(self.mesh, P())
        return jax.tree.map(lambda v: jax.device_put(v, rep), tree)

    def constrain(self, tree):
        """In-graph ``with_sharding_constraint`` to the rule-table layout —
        applied to the aggregate and the updated server state inside the
        round program, this is what makes XLA reduce-scatter the update
        sum and keep the server step shard-local (arXiv:2004.13336's
        rewrite, done by the partitioner instead of by hand)."""
        return jax.tree.map(
            lambda v, sh: jax.lax.with_sharding_constraint(v, sh),
            tree, self.shardings(tree))

    def stacked_constrainer(self, template, *, leaf_list: bool = False,
                            shape_guard: bool = False):
        """A constraint fn for STACKED ``[K, ...]`` client-update trees
        matching ``template``'s treedef: each leaf takes the template
        leaf's rule-table spec shifted one dim right (client axis
        replicated, the param dim sharded) — the layout under which
        coordinate-wise estimators (median / trimmed-mean sorts along K)
        run shard-local. Specs are matched against the TEMPLATE — the
        unstacked server state, whose leaf paths the regexes were written
        for — because a stacked tree inside jit has lost its names; this
        keeps custom tables (e.g. a replicated-embeddings rule) consistent
        between the state layout and the stacked-update layout.

        ``leaf_list=True``: the returned fn takes/returns a flat LIST of
        stacked leaves in ``jax.tree.leaves(template)`` order (the wire
        runtimes aggregate over packed leaf lists, not pytrees).
        ``shape_guard=True``: leaves whose trailing dims no longer match
        the template (codec-transformed uploads) pass through
        unconstrained instead of erroring. ONE definition of the stacked
        layout — the standalone engine and the cross-process server must
        never grow separate dialects of it."""
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, P(None, *s)),
            self.specs(template), is_leaf=lambda x: isinstance(x, P))
        if leaf_list:
            shs = jax.tree.leaves(shardings)
            shapes = [np.shape(v) for v in jax.tree.leaves(template)]

            def constrain_list(stacked):
                return [
                    jax.lax.with_sharding_constraint(v, sh)
                    if not shape_guard or np.shape(v)[1:] == shp else v
                    for v, sh, shp in zip(stacked, shs, shapes)]

            return constrain_list

        def constrain(stacked):
            return jax.tree.map(
                lambda v, sh: jax.lax.with_sharding_constraint(v, sh),
                stacked, shardings)

        return constrain

    # ------------------------------------------------------------- sizing
    def bytes_per_device(self, tree) -> int:
        """Per-device resident bytes of ``tree`` under the rule table —
        sharded dims divided by the axis size, replicated leaves counted
        whole. Feeds ``fed_server_state_bytes{placement="sharded"}``."""
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        specs = jax.tree.leaves(self.specs(tree),
                                is_leaf=lambda x: isinstance(x, P))
        tot = 0
        for (_, leaf), spec in zip(flat, specs):
            shape = list(np.shape(leaf))
            for d, e in enumerate(spec):
                if e is not None:
                    # divide by the size of the axes this entry names —
                    # an explicit spec may shard over a different mesh
                    # axis than the partitioner's own
                    shape[d] //= self._entry_axis_size(e)
            dt = np.dtype(getattr(leaf, "dtype", np.float32))
            tot += math.prod(shape) * dt.itemsize
        return tot
