"""Local client update + evaluation engine (L2).

Replaces the reference's ModelTrainer ABC and its concrete local-SGD loops
(fedml_core/trainer/model_trainer.py:4-37;
fedml_api/distributed/fedavg/MyModelTrainer.py:19-49 — epochs x batches of
fwd/bwd/step on one device). Here the whole local fit is a pure function

    local_update(rng, global_net, x, y, mask) -> (new_net, metrics)

built from a Task (model-specific loss/predict) and an optax optimizer, with
the epoch/batch loops as lax.scan so XLA compiles ONE program per round. The
function is vmap-able over a leading client axis and shard_map-able over a
'clients' mesh axis — that composition is the entire distributed runtime.

Design notes (TPU semantics):
- Padded batches (mask all zero) are exact no-ops: the parameter/opt-state
  update is lax.select'ed out, so ragged client sizes cost no correctness for
  ANY optimizer, not just SGD.
- NetState carries {'params', 'extra'}: extra holds non-gradient collections
  (BatchNorm running stats, etc.). The reference averages the full state_dict
  including BN buffers (FedAVGAggregator.py:72-80), so both parts aggregate.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax


class NetState(NamedTuple):
    """Model variables split into trainable params and mutable extras."""

    params: Any
    extra: Any  # dict of non-param collections (batch_stats, ...); may be {}


class Task(NamedTuple):
    """Model+objective bundle. The fedml_tpu analogue of a concrete
    ModelTrainer subclass (my_model_trainer_classification.py etc.)."""

    init: Callable  # (rng, x_sample) -> NetState
    # (params, extra, x, y, mask, rng, train) -> (loss, new_extra, metrics)
    loss: Callable
    # (params, extra, x) -> model outputs (eval mode)
    predict: Callable
    # (params, extra, x, y, mask) -> metrics dict with 'loss_sum','correct','count'
    eval_batch: Callable


@dataclasses.dataclass(frozen=True)
class LocalSpec:
    """Static configuration of a client's local fit."""

    optimizer: optax.GradientTransformation
    epochs: int = 1
    prox_mu: float = 0.0  # FedProx proximal coefficient (0 = plain FedAvg)
    # rematerialize the per-batch forward under autodiff (jax.checkpoint):
    # activations are recomputed in the backward pass instead of living in
    # HBM across it — the standard TPU memory/FLOPs trade for deep models
    # or long sequences. Numerics are identical (test-enforced).
    remat: bool = False
    # client-compute precision policy (docs/PERFORMANCE.md §Mixed
    # precision): 'bf16' casts params/extras/float inputs to bfloat16 for
    # the per-batch forward+backward (MXU-rate matmuls on TPU) while the
    # f32 MASTER weights stay the scan carry — gradients flow back through
    # the cast as f32 cotangents, the optimizer step / aggregation /
    # server update stay f32, and no loss scaling is needed (bfloat16
    # keeps f32's exponent range). 'f32' (default) traces NO casts: the
    # round program is bit-identical to the pre-policy build
    # (test-enforced).
    compute_dtype: str = "f32"


def _vma_of(tree) -> frozenset:
    """Union of shard_map varying-manual-axes across a pytree's leaves."""
    out: frozenset = frozenset()
    for v in jax.tree.leaves(tree):
        out = out | getattr(jax.typeof(v), "vma", frozenset())
    return out


def _match_vma(tree, target_vma: frozenset):
    """Mark invariant leaves device-varying over ``target_vma`` axes.

    Opt states may mix param-derived leaves (already varying inside shard_map)
    with freshly-created counters (e.g. the schedule step in
    ScaleByScheduleState) that are invariant; the per-client masked select in
    batch_step makes every carry leaf varying, so invariant ones must be cast
    up front or lax.scan rejects the carry."""

    def f(v):
        missing = target_vma - getattr(jax.typeof(v), "vma", frozenset())
        return lax.pcast(v, tuple(missing), to="varying") if missing else v

    return jax.tree.map(f, tree)


# accepted spellings of the LocalSpec precision policy -> compute dtype
# (None = no casts traced at all; the policy table of docs/PERFORMANCE.md
# §Mixed precision)
COMPUTE_DTYPES = {"f32": None, "float32": None,
                  "bf16": "bfloat16", "bfloat16": "bfloat16"}


def _cast_floats(tree, dtype):
    """Float leaves -> ``dtype``; everything else untouched (labels,
    masks, integer counters keep their dtypes)."""
    return jax.tree.map(
        lambda v: v.astype(dtype)
        if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating) else v, tree)


def make_local_update(task: Task, spec: LocalSpec):
    """Build the pure local-fit function for one client.

    Returned fn:
        local_update(rng, global_net: NetState, x[B,bs,...], y[B,bs,...],
                     mask[B,bs]) -> (NetState, metrics)

    metrics: dict of scalars averaged/summed over real samples only.
    The fn is vma-aware: when traced inside shard_map (varying params) it
    casts the opt-state carry to match, so it needs no axis plumbing.

    ``spec.compute_dtype='bf16'`` arms the mixed-precision policy: the
    loss/grad pass runs on bf16 casts of the f32 master params (and float
    inputs/extras), grads land f32 through the cast's transpose, and the
    optimizer/carry/upload stay f32 — see docs/PERFORMANCE.md §Mixed
    precision. The default traces no casts (bit-identity contract).
    """
    optimizer = spec.optimizer
    if spec.compute_dtype not in COMPUTE_DTYPES:
        raise ValueError(
            f"compute_dtype={spec.compute_dtype!r} (one of "
            f"{sorted(COMPUTE_DTYPES)})")
    cdt = COMPUTE_DTYPES[spec.compute_dtype]
    cdt = jnp.dtype(cdt) if cdt is not None else None

    def batch_step(carry, batch):
        params, extra, opt_state, global_params, rng = carry
        x, y, m = batch
        rng, sub = jax.random.split(rng)

        def total_loss(p):
            if cdt is None:
                loss, new_extra, metr = task.loss(p, extra, x, y, m, sub,
                                                  True)
            else:
                # bf16 compute, f32 masters: the casts sit INSIDE the
                # grad closure so autodiff transposes them back to f32
                # cotangents; loss/metrics/extras re-land f32 so the scan
                # carry (and the uploaded NetState) never changes dtype.
                # grad-scale-free by design — bf16 keeps f32's exponent
                # range, so underflow scaling (the fp16 ritual) is moot.
                loss, new_extra, metr = task.loss(
                    _cast_floats(p, cdt), _cast_floats(extra, cdt),
                    _cast_floats(x, cdt), y, m, sub, True)
                loss = loss.astype(jnp.float32)
                new_extra = jax.tree.map(
                    lambda nv, ov: nv.astype(jnp.asarray(ov).dtype),
                    new_extra, extra)
                metr = _cast_floats(metr, jnp.float32)
            if spec.prox_mu > 0.0:
                # FedProx: + mu/2 * ||w - w_global||^2. The reference's
                # distributed FedProx trainer omits this term (its trainer is
                # byte-identical to FedAvg's — see SURVEY.md §2.2); we
                # implement the algorithm as published.
                sq = jax.tree.map(
                    lambda a, b: jnp.sum(jnp.square(a - b)), p, global_params
                )
                loss = loss + 0.5 * spec.prox_mu * sum(jax.tree.leaves(sq))
            return loss, (new_extra, metr)

        if spec.remat:
            # prevent_cse=False: inside lax.scan the CSE barriers are
            # unnecessary (per the jax.checkpoint docs) and only cost fusion
            total_loss = jax.checkpoint(total_loss, prevent_cse=False)

        # NOTE sequence-parallel fits need no grad psum here: with the task's
        # loss psum-ed over the seq axis and params entering seq-INVARIANT,
        # shard_map's vma-aware transpose emits the cross-shard psum of the
        # cotangent automatically (pinned by test_fedavg_seq equivalence).
        (loss, (new_extra, metr)), grads = jax.value_and_grad(
            total_loss, has_aux=True
        )(params)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)

        # Padded (all-masked) batch -> exact no-op for params/opt/extra.
        has_data = jnp.sum(m) > 0
        keep = lambda new, old: jax.tree.map(
            lambda a, b: lax.select(has_data, a, b), new, old
        )
        params = keep(new_params, params)
        opt_state = keep(new_opt_state, opt_state)
        extra = keep(new_extra, extra)
        return (params, extra, opt_state, global_params, rng), metr

    def local_update(rng, global_net: NetState, x, y, mask):
        params, extra = global_net.params, global_net.extra
        opt_state = optimizer.init(params)
        vma = _vma_of(params)
        if vma:
            opt_state = _match_vma(opt_state, vma)

        def run_epoch(carry, _):
            params, extra, opt_state, rng = carry
            rng, sub = jax.random.split(rng)
            (params, extra, opt_state, _, _), metrs = lax.scan(
                batch_step,
                (params, extra, opt_state, global_net.params, sub),
                (x, y, mask),
            )
            return (params, extra, opt_state, rng), metrs

        (params, extra, _, _), metrs = lax.scan(
            run_epoch, (params, extra, opt_state, rng), None, length=spec.epochs
        )
        # metrs leaves: [epochs, B]; return SUMS so they aggregate across
        # clients by addition (weighted means are computed at the server)
        metrics = {
            "loss_sum": jnp.sum(metrs["loss_sum"]),
            "correct": jnp.sum(metrs["correct"]),
            "count": jnp.sum(metrs["count"]),
        }
        return NetState(params, extra), metrics

    return local_update


def make_eval_fn(task: Task):
    """Jitted masked evaluation over a padded global batch set [B, bs, ...].

    The analogue of ModelTrainer.test / the server's
    test_on_server_for_all_clients (FedAVGAggregator.py:109-163), but the
    whole eval set is one scan on device.
    """

    def eval_fn(net: NetState, xb, yb, mb):
        def body(acc, batch):
            x, y, m = batch
            metr = task.eval_batch(net.params, net.extra, x, y, m)
            return {k: acc[k] + metr[k] for k in acc}, None

        init = {"loss_sum": jnp.zeros(()), "correct": jnp.zeros(()), "count": jnp.zeros(())}
        acc, _ = lax.scan(body, init, (xb, yb, mb))
        n = jnp.maximum(acc["count"], 1.0)
        return {"loss": acc["loss_sum"] / n, "acc": acc["correct"] / n, "count": acc["count"]}

    return jax.jit(eval_fn)
