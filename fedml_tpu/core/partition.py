"""Non-IID data partitioning (numpy, host-side).

Re-implementation of the reference partitioners:
- latent-Dirichlet partition with a min-size retry loop
  (fedml_core/non_iid_partition/noniid_partition.py:6-73 and the CIFAR variant
  fedml_api/data_preprocessing/cifar10/data_loader.py:172-196)
- uniform ("homo") partition (cifar10/data_loader.py:144-148)
- per-client class histogram logging (noniid_partition.py:94-103)

Partitioning is one-time host-side preprocessing; it stays numpy. The output
client->index map is then packed into fixed-shape device arrays by
fedml_tpu.core.client_data.
"""

from __future__ import annotations

import numpy as np


def homo_partition(n_samples: int, n_clients: int, seed: int = 0) -> dict[int, np.ndarray]:
    """Uniform IID split: shuffle then equal chunks."""
    rng = np.random.RandomState(seed)
    idxs = rng.permutation(n_samples)
    return {i: np.sort(chunk) for i, chunk in enumerate(np.array_split(idxs, n_clients))}


def dirichlet_partition(
    labels: np.ndarray,
    n_clients: int,
    alpha: float,
    seed: int = 0,
    min_size_floor: int = 10,
) -> dict[int, np.ndarray]:
    """LDA partition: for each class, split its indices among clients by a
    Dirichlet(alpha) draw, retrying until every client has >= min_size_floor
    samples (the reference's `while min_size < 10` loop,
    noniid_partition.py:24-49). Balance correction: a client already holding
    more than n/n_clients samples gets probability 0 for the current class
    (noniid_partition.py:39 / cifar10/data_loader.py:184).
    """
    rng = np.random.RandomState(seed)
    labels = np.asarray(labels).ravel()
    n = labels.shape[0]
    classes = np.unique(labels)
    min_size = 0
    attempts = 0
    while min_size < min_size_floor:
        attempts += 1
        if attempts > 1000:
            # unreachable floor (e.g. n_clients > n_samples): fail loudly
            # instead of the reference's unbounded `while min_size < 10` spin
            raise ValueError(
                f"dirichlet_partition: cannot give {n_clients} clients >= "
                f"{min_size_floor} of {n} samples (alpha={alpha})")
        idx_batch: list[list[int]] = [[] for _ in range(n_clients)]
        for c in classes:
            idx_c = np.where(labels == c)[0]
            rng.shuffle(idx_c)
            props = rng.dirichlet(np.repeat(alpha, n_clients))
            props = np.array(
                [p * (len(b) < n / n_clients) for p, b in zip(props, idx_batch)]
            )
            if props.sum() <= 0:  # every client exactly at capacity
                props = np.full(n_clients, 1.0 / n_clients)
            props = props / props.sum()
            cuts = (np.cumsum(props) * len(idx_c)).astype(int)[:-1]
            for i, part in enumerate(np.split(idx_c, cuts)):
                idx_batch[i].extend(part.tolist())
        min_size = min(len(b) for b in idx_batch)
    out = {}
    for i in range(n_clients):
        rng.shuffle(idx_batch[i])
        out[i] = np.asarray(idx_batch[i], dtype=np.int64)
    return out


def dirichlet_partition_balanced(
    labels: np.ndarray,
    n_clients: int,
    alpha: float = 0.5,
    seed: int = 0,
) -> dict[int, np.ndarray]:
    """Size-balanced LDA — the reference's partition_data_equally stop rule
    (cifar10/data_loader.py:211-321): the shared LDA loop retried until min
    client size >= 0.5*N/n instead of the default absolute floor of 10.
    Label heterogeneity of LDA, near-equal client sizes."""
    n = len(np.asarray(labels).ravel())
    floor = max(1, int(0.5 * n / n_clients))
    return dirichlet_partition(labels, n_clients, alpha, seed,
                               min_size_floor=floor)


# the canonical frozen partition's seed — 'hetero-fix' must give the SAME
# map on every run regardless of --seed (the reference freezes it as a
# checked-in net_dataidx_map.txt, cifar10/data_loader.py:325-330)
_HETERO_FIX_SEED = 2021


def read_net_dataidx_map(path: str) -> dict[int, np.ndarray]:
    """Parse the reference's checked-in fixed-partition txt format
    (read_net_dataidx_map, cifar10/data_loader.py:35-47): lines of
    '<client>: [' opening a client, then comma-separated indices."""
    out: dict[int, list[int]] = {}
    key = None
    with open(path) as f:
        for line in f:
            s = line.strip()
            if not s or s[0] in "{}]":
                continue
            head, _, tail = s.partition(":")
            if tail.strip() == "[":
                key = int(head)
                out[key] = []
            else:
                if key is None:
                    raise ValueError(f"malformed dataidx map {path!r}: "
                                     f"indices before any client header")
                out[key].extend(int(t) for t in s.replace("]", "").split(",") if t.strip())
    return {k: np.asarray(v, dtype=np.int64) for k, v in out.items()}


def partition_data(
    labels: np.ndarray,
    n_clients: int,
    method: str = "hetero",
    alpha: float = 0.5,
    seed: int = 0,
    fix_path: str | None = None,
) -> dict[int, np.ndarray]:
    """Dispatch matching the reference's partition_data
    (cifar10/data_loader.py:140-209): 'homo' | 'hetero' (LDA) |
    'hetero-bal' (size-balanced LDA, partition_data_equally) |
    'hetero-fix' (frozen map: from ``fix_path`` if given — the reference's
    checked-in net_dataidx_map.txt — else LDA with a fixed canonical seed,
    identical on every run regardless of ``seed``)."""
    if fix_path is not None and method != "hetero-fix":
        raise ValueError(
            f"fix_path given but partition method is {method!r}; a frozen "
            "map only applies with method='hetero-fix' (refusing to silently "
            "train on a different partition)")
    if method == "homo":
        return homo_partition(len(labels), n_clients, seed)
    if method in ("hetero", "noniid", "lda"):
        return dirichlet_partition(labels, n_clients, alpha, seed)
    if method in ("hetero-bal", "hetero-equal"):
        return dirichlet_partition_balanced(labels, n_clients, alpha, seed)
    if method == "hetero-fix":
        if fix_path is not None:
            m = read_net_dataidx_map(fix_path)
            n = len(np.asarray(labels).ravel())
            hi = max((int(v.max()) for v in m.values() if len(v)), default=-1)
            if hi >= n:
                raise ValueError(
                    f"{fix_path!r}: index {hi} out of range for {n} samples")
            if set(m) != set(range(n_clients)):
                raise ValueError(
                    f"{fix_path!r} holds clients {sorted(m)[:5]}..., expected "
                    f"exactly 0..{n_clients - 1} (samplers index contiguously)")
            return m
        return dirichlet_partition(labels, n_clients, alpha, _HETERO_FIX_SEED)
    raise ValueError(f"unknown partition method: {method}")


def record_data_stats(labels: np.ndarray, net_dataidx_map: dict[int, np.ndarray]):
    """Per-client class histograms (noniid_partition.py:94-103)."""
    labels = np.asarray(labels).ravel()
    stats = {}
    for cid, idxs in net_dataidx_map.items():
        vals, counts = np.unique(labels[idxs], return_counts=True)
        stats[cid] = {int(v): int(c) for v, c in zip(vals, counts)}
    return stats
