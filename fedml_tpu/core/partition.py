"""Non-IID data partitioning (numpy, host-side).

Re-implementation of the reference partitioners:
- latent-Dirichlet partition with a min-size retry loop
  (fedml_core/non_iid_partition/noniid_partition.py:6-73 and the CIFAR variant
  fedml_api/data_preprocessing/cifar10/data_loader.py:172-196)
- uniform ("homo") partition (cifar10/data_loader.py:144-148)
- per-client class histogram logging (noniid_partition.py:94-103)

Partitioning is one-time host-side preprocessing; it stays numpy. The output
client->index map is then packed into fixed-shape device arrays by
fedml_tpu.core.client_data.
"""

from __future__ import annotations

import numpy as np


def homo_partition(n_samples: int, n_clients: int, seed: int = 0) -> dict[int, np.ndarray]:
    """Uniform IID split: shuffle then equal chunks."""
    rng = np.random.RandomState(seed)
    idxs = rng.permutation(n_samples)
    return {i: np.sort(chunk) for i, chunk in enumerate(np.array_split(idxs, n_clients))}


def dirichlet_partition(
    labels: np.ndarray,
    n_clients: int,
    alpha: float,
    seed: int = 0,
    min_size_floor: int = 10,
) -> dict[int, np.ndarray]:
    """LDA partition: for each class, split its indices among clients by a
    Dirichlet(alpha) draw, retrying until every client has >= min_size_floor
    samples (the reference's `while min_size < 10` loop,
    noniid_partition.py:24-49). Balance correction: a client already holding
    more than n/n_clients samples gets probability 0 for the current class
    (noniid_partition.py:39 / cifar10/data_loader.py:184).
    """
    rng = np.random.RandomState(seed)
    labels = np.asarray(labels).ravel()
    n = labels.shape[0]
    classes = np.unique(labels)
    min_size = 0
    while min_size < min_size_floor:
        idx_batch: list[list[int]] = [[] for _ in range(n_clients)]
        for c in classes:
            idx_c = np.where(labels == c)[0]
            rng.shuffle(idx_c)
            props = rng.dirichlet(np.repeat(alpha, n_clients))
            props = np.array(
                [p * (len(b) < n / n_clients) for p, b in zip(props, idx_batch)]
            )
            props = props / props.sum()
            cuts = (np.cumsum(props) * len(idx_c)).astype(int)[:-1]
            for i, part in enumerate(np.split(idx_c, cuts)):
                idx_batch[i].extend(part.tolist())
        min_size = min(len(b) for b in idx_batch)
    out = {}
    for i in range(n_clients):
        rng.shuffle(idx_batch[i])
        out[i] = np.asarray(idx_batch[i], dtype=np.int64)
    return out


def partition_data(
    labels: np.ndarray,
    n_clients: int,
    method: str = "hetero",
    alpha: float = 0.5,
    seed: int = 0,
) -> dict[int, np.ndarray]:
    """Dispatch matching the reference's partition_data
    (cifar10/data_loader.py:140-209): 'homo' | 'hetero' (LDA)."""
    if method == "homo":
        return homo_partition(len(labels), n_clients, seed)
    if method in ("hetero", "noniid", "lda"):
        return dirichlet_partition(labels, n_clients, alpha, seed)
    raise ValueError(f"unknown partition method: {method}")


def record_data_stats(labels: np.ndarray, net_dataidx_map: dict[int, np.ndarray]):
    """Per-client class histograms (noniid_partition.py:94-103)."""
    labels = np.asarray(labels).ravel()
    stats = {}
    for cid, idxs in net_dataidx_map.items():
        vals, counts = np.unique(labels[idxs], return_counts=True)
        stats[cid] = {int(v): int(c) for v, c in zip(vals, counts)}
    return stats
