"""Durable round write-ahead log — the server's crash-recovery journal.

Every robustness layer before this one hardens the fleet against *client*
failure; rank 0 stayed a single point of failure — a mid-round server death
lost the async buffer, the quarantine ledger deltas, and (worst) could
under-report the privacy ε the budget ledger promises to account exactly.
This module is the durability half of the fix (docs/ROBUSTNESS.md §Server
crash recovery): an append-only, CRC-framed, fsync-at-commit log of round
lifecycle events, so recovery = latest checkpoint + WAL replay reconstructs
the in-flight state with exactly-once round semantics:

- **no round folded twice** — the newest RESTORABLE checkpoint is the
  state authority (a round's fold is durable iff its checkpoint is);
  recovery resumes one past it and re-runs the open round under a fresh
  ``restart_epoch``, whose echo on every upload sheds the pre-crash
  duplicates. The ``commit`` record (fsync'd after the checkpoint rename)
  witnesses the commit and bounds ``since_last_commit`` — the in-flight
  set recovery must ledger;
- **no upload double-counted** — uploads accepted for the open round are
  journaled at accept; recovery ledgers each as ``server_restart`` (the
  payloads died with the process) and the epoch gate drops their late
  wire twins;
- **ε never under-reported** — the DP pre-charge record is fsync'd
  *before* the noise key is drawn, so a crash between charge and noise
  replays the charge (the conservative direction: the accountant may
  over-count by one round, never under-count). The same record carries
  the round's surviving client ids (``clients=[...]``), extending the
  contract to CLIENT granularity: the per-client privacy ledgers
  (core/privacy.ClientPrivacyLedger) ride no checkpoint — recovery
  rebuilds them by replaying every pre-charge record, so per-user ε
  survives a SIGKILL under the same never-under-report guarantee;

Record framing: the file opens with an 8-byte magic, then each record is
``[u32 length][u32 crc32(payload)][payload]`` with a canonical-JSON
payload. Replay stops at the first torn/corrupt frame (counted — a crash
mid-append must cost the tail, never a misparse) and everything before it
is intact by CRC.

The durable-write helpers at the bottom are the ONLY sanctioned way this
module, ``core/checkpoint.py``, and ``core/privacy.py`` open files for
writing — the fedlint ``fsync-discipline`` rule flags any bare
``open(..., 'w')`` in those modules, because a commit point that skips
the fsync turns "crash-safe" into "crash-safe until the page cache says
otherwise".
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field

log = logging.getLogger("fedml_tpu.core.wal")

_MAGIC = b"FWAL0001"
_HDR = struct.Struct("<II")  # payload length, crc32(payload)

# one segment per directory: recovery replays are O(run length) scans of
# small JSON records — a soak's few thousand rounds is kilobytes, and a
# single append-only file keeps the torn-tail contract trivially true
_SEGMENT = "wal.log"


# ---------------------------------------------------------------- durability
def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY: the rename that publishes an atomic write is
    itself only durable once the directory entry is flushed."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms/filesystems without dir-fd semantics
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


@contextlib.contextmanager
def durable_open(path: str, mode: str = "wb"):
    """Open-for-write that flushes + fsyncs before close — the shared
    fsync helper every WAL/checkpoint commit point must route through
    (fedlint ``fsync-discipline``)."""
    f = open(path, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
    finally:
        f.close()


def durable_replace(tmp: str, path: str) -> None:
    """Atomic publish: rename tmp over path, then fsync the directory so
    the rename survives power loss."""
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)))


def durable_write(path: str, data: bytes) -> None:
    """tmp-file → fsync → atomic rename: a reader (or a post-crash
    recovery) sees either the old content or the complete new content,
    never a torn file under the real name."""
    tmp = path + ".tmp"
    try:
        with durable_open(tmp, "wb") as f:
            f.write(data)
        durable_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


# -------------------------------------------------------------------- replay
@dataclass
class WalReplay:
    """Parsed view of a WAL directory — what recovery reasons over."""

    records: list[dict] = field(default_factory=list)
    torn: int = 0  # torn/corrupt tail frames dropped (0 or 1 per scan)

    @property
    def restart_epochs(self) -> int:
        """Prior server boots = the next boot's restart epoch (0 on a
        fresh directory)."""
        return sum(1 for r in self.records if r.get("kind") == "restart")

    @property
    def last_commit(self) -> int:
        """Highest committed round, -1 when none committed yet."""
        return max((int(r["round"]) for r in self.records
                    if r.get("kind") == "commit"), default=-1)

    def open_round(self, committed: int) -> int | None:
        """The in-flight round a crash interrupted: the highest
        ``broadcast`` round past ``committed`` (None = the crash fell
        between commits — nothing was in flight)."""
        r = max((int(r["round"]) for r in self.records
                 if r.get("kind") == "broadcast"), default=-1)
        return r if r > committed else None

    def for_round(self, round_idx: int, kind: str | None = None
                  ) -> list[dict]:
        return [r for r in self.records
                if int(r.get("round", -1)) == int(round_idx)
                and (kind is None or r.get("kind") == kind)]

    def of_kind(self, kind: str) -> list[dict]:
        return [r for r in self.records if r.get("kind") == kind]

    def since_last_commit(self, kinds=None) -> list[dict]:
        """Records appended after the last ``commit`` — the in-flight
        state a crash destroyed. Positional, not round-filtered: across a
        double crash in one round, each boot's lost work accumulates here
        until a commit finally lands (exactly the set recovery must
        ledger ``server_restart``)."""
        if isinstance(kinds, str):
            kinds = (kinds,)
        idx = -1
        for i, r in enumerate(self.records):
            if r.get("kind") == "commit":
                idx = i
        return [r for r in self.records[idx + 1:]
                if kinds is None or r.get("kind") in kinds]

    def dispatch_waves(self) -> dict[int, int]:
        """rank -> highest journaled async dispatch wave (recovery resumes
        each rank's wave counter past it, keeping the sampling chain
        monotonic across restarts)."""
        waves: dict[int, int] = {}
        for r in self.records:
            if r.get("kind") == "dispatch":
                rank = int(r["rank"])
                waves[rank] = max(waves.get(rank, -1), int(r["wave"]))
        return waves


class RoundWAL:
    """Append-only round journal. ``append(..., sync=True)`` is the commit
    discipline: buffered appends ride the OS cache (cheap, lost on crash
    = lost bookkeeping only), sync'd appends are durable before the call
    returns (anything correctness-critical: broadcast, upload accept,
    privacy pre-charge, commit, restart)."""

    def __init__(self, wal_dir: str):
        os.makedirs(wal_dir, exist_ok=True)
        self.wal_dir = wal_dir
        self.path = os.path.join(wal_dir, _SEGMENT)
        self._lock = threading.Lock()
        fresh = not os.path.exists(self.path) \
            or os.path.getsize(self.path) == 0
        if not fresh:
            # repair BEFORE appending: a torn tail (crash mid-append) must
            # be truncated away, or this boot's records land after bytes
            # every future replay stops at — invisible forever (restart
            # epochs undercount, commits vanish, lost uploads unledgered)
            fresh = self._durable_truncate_tail()
        self._f = self._durable_append_handle()
        if fresh:
            with self._lock:
                self._f.write(_MAGIC)
                self._f.flush()
                os.fsync(self._f.fileno())
            fsync_dir(wal_dir)

    def _durable_truncate_tail(self) -> bool:
        """Scan the existing segment and truncate past the last intact
        frame. Returns True when the file is unusable (bad magic — set
        aside, start fresh) so __init__ rewrites the header."""
        with open(self.path, "rb") as f:
            data = f.read()
        if data[:len(_MAGIC)] != _MAGIC:
            corrupt = self.path + ".corrupt"
            os.replace(self.path, corrupt)
            fsync_dir(self.wal_dir)
            log.warning("WAL at %s has a bad magic — set aside as %s, "
                        "starting a fresh segment", self.path, corrupt)
            return True
        off = len(_MAGIC)
        while off < len(data):
            if off + _HDR.size > len(data):
                break
            length, crc = _HDR.unpack_from(data, off)
            start, end = off + _HDR.size, off + _HDR.size + length
            if end > len(data) or zlib.crc32(data[start:end]) != crc:
                break
            off = end
        if off < len(data):
            with open(self.path, "r+b") as f:
                f.truncate(off)
                f.flush()
                os.fsync(f.fileno())
            log.warning("WAL at %s: truncated a torn tail at offset %d "
                        "so this boot's records stay replayable", self.path,
                        off)
        return False

    def _durable_append_handle(self):
        # the long-lived append handle: every sync'd append fsyncs it, so
        # the handle itself needs no close-time flush ceremony
        return open(self.path, "ab")

    # --------------------------------------------------------------- append
    def append(self, kind: str, sync: bool = False, **fields) -> None:
        rec = dict(fields)
        rec["kind"] = str(kind)
        # wall-clock stamp: the post-mortem timeline (obs/flightrec.py)
        # orders WAL records against flight-record dumps by it. setdefault
        # so a caller (or a replay-driven rewrite) can pin its own.
        rec.setdefault("ts", round(time.time(), 6))  # fedlint: disable=determinism — wall-clock stamp for the post-mortem timeline only; replay ignores it and a replay-driven rewrite pins its own
        payload = json.dumps(rec, sort_keys=True).encode()
        frame = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._f.closed:
                return  # a post-close append is bookkeeping from teardown
            self._f.write(frame)
            if sync:
                self._f.flush()
                os.fsync(self._f.fileno())

    def commit(self, round_idx: int) -> None:
        """The round-commit record — fsync'd AFTER the checkpoint rename
        (the checkpoint is the state authority; the commit record makes
        the round's completion explicit even when checkpoint pruning or a
        save cadence skips the round)."""
        self.append("commit", sync=True, round=int(round_idx))

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._f.close()

    # --------------------------------------------------------------- replay
    @classmethod
    def replay(cls, wal_dir: str) -> WalReplay:
        """Scan the directory's WAL into a :class:`WalReplay`. Robust to a
        torn tail (counted, suffix dropped) and to a missing/short file
        (empty replay) — recovery must never crash on the artifact a
        crash produced."""
        out = WalReplay()
        path = os.path.join(wal_dir, _SEGMENT)
        if not os.path.exists(path):
            return out
        with open(path, "rb") as f:
            data = f.read()
        if len(data) < len(_MAGIC) or data[:len(_MAGIC)] != _MAGIC:
            if data:
                out.torn = 1
                log.warning("WAL at %s has a bad/short magic (%d bytes) — "
                            "treating as empty", path, len(data))
            return out
        off = len(_MAGIC)
        while off < len(data):
            if off + _HDR.size > len(data):
                out.torn = 1
                break
            length, crc = _HDR.unpack_from(data, off)
            start, end = off + _HDR.size, off + _HDR.size + length
            if end > len(data) or zlib.crc32(data[start:end]) != crc:
                out.torn = 1
                log.warning("WAL at %s: torn/corrupt frame at offset %d — "
                            "dropping the tail (%d intact records kept)",
                            path, off, len(out.records))
                break
            try:
                out.records.append(json.loads(data[start:end]))
            except ValueError:
                out.torn = 1
                break
            off = end
        return out
