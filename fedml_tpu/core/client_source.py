"""Streamed client state — the million-client data plane.

``FederatedData`` materializes the whole population's arrays in host
memory, so standalone cohort scale is bounded by host RSS, not TPU
throughput (ROADMAP open item 4). FedJAX (arXiv:2108.02117) shows the fix:
a *client-indexed* dataset whose per-client shards are read lazily from
disk, with only the sampled cohort's rows ever touching memory. This
module is that abstraction:

- :class:`ClientDataSource` — the contract every engine packs against:
  per-client *sizes* are cheap metadata (``client_sizes``), per-client
  *rows* are fetched on demand (``client_rows``), and the global test
  split stays materialized (it is small and evaluated every round).
- :class:`InMemorySource` — wraps today's ``FederatedData`` (zero-copy
  views); the parity oracle for every out-of-core reader.
- :class:`PackedNpySource` — the out-of-core workhorse: standard ``.npy``
  containers read with plain ``seek``+``read`` (NOT ``mmap`` — resident
  mapped pages would count toward RSS and the flat-memory claim is
  asserted on ``fed_host_rss_bytes``, obs/memwatch.py), so a round's
  host footprint is exactly the sampled cohort's rows.
- :class:`LeafJsonSource` / :class:`TffH5Source` — lazy readers for the
  reference's LEAF-json and TFF-h5 layouts (data/files.py documents the
  formats); one parsed file / open h5 handle at a time.
- :func:`pack_clients_source` — ``pack_clients`` against a source:
  touches ONLY the sampled clients, same (seed, round, CLIENT-ID)
  splitmix shuffle, bit-identical batches (test-enforced).

``write_packed_npy`` converts any source (or ``FederatedData``) to the
packed layout, chunked so the writer's RSS stays flat too.
"""

from __future__ import annotations

import json
import logging
import os
import threading

import numpy as np

from fedml_tpu.core.client_data import (
    ClientBatch,
    FederatedData,
    _splitmix_shuffle,
    client_shuffle_seeds,
)

log = logging.getLogger("fedml_tpu.client_source")


class ClientDataSource:
    """Client-indexed dataset: metadata eager, payload lazy.

    Subclasses set ``class_num``, ``source`` ("real" | "synthetic"),
    ``test_x``/``test_y`` (materialized — the global eval split), and
    implement ``client_sizes`` + ``client_rows``. ``test_idx_map`` stays
    None unless the source carries natural per-client test splits (the
    engines' per-client eval then degrades to the global test set,
    exactly the capped-eval behavior large populations want anyway).
    """

    class_num: int = 0
    source: str = "real"
    test_idx_map = None

    @property
    def num_clients(self) -> int:
        return len(self.client_sizes)

    @property
    def client_sizes(self) -> np.ndarray:
        """[N] int64 per-client sample counts — metadata only, never
        triggers payload reads."""
        raise NotImplementedError

    def client_rows(self, cid: int) -> tuple[np.ndarray, np.ndarray]:
        """One client's (x, y) rows in canonical on-disk order. The
        arrays are fresh host buffers owned by the caller."""
        raise NotImplementedError

    def row_meta(self):
        """((x row shape, x dtype), (y row shape, y dtype)) — cached after
        ONE probe read, so per-round packing never re-reads a client's
        payload just to learn round-invariant shapes. Subclasses with
        metadata on hand (PackedNpySource) override with zero I/O."""
        if getattr(self, "_row_meta_cache", None) is None:
            sizes = self.client_sizes
            first = int(np.argmax(sizes > 0)) if np.any(sizes > 0) else 0
            x, y = self.client_rows(first)
            self._row_meta_cache = ((x.shape[1:], x.dtype),
                                    (y.shape[1:], y.dtype))
        return self._row_meta_cache

    # engines size jit programs and init models from these
    def init_batch(self, batch_size: int) -> np.ndarray:
        """A model-init sample batch (values irrelevant, shapes/dtypes
        matter) — the streamed analogue of ``train_x[:batch_size]``."""
        sizes = self.client_sizes
        first = int(np.argmax(sizes > 0)) if np.any(sizes > 0) else 0
        x, _ = self.client_rows(first)
        if len(x) >= batch_size:
            return x[:batch_size]
        reps = -(-batch_size // max(len(x), 1))
        return np.concatenate([x] * reps)[:batch_size]

    @property
    def train_data_local_num_dict(self) -> dict[int, int]:
        sizes = self.client_sizes
        return {c: int(sizes[c]) for c in range(len(sizes))}


class InMemorySource(ClientDataSource):
    """``FederatedData`` behind the source contract — views, no copies.
    The parity oracle: every out-of-core reader must pack bit-identically
    to this one over the same data."""

    def __init__(self, data: FederatedData):
        self.data = data
        self.class_num = data.class_num
        self.source = ("synthetic"
                       if getattr(data, "synthetic_fallback", False)
                       else "real")
        self.test_x, self.test_y = data.test_x, data.test_y
        self.test_idx_map = data.test_idx_map
        self._sizes = np.asarray(
            [len(data.train_idx_map[c]) for c in range(data.num_clients)],
            np.int64)

    @property
    def client_sizes(self) -> np.ndarray:
        return self._sizes

    def client_rows(self, cid: int):
        idx = np.asarray(self.data.train_idx_map[int(cid)], np.int64)
        return self.data.train_x[idx], self.data.train_y[idx]

    def init_batch(self, batch_size: int) -> np.ndarray:
        return self.data.train_x[:batch_size]


def _npy_header(path: str):
    """(shape, dtype, data_offset) of a standard .npy without mapping or
    loading it — the container stays np.save-compatible while reads go
    through plain seek+read (flat RSS; see module docstring)."""
    with open(path, "rb") as f:
        version = np.lib.format.read_magic(f)
        if version >= (2, 0):
            shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
        else:
            shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
        if fortran:
            raise ValueError(f"{path}: fortran-order npy unsupported")
        return shape, dtype, f.tell()


class _NpyColumn:
    """Row-addressable reads out of one .npy file via pread-style
    seek+read under a lock (sources are shared with the prefetch thread)."""

    def __init__(self, path: str):
        self.path = path
        self.shape, self.dtype, self.offset = _npy_header(path)
        self.row_shape = self.shape[1:]
        self.row_bytes = int(np.prod(self.row_shape, dtype=np.int64)
                             * self.dtype.itemsize) or self.dtype.itemsize
        self._f = open(path, "rb")
        self._lock = threading.Lock()

    def rows(self, start: int, stop: int) -> np.ndarray:
        n = max(int(stop) - int(start), 0)
        with self._lock:
            self._f.seek(self.offset + int(start) * self.row_bytes)
            buf = self._f.read(n * self.row_bytes)
        if len(buf) != n * self.row_bytes:
            raise EOFError(f"{self.path}: short read at rows "
                           f"[{start}, {stop})")
        return np.frombuffer(buf, dtype=self.dtype).reshape(
            (n,) + self.row_shape).copy()

    def close(self):
        self._f.close()


class PackedNpySource(ClientDataSource):
    """Out-of-core packed layout::

        <dir>/meta.json      {"format": "fedml-packed-npy", "class_num",
                              "num_clients", "source"}
        <dir>/offsets.npy    int64 [N+1] — client c owns rows
                             [offsets[c], offsets[c+1]) of x/y
        <dir>/x.npy, y.npy   all clients' rows, concatenated
        <dir>/test_x.npy, test_y.npy   the global eval split

    Only ``offsets`` (8(N+1) bytes) and the test split are resident;
    ``client_rows`` reads exactly one client's byte range.
    """

    def __init__(self, path: str, n_clients: int | None = None):
        self.path = path
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        if meta.get("format") != "fedml-packed-npy":
            raise ValueError(f"{path}: not a fedml-packed-npy dir "
                             f"(meta format={meta.get('format')!r})")
        self.class_num = int(meta["class_num"])
        self.source = str(meta.get("source", "real"))
        self._offsets = np.load(os.path.join(path, "offsets.npy"))
        self._x = _NpyColumn(os.path.join(path, "x.npy"))
        self._y = _NpyColumn(os.path.join(path, "y.npy"))
        self.test_x = np.load(os.path.join(path, "test_x.npy"))
        self.test_y = np.load(os.path.join(path, "test_y.npy"))
        if int(meta["num_clients"]) != len(self._offsets) - 1:
            raise ValueError(
                f"{path}: meta names {meta['num_clients']} clients but "
                f"offsets describe {len(self._offsets) - 1}")
        if n_clients is not None:
            # population cap, like the LEAF/h5 readers' n_clients: the
            # first n clients (their rows stay addressable; the rest of
            # the file is simply never read)
            self._offsets = self._offsets[: int(n_clients) + 1]
        self._sizes = np.diff(self._offsets).astype(np.int64)

    @property
    def client_sizes(self) -> np.ndarray:
        return self._sizes

    def row_meta(self):
        # the npy headers already hold this — no payload read at all
        return ((self._x.row_shape, self._x.dtype),
                (self._y.row_shape, self._y.dtype))

    def client_rows(self, cid: int):
        a, b = int(self._offsets[int(cid)]), int(self._offsets[int(cid) + 1])
        return self._x.rows(a, b), self._y.rows(a, b)

    def close(self):
        self._x.close()
        self._y.close()


def write_packed_npy(data, path: str, chunk_clients: int = 1024,
                     source: str | None = None) -> str:
    """Convert ``data`` (FederatedData or any ClientDataSource) to the
    packed-npy layout under ``path``. Streams ``chunk_clients`` clients at
    a time through ``np.lib.format`` so the writer never materializes the
    full population either."""
    src = as_source(data)
    os.makedirs(path, exist_ok=True)
    sizes = src.client_sizes
    n = len(sizes)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum(sizes, out=offsets[1:])
    total = int(offsets[-1])
    x0, y0 = src.client_rows(int(np.argmax(sizes > 0)))

    def write_column(name, row_shape, dtype, pick):
        p = os.path.join(path, name)
        with open(p, "wb") as f:
            np.lib.format.write_array_header_2_0(
                f, {"descr": np.lib.format.dtype_to_descr(np.dtype(dtype)),
                    "fortran_order": False,
                    "shape": (total,) + tuple(row_shape)})
            for s in range(0, n, chunk_clients):
                block = [pick(c) for c in range(s, min(s + chunk_clients, n))
                         if sizes[c] > 0]
                if block:
                    f.write(np.ascontiguousarray(
                        np.concatenate(block)).tobytes())

    write_column("x.npy", x0.shape[1:], x0.dtype,
                 lambda c: src.client_rows(c)[0])
    write_column("y.npy", y0.shape[1:], y0.dtype,
                 lambda c: src.client_rows(c)[1])
    np.save(os.path.join(path, "offsets.npy"), offsets)
    np.save(os.path.join(path, "test_x.npy"), np.asarray(src.test_x))
    np.save(os.path.join(path, "test_y.npy"), np.asarray(src.test_y))
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({"format": "fedml-packed-npy", "num_clients": n,
                   "class_num": int(src.class_num),
                   "source": source or src.source}, f)
    return path


class LeafJsonSource(ClientDataSource):
    """Lazy LEAF-json reader (``{train,test}/*.json`` with users/
    user_data — data/files.py ``_load_leaf_json`` documents the format).
    The index pass records (file, user) per client and per-client sizes;
    ``client_rows`` re-parses one json file on demand with a 1-file
    cache, so memory holds at most one shard file's worth of payload."""

    def __init__(self, data_dir: str, input_shape: tuple, class_num: int,
                 n_clients: int | None = None):
        import glob

        self.data_dir = data_dir
        self.class_num = int(class_num)
        self.input_shape = tuple(input_shape)
        self._index: list[tuple[str, str]] = []  # client -> (path, user)
        sizes: list[int] = []
        for p in sorted(glob.glob(os.path.join(data_dir, "train",
                                               "*.json"))):
            with open(p) as f:
                blob = json.load(f)
            for u in blob["users"]:
                self._index.append((p, u))
                sizes.append(len(blob["user_data"][u]["y"]))
            del blob
        if n_clients is not None:
            self._index = self._index[:n_clients]
            sizes = sizes[:n_clients]
        if not self._index:
            raise FileNotFoundError(f"no LEAF train jsons under {data_dir}")
        self._sizes = np.asarray(sizes, np.int64)
        self._cache: tuple[str, dict] | None = None
        self._lock = threading.Lock()
        self.test_x, self.test_y = self._load_test()

    def _load_test(self):
        import glob

        xs, ys = [], []
        for p in sorted(glob.glob(os.path.join(self.data_dir, "test",
                                               "*.json"))):
            with open(p) as f:
                blob = json.load(f)
            for u in blob["users"]:
                ud = blob["user_data"][u]
                xs.append(np.asarray(ud["x"], np.float32))
                ys.append(np.asarray(ud["y"], np.int64))
        if not xs:
            # no test split shipped: fall back to the first train shard —
            # said LOUDLY, because every eval record would otherwise pass
            # training accuracy off as test_acc
            log.warning("%s: no test/*.json — evaluating on the first "
                        "TRAIN shard (test_acc will be training accuracy)",
                        self.data_dir)
            p, u = self._index[0]
            blob = self._parsed(p)
            ud = blob["user_data"][u]
            xs = [np.asarray(ud["x"], np.float32)]
            ys = [np.asarray(ud["y"], np.int64)]
        x = np.concatenate(xs).reshape((-1,) + self.input_shape)
        return x, np.concatenate(ys)

    def _parsed(self, path: str) -> dict:
        with self._lock:
            if self._cache is None or self._cache[0] != path:
                with open(path) as f:
                    self._cache = (path, json.load(f))
            return self._cache[1]

    @property
    def client_sizes(self) -> np.ndarray:
        return self._sizes

    def client_rows(self, cid: int):
        path, user = self._index[int(cid)]
        ud = self._parsed(path)["user_data"][user]
        x = np.asarray(ud["x"], np.float32).reshape(
            (-1,) + self.input_shape)
        return x, np.asarray(ud["y"], np.int64)


class TffH5Source(ClientDataSource):
    """Lazy TFF-h5 reader (``examples/<cid>/{pixels|image, label}`` —
    data/files.py ``_load_tff_h5``). h5py reads one client group per
    ``client_rows`` call; sizes come from the dataset shapes (h5 metadata,
    no payload read). Gated on h5py at construction."""

    def __init__(self, train_path: str, class_num: int,
                 test_path: str | None = None,
                 n_clients: int | None = None):
        import h5py  # ImportError is the caller's gate

        self._h5 = h5py.File(train_path, "r")
        self._lock = threading.Lock()
        self.class_num = int(class_num)
        ex = self._h5["examples"]
        self._cids = sorted(ex.keys())[:n_clients]
        if not self._cids:
            raise ValueError(f"{train_path}: no clients under examples/")
        g0 = ex[self._cids[0]]
        self._xkey = ("pixels" if "pixels" in g0
                      else ("image" if "image" in g0 else "snippets"))
        self._ykey = "label" if "label" in g0 else None
        self._sizes = np.asarray(
            [ex[c][self._xkey].shape[0] for c in self._cids], np.int64)
        self.test_x, self.test_y = self._load_test(
            h5py, test_path, n_clients)

    def _load_test(self, h5py, test_path, n_clients):
        if test_path is None or not os.path.exists(test_path):
            log.warning("%s: no test h5 — evaluating on client 0's TRAIN "
                        "rows (test_acc will be training accuracy)",
                        self._h5.filename)
            x, y = self.client_rows(0)
            return x, y
        xs, ys = [], []
        with h5py.File(test_path, "r") as f:
            ex = f["examples"]
            for c in sorted(ex.keys())[:n_clients]:
                xs.append(self._arrify_x(np.asarray(ex[c][self._xkey])))
                if self._ykey:
                    ys.append(np.asarray(ex[c][self._ykey], np.int64))
        x = np.concatenate(xs)
        y = (np.concatenate(ys) if ys
             else np.zeros((len(x),), np.int64))
        return x, y

    @staticmethod
    def _arrify_x(x: np.ndarray) -> np.ndarray:
        if x.dtype != np.dtype("O"):
            x = x.astype(np.float32)
        if x.ndim == 3:  # [N, H, W] -> NHWC, like _load_tff_h5
            x = x[..., None]
        return x

    @property
    def client_sizes(self) -> np.ndarray:
        return self._sizes

    def client_rows(self, cid: int):
        with self._lock:
            g = self._h5["examples"][self._cids[int(cid)]]
            x = self._arrify_x(np.asarray(g[self._xkey]))
            y = (np.asarray(g[self._ykey], np.int64) if self._ykey
                 else np.zeros((len(x),), np.int64))
        return x, y

    def close(self):
        self._h5.close()


def as_source(data) -> ClientDataSource:
    """Normalize: a ClientDataSource passes through, FederatedData wraps."""
    if isinstance(data, ClientDataSource):
        return data
    if isinstance(data, FederatedData):
        return InMemorySource(data)
    raise TypeError(f"expected FederatedData or ClientDataSource, got "
                    f"{type(data).__name__}")


def open_source(path: str, input_shape=None, class_num: int | None = None,
                n_clients: int | None = None) -> ClientDataSource:
    """Open an on-disk dataset as a streamed source by layout sniffing:
    packed-npy (meta.json), LEAF-json (train/*.json), TFF-h5 (*.h5)."""
    import glob

    if os.path.isfile(os.path.join(path, "meta.json")):
        return PackedNpySource(path, n_clients=n_clients)
    if glob.glob(os.path.join(path, "train", "*.json")):
        if input_shape is None or class_num is None:
            raise ValueError("LEAF-json sources need input_shape= and "
                             "class_num= (no meta.json to read them from)")
        return LeafJsonSource(path, input_shape, class_num,
                              n_clients=n_clients)
    h5s = sorted(glob.glob(os.path.join(path, "*.h5")))
    if h5s:
        if class_num is None:
            raise ValueError("TFF-h5 sources need class_num=")
        train = next((p for p in h5s if "train" in os.path.basename(p)),
                     h5s[0])
        test = next((p for p in h5s if "test" in os.path.basename(p)), None)
        return TffH5Source(train, class_num, test_path=test,
                           n_clients=n_clients)
    raise FileNotFoundError(
        f"{path}: no packed-npy meta.json, LEAF train/*.json, or *.h5")


def pack_clients_source(
    source: ClientDataSource,
    client_ids,
    batch_size: int,
    max_batches: int | None = None,
    seed: int = 0,
    round_idx: int = 0,
) -> ClientBatch:
    """``pack_clients`` against a streamed source: only the SAMPLED
    clients' rows are read, shuffled with the same (seed, round,
    CLIENT-ID) splitmix chain (positions instead of global indices — the
    permutation is identical, so batches are bit-identical to the
    in-memory packer over equivalent data; test-enforced)."""
    sizes = source.client_sizes
    counts = [int(sizes[int(c)]) for c in client_ids]
    b_needed = max(int(np.ceil(n / batch_size)) for n in counts)
    B = b_needed if max_batches is None else min(max_batches, b_needed)
    K, bs = len(client_ids), batch_size
    seeds = client_shuffle_seeds(client_ids, seed, round_idx)

    (xshape, xdtype), (yshape, ydtype) = source.row_meta()
    if B == 0:
        return ClientBatch(
            x=np.zeros((K, 0, bs) + xshape, xdtype),
            y=np.zeros((K, 0, bs) + yshape, ydtype),
            mask=np.zeros((K, 0, bs), np.float32),
            num_samples=np.zeros((K,), np.float32))

    x = np.zeros((K, B, bs) + xshape, dtype=xdtype)
    y = np.zeros((K, B, bs) + yshape, dtype=ydtype)
    mask = np.zeros((K, B, bs), dtype=np.float32)
    num = np.zeros((K,), dtype=np.float32)
    for k, cid in enumerate(client_ids):
        cx, cy = source.client_rows(int(cid))
        pos = np.arange(len(cx), dtype=np.int64)
        _splitmix_shuffle(pos, int(seeds[k]))
        pos = pos[: B * bs]
        n = len(pos)
        num[k] = n
        x[k].reshape(B * bs, *xshape)[:n] = cx[pos]
        y[k].reshape(B * bs, *yshape)[:n] = cy[pos]
        mask[k].reshape(B * bs)[:n] = 1.0
    return ClientBatch(x=x, y=y, mask=mask, num_samples=num)
