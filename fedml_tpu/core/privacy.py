"""Differential-privacy accounting for DP-FedAvg (Rényi DP).

The reference ships "weak DP" — uncalibrated Gaussian noise with no
privacy accounting (fedml_core/robustness/robust_aggregation.py:51-55,
``--stddev`` chosen by hand). This module adds the real recipe
(DP-FedAvg, McMahan et al. 2018): per-client update clipping to an L2
ball C, server noise calibrated as ``z * C / m`` on the m-client average,
and an RDP accountant that converts the per-round subsampled-Gaussian
mechanism into a cumulative (ε, δ) statement.

Accounting math (standard results, implemented from the formulas):
  * Gaussian mechanism RDP at order α: ``α / (2 z²)``.
  * Poisson-subsampled Gaussian at sampling rate q, integer α ≥ 2
    (Mironov-Talwar-Zhang '19 / the Opacus-style binomial bound):
        RDP(α) = 1/(α-1) · log Σ_{k=0..α} C(α,k) (1-q)^(α-k) q^k
                                     · exp(k(k-1) / (2 z²))
    computed in log-space so large α / tiny q don't underflow.
  * Composition: RDP adds across rounds; conversion
    ε = min_α [ RDP(α) + log(1/δ)/(α-1) ].
Client sampling here is uniform-without-replacement per round; the
Poisson-subsampling bound is the standard (slightly optimistic for
q ≪ 1, widely used) surrogate — stated rather than hidden.
"""

from __future__ import annotations

import math

import numpy as np

# integer orders + a few fractional-free extras; the classic default grid
DEFAULT_ALPHAS = tuple(range(2, 64)) + (128, 256, 512)


def gaussian_rdp(noise_multiplier: float, alpha: int) -> float:
    """RDP of the (unsubsampled) Gaussian mechanism at order alpha."""
    return alpha / (2.0 * noise_multiplier ** 2)


def subsampled_gaussian_rdp(q: float, noise_multiplier: float,
                            alpha: int) -> float:
    """RDP at integer order alpha of the Poisson-subsampled Gaussian
    (log-space binomial sum; exact for integer alpha)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"sampling rate q={q} outside [0, 1]")
    if noise_multiplier <= 0.0:
        # z=0 means NO privacy (eps would be infinite); fail fast instead
        # of dividing by zero after a training round was already spent
        raise ValueError(f"noise_multiplier must be > 0, got {noise_multiplier}")
    if alpha < 2 or int(alpha) != alpha:
        raise ValueError(f"integer alpha >= 2 required, got {alpha}")
    if q == 0.0:
        return 0.0
    if q == 1.0:
        return gaussian_rdp(noise_multiplier, alpha)
    z2 = noise_multiplier ** 2
    k = np.arange(alpha + 1, dtype=np.float64)
    # log C(alpha, k) from cumulative log-factorials; terms summed in log
    # space with logaddexp so large alpha / tiny q never underflow
    log_fact = np.concatenate(
        [[0.0], np.cumsum(np.log(np.arange(1, alpha + 1)))])
    log_binom = log_fact[alpha] - log_fact - log_fact[::-1]
    log_terms = (log_binom + k * math.log(q) + (alpha - k) * math.log1p(-q)
                 + k * (k - 1) / (2.0 * z2))
    return max(0.0, float(np.logaddexp.reduce(log_terms)) / (alpha - 1))


def rdp_to_epsilon(rdp_by_alpha, alphas, delta: float) -> float:
    """Best (ε, δ) over the order grid."""
    if not 0.0 < delta < 1.0:
        raise ValueError(f"delta={delta} outside (0, 1)")
    log_inv_delta = math.log(1.0 / delta)
    return float(min(r + log_inv_delta / (a - 1)
                     for r, a in zip(rdp_by_alpha, alphas)))


class DPAccountant:
    """Cumulative RDP over FedAvg rounds.

    One ``step(q, z)`` per round (q = clients sampled / clients total,
    z = noise multiplier); ``epsilon(delta)`` any time for the cumulative
    guarantee."""

    def __init__(self, alphas=DEFAULT_ALPHAS):
        self.alphas = tuple(alphas)
        self._rdp = np.zeros(len(self.alphas))

    def step(self, q: float, noise_multiplier: float, rounds: int = 1):
        self._rdp = self._rdp + rounds * np.array(
            [subsampled_gaussian_rdp(q, noise_multiplier, a)
             for a in self.alphas])
        return self

    def epsilon(self, delta: float) -> float:
        return rdp_to_epsilon(self._rdp, self.alphas, delta)

    def best_order(self, delta: float) -> tuple[int, float]:
        """(alpha*, cumulative RDP at alpha*) — the order the ε conversion
        settled on, the 'cumulative RDP' half of the privacy ledger."""
        log_inv_delta = math.log(1.0 / delta)
        i = int(np.argmin([r + log_inv_delta / (a - 1)
                           for r, a in zip(self._rdp, self.alphas)]))
        return self.alphas[i], float(self._rdp[i])


# the privacy ledger's default reporting delta; every surface that renders
# ε (round records, /healthz, the bench artifact) states it alongside
DEFAULT_DELTA = 1e-5


class ClientPrivacyLedger:
    """Per-client RDP ledgers — ε budgets at client granularity.

    The cohort-level :class:`DPAccountant` answers "how much privacy has
    this RUN spent"; multi-tenant deployments need "how much has THIS
    user spent", which only grows on the rounds the client actually
    participated in. Each participation is charged at the UNsubsampled
    Gaussian bound ``α / (2 z²)`` — conditioning on "client i was
    sampled" forfeits the amplification-by-subsampling discount, so the
    per-client figure is the conservative (never-under-reporting) side
    of the cohort bound.

    Durability contract: the charge sites journal the participating
    client ids on the WAL ``precharge`` record BEFORE the noise key is
    drawn (core/wal.py module docstring), so a server SIGKILL between
    charge and noise replays the per-client charges too — ε may
    over-count by one round per crash, never under-count. Keys are
    client ids (namespace-ready for multi-tenancy: a tenant prefix on
    the id is all a shared fleet needs)."""

    def __init__(self, alphas=DEFAULT_ALPHAS):
        self.alphas = tuple(alphas)
        self._rdp: dict[int, np.ndarray] = {}

    def charge(self, client_ids, noise_multiplier: float,
               rounds: int = 1) -> None:
        """Charge one participation (``rounds`` of them) to each listed
        client at the unamplified Gaussian bound."""
        if noise_multiplier <= 0.0:
            raise ValueError(
                f"noise_multiplier must be > 0, got {noise_multiplier}")
        step = rounds * np.array(
            [gaussian_rdp(noise_multiplier, a) for a in self.alphas])
        for cid in client_ids:
            cid = int(cid)
            prev = self._rdp.get(cid)
            self._rdp[cid] = step if prev is None else prev + step

    def epsilon(self, client_id: int, delta: float = DEFAULT_DELTA) -> float:
        rdp = self._rdp.get(int(client_id))
        if rdp is None:
            return 0.0
        return rdp_to_epsilon(rdp, self.alphas, delta)

    def eps_max(self, delta: float = DEFAULT_DELTA) -> float:
        """The worst per-client ε — the budget figure /healthz and the
        ``fed_privacy_client_epsilon`` gauge family surface."""
        if not self._rdp:
            return 0.0
        return max(self.epsilon(cid, delta) for cid in self._rdp)

    def summary(self, delta: float = DEFAULT_DELTA) -> dict:
        """{eps_client_max, eps_client_mean, clients_charged} — the
        rollup the round record's privacy block carries."""
        if not self._rdp:
            return {"eps_client_max": 0.0, "eps_client_mean": 0.0,
                    "clients_charged": 0}
        eps = [self.epsilon(cid, delta) for cid in self._rdp]
        return {"eps_client_max": round(max(eps), 6),
                "eps_client_mean": round(float(np.mean(eps)), 6),
                "clients_charged": len(eps)}


def privacy_block(accountant: DPAccountant, q: float, noise_multiplier: float,
                  clip: float, delta: float = DEFAULT_DELTA,
                  realized_m: int | None = None) -> dict:
    """The ``privacy`` block a DP round record carries (docs/ROBUSTNESS.md
    §Privacy ledger): cumulative ε@δ plus the round's mechanism parameters
    — sampling rate q, noise multiplier z, clip bound C, the REALIZED
    survivor count m the noise was calibrated over (elastic/secure rounds
    shrink it), and the RDP order the conversion settled on. ε is computed
    from the accountant's cumulative RDP totals, which ride checkpoints —
    resume neither under-reports ε nor replays noise keys."""
    alpha, rdp = accountant.best_order(delta)
    block = {
        "eps": round(accountant.epsilon(delta), 6),
        "delta": delta,
        "q": round(float(q), 8),
        "z": float(noise_multiplier),
        "clip": float(clip),
        "rdp_alpha": int(alpha),
        "rdp": round(rdp, 6),
    }
    if realized_m is not None:
        block["m"] = int(realized_m)
    return block


def charge_and_record(accountant: DPAccountant, q: float,
                      noise_multiplier: float, clip: float,
                      realized_m: int | None = None,
                      rounds: int = 1,
                      client_ledger: ClientPrivacyLedger | None = None,
                      client_ids=None) -> dict:
    """The one step-then-surface sequence every DP aggregator runs:
    charge the accountant, build the round record's ``privacy`` block,
    refresh the live ``fed_privacy_epsilon`` gauge (the privacy_budget
    health rule's input). Three engines ride this — the masked secure
    tier, the cross-process dp defense, the standalone engine — and the
    ledger fields must not drift between them.

    With a ``client_ledger`` + the round's participating ``client_ids``,
    the per-client ledgers are charged too and the block gains the
    ``eps_client_max`` / ``eps_client_mean`` / ``clients_charged``
    rollup, mirrored onto the ``fed_privacy_client_epsilon`` gauges."""
    from fedml_tpu.obs import perf_instrument as _perf

    accountant.step(q, noise_multiplier, rounds=rounds)
    block = privacy_block(accountant, q, noise_multiplier, clip,
                          realized_m=realized_m)
    _perf.set_privacy_epsilon(block["eps"])
    if client_ledger is not None and client_ids is not None:
        client_ledger.charge(client_ids, noise_multiplier, rounds=rounds)
        block.update(client_ledger.summary())
        _perf.set_client_epsilon(block["eps_client_max"],
                                 block["eps_client_mean"],
                                 block["clients_charged"])
    return block
