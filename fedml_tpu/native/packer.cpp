// Native client-data packer — the host-side hot loop of every round.
//
// Role: the reference's per-round data plane is Python DataLoaders feeding
// pickled tensors into MPI sends (one process per client). Here the round's
// sampled clients are packed into ONE dense [K, B, bs, ...] block that is
// DMA'd to the TPU; this file is that packing loop in C++ (std::thread fan-out
// over clients, memcpy row gather, splitmix64/Fisher-Yates shuffle) so the
// host never bottlenecks the device at 3400-client scale.
//
// Contract (row-major, preallocated outputs, bytes-typed rows so any dtype
// works):
//   x        [N, x_row_bytes]          y        [N, y_row_bytes]
//   idx      concatenated client index lists; offsets[K+1] frames client k
//   out_x    [K, B*bs, x_row_bytes]    out_y    [K, B*bs, y_row_bytes]
//   out_mask [K, B*bs] float32         out_num  [K] float32
// Each client's indices are shuffled with splitmix64(seeds[k]) Fisher-Yates
// (seeds are derived from the CLIENT ID by the caller, so packing a client
// alone or in a group yields the same rows — required for the
// distributed ≡ standalone equivalence oracle), truncated to B*bs,
// gathered, zero-padded. Returns 0 on success.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

void pack_one_client(
    const char* x, int64_t x_row_bytes,
    const char* y, int64_t y_row_bytes,
    const int64_t* idx, int64_t n_idx,
    int64_t capacity,  // B * bs
    uint64_t seed, int assume_zeroed,
    char* out_x, char* out_y, float* out_mask, float* out_num) {
  // Fisher-Yates shuffle of a local copy of the index list
  std::vector<int64_t> order(idx, idx + n_idx);
  uint64_t s = seed;
  for (int64_t i = n_idx - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(splitmix64(s) % static_cast<uint64_t>(i + 1));
    std::swap(order[i], order[j]);
  }
  int64_t n = std::min(n_idx, capacity);
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(out_x + i * x_row_bytes, x + order[i] * x_row_bytes,
                static_cast<size_t>(x_row_bytes));
    std::memcpy(out_y + i * y_row_bytes, y + order[i] * y_row_bytes,
                static_cast<size_t>(y_row_bytes));
    out_mask[i] = 1.0f;
  }
  // padding: with calloc'd (pre-zeroed) buffers the pages are already zero
  // and touching them would only fault them in — skip the memset then.
  if (n < capacity && !assume_zeroed) {
    std::memset(out_x + n * x_row_bytes, 0,
                static_cast<size_t>((capacity - n) * x_row_bytes));
    std::memset(out_y + n * y_row_bytes, 0,
                static_cast<size_t>((capacity - n) * y_row_bytes));
    std::memset(out_mask + n, 0, static_cast<size_t>(capacity - n) * sizeof(float));
  }
  *out_num = static_cast<float>(n);
}

}  // namespace

extern "C" {

int fedml_pack_clients(
    const char* x, int64_t x_row_bytes,
    const char* y, int64_t y_row_bytes,
    const int64_t* idx_concat, const int64_t* idx_offsets, int64_t K,
    int64_t capacity, const uint64_t* seeds, int assume_zeroed,
    char* out_x, char* out_y, float* out_mask, float* out_num,
    int n_threads) {
  if (K <= 0 || capacity <= 0 || x_row_bytes <= 0 || y_row_bytes <= 0) return 1;
  int hw = n_threads > 0 ? n_threads
                         : static_cast<int>(std::thread::hardware_concurrency());
  if (hw < 1) hw = 1;
  hw = std::min<int64_t>(hw, K);

  auto work = [&](int64_t k0, int64_t k1) {
    for (int64_t k = k0; k < k1; ++k) {
      const int64_t* idx = idx_concat + idx_offsets[k];
      int64_t n_idx = idx_offsets[k + 1] - idx_offsets[k];
      pack_one_client(x, x_row_bytes, y, y_row_bytes, idx, n_idx, capacity, seeds[k],
                      assume_zeroed,
                      out_x + k * capacity * x_row_bytes,
                      out_y + k * capacity * y_row_bytes,
                      out_mask + k * capacity, out_num + k);
    }
  };

  if (hw == 1) {
    work(0, K);
    return 0;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (K + hw - 1) / hw;
  for (int t = 0; t < hw; ++t) {
    int64_t k0 = t * chunk, k1 = std::min<int64_t>(K, k0 + chunk);
    if (k0 >= k1) break;
    ts.emplace_back(work, k0, k1);
  }
  for (auto& t : ts) t.join();
  return 0;
}

// Dirichlet-style partition shuffle helper: shuffles ``n`` int64 indices
// in-place with splitmix64 — exported so partitioning large datasets can
// skip numpy's RandomState overhead.
void fedml_shuffle_indices(int64_t* idx, int64_t n, uint64_t seed) {
  uint64_t s = seed;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(splitmix64(s) % static_cast<uint64_t>(i + 1));
    std::swap(idx[i], idx[j]);
  }
}

}  // extern "C"
