"""Native (C++) host runtime components, loaded via ctypes.

The compute path is JAX/XLA; this package holds the host-side data plane in
C++: the per-round client packer (packer.cpp) that gathers/shuffles/pads the
sampled clients' samples into the dense device block. Compiled on first use
with g++ -O3 (portable flags — the .so is never committed) and cached next to
the source; everything degrades to the numpy implementation if the toolchain
is missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "packer.cpp")
_SO = os.path.join(_DIR, "_packer.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    # build to a private temp path then atomically rename: concurrent
    # first-use builds from several processes must not corrupt the shared .so
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.fedml_pack_clients.restype = ctypes.c_int
        lib.fedml_pack_clients.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,        # x, x_row_bytes
            ctypes.c_char_p, ctypes.c_int64,        # y, y_row_bytes
            ctypes.POINTER(ctypes.c_int64),         # idx_concat
            ctypes.POINTER(ctypes.c_int64),         # idx_offsets
            ctypes.c_int64, ctypes.c_int64,         # K, capacity
            ctypes.POINTER(ctypes.c_uint64),        # per-client seeds [K]
            ctypes.c_int,                           # assume_zeroed
            ctypes.c_char_p, ctypes.c_char_p,       # out_x, out_y
            ctypes.POINTER(ctypes.c_float),         # out_mask
            ctypes.POINTER(ctypes.c_float),         # out_num
            ctypes.c_int,                           # n_threads
        ]
        lib.fedml_shuffle_indices.restype = None
        lib.fedml_shuffle_indices.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_uint64]
        _lib = lib
        return _lib


def native_available() -> bool:
    return get_lib() is not None


def pack_clients_native(train_x: np.ndarray, train_y: np.ndarray,
                        idx_lists: list[np.ndarray], capacity: int,
                        seeds: np.ndarray, n_threads: int = 0):
    """C++ fast path of core.client_data.pack_clients' inner loop.

    Returns (x [K, capacity, ...], y [K, capacity, ...], mask [K, capacity],
    num [K]) with client k's rows shuffled by splitmix64(seeds[k]); the
    caller derives seeds from client IDs so packing is grouping-invariant.
    """
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native packer unavailable")
    x = np.ascontiguousarray(train_x)
    y = np.ascontiguousarray(train_y)
    K = len(idx_lists)
    offsets = np.zeros(K + 1, np.int64)
    for k, il in enumerate(idx_lists):
        offsets[k + 1] = offsets[k] + len(il)
    idx_concat = (np.concatenate(idx_lists).astype(np.int64) if K
                  else np.zeros(0, np.int64))
    x_row = int(np.prod(x.shape[1:])) * x.itemsize
    y_row = (int(np.prod(y.shape[1:])) if y.ndim > 1 else 1) * y.itemsize

    # np.zeros -> calloc zero pages: padding never gets touched, so the
    # packer only writes real rows (see packer.cpp assume_zeroed)
    out_x = np.zeros((K, capacity) + x.shape[1:], x.dtype)
    out_y = np.zeros((K, capacity) + y.shape[1:], y.dtype)
    out_mask = np.zeros((K, capacity), np.float32)
    out_num = np.empty((K,), np.float32)

    rc = lib.fedml_pack_clients(
        x.ctypes.data_as(ctypes.c_char_p), x_row,
        y.ctypes.data_as(ctypes.c_char_p), y_row,
        idx_concat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        K, capacity,
        np.ascontiguousarray(seeds, np.uint64).ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint64)), 1,
        out_x.ctypes.data_as(ctypes.c_char_p),
        out_y.ctypes.data_as(ctypes.c_char_p),
        out_mask.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out_num.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(n_threads),
    )
    if rc != 0:
        raise RuntimeError(f"fedml_pack_clients failed rc={rc}")
    return out_x, out_y, out_mask, out_num
