"""Client-scaling benchmark: rounds/sec and samples/sec vs clients-per-round.

BASELINE.md north-star row 3: "client scaling 8 -> 256 simulated clients,
near-linear". The SPMD engine vmaps clients, so scaling K multiplies work
per round; throughput in samples/sec should grow until the chip saturates.

Usage:  python bench_scaling.py [--device_data 1] [--points 8,32,128,256]
Prints one JSON line per point (bench.py remains the single-line driver
benchmark; this script is the scaling study). A point that fails (e.g. a
remote-compile drop) prints an error line and the sweep continues.
"""

from __future__ import annotations

import argparse
import json
import time


def _one_point(args, data, task, k):
    import jax

    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig

    cfg = FedAvgConfig(
        comm_round=args.rounds, client_num_in_total=data.num_clients,
        client_num_per_round=k, epochs=1, batch_size=20, lr=0.1,
        frequency_of_the_test=10_000, max_batches=28,
    )
    api = FedAvgAPI(data, task, cfg, device_data=bool(args.device_data))
    if args.device_data:
        # one compiled scan per block: measures device throughput, not
        # per-round host dispatch (bench.py uses the same path)
        api.run_rounds(0, args.rounds)
        jax.block_until_ready(api.net.params)
        t0 = time.perf_counter()
        ms = api.run_rounds(args.rounds, args.rounds)
        jax.block_until_ready(api.net.params)
        count = float(ms["count"][-1])
    else:
        api.run_round(0)
        jax.block_until_ready(api.net.params)
        t0 = time.perf_counter()
        for r in range(1, args.rounds + 1):
            m = api.run_round(r)
        jax.block_until_ready(api.net.params)
        count = float(m["count"])
    dt = time.perf_counter() - t0
    rps = args.rounds / dt
    print(json.dumps({
        "clients_per_round": k,
        "rounds_per_sec": round(rps, 3),
        "samples_per_sec": round(count * rps, 1),
        "device": jax.devices()[0].platform,
    }), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=str, default="8,32,128,256")
    ap.add_argument("--device_data", type=int, default=1)
    ap.add_argument("--rounds", type=int, default=10)
    args = ap.parse_args()

    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.cnn import CNNOriginalFedAvg

    data = load_dataset("femnist", seed=0, uint8_pixels=True)
    task = classification_task(CNNOriginalFedAvg(only_digits=False))

    for k in [int(p) for p in args.points.split(",")]:
        try:
            _one_point(args, data, task, k)
        except Exception as e:  # noqa: BLE001 — later points still measured
            print(json.dumps({"clients_per_round": k,
                              "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)


if __name__ == "__main__":
    main()
