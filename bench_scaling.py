"""Client-scaling + cross-silo benchmarks: rounds/sec vs clients-per-round.

BASELINE.md north-star row 3: "client scaling 8 -> 256 simulated clients,
near-linear". The SPMD engine vmaps clients, so scaling K multiplies work
per round; throughput in samples/sec should grow until the chip saturates.

Workloads:
  - femnist_cnn (default): the flagship cross-device config (FedAvg CNN,
    28x28x1, 62 classes) — bench.py's workload at varying K.
  - cifar_resnet56: the reference's cross-silo setting (ResNet-56 on
    CIFAR-10 shapes, 10 clients, benchmark/README.md:105 — its RTX-2080Ti
    x4 distributed row) as one SPMD program on the chip.

Usage:  python bench_scaling.py [--workload cifar_resnet56] [--device_data 1]
                                [--points 8,32,128,256] [--spans 1]
Prints one JSON line per point (bench.py remains the single-line driver
benchmark; this script is the scaling study). A point that fails (e.g. a
remote-compile drop) prints an error line and the sweep continues.
--spans 1 adds a host-side span breakdown (pack vs device compute vs eval,
utils/tracing.RoundTracer) to each point — where round time goes.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


_MEMO: dict = {}


def _one_point(args, data, task, k):
    import os

    import jax
    import numpy as np

    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig

    cfg = FedAvgConfig(
        comm_round=args.rounds, client_num_in_total=data.num_clients,
        client_num_per_round=k, epochs=1, batch_size=args.batch_size, lr=0.1,
        frequency_of_the_test=10_000, max_batches=args.max_batches,
        remat=bool(args.remat),
    )
    # FEDML_BENCH_SHARDED_AGG=0|1 — the replicated-vs-sharded server-state
    # A/B (docs/PERFORMANCE.md §Partitioned server state): both legs run
    # the SAME mesh over every local device (so the comparison isolates
    # the server-plane layout, not mesh-vs-single-chip), 1 additionally
    # partitions the global model per the rule table. Unset = the
    # historical single-chip sweep, untouched.
    sharded_env = os.environ.get("FEDML_BENCH_SHARDED_AGG")
    mesh, shard = None, False
    if sharded_env is not None:
        ndev = jax.device_count()
        if ndev > 1 and k % ndev == 0:
            from jax.sharding import Mesh

            mesh = Mesh(np.array(jax.devices()), ("clients",))
            # same lenient spelling as bench.py's FEDML_BENCH_PIPELINE
            shard = sharded_env != "0"
        else:
            why = ("only one device visible" if ndev <= 1
                   else f"k={k} not a multiple of {ndev} devices")
            print(f"bench_scaling: FEDML_BENCH_SHARDED_AGG set but {why} "
                  "— point runs unmeshed", file=sys.stderr)
    api = FedAvgAPI(data, task, cfg, device_data=bool(args.device_data),
                    donate=True, mesh=mesh, shard_server_state=shard,
                    block_working_set=bool(args.device_data)
                    and bool(args.working_set))

    if args.device_data:
        # one compiled scan per block, no per-round host dispatch (bench.py
        # uses the same path). NOTE: with the working-set plane the timed
        # window deliberately includes each block's host-side row compaction
        # + upload — that IS the per-block cost of this plane; the span
        # breakdown separates it (host_pack). --working_set 0 (or
        # FEDML_BENCH_FULL_PARK=1) restores pure device throughput with the
        # whole train set parked before timing starts.
        api.run_rounds(0, args.rounds)
        jax.block_until_ready(api.net.params)
        base = api.tracer.totals()  # warmup holds the compile; exclude
        t0 = time.perf_counter()
        ms = api.run_rounds(args.rounds, args.rounds)
        jax.block_until_ready(api.net.params)
        count = float(ms["count"][-1])
    else:
        api.run_round(0)
        jax.block_until_ready(api.net.params)
        base = api.tracer.totals()
        t0 = time.perf_counter()
        for r in range(1, args.rounds + 1):
            m = api.run_round(r)
        jax.block_until_ready(api.net.params)
        count = float(m["count"])
    dt = time.perf_counter() - t0
    rps = args.rounds / dt
    rec = {
        "workload": args.workload,
        "clients_per_round": k,
        "rounds_per_sec": round(rps, 3),
        "samples_per_sec": round(count * rps, 1),
        "device": jax.devices()[0].platform,
        "data_plane": (("working_set" if api.block_working_set else "full_park")
                       if args.device_data else "host_pack"),
        "dtype": "bf16" if args.bf16 else "f32",
        "remat": bool(args.remat),
    }
    if mesh is not None:
        # per-device memory stats for the A/B blob: the rule-table figure
        # (exact, what fed_server_state_bytes exports) plus the backend's
        # live allocator view where it exists (TPU; CPU returns nothing)
        rec["server_state"] = {
            "mode": api._state_placement,
            "bytes_per_device": api._agg_record[
                "server_state_bytes_per_device"],
            "devices": int(np.prod(list(mesh.shape.values()))),
        }
        try:
            mstats = jax.devices()[0].memory_stats() or {}
            if "bytes_in_use" in mstats:
                rec["server_state"]["device0_bytes_in_use"] = int(
                    mstats["bytes_in_use"])
        except Exception:  # noqa: BLE001 — allocator stats are best-effort
            pass
    # MFU vs bf16 peak (TPU only): XLA's own FLOP count of the compiled
    # forward on one batch, 3x-forward train accounting (utils/flops.py).
    # Memoized: the forward is identical across every sweep point.
    import jax.numpy as jnp

    from fedml_tpu.utils.flops import compiled_flops, train_mfu

    if "fwd_flops" not in _MEMO:
        xb = jnp.asarray(data.train_x[: args.batch_size])
        _MEMO["fwd_flops"] = compiled_flops(api.task.predict, api.net.params,
                                            api.net.extra, xb)
    fwd = _MEMO["fwd_flops"]
    if fwd:
        mfu = train_mfu(count * rps, fwd / args.batch_size)
        if mfu is not None:
            rec["mfu_vs_bf16_peak"] = round(mfu, 5)
            rec["fwd_flops_per_sample"] = round(fwd / args.batch_size)
    if args.spans:
        # where TIMED-window wall-clock goes. Tracer spans give the host
        # side (index/data packing); everything else is the device program
        # + dispatch (the engines dispatch asynchronously, so per-span
        # device timing is not separable host-side — the residual is).
        # The warmup compile is excluded (delta vs the post-warmup base).
        end = api.tracer.totals()
        pack = end.get("pack", 0.0) - base.get("pack", 0.0)
        rec["span_seconds"] = {
            "host_pack": round(pack, 3),
            "device_plus_dispatch": round(max(0.0, dt - pack), 3),
        }
    try:
        # provenance header (obs/provenance.py): git sha, versions, device
        # kind/count, date — consumers tolerate absence on historical blobs
        from fedml_tpu.obs.provenance import stamp
        stamp(rec, date=time.strftime("%Y-%m-%d"))
    except Exception:  # noqa: BLE001 — provenance must never sink a point
        pass
    print(json.dumps(rec), flush=True)


def main():
    from fedml_tpu.utils.metrics import enable_compile_cache

    enable_compile_cache()
    # a timeout(1)-TERMed sweep must release the accelerator grant (raw
    # SIGTERM would skip PJRT teardown and wedge it, like bench.py's child)
    import signal

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", type=str, default="femnist_cnn",
                    choices=["femnist_cnn", "cifar_resnet56"])
    ap.add_argument("--points", type=str, default=None,
                    help="clients-per-round sweep; default 8,32,128,256 "
                         "(femnist_cnn) or 10 (cifar_resnet56 = the "
                         "reference cross-silo client count)")
    ap.add_argument("--device_data", type=int, default=1)
    ap.add_argument("--working_set", type=int, default=0,
                    help="with --device_data: per-block working-set park "
                         "(upload only the rows a block touches) instead "
                         "of parking the whole train set up front. Opt-in "
                         "(like the CLI's --working_set): it moves per-block "
                         "host compaction+upload INTO the timed window, so "
                         "sweep numbers are only comparable to other "
                         "working-set sweeps")
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--batch_size", type=int, default=None)
    ap.add_argument("--max_batches", type=int, default=None)
    ap.add_argument("--spans", type=int, default=1)
    ap.add_argument("--samples_per_client", type=int, default=None)
    # HBM-pressure knobs for the cross-silo workload (the 10-client vmapped
    # ResNet-56 program): bf16 activations halve activation HBM; remat
    # (jax.checkpoint around the per-batch local update) trades FLOPs for
    # activation memory. Exercise on the real chip if the full-precision
    # program doesn't fit.
    ap.add_argument("--bf16", type=int, default=0)
    ap.add_argument("--remat", type=int, default=0)
    args = ap.parse_args()
    if args.device_data and args.working_set:
        print("bench_scaling: working-set plane ON — the timed window now "
              "includes per-block host compaction+upload; numbers are not "
              "comparable to full-park sweeps", file=sys.stderr)

    from fedml_tpu.core.tasks import classification_task

    dtype = None
    if args.bf16:
        import jax.numpy as jnp

        dtype = jnp.bfloat16
    if args.workload == "cifar_resnet56":
        from fedml_tpu.data.synthetic import synthetic_images
        from fedml_tpu.models.resnet import ResNetCIFAR

        args.points = args.points or "10"
        args.batch_size = args.batch_size or 64
        args.max_batches = args.max_batches or 8
        # 10 silos, CIFAR-10 shapes (benchmark/README.md:105 setting);
        # uint8 pixels like the flagship path
        data = synthetic_images(
            num_clients=10, image_shape=(32, 32, 3), num_classes=10,
            samples_per_client=args.samples_per_client or 512,
            test_samples=512, seed=0, size_lognormal=False, as_uint8=True)
        task = classification_task(ResNetCIFAR(depth=56, num_classes=10,
                                               norm_type="group", dtype=dtype))
    else:
        from fedml_tpu.data.registry import load_dataset
        from fedml_tpu.models.cnn import CNNOriginalFedAvg

        args.points = args.points or "8,32,128,256"
        args.batch_size = args.batch_size or 20
        args.max_batches = args.max_batches or 28
        data = load_dataset("femnist", seed=0, uint8_pixels=True)
        task = classification_task(CNNOriginalFedAvg(only_digits=False,
                                                     dtype=dtype))

    for k in [int(p) for p in args.points.split(",")]:
        try:
            _one_point(args, data, task, k)
        except Exception as e:  # noqa: BLE001 — later points still measured
            print(json.dumps({"clients_per_round": k,
                              "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)


if __name__ == "__main__":
    main()
