"""Benchmark: FedAvg rounds/sec on FEMNIST-shaped workload (BASELINE.json).

Runs the flagship config — FedAvg-paper CNN, 3400 simulated clients, 10
sampled per round, batch 20, E=1 (benchmark/README.md:54 setting) — on the
available device(s) and prints ONE JSON line.

vs_baseline: the reference publishes no throughput numbers
(BASELINE.json.published = {}); its round latency is bounded below by the
MPI manager's 0.3 s receive-poll sleep (mpi/com_manager.py:71-78), so we use
1/0.3 ≈ 3.33 rounds/sec as the reference ceiling for the ratio.
"""

from __future__ import annotations

import json
import time


def main():
    import jax

    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.cnn import CNNOriginalFedAvg

    # FEMNIST-shaped: 3400 clients, ~110 samples each (lognormal sizes);
    # uint8 pixels -> 4x less host->device transfer, normalized on device
    data = load_dataset("femnist", seed=0, uint8_pixels=True)
    cfg = FedAvgConfig(
        comm_round=30,
        client_num_in_total=3400,
        client_num_per_round=10,
        epochs=1,
        batch_size=20,
        lr=0.1,
        frequency_of_the_test=10_000,  # pure training throughput
        max_batches=28,  # covers ~[22,550]-sample clients at bs=20
    )
    task = classification_task(CNNOriginalFedAvg(only_digits=False))
    # device_data: whole train set parked in HBM (~300 MB uint8); a round
    # ships only the shuffled index block (~KBs) and gathers on device
    api = FedAvgAPI(data, task, cfg, device_data=True)

    n_rounds = 30
    # warmup = compile; scan length is a static shape, so warm up with the
    # same block length as the timed run
    api.run_rounds(0, n_rounds)
    jax.block_until_ready(api.net.params)

    t0 = time.perf_counter()
    # the whole block is ONE compiled lax.scan over rounds: no per-round
    # dispatch, no per-round host->device transfer beyond the index blocks
    api.run_rounds(n_rounds, n_rounds)
    jax.block_until_ready(api.net.params)
    dt = time.perf_counter() - t0

    rounds_per_sec = n_rounds / dt
    baseline_rounds_per_sec = 1.0 / 0.3  # MPI poll-loop lower bound, see docstring
    print(
        json.dumps(
            {
                "metric": "fedavg_femnist_rounds_per_sec",
                "value": round(rounds_per_sec, 3),
                "unit": "rounds/sec",
                "vs_baseline": round(rounds_per_sec / baseline_rounds_per_sec, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
