"""Benchmark: FedAvg rounds/sec on FEMNIST-shaped workload (BASELINE.json).

Runs the flagship config — FedAvg-paper CNN, 3400 simulated clients, 10
sampled per round, batch 20, E=1 (benchmark/README.md:54 setting) — and
prints ONE JSON line (the last stdout line is the authoritative result).

Structure (robustness on flaky/remote-compile backends, e.g. a TPU reached
through a relay that can die mid-compile):

  PARENT (this process, never imports jax — cannot hang on backend init):
    1. probe the backend in a time-boxed subprocess, with retries/backoff;
       if the accelerator never comes up, fall back to JAX_PLATFORMS=cpu
       (a degraded but real number beats a stack trace);
    2. run the CHEAP per-round measurement first in a time-boxed child and
       keep its JSON (small program = small compile = most likely to
       survive);
    3. then attempt the flagship scanned-block measurement in another child
       and take its JSON if it succeeds;
    4. emit exactly one JSON line: block result if available, else the
       per-round result.

  CHILD (``bench.py --measure per_round|block``): builds the workload,
  warms one compile, times rounds, prints its own JSON line.

vs_baseline: the reference publishes no throughput numbers
(BASELINE.json.published = {}); its round latency is bounded below by the
MPI manager's 0.3 s receive-poll sleep (mpi/com_manager.py:71-78), so we use
1/0.3 ≈ 3.33 rounds/sec as the reference ceiling for the ratio.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_BASELINE_ROUNDS_PER_SEC = 1.0 / 0.3  # MPI poll-loop lower bound, see docstring


def _cpu_cheap_rounds() -> str:
    """Timed rounds for a CPU-degraded measurement (a 1-core box fits ~2
    rounds + the 215 s compile in a stretched child budget)."""
    return os.environ.get("FEDML_BENCH_ROUNDS_CHEAP_CPU", "2")


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, "") or default))
    except ValueError:
        print(f"bench: ignoring non-integer {name}", file=sys.stderr)
        return default


# Analytic forward FLOPs/sample for the flagship CNNOriginalFedAvg
# (reference model shapes, cnn.py:26-163): two SAME 5x5 convs with 2x2
# pooling between, then 3136->512->62 dense. A training step is ~3x the
# forward (fwd + 2 bwd matmul passes) — the standard MFU accounting.
_CNN_FWD_FLOPS = 2 * (28 * 28 * 5 * 5 * 1 * 32        # conv1 @ 28x28
                      + 14 * 14 * 5 * 5 * 32 * 64     # conv2 @ 14x14
                      + 3136 * 512 + 512 * 62)        # dense head
# Peak dense-matmul throughput per chip, bf16, FLOPs/s (public figures:
# v2 45 TF, v3 123 TF, v4 275 TF, v5e 197 TF, v5p 459 TF, v6e 918 TF).
# MFU is quoted against bf16 peak even for f32 runs (XLA runs f32
# contractions through the MXU in multi-pass bf16), so the f32 number is
# conservative. More-specific keys first: next() takes the first substring
# hit, and "v5"/"v6" alone would shadow the lite/p variants.
_PEAK_BF16 = {"v5 lite": 1.97e14, "v5e": 1.97e14, "v5p": 4.59e14,
              "v6 lite": 9.18e14, "v6e": 9.18e14,
              "v4": 2.75e14, "v3": 1.23e14, "v2": 4.5e13}


def _mfu(samples_per_sec_per_chip: float, platform: str) -> float | None:
    if platform != "tpu":
        return None  # no meaningful peak to quote against off-TPU
    kind = ""
    if "jax" in sys.modules:  # never IMPORT jax here: in a fresh process
        #                       that can dial a dead accelerator relay and
        #                       hang; when platform=='tpu' the measuring
        #                       child has long since imported it
        try:
            kind = sys.modules["jax"].devices()[0].device_kind.lower()
        except Exception:  # noqa: BLE001 — MFU is garnish, never fail
            pass
    peak = next((v for k, v in _PEAK_BF16.items() if k in kind), None)
    if peak is None:
        return None  # unknown generation: a guessed peak would misreport
    return samples_per_sec_per_chip * 3 * _CNN_FWD_FLOPS / peak


def _result(rounds_per_sec: float, mode: str, samples_per_sec: float,
            n_chips: int, platform: str) -> dict:
    rec = {
        "metric": "fedavg_femnist_rounds_per_sec",
        "value": round(rounds_per_sec, 3),
        "unit": "rounds/sec",
        "vs_baseline": round(rounds_per_sec / _BASELINE_ROUNDS_PER_SEC, 2),
        # "block" = flagship scanned-block path; "per_round" = cheap
        # measurement (per-round dispatch) — do NOT compare the two against
        # each other
        "mode": mode,
        "samples_per_sec_per_chip": round(samples_per_sec / max(n_chips, 1), 1),
        "n_chips": n_chips,
        "platform": platform,
    }
    mfu = _mfu(rec["samples_per_sec_per_chip"], platform)
    if mfu is not None:
        # model FLOPs utilization vs bf16 peak — tiny by construction: the
        # flagship model is a 1.66M-param CNN at bs=20 (a cross-DEVICE
        # federated workload is dispatch/HBM-bound, not MXU-bound)
        rec["mfu_vs_bf16_peak"] = round(mfu, 5)
    return rec


# --------------------------------------------------------------------- child

def _mark(t0: float, msg: str) -> None:
    """Phase mark on stderr: post-mortems of timed-out children need to know
    WHERE the budget went (1-core host + TPU-through-a-relay: data gen,
    329 MB park, remote compile, and round dispatch all have very different
    costs here)."""
    print(f"bench[{time.perf_counter() - t0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def _stamped(rec: dict) -> dict:
    """Provenance header (obs/provenance.py) on every child-printed blob:
    git sha, jax/jaxlib versions, device kind+count, and the wall-clock
    date — stamped HERE (the child already imports jax) and never in the
    parent ``_emit`` relay, which must stay jax-free. ``stamp`` never
    overwrites, so re-stamping a relayed blob is a no-op."""
    try:
        from fedml_tpu.obs.provenance import stamp
        stamp(rec, date=time.strftime("%Y-%m-%d"))
    except Exception:  # noqa: BLE001 — provenance must never sink a bench
        pass
    return rec


def _measure(mode: str) -> None:
    """Build the flagship workload and time it; prints one JSON line."""
    t0 = time.perf_counter()
    # the parent TERMs us on timeout: turn that into a normal interpreter
    # exit so the PJRT client tears down and RELEASES the accelerator grant
    # (default SIGTERM disposition would skip cleanup exactly like SIGKILL,
    # wedging the grant for the next child). Best-effort: only helps when
    # the main thread is in Python between dispatches, which is where the
    # per-round loop spends its host time.
    import signal

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))

    import jax

    _mark(t0, f"jax imported; backend={jax.default_backend()}")

    # persistent compile cache: repeat bench runs (and driver re-runs)
    # skip the expensive first compile when the program is unchanged;
    # shared setup with every other entry point so they HIT the same cache
    from fedml_tpu.utils.metrics import enable_compile_cache

    enable_compile_cache()

    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.cnn import CNNOriginalFedAvg

    platform = jax.default_backend()
    n_chips = jax.device_count()

    block = _env_int("FEDML_BENCH_BLOCK", 10)
    n_timed = _env_int("FEDML_BENCH_ROUNDS", 20)
    n_timed = max(block, (n_timed // block) * block)  # whole blocks only
    n_cheap = _env_int("FEDML_BENCH_ROUNDS_CHEAP", 8)
    # debug/test knobs — leave unset for the flagship measurement
    clients_per_round = _env_int("FEDML_BENCH_CLIENTS_PER_ROUND", 10)
    max_batches = _env_int("FEDML_BENCH_MAX_BATCHES", 28)

    # FEDML_BENCH_MESH=N: shard the flagship round over an N-way
    # ('clients',) mesh (psum aggregation on ICI) instead of single-chip
    # vmap — the multi-chip path the dryrun validates, measurable wherever
    # N devices exist. Default: single-device (1 real chip under the
    # driver). clients_per_round rounds UP to a mesh multiple (the engine
    # requires even shards); the JSON's samples_per_sec_per_chip stays
    # comparable because count scales with the extra clients.
    mesh = None
    mesh_n = _env_int("FEDML_BENCH_MESH", 1)
    if mesh_n > 1:
        if n_chips < mesh_n:
            print(f"bench: FEDML_BENCH_MESH={mesh_n} but only {n_chips} "
                  "devices; staying single-device", file=sys.stderr)
        else:
            import numpy as np
            from jax.sharding import Mesh

            mesh = Mesh(np.asarray(jax.devices()[:mesh_n]), ("clients",))
            n_chips = mesh_n
            if clients_per_round % mesh_n:
                clients_per_round = -(-clients_per_round // mesh_n) * mesh_n
                print(f"bench: clients_per_round rounded up to "
                      f"{clients_per_round} (multiple of mesh {mesh_n})",
                      file=sys.stderr)

    # FEMNIST-shaped: 3400 clients, ~110 samples each (lognormal sizes);
    # uint8 pixels -> 4x less host->device transfer, normalized on device
    data = load_dataset("femnist", seed=0, uint8_pixels=True)
    _mark(t0, f"dataset built ({data.train_x.nbytes / 1e6:.0f} MB train)")
    cfg = FedAvgConfig(
        comm_round=block + n_timed,
        client_num_in_total=3400,
        client_num_per_round=clients_per_round,
        epochs=1,
        batch_size=20,
        lr=0.1,
        frequency_of_the_test=10_000,  # pure training throughput
        max_batches=max_batches,  # 28 covers ~[22,550]-sample clients at bs=20
    )
    # FEDML_BENCH_BF16=1: the full mixed-precision policy (docs/
    # PERFORMANCE.md §Mixed precision) — bf16 activations on the MXU AND
    # cfg.precision='bf16' so the vmapped local fits run on bf16 casts of
    # the f32 masters; f32 default for exact reference-comparable numerics
    dtype = None
    if os.environ.get("FEDML_BENCH_BF16") == "1":
        import dataclasses as _dc

        import jax.numpy as jnp

        dtype = jnp.bfloat16
        cfg = _dc.replace(cfg, precision="bf16")
    task = classification_task(CNNOriginalFedAvg(only_digits=False, dtype=dtype))
    # block mode parks the whole train set in HBM (~330 MB uint8) so a round
    # ships only the shuffled index block (~KBs) and gathers on device.
    # per_round mode deliberately does NOT (device_data=False): over a slow
    # relay link the one-time park can eat the whole child budget, while the
    # host-packed path ships only the sampled clients' rows (~4 MB/round) —
    # the cheap measurement must be cheap in TRANSFER, not just compute.
    # donate: round programs write outputs into the incoming model buffers.
    # block mode: working-set park by default — each block uploads only the
    # rows its sampled clients touch (~tens of MB) instead of parking the
    # full train set (~330 MB) up front; FEDML_BENCH_FULL_PARK=1 restores
    # the whole-set park (the right call on a fast local link)
    working_set = os.environ.get("FEDML_BENCH_FULL_PARK") != "1"
    # FEDML_BENCH_BUCKET_B=1: bucketed dynamic batch depth — bit-exact,
    # skips padded no-op batch compute; a mid-timing bucket change costs a
    # recompile, so it is a measured VARIANT, not the headline default
    bucket = os.environ.get("FEDML_BENCH_BUCKET_B") == "1"
    # FEDML_BENCH_TELEMETRY_DIR=<dir>: write the obs event log (per-round
    # records + Prometheus dump; scripts/report.py renders it). A measured
    # VARIANT, never the headline default: floating the round metrics for
    # the event log syncs per round, which the overlap-dependent paths pay
    # for. Off (the default) adds zero work — FedAvgAPI(telemetry=None)
    # builds the identical round program.
    telemetry = None
    tdir = os.environ.get("FEDML_BENCH_TELEMETRY_DIR")
    # FEDML_BENCH_TRACE_DIR=<dir>: also ship the stitched per-round
    # timeline (obs/tracing.py) — trace.json per mode, Perfetto-loadable —
    # so the next TPU battery can decompose its rounds/sec figure into
    # pack/compute/eval wall-clock instead of quoting one opaque number.
    # Implies telemetry (the spans ride the same bundle); a measured
    # VARIANT like the event log, never the headline default.
    trdir = os.environ.get("FEDML_BENCH_TRACE_DIR")
    # FEDML_BENCH_METRICS_PORT=<port>: live /metrics + /healthz for the
    # measuring child (docs/OBSERVABILITY.md §Live endpoints) — watch a
    # long TPU bench instead of waiting for its one JSON line. 0 = an
    # ephemeral port (logged + in the run header). Implies telemetry
    # (same measured-variant caveat as the event log).
    mport = os.environ.get("FEDML_BENCH_METRICS_PORT")
    if tdir or trdir or mport is not None:
        import atexit

        from fedml_tpu.obs import Telemetry

        # per-mode subdirectory: the parent runs per_round and block as
        # SEPARATE children — sharing one events.jsonl would interleave two
        # runs' round records (duplicate round numbers, mixed span bases)
        # and the second child's close() would clobber the first's
        # metrics.prom
        telemetry = Telemetry(log_dir=(os.path.join(tdir or trdir, mode)
                                       if tdir or trdir else None),
                              trace_dir=(os.path.join(trdir, mode)
                                         if trdir else None),
                              run_id=f"bench_{mode}",
                              http_port=(int(mport) if mport is not None
                                         else None))
        if telemetry.http_port is not None:
            print(f"bench: live endpoints on "
                  f"http://127.0.0.1:{telemetry.http_port}/metrics",
                  file=sys.stderr)
        atexit.register(telemetry.close)
    api = FedAvgAPI(data, task, cfg, device_data=(mode == "block"),
                    donate=True, mesh=mesh,
                    block_working_set=(mode == "block" and working_set),
                    bucket_batches=bucket, telemetry=telemetry)
    _mark(t0, f"api built (device_data={mode == 'block'}, "
              f"working_set={mode == 'block' and working_set})")

    if mode == "per_round":
        # cheap path: ONE small per-round program, timed a handful of
        # times — the measurement most likely to survive a flaky backend.
        # Compile/warm-up cost is measured SEPARATELY from the timed
        # rounds and reported as compile_seconds: the parallel AOT warm-up
        # (api.warmup — .lower().compile() through the persistent cache)
        # plus the first executed round.
        t_c = time.perf_counter()
        wrep = api.warmup()
        api.run_round(0)  # warm: fills the jit dispatch cache from disk
        jax.block_until_ready(api.net.params)
        compile_seconds = time.perf_counter() - t_c
        _mark(t0, f"per_round warmup done ({wrep['fresh_compiles']} fresh "
                  f"compiles, {wrep['cache_hits']} cache hits)")
        api.prefetch = 2  # pipelined variant: double-buffered prefetch

        def timed_rounds(start: int, n: int, pipelined: bool):
            """(seconds, samples) over n rounds from a synced start."""
            tm = time.perf_counter()
            if pipelined:
                out = api.run_pipelined(start, n)
                ns = sum(float(m["count"]) for _, m in out)
            else:
                ns = 0.0
                for r in range(start, start + n):
                    ns += float(api.run_round(r)["count"])
            jax.block_until_ready(api.net.params)
            return time.perf_counter() - tm, ns

        # FEDML_BENCH_PIPELINE=0|1 picks the HEADLINE variant (default 1:
        # prefetch + lagged drain); the blob always carries the measured
        # A/B pair when the round budget allows both. A trace-dir run
        # defaults to 0: the pipelined driver emits no per-round
        # distributed traces (rounds overlap), so the variant being traced
        # must be the synchronous one unless the env says otherwise.
        head_pipe = os.environ.get("FEDML_BENCH_PIPELINE",
                                   "0" if trdir else "1") != "0"
        r_next, head_n = 1, n_cheap
        if n_cheap > 2:
            # salvage point: a timed-out child's partial stdout still
            # carries a real (coarser) number — early JSON after 2 rounds;
            # the parent takes the LAST parseable line
            dt, ns = timed_rounds(r_next, 2, head_pipe)
            r_next += 2
            head_n = n_cheap - 2
            early = _result(2 / dt, "per_round", ns / dt, n_chips, platform)
            early["pipeline"] = int(head_pipe)
            print(json.dumps(_stamped(early)), flush=True)
            _mark(t0, "early 2-round salvage line printed")
        dt, ns = timed_rounds(r_next, head_n, head_pipe)
        r_next += head_n
        rec = _result(head_n / dt, "per_round", ns / dt, n_chips, platform)
        rec["pipeline"] = int(head_pipe)
        rec["compile_seconds"] = round(compile_seconds, 2)
        side = {"value": rec["value"],
                "samples_per_sec_per_chip": rec["samples_per_sec_per_chip"]}
        ab = {("on" if head_pipe else "off"): side}
        if n_cheap >= 4:
            # the refined headline is already measured — print it BEFORE
            # spending budget on the A/B other half, so a timeout during
            # the alt rounds salvages the full-precision number instead of
            # falling back to the coarse 2-round line
            print(json.dumps(_stamped(rec)), flush=True)
            _mark(t0, f"{head_n}-round headline printed (A/B half next)")
            # the A/B other half — skipped on degraded budgets (a 1-core
            # CPU box can barely afford the headline rounds)
            alt_n = max(2, n_cheap // 2)
            dt2, ns2 = timed_rounds(r_next, alt_n, not head_pipe)
            alt = _result(alt_n / dt2, "per_round", ns2 / dt2, n_chips,
                          platform)
            ab["off" if head_pipe else "on"] = {
                "value": alt["value"],
                "samples_per_sec_per_chip": alt["samples_per_sec_per_chip"]}
            _mark(t0, f"pipeline A/B pair measured: {ab}")
        rec["pipeline_ab"] = ab
        _mark(t0, f"{head_n} timed rounds done")
        print(json.dumps(_stamped(rec)))
        return

    # flagship path: rounds run in fixed-size blocks; jit caches by shape so
    # ONE compiled lax.scan block executable serves the warmup and every
    # timed block — no per-round dispatch, no per-round transfer beyond the
    # index blocks. Compile cost (AOT block warm-up where the shapes are
    # known up front + park + first block) is reported as compile_seconds,
    # never inside the timed rounds.
    t_c = time.perf_counter()
    if not working_set:
        # full park: block shapes are static — AOT-compile the block fn
        # (working-set row counts are data-dependent; the first block
        # compiles that variant instead)
        wrep = api.warmup(block_rounds=block, per_round=False)
        _mark(t0, f"block AOT warmup done ({wrep['fresh_compiles']} fresh "
                  f"compiles, {wrep['cache_hits']} cache hits)")
    api.run_rounds(0, block)
    jax.block_until_ready(api.net.params)
    compile_seconds = time.perf_counter() - t_c
    _mark(t0, "block warmup (park + compile + first block) done")
    tm = time.perf_counter()
    n_samples = 0.0
    timed = n_timed
    for i, start in enumerate(range(block, block + n_timed, block)):
        ms = api.run_rounds(start, block)
        n_samples += float(ms["count"].sum())
        if i == 0 and n_timed > block:
            jax.block_until_ready(api.net.params)
            dt = time.perf_counter() - tm
            print(json.dumps(_stamped(_result(block / dt, "block", n_samples / dt,
                                              n_chips, platform))), flush=True)
            _mark(t0, "early 1-block salvage line printed")
            # restart the clock (same reason as the per_round salvage): the
            # final number must not include the salvage sync/print
            n_samples, tm, timed = 0.0, time.perf_counter(), n_timed - block
    jax.block_until_ready(api.net.params)
    dt = time.perf_counter() - tm
    _mark(t0, f"{timed} timed rounds done")
    rec = _result(timed / dt, "block", n_samples / dt, n_chips, platform)
    rec["compile_seconds"] = round(compile_seconds, 2)
    print(json.dumps(_stamped(rec)))


# -------------------------------------------------------------------- parent

def _run_child(args: list[str], env: dict, timeout: int) -> tuple[int, str]:
    """Run a time-boxed child; returns (rc, stdout). Never raises.

    On timeout the child gets SIGTERM first and 20 s to unwind before
    SIGKILL: a SIGKILLed TPU holder leaves the accelerator grant wedged for
    minutes (every later backend init hangs until the lease expires), while
    a terminated child releases it — and its already-printed salvage JSON
    still reaches us through the pipe."""
    try:
        proc = subprocess.Popen(
            [sys.executable, "-u", *args], env=env,
            stdout=subprocess.PIPE, stderr=sys.stderr,
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
    except Exception as e:  # noqa: BLE001 — orchestrator must not die
        print(f"bench: child {args} failed to launch ({e})", file=sys.stderr)
        return 1, ""
    try:
        out, _ = proc.communicate(timeout=timeout)
        return proc.returncode, (out or b"").decode("utf-8", "replace")
    except subprocess.TimeoutExpired:
        print(f"bench: child {args} timed out after {timeout}s; terminating",
              file=sys.stderr)
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
        return 124, (out or b"").decode("utf-8", "replace")
    except Exception as e:  # noqa: BLE001
        print(f"bench: child {args} failed ({e})", file=sys.stderr)
        proc.kill()
        proc.communicate()  # reap; leave no zombie/open pipe behind
        return 1, ""


def _last_json_line(out: str) -> dict | None:
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _cpu_env(base) -> dict:
    """Forced-CPU child env: every accelerator/relay env var scrubbed (same
    anchored-prefix rule as the dryrun entrypoint — one var left behind is
    enough for a site hook to dial a dead relay and hang interpreter
    startup) and PYTHONPATH repointed at the repo, which both drops any
    site-hook dir AND keeps fedml_tpu importable for ``--measure``
    children."""
    import __graft_entry__ as ge

    env = {k: v for k, v in dict(base).items() if not ge._is_scrubbed(k)}
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__)) or "."
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _probe_backend() -> tuple[dict, str]:
    """Find a backend that can actually run a device op, with retries.

    Returns (env dict children should run under, backend name the probe
    REPORTED — 'tpu'/'cpu'/...; the name comes from the probe's own
    jax.default_backend(), not from env-var sniffing, so a CPU-only host
    with no JAX_PLATFORMS set is still classified as cpu). Order: the
    inherited env (TPU via relay if configured) with retries/backoff, then
    a forced-CPU env (remote-backend plugin vars dropped so a dead relay
    can't hang interpreter startup).
    """

    def _reported(out: str) -> str:
        for line in reversed(out.strip().splitlines()):
            if line.startswith("probe-ok"):
                parts = line.split()
                if len(parts) >= 2:
                    return parts[1]
        return "unknown"
    probe_timeout = _env_int("FEDML_BENCH_PROBE_TIMEOUT", 120)
    # a SIGKILLed TPU holder (e.g. a timed-out earlier bench child) wedges
    # the axon grant for ~2-5 min and every backend init hangs until the
    # lease expires — so the retry schedule must span that window, not
    # seconds (round-1 lesson; see also .claude/skills/verify gotchas)
    attempts = _env_int("FEDML_BENCH_PROBE_ATTEMPTS", 5)
    probe_code = ("import jax, jax.numpy as jnp; "
                  "x = jnp.ones((256, 256)) @ jnp.ones((256, 256)); "
                  "x.block_until_ready(); "
                  "print('probe-ok', jax.default_backend(), jax.device_count())")

    env = dict(os.environ)
    for i in range(attempts):
        rc, out = _run_child(["-c", probe_code], env, probe_timeout)
        if rc == 0 and "probe-ok" in out:
            print(f"bench: backend probe ok: {out.strip().splitlines()[-1]}",
                  file=sys.stderr)
            return env, _reported(out)
        print(f"bench: backend probe attempt {i + 1}/{attempts} failed "
              f"(rc={rc})", file=sys.stderr)
        if i < attempts - 1:  # no point sleeping before the CPU fallback
            time.sleep(min(30 * (i + 1), 120))

    cpu_env = _cpu_env(os.environ)
    rc, out = _run_child(["-c", probe_code], cpu_env, probe_timeout)
    if rc == 0 and "probe-ok" in out:
        print("bench: accelerator unavailable; falling back to CPU",
              file=sys.stderr)
        return cpu_env, "cpu"
    raise RuntimeError("bench: no working jax backend (accelerator and CPU "
                       "probes both failed)")


def _measure_async() -> None:
    """FEDML_BENCH_ASYNC A/B (docs/ROBUSTNESS.md §Asynchronous buffered
    rounds): the loopback cross-process stack under a seeded 1-rank
    straggler plan, synchronous barrier vs buffered-async — same number of
    global updates, wall-clock compared. The straggler owns every sync
    round (PR 3's critical path); async keeps aggregating without it. The
    env var picks the HEADLINE leg (lenient 0|1 spelling like
    FEDML_BENCH_PIPELINE); both legs always ride the blob. Runs forced-CPU
    loopback — the measurement isolates the round-coordination protocol,
    not device throughput."""
    t0 = time.perf_counter()
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.chaos import FaultPlan
    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.data.synthetic import synthetic_images
    from fedml_tpu.distributed.fedavg import run_simulated
    from fedml_tpu.models.linear import LogisticRegression

    rounds = _env_int("FEDML_BENCH_ASYNC_ROUNDS", 6)
    world = _env_int("FEDML_BENCH_ASYNC_WORLD", 4)
    delay_s = float(os.environ.get("FEDML_BENCH_ASYNC_STRAGGLE_S", "0.3"))
    data = synthetic_images(num_clients=8, image_shape=(8, 8, 1),
                            num_classes=4, samples_per_client=24,
                            test_samples=96, seed=3)
    task = classification_task(LogisticRegression(num_classes=4))
    cfg = FedAvgConfig(comm_round=rounds, client_num_in_total=8,
                       client_num_per_round=world - 1, batch_size=8, lr=0.1,
                       frequency_of_the_test=10_000, seed=0)
    plan = lambda: FaultPlan.from_json(  # noqa: E731 — rebuilt per leg
        {"seed": 11, "rules": [{"fault": "straggle", "src": [2], "dst": [0],
                                "delay_s": delay_s}]})
    run_simulated(data, task, cfg, job_id="bench-async-warm")  # compile leg
    _mark(t0, "async A/B warm run done")

    def leg(async_mode: bool) -> dict:
        tl = time.perf_counter()
        agg = run_simulated(
            data, task, cfg, job_id=f"bench-async-{int(async_mode)}",
            chaos_plan=plan(), round_timeout_s=10.0,
            **(dict(async_buffer_k=max(2, (world - 1) // 2),
                    staleness="poly:0.5") if async_mode else {}))
        dt = time.perf_counter() - tl
        if not agg.history or agg.history[-1]["round"] != rounds - 1:
            raise RuntimeError(
                f"async A/B leg(async={async_mode}) did not complete "
                f"{rounds} global updates: {agg.history[-1:]}")
        return {"seconds": round(dt, 3),
                "rounds_per_sec": round(rounds / dt, 3),
                "updates": rounds}

    ab = {"off": leg(False), "on": leg(True)}
    _mark(t0, f"async A/B measured: {ab}")
    head = "on" if os.environ.get("FEDML_BENCH_ASYNC", "1") != "0" else "off"
    rec = {
        "metric": "fedavg_async_buffered_rounds_per_sec",
        "value": ab[head]["rounds_per_sec"],
        "unit": "rounds/sec",
        "mode": f"async_ab_{head}",
        "async_ab": ab,
        "straggle_s": delay_s,
        "rounds": rounds,
        "world_size": world,
        "speedup_async_vs_sync": round(
            ab["off"]["seconds"] / max(ab["on"]["seconds"], 1e-9), 2),
        "platform": "cpu",
    }
    print(json.dumps(_stamped(rec)), flush=True)


def _measure_dp() -> None:
    """FEDML_BENCH_DP ε-vs-accuracy A/B (docs/ROBUSTNESS.md §Privacy
    ledger): the masked secure-aggregation tier (distributed/
    turboaggregate.py) run once without DP and once per noise multiplier
    at MATCHED rounds and seed — per leg the final eval plus the privacy
    ledger's cumulative ε@δ (the round records carry the same block the
    blob summarizes). The blob is the privacy-cost evidence the CI gate
    (scripts/ci_dp_gate.json) pins: ε must fall as z rises, and the
    accuracy cost at the working point must stay bounded. Runs forced-CPU
    loopback — the measurement isolates the DP mechanism, not device
    throughput."""
    t0 = time.perf_counter()
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.data.synthetic import synthetic_images
    from fedml_tpu.distributed import turboaggregate as ta
    from fedml_tpu.models.linear import LogisticRegression

    rounds = _env_int("FEDML_BENCH_DP_ROUNDS", 8)
    world = _env_int("FEDML_BENCH_DP_WORLD", 9)
    clip = float(os.environ.get("FEDML_BENCH_DP_CLIP", "0.5"))
    data = synthetic_images(num_clients=32, image_shape=(8, 8, 1),
                            num_classes=4, samples_per_client=24,
                            test_samples=128, seed=3)
    task = classification_task(LogisticRegression(num_classes=4))
    cfg = FedAvgConfig(comm_round=rounds, client_num_in_total=32,
                       client_num_per_round=world - 1, epochs=1,
                       batch_size=8, lr=0.1, frequency_of_the_test=1,
                       seed=0)

    def leg(name: str, **kw) -> dict:
        agg = ta.run_simulated(data, task, cfg, job_id=f"bench-dp-{name}",
                               **kw)
        if not agg.history or agg.history[-1]["round"] != rounds - 1:
            raise RuntimeError(f"dp A/B leg {name} did not complete "
                               f"{rounds} rounds: {agg.history[-1:]}")
        rec = {"final_acc": round(agg.history[-1]["test_acc"], 4),
               "final_loss": round(agg.history[-1]["test_loss"], 4)}
        block = agg.privacy_record()
        if block:
            rec.update(eps=block["eps"], delta=block["delta"],
                       z=block["z"], clip=block["clip"], q=block["q"])
        return rec

    legs = {"plain": leg("plain")}
    for z in (0.6, 1.2):
        legs[f"z{z:g}"] = leg(
            f"z{z:g}", defense_type="dp", noise_multiplier=z,
            norm_bound=clip)
    _mark(t0, f"dp A/B measured: {legs}")
    rec = {
        "metric": "fedavg_dp_epsilon_at_z1.2",
        "value": legs["z1.2"]["eps"],
        "unit": "epsilon",
        "mode": "dp_ab",
        "dp_ab": legs,
        "rounds": rounds,
        "world_size": world,
        "clip": clip,
        # ε must FALL as z rises (the accountant's basic monotonicity,
        # gated), and the working point's accuracy cost stays bounded
        "eps_ratio_z0.6_over_z1.2": round(
            legs["z0.6"]["eps"] / max(legs["z1.2"]["eps"], 1e-9), 3),
        "dp_acc_drop_at_z0.6": round(
            legs["plain"]["final_acc"] - legs["z0.6"]["final_acc"], 4),
        "platform": "cpu",
    }
    print(json.dumps(_stamped(rec)), flush=True)


def _measure_codec() -> None:
    """FEDML_BENCH_CODEC A/B (docs/PERFORMANCE.md §Wire efficiency): the
    loopback cross-process stack run once per uplink codec tier — dense
    f32, lossless round-delta, deadzoned int8 delta, 1-bit scaled sign,
    top-k — at MATCHED round count and seed, measuring actual wire bytes
    per direction (``comm_bytes_total{codec,direction}`` deltas around
    each leg) against each tier's convergence curve. The blob is the
    bytes-vs-convergence evidence: per tier, uplink/downlink bytes,
    bytes/round, reduction vs dense, per-round losses, final eval. Runs
    forced-CPU loopback — the measurement isolates wire bytes and codec
    math, not device throughput."""
    t0 = time.perf_counter()
    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.data.synthetic import synthetic_images
    from fedml_tpu.distributed.fedavg import run_simulated
    from fedml_tpu.models.linear import LogisticRegression
    from fedml_tpu.obs.comm_instrument import directional_bytes

    rounds = _env_int("FEDML_BENCH_CODEC_ROUNDS", 10)
    world = _env_int("FEDML_BENCH_CODEC_WORLD", 5)
    # ~16k params: big enough that frame headers don't dilute the byte
    # ratios (the regime the tiers target is models >> headers)
    data = synthetic_images(num_clients=8, image_shape=(40, 40, 1),
                            num_classes=10, samples_per_client=24,
                            test_samples=96, seed=3)
    task = classification_task(LogisticRegression(num_classes=10))
    cfg = FedAvgConfig(comm_round=rounds, client_num_in_total=8,
                       client_num_per_round=world - 1, epochs=1,
                       batch_size=8, lr=0.05, frequency_of_the_test=1,
                       seed=0)

    tiers = {
        "dense": {},
        "delta": {"update_codec": "delta"},
        "delta-int8": {"update_codec": "delta-int8"},
        "delta-sign1": {"update_codec": "delta-sign1"},
        "topk0.1": {"sparsify_ratio": 0.1},
    }
    out: dict = {}
    for name, kw in tiers.items():
        before = directional_bytes()
        tl = time.perf_counter()
        agg = run_simulated(data, task, cfg, job_id=f"bench-codec-{name}",
                            **kw)
        after = directional_bytes()
        if not agg.history or agg.history[-1]["round"] != rounds - 1:
            raise RuntimeError(f"codec leg {name} did not complete "
                               f"{rounds} rounds: {agg.history[-1:]}")
        up = after["uplink"] - before["uplink"]
        out[name] = {
            "uplink_bytes": int(up),
            "downlink_bytes": int(after["downlink"] - before["downlink"]),
            "uplink_bytes_per_round": round(up / rounds, 1),
            "losses": [round(float(h["test_loss"]), 6)
                       for h in agg.history],
            "final_loss": round(float(agg.history[-1]["test_loss"]), 6),
            "final_acc": round(float(agg.history[-1]["test_acc"]), 4),
            "seconds": round(time.perf_counter() - tl, 2),
        }
        _mark(t0, f"codec leg {name}: {out[name]['uplink_bytes']} uplink B, "
                  f"final loss {out[name]['final_loss']}")
    dense_up = out["dense"]["uplink_bytes"]
    for name, rec in out.items():
        rec["uplink_reduction_vs_dense"] = round(
            dense_up / max(rec["uplink_bytes"], 1), 2)
    rec = {
        "metric": "fedavg_uplink_reduction_int8_delta",
        "value": out["delta-int8"]["uplink_reduction_vs_dense"],
        "unit": "x_vs_dense_f32",
        "mode": "codec_ab",
        "rounds": rounds,
        "world_size": world,
        "uplink_reduction_sign1": out["delta-sign1"]
        ["uplink_reduction_vs_dense"],
        "tiers": out,
        "platform": "cpu",
    }
    print(json.dumps(_stamped(rec)), flush=True)


def _measure_fused_agg() -> None:
    """FEDML_BENCH_FUSED fused-vs-stacked server flush A/B (docs/
    PERFORMANCE.md §Fused aggregation): synthesize one cohort of
    delta-int8 uploads at fan-in FEDML_BENCH_FUSED_FANIN (default 128) and
    drive the two server ingest+aggregate routes at matched bits — the
    stacked route host-densifies every upload (zlib + numpy + apply_delta)
    and stacks the cohort, the fused route inflates to int8 and lets the
    per-arrival jit decode/gate/fold on device. Two timed phases per
    round, both synced: INGEST (per-arrival work — overlaps client
    training in production) and FLUSH (barrier -> new global model, the
    serialized critical path and the Smart-NIC seconds-per-flush number:
    stacked pays the [K, ...] stack + gagg jit there, fused only merges
    O(log K) partials and divides). Also reports the whole-server-round
    ratio (conservative) and the host-RSS delta across the ingest (the
    per-client f32 trees are exactly what fused never allocates).
    Forced-CPU child — the measurement isolates the server's decode→sum
    chain, not accelerator FLOPs."""
    t0 = time.perf_counter()
    import jax
    import numpy as np

    from fedml_tpu.algorithms.fedavg import FedAvgConfig
    from fedml_tpu.comm.delta import (encode_update, inflate_update,
                                      round_delta, decode_update,
                                      apply_delta)
    from fedml_tpu.comm.message import pack_pytree
    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.data.synthetic import synthetic_images
    from fedml_tpu.distributed.fedavg.aggregator import FedAvgAggregator
    from fedml_tpu.models.linear import LogisticRegression
    from fedml_tpu.obs.memwatch import host_rss_bytes

    fan_in = _env_int("FEDML_BENCH_FUSED_FANIN", 128)
    rounds = _env_int("FEDML_BENCH_FUSED_ROUNDS", 5)
    # ~92k params (96x96 image -> 10 classes): big enough that the
    # per-upload decode/stack cost dominates the fixed jit dispatch
    data = synthetic_images(num_clients=8, image_shape=(96, 96, 1),
                            num_classes=10, samples_per_client=4,
                            test_samples=8, seed=3)
    task = classification_task(LogisticRegression(num_classes=10))
    cfg = FedAvgConfig(comm_round=rounds, client_num_in_total=fan_in,
                       client_num_per_round=fan_in, batch_size=4,
                       frequency_of_the_test=10_000, seed=0)
    _mark(t0, f"fused A/B workload built (fan-in {fan_in})")

    def synth_uploads(net_leaves, seed):
        """One cohort's encoded delta-int8 uploads (client work — never
        inside the flush timer)."""
        rs = np.random.RandomState(seed)
        out = []
        for _ in range(fan_in):
            local = [v + rs.randn(*np.shape(v)).astype(np.float32) * 0.01
                     for v in net_leaves]
            out.append(encode_update(round_delta(local, net_leaves),
                                     "delta-int8"))
        return out

    def leg(fused: bool, estimator: str | None = None) -> dict:
        # the robust leg (PR-21): estimator legs run the two-phase verdict
        # composition — stacked stages then runs the one-jit evidence →
        # verdicts → survivor fold over the [K, ...] stack; fused emits
        # per-arrival evidence rows and flushes the staged slots through
        # the identical shared composition (robust_agg.verdict_flush)
        agg = FedAvgAggregator(data, task, cfg, worker_num=fan_in,
                               fused_agg=fused, aggregator=estimator,
                               sum_assoc="auto" if fused else "pairwise")
        flush_s, ingest_s, rss_deltas = [], [], []
        for r in range(rounds + 1):  # round 0 = warm (jit compiles)
            agg.begin_round(r)
            base = [np.asarray(v) for v in pack_pytree(agg.net)]
            base_dev = [jax.device_put(v) for v in base] if fused else None
            uploads = synth_uploads(base, seed=100 + r)
            rss0 = host_rss_bytes() or 0
            # INGEST phase: per-arrival work — in production this runs
            # under the receive path while OTHER clients still train, so
            # it is off the barrier's critical path at realistic arrival
            # spreads; timed per cohort (synced) for the A/B anyway
            tl = time.perf_counter()
            for rank, (payload, scales) in enumerate(uploads):
                if fused:
                    raw, sc = inflate_update(payload, scales, "delta-int8",
                                             base)
                    agg.add_fused_result(rank, "delta-int8", raw, sc,
                                         10, r, base_dev)
                else:
                    dec = decode_update(payload, scales, "delta-int8", base)
                    agg.add_local_trained_result(
                        rank, apply_delta(base, dec), 10, r)
            if fused:
                agg._fused.block_until_ready()
            else:
                jax.block_until_ready(
                    [v for leaves in agg.model_dict.values()
                     for v in leaves if isinstance(v, jax.Array)])
            t_ing = time.perf_counter() - tl
            rss1 = host_rss_bytes() or 0
            # FLUSH phase: barrier -> new global model. ALWAYS serialized
            # on the round's critical path — this is the Smart-NIC
            # seconds-per-flush number. Stacked pays the [K, ...] stack +
            # gagg here; fused only merges O(log K) partials + divides.
            tl = time.perf_counter()
            agg._aggregate_core()
            jax.block_until_ready(jax.tree.leaves(agg.net))
            t_fl = time.perf_counter() - tl
            if r > 0:
                ingest_s.append(t_ing)
                flush_s.append(t_fl)
                rss_deltas.append(rss1 - rss0)
        return {"seconds_per_flush": round(float(np.mean(flush_s)), 4),
                "flush_s": [round(float(s), 4) for s in flush_s],
                "ingest_seconds_per_cohort":
                    round(float(np.mean(ingest_s)), 4),
                "server_seconds_per_round": round(
                    float(np.mean(ingest_s) + np.mean(flush_s)), 4),
                "ingest_rss_delta_bytes": int(np.max(rss_deltas)),
                "rss_end_bytes": int(host_rss_bytes() or 0),
                "stack_bytes": int(agg._last_flush["stack_bytes"]),
                "fan_in": fan_in}

    stacked = leg(False)
    _mark(t0, f"stacked leg: {stacked['seconds_per_flush']}s/flush + "
              f"{stacked['ingest_seconds_per_cohort']}s ingest")
    fused = leg(True)
    _mark(t0, f"fused leg: {fused['seconds_per_flush']}s/flush + "
              f"{fused['ingest_seconds_per_cohort']}s ingest")
    stacked_med = leg(False, estimator="median")
    _mark(t0, f"stacked median leg: "
              f"{stacked_med['seconds_per_flush']}s/flush")
    fused_med = leg(True, estimator="median")
    _mark(t0, f"fused median leg: {fused_med['seconds_per_flush']}s/flush")
    rec = {
        "metric": "fedavg_fused_flush_speedup",
        "value": round(stacked["seconds_per_flush"]
                       / max(fused["seconds_per_flush"], 1e-9), 2),
        "unit": "x_stacked_flush_over_fused",
        "mode": "fused_ab",
        "fused_ab": {"stacked": stacked, "fused": fused},
        "fused_flush_speedup": round(
            stacked["seconds_per_flush"]
            / max(fused["seconds_per_flush"], 1e-9), 2),
        # whole-server-round ratio (ingest + flush, both synced): the
        # conservative number — ingest normally overlaps client training
        "fused_server_round_speedup": round(
            stacked["server_seconds_per_round"]
            / max(fused["server_seconds_per_round"], 1e-9), 2),
        # the robust A/B (PR-21 universal ingest): fused×median's staged
        # flush vs stacked×median's verdict flush at the same fan-in
        "fused_robust_ab": {"stacked_median": stacked_med,
                            "fused_median": fused_med},
        "fused_robust_flush_speedup": round(
            stacked_med["seconds_per_flush"]
            / max(fused_med["seconds_per_flush"], 1e-9), 2),
        "fused_robust_server_round_speedup": round(
            stacked_med["server_seconds_per_round"]
            / max(fused_med["server_seconds_per_round"], 1e-9), 2),
        "fused_ingest_rss_delta_bytes": fused["ingest_rss_delta_bytes"],
        "stacked_ingest_rss_delta_bytes": stacked["ingest_rss_delta_bytes"],
        "fused_stack_bytes": fused["stack_bytes"],
        "stacked_stack_bytes": stacked["stack_bytes"],
        "fan_in": fan_in,
        "rounds": rounds,
        "platform": "cpu",
    }
    print(json.dumps(_stamped(rec)), flush=True)


def _bf16_dataset_dir() -> tuple[str, int]:
    """Size-skewed packed population for the bf16+bucket A/B: the static
    batch budget is priced by a 480-row tail client (0.1% of the
    population) while typical cohorts need a fraction of it — the
    FEMNIST-lognormal shape the bucket ladder exists for."""
    from fedml_tpu.core.client_source import PackedNpySource
    from fedml_tpu.data.synthetic import synthetic_packed_population

    n = _env_int("FEDML_BENCH_BF16_CLIENTS", 100_000)
    dim = _env_int("FEDML_BENCH_BF16_DIM", 32)
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tmp",
                     f"bench_bf16_{n}x{dim}")
    if not os.path.isfile(os.path.join(d, "meta.json")):
        # 0.1% of clients at 480 rows, the rest 6-25: the static budget is
        # 60 batches while a typical 16-client cohort needs <= 4 — REAL
        # natural-partition shape (FEMNIST's lognormal max is ~20x its
        # p50), and exactly the regime the bucket ladder targets
        synthetic_packed_population(d, n, dim=dim, tail_size=480,
                                    tail_every=1000)
        PackedNpySource(d).close()
    return d, n


def _measure_bf16(leg: str) -> None:
    """One FEDML_BENCH_FUSED bf16 A/B leg in its own process: ``f32`` is
    the pre-policy engine (f32 compute, static batch budget every round),
    ``bf16`` the bf16+bucketed-vmap path (bf16 casts in the vmapped fits,
    per-cohort ladder depth). Matched rounds/seed/cohort over the same
    100k-client streamed population; reports rounds/s."""
    import dataclasses as _dc

    import jax

    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
    from fedml_tpu.core.client_source import PackedNpySource
    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.models.linear import LogisticRegression

    t0 = time.perf_counter()
    d, n = _bf16_dataset_dir()
    rounds = _env_int("FEDML_BENCH_BF16_ROUNDS", 42)
    src = PackedNpySource(d)
    cfg = FedAvgConfig(comm_round=rounds, client_num_in_total=n,
                       client_num_per_round=16, batch_size=8, lr=0.1,
                       epochs=_env_int("FEDML_BENCH_BF16_EPOCHS", 6),
                       frequency_of_the_test=10_000, seed=0)
    if leg == "bf16":
        cfg = _dc.replace(cfg, precision="bf16")
    task = classification_task(LogisticRegression(num_classes=5))
    api = FedAvgAPI(src, task, cfg, bucket_batches=(leg == "bf16"))
    api.warmup()
    api.run_round(0)
    api.run_round(1)
    _mark(t0, f"bf16 A/B leg {leg}: warm (2 rounds)")
    tl = time.perf_counter()
    for r in range(2, rounds):
        api.run_round(r)
    jax.block_until_ready(jax.tree.leaves(api.net.params))
    dt = time.perf_counter() - tl
    src.close()
    rec = {
        "leg": leg, "clients": n, "rounds": rounds,
        "bucketed": leg == "bf16",
        "seconds": round(dt, 3),
        "rounds_per_sec": round((rounds - 2) / dt, 3),
    }
    print(json.dumps(_stamped(rec)), flush=True)


def _stream_dataset_dir() -> tuple[str, int]:
    """Deterministic packed-npy population under ./tmp (built once,
    reused by both A/B legs so they read identical bytes) — the ONE
    shared fixture writer (data/synthetic.synthetic_packed_population),
    so this and the ci.sh flat-memory smoke cannot drift."""
    from fedml_tpu.core.client_source import PackedNpySource
    from fedml_tpu.data.synthetic import synthetic_packed_population

    n = _env_int("FEDML_BENCH_STREAM_CLIENTS", 100_000)
    dim = _env_int("FEDML_BENCH_STREAM_DIM", 16)
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tmp",
                     f"bench_stream_{n}x{dim}")
    if not os.path.isfile(os.path.join(d, "meta.json")):
        synthetic_packed_population(d, n, dim=dim)
        PackedNpySource(d).close()  # smoke the layout before the legs run
    return d, n


def _measure_stream(leg: str) -> None:
    """One FEDML_BENCH_STREAM A/B leg in its own process (RSS is a
    process-level number — sharing a process would contaminate it):
    ``streamed`` runs the engine over the PackedNpySource (only the
    sampled cohort's rows ever reach memory), ``materialized`` loads the
    same population into a full FederatedData first (the pre-PR data
    plane). Matched rounds/seed/cohort; reports end RSS, across-round RSS
    growth, pack seconds, rounds/s."""
    import jax
    import numpy as np

    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
    from fedml_tpu.core.client_source import PackedNpySource
    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.models.linear import LogisticRegression
    from fedml_tpu.obs.memwatch import host_rss_bytes

    t0 = time.perf_counter()
    d, n = _stream_dataset_dir()
    rounds = _env_int("FEDML_BENCH_STREAM_ROUNDS", 12)
    if leg == "streamed":
        data = PackedNpySource(d)
    else:
        from fedml_tpu.core.client_data import FederatedData

        src = PackedNpySource(d)
        offsets = np.load(os.path.join(d, "offsets.npy"))
        data = FederatedData(
            train_x=np.load(os.path.join(d, "x.npy")),
            train_y=np.load(os.path.join(d, "y.npy")),
            test_x=src.test_x, test_y=src.test_y,
            train_idx_map={c: np.arange(offsets[c], offsets[c + 1])
                           for c in range(n)},
            test_idx_map=None, class_num=5)
        src.close()
    cfg = FedAvgConfig(comm_round=rounds, client_num_in_total=n,
                       client_num_per_round=16, batch_size=8, lr=0.1,
                       frequency_of_the_test=10_000, seed=0)
    task = classification_task(LogisticRegression(num_classes=5))
    api = FedAvgAPI(data, task, cfg, bucket_batches=True)
    api.warmup()  # every bucket variant AOT-compiled before measuring
    api.run_round(0)
    api.run_round(1)
    _mark(t0, f"stream leg {leg}: warm (2 rounds)")
    rss0 = host_rss_bytes() or 0
    tl = time.perf_counter()
    for r in range(2, rounds):
        api.run_round(r)
    jax.block_until_ready(jax.tree.leaves(api.net.params))
    dt = time.perf_counter() - tl
    rss1 = host_rss_bytes() or 0
    rec = {
        "leg": leg, "clients": n, "rounds": rounds,
        "rss_end_bytes": int(rss1),
        "rss_growth_bytes": int(rss1 - rss0),
        "rss_growth_ratio": round(rss1 / max(rss0, 1), 4),
        "pack_seconds": round(float(
            api.tracer.rounds[-1].get("pack", 0.0)), 3),
        "seconds": round(dt, 3),
        "rounds_per_sec": round((rounds - 2) / dt, 3),
    }
    print(json.dumps(_stamped(rec)), flush=True)


def main() -> None:
    here = os.path.abspath(__file__)
    if os.environ.get("FEDML_BENCH_FUSED") is not None or \
            os.environ.get("FEDML_BENCH_FUSED_AGG") is not None:
        # fused-aggregation + bf16 A/B pair (docs/PERFORMANCE.md §Fused
        # aggregation / §Mixed precision) -> the BENCH_FUSED blob. Either
        # env var (any value) TRIGGERS the full A/B: both halves' legs
        # always run and ride the blob, and the headline is the fused
        # flush speedup (a ratio has no single-leg form to pick).
        # Forced-CPU children: the flush A/B isolates the server's
        # decode→sum chain, the bf16 A/B runs one child per leg at
        # matched rounds.
        rc, out = _run_child([here, "--measure", "fused_agg"],
                             _cpu_env(os.environ),
                             _env_int("FEDML_BENCH_FUSED_TIMEOUT", 900))
        fused_rec = _last_json_line(out)
        if fused_rec is None:
            raise RuntimeError(f"bench: fused A/B child failed (rc={rc})")
        legs = {}
        for leg in ("f32", "bf16"):
            rc, out = _run_child([here, "--measure", f"bf16_{leg}"],
                                 _cpu_env(os.environ),
                                 _env_int("FEDML_BENCH_BF16_TIMEOUT", 900))
            rec = _last_json_line(out)
            if rec is None:
                raise RuntimeError(
                    f"bench: bf16 A/B {leg} child failed (rc={rc})")
            legs[leg] = rec
        speedup = round(legs["bf16"]["rounds_per_sec"]
                        / max(legs["f32"]["rounds_per_sec"], 1e-9), 2)
        fused_rec.update({
            "bf16_ab": legs,
            "bf16_rounds_per_sec_speedup": speedup,
            "bf16_clients": legs["bf16"]["clients"],
        })
        _emit(fused_rec)
        return
    if os.environ.get("FEDML_BENCH_STREAM") is not None:
        # streamed-vs-materialized data-plane A/B (docs/PERFORMANCE.md
        # §Streaming & cohort bucketing) — one forced-CPU child PER LEG
        # (RSS is process-level; a shared process would contaminate it)
        legs = {}
        for leg in ("materialized", "streamed"):
            rc, out = _run_child([here, "--measure", f"stream_{leg}"],
                                 _cpu_env(os.environ),
                                 _env_int("FEDML_BENCH_STREAM_TIMEOUT",
                                          900))
            rec = _last_json_line(out)
            if rec is None:
                raise RuntimeError(
                    f"bench: stream A/B {leg} child failed (rc={rc})")
            legs[leg] = rec
        ratio = round(legs["streamed"]["rss_end_bytes"]
                      / max(legs["materialized"]["rss_end_bytes"], 1), 4)
        _emit({
            "metric": "fedavg_stream_rss_end_ratio",
            "value": ratio,
            "unit": "streamed_rss/materialized_rss",
            "mode": "stream_ab",
            "stream_ab": legs,
            "stream_clients": legs["streamed"]["clients"],
            "stream_rss_growth_bytes":
                legs["streamed"]["rss_growth_bytes"],
            "stream_rss_growth_ratio":
                legs["streamed"]["rss_growth_ratio"],
            "platform": "cpu",
        })
        return
    if os.environ.get("FEDML_BENCH_CODEC") is not None:
        # wire-efficiency A/B — forced-CPU child (loopback threads; the
        # measurement is bytes-on-the-wire per codec tier, not FLOPs)
        rc, out = _run_child([here, "--measure", "codec"],
                             _cpu_env(os.environ),
                             _env_int("FEDML_BENCH_CODEC_TIMEOUT", 600))
        rec = _last_json_line(out)
        if rec is None:
            raise RuntimeError(f"bench: codec A/B child failed (rc={rc})")
        _emit(rec)
        return
    if os.environ.get("FEDML_BENCH_ASYNC") is not None:
        # protocol-level A/B — forced-CPU child (loopback threads; the
        # accelerator adds nothing but lease risk to this measurement)
        rc, out = _run_child([here, "--measure", "async"],
                             _cpu_env(os.environ),
                             _env_int("FEDML_BENCH_ASYNC_TIMEOUT", 600))
        rec = _last_json_line(out)
        if rec is None:
            raise RuntimeError(f"bench: async A/B child failed (rc={rc})")
        _emit(rec)
        return
    if os.environ.get("FEDML_BENCH_DP") is not None:
        # ε-vs-accuracy A/B over the masked secure tier — forced-CPU
        # child (loopback threads; the DP mechanism is the measurement)
        rc, out = _run_child([here, "--measure", "dp"],
                             _cpu_env(os.environ),
                             _env_int("FEDML_BENCH_DP_TIMEOUT", 600))
        rec = _last_json_line(out)
        if rec is None:
            raise RuntimeError(f"bench: dp A/B child failed (rc={rc})")
        _emit(rec)
        return
    env, backend = _probe_backend()

    cheap_timeout = _env_int("FEDML_BENCH_CHEAP_TIMEOUT", 900)
    block_timeout = _env_int("FEDML_BENCH_BLOCK_TIMEOUT", 1200)

    lease_sleep = _env_int("FEDML_BENCH_LEASE_SLEEP", 180)

    # lease-recovery sleeps only make sense when an accelerator grant exists
    # (forced-CPU children never hold one)
    on_accel = backend != "cpu"
    low_core = (os.cpu_count() or 1) <= 2
    if not on_accel and low_core:
        # the probe already fell back to CPU on a near-coreless box: the full
        # 8-round cheap measurement (~215 s compile + >80 s/round here) and
        # the block compile cannot fit any child budget — degrade up front
        env.setdefault("FEDML_BENCH_ROUNDS_CHEAP", _cpu_cheap_rounds())
        cheap_timeout = max(cheap_timeout, 1500)

    cheap, rc = None, 0
    for attempt in range(2):
        rc, out = _run_child([here, "--measure", "per_round"], env, cheap_timeout)
        # a child that printed its JSON and THEN died (teardown crash,
        # timeout during exit) still produced a usable measurement — keep it
        cheap = _last_json_line(out)
        if cheap:
            print(f"bench: per-round result stashed (rc={rc}): "
                  f"{json.dumps(cheap)}", file=sys.stderr)
            break
        print(f"bench: per-round measurement failed (rc={rc}, "
              f"attempt {attempt + 1}/2)", file=sys.stderr)
        if rc != 124:
            break  # deterministic crash: retrying pays the build again for 0
        if attempt == 0 and on_accel:
            # the killed child was holding the accelerator: wait out the
            # wedged grant, then retry once (the compile cache the dead
            # child already populated makes the retry much cheaper)
            print(f"bench: sleeping {lease_sleep}s for lease recovery",
                  file=sys.stderr)
            time.sleep(lease_sleep)

    if not on_accel and low_core:
        # CPU-on-1-core: the block program's compile alone exceeds any
        # sensible budget; the per-round number is the honest result
        if cheap is None:
            raise RuntimeError("bench: all measurement paths failed")
        _emit(cheap)
        return
    if rc == 124 and on_accel:
        # whatever the last per-round child salvaged, a SIGKILLed-on-timeout
        # child leaves the grant wedged — let it expire before the flagship
        # block child (the only remaining accelerator user) launches
        print(f"bench: last child timed out; sleeping {lease_sleep}s before "
              "the block measurement", file=sys.stderr)
        time.sleep(lease_sleep)
    rc, out = _run_child([here, "--measure", "block"], env, block_timeout)
    best = _last_json_line(out) or cheap
    if (best is not None and best.get("mode") == "block" and cheap is not None
            and cheap.get("platform") == best.get("platform")):
        # one line, BOTH modes: the block number assumes the workload rides
        # the scanned round-block; per_round is what run_round-only engines
        # (FedDF/FedCon host-driven stages) actually get
        best["per_round"] = {k: cheap[k] for k in
                             ("value", "samples_per_sec_per_chip",
                              "mfu_vs_bf16_peak") if k in cheap}
    if best is None and on_accel:
        # last resort: a degraded-but-real CPU number beats a stack trace
        # (the forced-CPU child never touches the accelerator, so no
        # lease-recovery sleep is needed first). Measured on this 1-core
        # host: ~215 s compile + >80 s/round — so cap the timed rounds at 2
        # and stretch the box; the early salvage line needs exactly 2.
        print("bench: accelerator measurements failed; CPU last resort",
              file=sys.stderr)
        cpu_env = _cpu_env(env)
        cpu_env["FEDML_BENCH_ROUNDS_CHEAP"] = _cpu_cheap_rounds()
        rc, out = _run_child([here, "--measure", "per_round"], cpu_env,
                             max(cheap_timeout, 1500))
        best = _last_json_line(out)
    if best is None:
        raise RuntimeError("bench: all measurement paths failed")
    _emit(best)


def _emit(best: dict) -> None:
    """Print the ONE authoritative JSON line. A degraded (CPU) liveness
    number must not read as "no TPU evidence exists": it carries a pointer
    to the newest committed real-TPU measurement when one is on disk."""
    if best.get("platform") != "tpu":
        ref = _last_recorded_tpu_result()
        if ref is not None:
            best["last_recorded_tpu"] = ref
    print(json.dumps(best))


def _natural_key(path: str) -> list:
    """Descending-sort key that orders embedded integers numerically:
    bench_tpu_r10 must beat bench_tpu_r4 and attempt10 beat attempt2 (plain
    reverse string sort gets both wrong once a counter hits two digits).
    Text chunks rank above number chunks so `attempt_clean` still sorts
    after (wins over, in reverse) `attempt1`."""
    import re

    return [(0, int(c)) if c.isdigit() else (1, c)
            for c in re.split(r"(\d+)", path)]


def _last_recorded_tpu_result(base: str | None = None) -> dict | None:
    """Newest committed real-TPU bench line under runs/bench_tpu_*/.

    "Newest" by descending natural-sorted path (round dirs then attempt
    names — git does not preserve mtimes, so a fresh clone would make mtime
    order arbitrary; `attempt_clean` deliberately sorts after `attempt1`).
    ``FEDML_BENCH_TPU_EVIDENCE_DIR`` overrides the search root (tests)."""
    import glob

    base = (base or os.environ.get("FEDML_BENCH_TPU_EVIDENCE_DIR")
            or os.path.dirname(os.path.abspath(__file__)))
    logs = sorted(glob.glob(os.path.join(base, "runs", "bench_tpu_*",
                                         "*.stdout.log")),
                  key=_natural_key, reverse=True)
    for p in logs:
        try:
            with open(p, errors="replace") as f:
                rec = _last_json_line(f.read())
        except OSError:
            continue
        if rec and rec.get("platform") == "tpu":
            rec["source"] = os.path.relpath(p, base)
            return rec
    return None


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--measure":
        if sys.argv[2] == "async":
            _measure_async()
        elif sys.argv[2] == "codec":
            _measure_codec()
        elif sys.argv[2] == "dp":
            _measure_dp()
        elif sys.argv[2] == "fused_agg":
            _measure_fused_agg()
        elif sys.argv[2].startswith("bf16_"):
            _measure_bf16(sys.argv[2][len("bf16_"):])
        elif sys.argv[2].startswith("stream_"):
            _measure_stream(sys.argv[2][len("stream_"):])
        else:
            _measure(sys.argv[2])
    else:
        main()
