"""Benchmark: FedAvg rounds/sec on FEMNIST-shaped workload (BASELINE.json).

Runs the flagship config — FedAvg-paper CNN, 3400 simulated clients, 10
sampled per round, batch 20, E=1 (benchmark/README.md:54 setting) — on the
available device(s) and prints ONE JSON line.

Structure (robustness on flaky/remote-compile backends):
  - Rounds run in fixed-size blocks (FEDML_BENCH_BLOCK, default 10): jit
    caches by shape, so ONE compiled block executable serves the warmup and
    every timed block — a single compile regardless of how many rounds are
    timed.
  - If the scanned-block path fails (e.g. a remote-compile transport drops
    mid-flight), the bench falls back to the per-round jitted path and still
    prints its JSON line.

vs_baseline: the reference publishes no throughput numbers
(BASELINE.json.published = {}); its round latency is bounded below by the
MPI manager's 0.3 s receive-poll sleep (mpi/com_manager.py:71-78), so we use
1/0.3 ≈ 3.33 rounds/sec as the reference ceiling for the ratio.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _emit(rounds_per_sec: float, mode: str) -> None:
    baseline_rounds_per_sec = 1.0 / 0.3  # MPI poll-loop lower bound, see docstring
    print(
        json.dumps(
            {
                "metric": "fedavg_femnist_rounds_per_sec",
                "value": round(rounds_per_sec, 3),
                "unit": "rounds/sec",
                "vs_baseline": round(rounds_per_sec / baseline_rounds_per_sec, 2),
                # "block" = flagship scanned-block path; "per_round_fallback"
                # = degraded measurement after a block-path failure — do NOT
                # compare the two against each other
                "mode": mode,
            }
        )
    )


def main():
    import jax

    try:
        # persistent compile cache: repeat bench runs (and driver re-runs)
        # skip the expensive first compile when the program is unchanged
        cache_dir = os.environ.get("FEDML_COMPILE_CACHE",
                                   os.path.expanduser("~/.cache/fedml_tpu_xla"))
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        print(f"bench: compile cache unavailable ({e})", file=sys.stderr)

    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
    from fedml_tpu.core.tasks import classification_task
    from fedml_tpu.data.registry import load_dataset
    from fedml_tpu.models.cnn import CNNOriginalFedAvg

    def _env_int(name: str, default: int) -> int:
        try:
            return max(1, int(os.environ.get(name, "") or default))
        except ValueError:
            print(f"bench: ignoring non-integer {name}", file=sys.stderr)
            return default

    block = _env_int("FEDML_BENCH_BLOCK", 10)
    n_timed = _env_int("FEDML_BENCH_ROUNDS", 20)
    n_timed = max(block, (n_timed // block) * block)  # whole blocks only
    # debug/test knobs — leave unset for the flagship measurement
    clients_per_round = _env_int("FEDML_BENCH_CLIENTS_PER_ROUND", 10)
    max_batches = _env_int("FEDML_BENCH_MAX_BATCHES", 28)

    # FEMNIST-shaped: 3400 clients, ~110 samples each (lognormal sizes);
    # uint8 pixels -> 4x less host->device transfer, normalized on device
    data = load_dataset("femnist", seed=0, uint8_pixels=True)
    cfg = FedAvgConfig(
        comm_round=block + n_timed,
        client_num_in_total=3400,
        client_num_per_round=clients_per_round,
        epochs=1,
        batch_size=20,
        lr=0.1,
        frequency_of_the_test=10_000,  # pure training throughput
        max_batches=max_batches,  # 28 covers ~[22,550]-sample clients at bs=20
    )
    task = classification_task(CNNOriginalFedAvg(only_digits=False))
    # device_data: whole train set parked in HBM (~300 MB uint8); a round
    # ships only the shuffled index block (~KBs) and gathers on device
    api = FedAvgAPI(data, task, cfg, device_data=True)

    try:
        # warmup block = the one and only compile (jit caches by shape; every
        # later block of the same length reuses the executable)
        api.run_rounds(0, block)
        jax.block_until_ready(api.net.params)

        t0 = time.perf_counter()
        for start in range(block, block + n_timed, block):
            # each block is ONE compiled lax.scan over rounds: no per-round
            # dispatch, no per-round transfer beyond the index blocks
            api.run_rounds(start, block)
        jax.block_until_ready(api.net.params)
        dt = time.perf_counter() - t0
        _emit(n_timed / dt, "block")
        return
    except Exception as e:  # noqa: BLE001 — fall back, still emit a number
        print(f"bench: block path failed ({type(e).__name__}: {e}); "
              "falling back to per-round path", file=sys.stderr)

    del api  # free the first engine's HBM (full uint8 train set + params)
    api2 = FedAvgAPI(data, task, cfg, device_data=True)
    api2.run_round(0)  # warm: compile the per-round program
    jax.block_until_ready(api2.net.params)
    n_seq = max(3, n_timed // 4)
    t0 = time.perf_counter()
    for r in range(1, 1 + n_seq):
        api2.run_round(r)
    jax.block_until_ready(api2.net.params)
    _emit(n_seq / (time.perf_counter() - t0), "per_round_fallback")


if __name__ == "__main__":
    main()
